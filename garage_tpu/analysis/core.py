"""graft-lint core: source model, pragma parsing, call graph, driver.

Everything here is stdlib-`ast` only.  The model is deliberately
approximate — it resolves calls by NAME (bare names, ``self.method`` /
``cls.method``, and names imported with ``from .mod import name``), not
by type inference.  That is enough to follow blocking I/O two levels
through the sync helpers coroutines actually use, while staying
dependency-free and fast (~the whole tree in well under a second).

Violation keys are line-number-free (``rule:path:symbol:detail``) so the
committed baseline survives unrelated edits to the same file.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Iterable

# pragma grammar:  # graft-lint: allow-<kind>(<reason>)
# The reason is REQUIRED — a suppression nobody can explain is debt, not
# triage.  Unknown kinds and empty reasons are themselves violations.
PRAGMA_RE = re.compile(r"#\s*graft-lint:\s*allow-([a-z][a-z-]*)\s*\(([^)]*)\)")

PRAGMA_KINDS = {
    "blocking",  # loop-blocker
    "orphan-task",  # orphan-task
    "swallow",  # swallowed-exception
    "unpaired-metric",  # resource-discipline (register/unregister)
    "unvalidated-knob",  # resource-discipline (config knobs)
    "cancel",  # cancel-safety (await-in-finally / swallowed cancel / no-drain)
    "lock-await",  # lock-across-await (slow await under a mutex)
    "taint",  # trust-boundary (pre-auth/peer data reaching a sink)
    "wire",  # wire-compat (CRDT mutation discipline)
    "host-sync",  # host-sync (device->host sync point on the loop)
    "recompile",  # recompile-hazard (unbucketed dispatch / traced branch)
    "donation",  # use-after-donation (donated buffer re-read / advisory)
    "backend-gate",  # backend-conditional (platform compare / uncounted path)
}


@dataclass(frozen=True)
class Violation:
    rule: str
    path: str  # repo-relative, '/'-separated
    line: int
    symbol: str  # enclosing function qualname, or '<module>'
    detail: str  # short stable discriminator (no line numbers)
    message: str

    @property
    def key(self) -> str:
        return f"{self.rule}:{self.path}:{self.symbol}:{self.detail}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclass
class Pragma:
    kind: str
    reason: str
    line: int
    used: bool = False


@dataclass
class FunctionInfo:
    """One (async) function/method: where it is and what it calls."""

    module: str  # repo-relative path of the defining file
    qualname: str  # Class.method / func / outer.<locals>.inner
    node: ast.AST
    is_async: bool
    # calls made DIRECTLY by this function's body (nested defs excluded —
    # defining an inner function does not run it): (callee_repr, line)
    # where callee_repr is a bare name ("helper"), "self.method", or a
    # dotted chain ("os.fsync", "asyncio.create_task")
    calls: list[tuple[str, int]] = field(default_factory=list)


class SourceFile:
    def __init__(self, relpath: str, text: str):
        self.relpath = relpath
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=relpath)
        # pragmas live in COMMENTS only — tokenize so pragma syntax quoted
        # in a docstring or a log-message string (this package's own docs
        # do both) can never register a live suppression
        self.pragmas: dict[int, Pragma] = {}
        import io
        import tokenize

        try:
            for tok in tokenize.generate_tokens(io.StringIO(text).readline):
                if tok.type != tokenize.COMMENT:
                    continue
                m = PRAGMA_RE.search(tok.string)
                if m:
                    line = tok.start[0]
                    self.pragmas[line] = Pragma(
                        m.group(1), m.group(2).strip(), line
                    )
        except tokenize.TokenError:
            # ast.parse accepted the file, so this is near-unreachable;
            # fall back to the line scan rather than dropping pragmas
            # (no pragmas at all would turn suppressions into findings)
            for i, line_text in enumerate(self.lines, 1):
                m = PRAGMA_RE.search(line_text)
                if m:
                    self.pragmas[i] = Pragma(m.group(1), m.group(2).strip(), i)

    def pragma_for(self, node: ast.AST, kind: str) -> Pragma | None:
        """Pragma covering `node`: on its first line, the line above, or
        its last line (multi-line calls often carry the comment on the
        closing-paren line)."""
        cands = {getattr(node, "lineno", 0)}
        cands.add(getattr(node, "lineno", 1) - 1)
        end = getattr(node, "end_lineno", None)
        if end:
            cands.add(end)
        for ln in cands:
            p = self.pragmas.get(ln)
            if p is not None and p.kind == kind:
                p.used = True
                return p
        return None


def _ann_class_repr(ann) -> str | None:
    """Class name out of a parameter annotation: `Foo`, `"Foo"`,
    `mod.Foo`, `Foo | None`, `Optional[Foo]`."""
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        try:  # string annotation: parse the expression and recurse
            return _ann_class_repr(ast.parse(ann.value, mode="eval").body)
        except SyntaxError:
            return None
    if isinstance(ann, (ast.Name, ast.Attribute)):
        parts: list[str] = []
        n = ann
        while isinstance(n, ast.Attribute):
            parts.append(n.attr)
            n = n.value
        if isinstance(n, ast.Name):
            parts.append(n.id)
            return ".".join(reversed(parts))
        return None
    if isinstance(ann, ast.BinOp) and isinstance(ann.op, ast.BitOr):
        for side in (ann.left, ann.right):
            if isinstance(side, ast.Constant) and side.value is None:
                continue
            r = _ann_class_repr(side)
            if r is not None:
                return r
        return None
    if isinstance(ann, ast.Subscript):  # Optional[Foo]
        base = ann.value
        if isinstance(base, ast.Name) and base.id == "Optional":
            return _ann_class_repr(ann.slice)
    return None


def _param_annotations(meth) -> dict[str, str]:
    out: dict[str, str] = {}
    a = meth.args
    for arg in a.posonlyargs + a.args + a.kwonlyargs:
        if arg.annotation is not None:
            r = _ann_class_repr(arg.annotation)
            if r is not None:
                out[arg.arg] = r
    return out


def _ctor_repr_of(value, ann: dict[str, str]) -> str | None:
    """The constructor repr a value plausibly came from: a direct call
    `Foo(...)`, the call branch of `Foo(...) if cond else None`, or a
    parameter pass-through `self.x = param` where the param carries a
    class annotation (the one type hint the analyzer honors)."""
    if isinstance(value, ast.Call):
        return call_repr(value.func)
    if isinstance(value, ast.IfExp):
        ctors = set()
        for side in (value.body, value.orelse):
            if isinstance(side, ast.Constant) and side.value is None:
                continue
            ctors.add(_ctor_repr_of(side, ann))
        ctors.discard(None)
        return ctors.pop() if len(ctors) == 1 else None
    if isinstance(value, ast.Name):
        return ann.get(value.id)
    return None


def call_repr(func: ast.AST) -> str | None:
    """Render a Call.func node to a resolvable string: 'name',
    'self.method', or a dotted chain 'a.b.c'.  None for anything
    dynamic (subscripts, calls-of-calls)."""
    parts: list[str] = []
    n = func
    while isinstance(n, ast.Attribute):
        parts.append(n.attr)
        n = n.value
    if isinstance(n, ast.Name):
        parts.append(n.id)
        return ".".join(reversed(parts))
    return None


class _FunctionCollector(ast.NodeVisitor):
    def __init__(self, sf: SourceFile):
        self.sf = sf
        self.stack: list[str] = []
        self.functions: list[FunctionInfo] = []

    def _visit_fn(self, node, is_async: bool):
        qual = ".".join(self.stack + [node.name]) if self.stack else node.name
        info = FunctionInfo(self.sf.relpath, qual, node, is_async)
        info.calls = _direct_calls(node)
        self.functions.append(info)
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    def visit_FunctionDef(self, node):
        self._visit_fn(node, False)

    def visit_AsyncFunctionDef(self, node):
        self._visit_fn(node, True)

    def visit_ClassDef(self, node):
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()


def _direct_calls(fn_node) -> list[tuple[str, int]]:
    """Calls lexically in `fn_node`'s body, excluding nested def/lambda
    bodies (defining an inner function does not execute it)."""
    out: list[tuple[str, int]] = []

    def walk(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            if isinstance(child, ast.Call):
                r = call_repr(child.func)
                if r is not None:
                    out.append((r, child.lineno))
            walk(child)

    for stmt in fn_node.body:
        walk(stmt)
    return out


class Project:
    """All analyzed sources + a name-resolved function index."""

    def __init__(self, root: str):
        self.root = root
        self.files: dict[str, SourceFile] = {}
        self.functions: dict[tuple[str, str], FunctionInfo] = {}
        # per-module: bare/last name -> [FunctionInfo] (same module)
        self._by_name: dict[str, dict[str, list[FunctionInfo]]] = {}
        # per-module: imported name -> (module relpath, original name)
        self.imports: dict[str, dict[str, tuple[str, str]]] = {}
        # per-module: top-level class names (receiver-type resolution)
        self.classes: dict[str, set[str]] = {}
        # (module, class) -> {attr: ctor repr}: `self.x = Foo(...)` seen in
        # a method body.  Conflicting ctors for one attr map to None
        # (ambiguous — resolution declines rather than guessing).
        self._self_attr_ctors: dict[tuple[str, str], dict[str, str | None]] = {}

    # --- loading -------------------------------------------------------------

    def add_file(self, abspath: str) -> SourceFile | None:
        rel = os.path.relpath(abspath, self.root).replace(os.sep, "/")
        try:
            with open(abspath, encoding="utf-8") as f:
                text = f.read()
        except (OSError, UnicodeDecodeError):
            return None
        try:
            sf = SourceFile(rel, text)
        except SyntaxError:
            return None
        self.files[rel] = sf
        col = _FunctionCollector(sf)
        col.visit(sf.tree)
        byname = self._by_name.setdefault(rel, {})
        for fn in col.functions:
            self.functions[(rel, fn.qualname)] = fn
            byname.setdefault(fn.qualname.rsplit(".", 1)[-1], []).append(fn)
        self.imports[rel] = _collect_imports(sf.tree, rel)
        classes = self.classes.setdefault(rel, set())
        for node in sf.tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            classes.add(node.name)
            attrs = self._self_attr_ctors.setdefault((rel, node.name), {})
            for meth in node.body:
                if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                ann = _param_annotations(meth)
                for sub in ast.walk(meth):
                    if not isinstance(sub, ast.Assign):
                        continue
                    ctor = _ctor_repr_of(sub.value, ann)
                    if ctor is None:
                        continue
                    for tgt in sub.targets:
                        if (
                            isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"
                        ):
                            prev = attrs.get(tgt.attr, ctor)
                            attrs[tgt.attr] = ctor if prev == ctor else None
        return sf

    def add_tree(self, subdir: str) -> None:
        base = os.path.join(self.root, subdir) if subdir else self.root
        if os.path.isfile(base):
            self.add_file(base)
            return
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [
                d for d in dirnames if d not in ("__pycache__", ".git", ".xla_cache")
            ]
            for name in sorted(filenames):
                if name.endswith(".py"):
                    self.add_file(os.path.join(dirpath, name))

    # --- resolution ----------------------------------------------------------

    def resolve_call(
        self, caller: FunctionInfo, callee: str
    ) -> FunctionInfo | None:
        """Name-based resolution of a call made by `caller`:
          - bare name -> function in the same module, else a same-named
            import from an analyzed module
          - self.X / cls.X -> method X in the same class, else any
            same-module function named X
          - self.X.Y -> method Y on the class CONSTRUCTED into self.X
            (`self.x = Foo()` tracked per class; ISSUE 10 lifted the
            PR 7 limit one level)
        Deeper chains (self.a.b.c) are still NOT resolved (no type
        inference) — they are matched against the blocking-call tables
        directly instead."""
        mod = caller.module
        if callee.startswith(("self.", "cls.")):
            name = callee.split(".", 1)[1]
            if "." in name:
                # self.obj.method: resolve through the ctor assignment
                # recorded for obj on the caller's class, if unambiguous
                attr, _, meth = name.partition(".")
                if "." in meth:
                    return None  # 3+ levels deep: untyped
                cls = self._enclosing_class(caller)
                if cls is None:
                    return None
                ctor = self._self_attr_ctors.get((mod, cls), {}).get(attr)
                target = self._resolve_class(mod, ctor) if ctor else None
                if target is None:
                    return None
                return self.functions.get((target[0], f"{target[1]}.{meth}"))
            cls = caller.qualname.rsplit(".", 1)[0] if "." in caller.qualname else None
            if cls:
                hit = self.functions.get((mod, f"{cls}.{name}"))
                if hit is not None:
                    return hit
            for fn in self._by_name.get(mod, {}).get(name, []):
                return fn
            return None
        if "." in callee:
            # module-qualified: "mod.func" where mod was imported
            head, _, tail = callee.partition(".")
            if "." in tail:
                return None
            imp = self.imports.get(mod, {}).get(head)
            if imp is not None:
                if imp[1] == "*module*":
                    target_mod = imp[0]
                else:
                    # `from . import mod [as m]` / `from .pkg import mod`
                    # bind a MODULE under a from-import: the target file
                    # is <package-dir>/<name>.py, not the package itself
                    target_mod = imp[0][:-3] + "/" + imp[1] + ".py"
                for fn in self._by_name.get(target_mod, {}).get(tail, []):
                    if "." not in fn.qualname:
                        return fn
            return None
        # bare name: same module first
        for fn in self._by_name.get(mod, {}).get(callee, []):
            if "." not in fn.qualname:  # plain function, not a method
                return fn
        imp = self.imports.get(mod, {}).get(callee)
        if imp is not None and imp[1] != "*module*":
            target_mod, orig = imp
            for fn in self._by_name.get(target_mod, {}).get(orig, []):
                if "." not in fn.qualname:
                    return fn
        return None

    def _enclosing_class(self, fn: FunctionInfo) -> str | None:
        """The class a method belongs to: the first qualname component,
        when it names a top-level class of the module (nested helpers
        inside methods keep working — Class.method.inner -> Class)."""
        head = fn.qualname.split(".", 1)[0]
        return head if head in self.classes.get(fn.module, set()) else None

    def _resolve_class(self, mod: str, ctor: str) -> tuple[str, str] | None:
        """Resolve a constructor repr ('Foo', 'mod.Foo', 'Foo.new') to
        (module relpath, class name) among analyzed files."""

        def local_or_imported(name: str) -> tuple[str, str] | None:
            if name in self.classes.get(mod, set()):
                return (mod, name)
            imp = self.imports.get(mod, {}).get(name)
            if imp is not None and imp[1] != "*module*":
                tmod, orig = imp
                if orig in self.classes.get(tmod, set()):
                    return (tmod, orig)
            return None

        if "." not in ctor:
            return local_or_imported(ctor)
        head, _, tail = ctor.partition(".")
        if "." in tail:
            return None
        # `Foo.new(...)` classmethod constructor: the class is the head
        hit = local_or_imported(head)
        if hit is not None:
            return hit
        # `mod.Foo(...)` through an imported module
        imp = self.imports.get(mod, {}).get(head)
        if imp is not None:
            tmod = (
                imp[0] if imp[1] == "*module*"
                else imp[0][:-3] + "/" + imp[1] + ".py"
            )
            if tail in self.classes.get(tmod, set()):
                return (tmod, tail)
        return None


def _collect_imports(
    tree: ast.Module, relpath: str
) -> dict[str, tuple[str, str]]:
    """Map local names to (module relpath, original name) for
    `from .x import y` forms; `import a.b as m` maps m -> (a/b.py,
    '*module*') so `m.func()` resolves."""
    out: dict[str, tuple[str, str]] = {}
    pkg_parts = relpath.split("/")[:-1]  # directory of this module

    def module_to_rel(level: int, module: str | None) -> str | None:
        if level == 0:
            parts = (module or "").split(".")
        else:
            base = pkg_parts[: len(pkg_parts) - (level - 1)]
            parts = base + ((module or "").split(".") if module else [])
        if not parts or parts == [""]:
            return None
        return "/".join(parts) + ".py"

    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            target = module_to_rel(node.level, node.module)
            if target is None:
                continue
            for alias in node.names:
                if alias.name == "*":
                    continue
                out[alias.asname or alias.name] = (target, alias.name)
        elif isinstance(node, ast.Import):
            for alias in node.names:
                rel = alias.name.replace(".", "/") + ".py"
                out[alias.asname or alias.name.split(".")[0]] = (rel, "*module*")
    return out


def walk_no_defs(node):
    """All descendants of `node`, excluding nested function/lambda
    bodies (defining an inner function does not execute it; a nested
    def's hazards belong to its own analysis).  THE shared skip-defs
    walker — rules must use this instead of growing private copies, so
    a change to the skip set lands everywhere at once."""
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        yield child
        yield from walk_no_defs(child)


def iter_async_reachable(project: "Project", fn: FunctionInfo, max_depth: int):
    """BFS from coroutine `fn` through name-resolved SYNC helpers:
    yields (func, chain, depth) for `fn` itself and every sync callee
    within `max_depth` hops.  Awaited coroutines are skipped (they get
    their own pass as BFS roots); functions only ever *passed* (e.g. to
    ``asyncio.to_thread``) never appear — they are not in the call
    graph.  THE shared reachability walk for the loop-blocker-shaped
    rules (loop-blocker, host-sync): a fix to hop resolution must land
    in both at once."""
    queue = [(fn, [fn.qualname], 0)]
    visited = {(fn.module, fn.qualname)}
    while queue:
        cur, chain, depth = queue.pop(0)
        yield cur, chain, depth
        if depth >= max_depth:
            continue
        for callee, _line in cur.calls:
            target = project.resolve_call(cur, callee)
            if target is None or target.is_async:
                continue
            key = (target.module, target.qualname)
            if key in visited:
                continue
            visited.add(key)
            queue.append((target, chain + [target.qualname], depth + 1))


def iter_nodes_with_owner(sf: SourceFile):
    """Yield (node, owner_qualname) for every AST node in the file,
    where owner is the NEAREST enclosing function ('<module>' outside
    any).  Rules use this instead of ast.walk so a node inside a nested
    function is attributed exactly once."""

    def walk(node, owner: str, stack: list[str]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = ".".join(stack + [child.name]) if stack else child.name
                yield child, owner
                yield from walk(child, qual, stack + [child.name])
            elif isinstance(child, ast.ClassDef):
                yield child, owner
                yield from walk(child, owner, stack + [child.name])
            else:
                yield child, owner
                yield from walk(child, owner, stack)

    yield from walk(sf.tree, "<module>", [])


# --- driver -------------------------------------------------------------------


def analyze(
    root: str,
    paths: Iterable[str] = ("garage_tpu",),
    rules: Iterable[str] | None = None,
    timings: dict[str, float] | None = None,
) -> list[Violation]:
    """Run all (or the selected) rule families over `paths` under `root`.
    Returns unsuppressed violations sorted by (path, line).  When a dict
    is passed as `timings` it is filled with per-rule wall seconds
    (served by `graft_lint.py --json`)."""
    import time

    from . import (
        backend_gate,
        cancel_safety,
        donation,
        host_sync,
        lock_await,
        loop_blocker,
        orphan_task,
        recompile,
        resource,
        swallowed,
        taint,
        wire_compat,
    )

    project = Project(root)
    for p in paths:
        project.add_tree(p)

    all_rules = {
        "loop-blocker": loop_blocker.check,
        "orphan-task": orphan_task.check,
        "swallowed-exception": swallowed.check,
        "resource-discipline": resource.check,
        "cancel-safety": cancel_safety.check,
        "lock-await": lock_await.check,
        "trust-boundary": taint.check,
        "wire-compat": wire_compat.check,
        "host-sync": host_sync.check,
        "recompile-hazard": recompile.check,
        "use-after-donation": donation.check,
        "backend-gate": backend_gate.check,
    }
    selected = set(rules) if rules else set(all_rules)
    unknown = selected - set(all_rules)
    if unknown:
        raise ValueError(f"unknown rule(s): {sorted(unknown)}")

    violations: list[Violation] = []
    for name in sorted(selected):
        t0 = time.perf_counter()
        violations.extend(all_rules[name](project))
        if timings is not None:
            timings[name] = time.perf_counter() - t0
    violations.extend(_check_pragmas(project))
    violations.sort(key=lambda v: (v.path, v.line, v.rule, v.detail))
    return violations


def _check_pragmas(project: Project) -> list[Violation]:
    """A pragma with an unknown kind or an empty reason is itself a
    violation — suppressions must stay explicable."""
    out: list[Violation] = []
    for rel, sf in project.files.items():
        for p in sf.pragmas.values():
            if p.kind not in PRAGMA_KINDS:
                out.append(
                    Violation(
                        "pragma", rel, p.line, "<module>",
                        f"unknown:{p.kind}",
                        f"unknown graft-lint pragma kind {p.kind!r} "
                        f"(valid: {', '.join(sorted(PRAGMA_KINDS))})",
                    )
                )
            elif not p.reason:
                out.append(
                    Violation(
                        "pragma", rel, p.line, "<module>",
                        f"empty-reason:{p.kind}",
                        f"graft-lint pragma allow-{p.kind} needs a "
                        "non-empty reason",
                    )
                )
    return out


# --- baseline -----------------------------------------------------------------


def load_baseline(path: str) -> dict[str, int]:
    with open(path, encoding="utf-8") as f:
        raw = json.load(f)
    if raw.get("version") != 1:
        raise ValueError(f"unsupported baseline version {raw.get('version')!r}")
    return {k: int(v["count"]) for k, v in raw["violations"].items()}


def write_baseline(path: str, violations: list[Violation]) -> None:
    counts: dict[str, int] = {}
    messages: dict[str, str] = {}
    for v in violations:
        counts[v.key] = counts.get(v.key, 0) + 1
        messages.setdefault(v.key, v.message)
    obj = {
        "version": 1,
        "generated_by": "script/graft_lint.py --write-baseline",
        "violations": {
            k: {"count": counts[k], "message": messages[k]}
            for k in sorted(counts)
        },
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(obj, f, indent=2, sort_keys=True)
        f.write("\n")


def diff_baseline(
    violations: list[Violation], baseline: dict[str, int]
) -> tuple[list[Violation], list[str]]:
    """(new_violations, stale_keys): a violation is NEW when its key
    occurs more times than the baseline allows; a baseline key is STALE
    when the code no longer produces that many occurrences (debt paid —
    regenerate the baseline so it can't silently re-accrue)."""
    seen: dict[str, int] = {}
    new: list[Violation] = []
    for v in violations:
        seen[v.key] = seen.get(v.key, 0) + 1
        if seen[v.key] > baseline.get(v.key, 0):
            new.append(v)
    stale = [k for k, n in sorted(baseline.items()) if seen.get(k, 0) < n]
    return new, stale
