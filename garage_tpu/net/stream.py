"""Byte streams attached to RPC messages (reference src/net/stream.rs:20).

A ByteStream is any `AsyncIterator[bytes]`.  `StreamWriter` is the
receiving-side bridge: the connection feeds chunks in, the application
consumes them as an async iterator; errors and cancellation propagate.
"""

from __future__ import annotations

import asyncio
from typing import AsyncIterator


class StreamError(Exception):
    pass


_END = object()


class StreamWriter:
    """In-memory bridge between the connection reader task and the
    application consuming an attached stream.

    `feed` never blocks (the connection's single recv loop must keep
    serving other multiplexed requests even if one stream's consumer is
    slow or absent).  The primary backpressure is CREDIT-BASED flow
    control (connection.py): the peer stops sending once its
    STREAM_WINDOW of credit runs out, and `on_consume(n)` — called as the
    application drains bytes — is how the connection grants more.  The
    `max_buffer` overflow failure remains as a safety net against peers
    that ignore credit."""

    def __init__(self, max_buffer: int = 16 * 1024 * 1024, on_consume=None):
        self.q: asyncio.Queue = asyncio.Queue()
        self.max_buffer = max_buffer
        self.on_consume = on_consume
        self._buffered = 0
        self._closed = False

    async def feed(self, chunk: bytes) -> None:
        if self._closed:
            return
        self._buffered += len(chunk)
        if self._buffered > self.max_buffer:
            await self.close("stream buffer overflow (consumer too slow)")
            return
        self.q.put_nowait(chunk)

    async def close(self, error: str | None = None) -> None:
        if not self._closed:
            self._closed = True
            self.q.put_nowait(StreamError(error) if error else _END)

    def reader(self) -> AsyncIterator[bytes]:
        async def gen():
            while True:
                item = await self.q.get()
                if item is _END:
                    return
                if isinstance(item, StreamError):
                    raise item
                self._buffered -= len(item)
                if self.on_consume is not None and item:
                    self.on_consume(len(item))
                yield item

        return gen()


async def read_stream_to_end(stream: AsyncIterator[bytes]) -> bytes:
    parts = []
    async for chunk in stream:
        parts.append(chunk)
    return b"".join(parts)


async def stream_from_bytes(data: bytes, chunk: int = 64 * 1024) -> AsyncIterator[bytes]:
    for i in range(0, len(data), chunk):
        yield data[i : i + chunk]


def bytes_stream(data: bytes, chunk: int = 64 * 1024) -> AsyncIterator[bytes]:
    return stream_from_bytes(data, chunk)
