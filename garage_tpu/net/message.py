"""Message priorities and request/response envelopes.

Reference: src/net/message.rs:49-58 (priorities), :62-89 (order tags),
:96-133 (typed Message with attached streams).
"""

from __future__ import annotations

import itertools
from typing import Any, AsyncIterator

# Request priorities: lower value = more urgent.  The secondary flag lets a
# class of traffic yield to its own primaries (reference message.rs:49-58).
PRIO_HIGH = 0
PRIO_NORMAL = 1
PRIO_BACKGROUND = 2
PRIO_SECONDARY = 0x10  # OR-able flag


def prio_level(prio: int) -> int:
    """Scheduling bucket: 2*class + secondary-bit (6 buckets total)."""
    return 2 * (prio & 0x0F) + (1 if prio & PRIO_SECONDARY else 0)


N_PRIO_LEVELS = 6


class OrderTag:
    """Orders chunks of several responses within one logical stream
    (reference message.rs:62-89): all messages tagged with the same
    `stream` id are delivered to the app in increasing `seq` order.
    Used by the block-read pipeline to prefetch blocks concurrently but
    deliver bytes in order."""

    __slots__ = ("stream", "seq")

    def __init__(self, stream: int, seq: int):
        self.stream = stream
        self.seq = seq

    @classmethod
    def stream_of(cls, sid: int) -> "OrderTagStream":
        return OrderTagStream(sid)

    def to_obj(self) -> list[int]:
        return [self.stream, self.seq]

    @classmethod
    def from_obj(cls, obj) -> "OrderTag | None":
        return None if obj is None else cls(obj[0], obj[1])


class OrderTagStream:
    def __init__(self, sid: int):
        self.sid = sid
        self._next = 0

    def order(self) -> OrderTag:
        t = OrderTag(self.sid, self._next)
        self._next += 1
        return t


_next_sid = itertools.count(1)


def new_order_stream() -> OrderTagStream:
    """Process-unique ordered sub-stream (one per GET pipeline)."""
    return OrderTagStream(next(_next_sid))


class Req:
    """An RPC request: msgpack-able body + optional attached byte stream.

    `traceparent` (utils/tracing.py inject() bytes) rides the request
    frame's meta so the serving node can parent its handler span under
    the caller's trace — None (the common case with tracing off) adds
    nothing to the wire."""

    def __init__(
        self,
        body: Any,
        stream: AsyncIterator[bytes] | None = None,
        order_tag: OrderTag | None = None,
        traceparent: bytes | None = None,
    ):
        self.body = body
        self.stream = stream
        self.order_tag = order_tag
        self.traceparent = traceparent


class Resp:
    """An RPC response: body + optional attached byte stream."""

    def __init__(
        self,
        body: Any,
        stream: AsyncIterator[bytes] | None = None,
        order_tag: OrderTag | None = None,
    ):
        self.body = body
        self.stream = stream
        self.order_tag = order_tag
