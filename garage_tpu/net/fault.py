"""Seedable fault-injection plane (deterministic chaos nemesis).

Generalizes the binary `NetApp.blocked_peers` seam: a `FaultPlan` is a
per-node description of the faults its outgoing RPC traffic and local
disk should suffer, driven by ONE PRNG seeded explicitly — the same seed
replays the exact same fault sequence, so a chaos-test failure is
reproducible from its logged seed.

Fault kinds (per peer, or a default for all peers):

  latency_ms / jitter_ms   added one-way delay per outgoing call
  drop                     probability a request is lost: the call hangs
                           until the caller's timeout fires (like a real
                           lost packet — this is what exercises adaptive
                           timeouts + the circuit breaker, not a fast
                           error)
  truncate                 probability a served response stream is cut
                           mid-transfer (the receiver sees a StreamError,
                           not a short read)
  disk_write_fail /        probability a local block-file write/read
  disk_read_fail           raises OSError (block/manager.py honors these
                           when a plan is installed on the manager)

Install with `netapp.fault_plan = FaultPlan(seed).set_rule(...)` and/or
`block_manager.fault_plan = plan`; remove by setting None.  Every decision
the plan takes is appended to `plan.trace` as (op, peer_prefix, outcome),
which tests assert on for deterministic replay.

Reference analog: the reference tests this layer with external tooling
(mknet topologies + jepsen.garage); here the nemesis lives in-process so
single-process integration tests can run it deterministically.
"""

from __future__ import annotations

import random
from dataclasses import dataclass

from .stream import StreamError


@dataclass
class FaultRule:
    latency_ms: float = 0.0
    jitter_ms: float = 0.0
    drop: float = 0.0
    truncate: float = 0.0
    disk_write_fail: float = 0.0
    disk_read_fail: float = 0.0


class InjectedDiskFault(OSError):
    pass


TRACE_MAX = 100_000  # decisions kept for replay assertions; benches with a
# long-lived plan (thousands of calls) must not grow memory unboundedly


class FaultPlan:
    def __init__(self, seed: int):
        self.seed = seed
        self.rng = random.Random(seed)
        self.rules: dict[bytes, FaultRule] = {}
        self.default_rule: FaultRule | None = None
        self.trace: list[tuple] = []

    def set_rule(self, rule: FaultRule, peer: bytes | None = None) -> "FaultPlan":
        """Faults for calls toward `peer` (None = every peer without a
        specific rule).  Returns self for chaining.  Disk faults are
        node-LOCAL (there is no peer on a disk read), so they are only
        accepted on the default rule — a per-peer disk rule would be
        silently dead, which a chaos test must never be."""
        if peer is None:
            self.default_rule = rule
        else:
            if rule.disk_write_fail or rule.disk_read_fail:
                raise ValueError(
                    "disk faults are node-local: set them on the default "
                    "rule (set_rule(rule) without peer=)"
                )
            self.rules[peer] = rule
        return self

    def _rule(self, peer: bytes) -> FaultRule | None:
        return self.rules.get(peer, self.default_rule)

    def _note(self, op: str, peer: bytes, outcome) -> None:
        if len(self.trace) < TRACE_MAX:
            self.trace.append((op, peer.hex()[:8], outcome))

    # --- decisions (each draws from the seeded PRNG in call order) -----------

    def rpc_delay(self, peer: bytes) -> float:
        """Seconds of injected delay for one outgoing call."""
        r = self._rule(peer)
        if r is None or (r.latency_ms <= 0 and r.jitter_ms <= 0):
            return 0.0
        if r.jitter_ms <= 0:
            # fixed latency is not a PRNG decision: no draw, no trace
            # (bench seams add 2 ms to every call — tracing each would
            # be pure memory growth with zero replay value)
            return r.latency_ms / 1000.0
        d = r.latency_ms + self.rng.random() * r.jitter_ms
        self._note("delay", peer, round(d, 6))
        return d / 1000.0

    def should_drop(self, peer: bytes) -> bool:
        r = self._rule(peer)
        if r is None or r.drop <= 0:
            return False
        hit = self.rng.random() < r.drop
        self._note("drop", peer, hit)
        return hit

    def maybe_truncate_stream(self, peer: bytes, stream):
        """Wrap a response stream so it fails partway through (~uniform
        fraction of the chunks delivered, then StreamError)."""
        r = self._rule(peer)
        if stream is None or r is None or r.truncate <= 0:
            return stream
        hit = self.rng.random() < r.truncate
        self._note("truncate", peer, hit)
        if not hit:
            return stream
        cut_after = self.rng.randint(1, 4)  # chunks delivered before the cut

        async def gen():
            n = 0
            async for chunk in stream:
                if n >= cut_after:
                    raise StreamError(
                        f"injected stream truncation after {n} chunks "
                        f"(FaultPlan seed {self.seed})"
                    )
                n += 1
                yield chunk
            # stream shorter than the cut point: the fault misses

        return gen()

    def should_fail_disk(self, op: str) -> bool:
        """op: 'read' | 'write' — local block-store fault."""
        r = self.default_rule
        if r is None:
            return False
        p = r.disk_write_fail if op == "write" else r.disk_read_fail
        if p <= 0:
            return False
        hit = self.rng.random() < p
        if len(self.trace) < TRACE_MAX:
            self.trace.append(("disk-" + op, "", hit))
        return hit
