"""Crypto primitives for the handshake, with a stdlib fallback.

When the `cryptography` package is installed, this module re-exports the
real primitives and `HAVE_REAL_CRYPTO` is True — nothing changes.

When it is missing (stripped test/CI containers), a stdlib-only fallback
with the same *API shape* is provided so the whole net/rpc/chaos stack
stays importable and testable.  THE FALLBACK IS NOT SECURE:

  - "ed25519" keys are random 32-byte strings; the public key is a hash
    of the private key; "signatures" are HMACs keyed by the PUBLIC key,
    so anyone who knows a node's id can forge its signature.
  - "x25519" exchange derives the shared secret from the two public
    values only — an eavesdropper can compute it.
  - "ChaCha20Poly1305" frames are NOT encrypted: payload + a 16-byte
    HMAC-SHA256 tag (integrity/auth against accidental corruption only).

What survives in fallback mode: cluster membership still requires the
shared network key (the hello HMAC in handshake.py uses stdlib hmac), and
frames are integrity-checked.  What is lost: confidentiality and
third-party-unforgeable node identity.  That is acceptable for loopback
dev clusters and tests, and useless against a real adversary — so
handshake.py swaps the protocol VERSION_TAG in fallback mode, making a
fallback node and a real-crypto node refuse each other at the first hello
instead of silently downgrading a production cluster.
"""

from __future__ import annotations

import hashlib
import hmac as hmac_mod
import logging
import os

logger = logging.getLogger("garage.net")

try:  # real primitives
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
        Ed25519PublicKey,
    )
    from cryptography.hazmat.primitives.asymmetric.x25519 import (
        X25519PrivateKey,
        X25519PublicKey,
    )
    from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305

    HAVE_REAL_CRYPTO = True
except ImportError:  # stdlib fallback
    HAVE_REAL_CRYPTO = False
    logger.warning(
        "python 'cryptography' package unavailable: using the INSECURE "
        "stdlib transport fallback (authenticated by network key only, "
        "no encryption). Do not expose RPC ports on untrusted networks."
    )

    class _InvalidSignature(Exception):
        pass

    class Ed25519PublicKey:  # type: ignore[no-redef]
        def __init__(self, raw: bytes):
            self._raw = raw

        @classmethod
        def from_public_bytes(cls, raw: bytes) -> "Ed25519PublicKey":
            return cls(bytes(raw))

        def public_bytes_raw(self) -> bytes:
            return self._raw

        def verify(self, signature: bytes, message: bytes) -> None:
            want = hmac_mod.new(
                b"garage-fallback-sig" + self._raw, message, hashlib.sha256
            ).digest()
            if not hmac_mod.compare_digest(signature, want):
                raise _InvalidSignature("fallback signature mismatch")

    class Ed25519PrivateKey:  # type: ignore[no-redef]
        def __init__(self, raw: bytes):
            self._raw = raw

        @classmethod
        def generate(cls) -> "Ed25519PrivateKey":
            return cls(os.urandom(32))

        @classmethod
        def from_private_bytes(cls, raw: bytes) -> "Ed25519PrivateKey":
            return cls(bytes(raw))

        def private_bytes_raw(self) -> bytes:
            return self._raw

        def public_key(self) -> Ed25519PublicKey:
            return Ed25519PublicKey(
                hashlib.sha256(b"garage-fallback-ed25519" + self._raw).digest()
            )

        def sign(self, message: bytes) -> bytes:
            pub = self.public_key().public_bytes_raw()
            return hmac_mod.new(
                b"garage-fallback-sig" + pub, message, hashlib.sha256
            ).digest()

    class X25519PublicKey:  # type: ignore[no-redef]
        def __init__(self, raw: bytes):
            self._raw = raw

        @classmethod
        def from_public_bytes(cls, raw: bytes) -> "X25519PublicKey":
            return cls(bytes(raw))

        def public_bytes_raw(self) -> bytes:
            return self._raw

    class X25519PrivateKey:  # type: ignore[no-redef]
        def __init__(self, raw: bytes):
            self._raw = raw

        @classmethod
        def generate(cls) -> "X25519PrivateKey":
            return cls(os.urandom(32))

        def public_key(self) -> X25519PublicKey:
            return X25519PublicKey(
                hashlib.sha256(b"garage-fallback-x25519" + self._raw).digest()
            )

        def exchange(self, peer: X25519PublicKey) -> bytes:
            # symmetric in the two public values; offers NO secrecy
            a = self.public_key().public_bytes_raw()
            b = peer.public_bytes_raw()
            lo, hi = (a, b) if a <= b else (b, a)
            return hashlib.sha256(b"garage-fallback-dh" + lo + hi).digest()

    class ChaCha20Poly1305:  # type: ignore[no-redef]
        """Tag-only frame protection: plaintext + HMAC-SHA256[:16]."""

        TAG = 16

        def __init__(self, key: bytes):
            self._key = key

        def encrypt(self, nonce: bytes, data: bytes, aad: bytes | None) -> bytes:
            tag = hmac_mod.new(
                self._key, nonce + (aad or b"") + data, hashlib.sha256
            ).digest()[: self.TAG]
            return data + tag

        def decrypt(self, nonce: bytes, data: bytes, aad: bytes | None) -> bytes:
            body, tag = data[: -self.TAG], data[-self.TAG :]
            want = hmac_mod.new(
                self._key, nonce + (aad or b"") + body, hashlib.sha256
            ).digest()[: self.TAG]
            if not hmac_mod.compare_digest(tag, want):
                raise ValueError("fallback frame tag mismatch")
            return body


__all__ = [
    "HAVE_REAL_CRYPTO",
    "Ed25519PrivateKey",
    "Ed25519PublicKey",
    "X25519PrivateKey",
    "X25519PublicKey",
    "ChaCha20Poly1305",
]
