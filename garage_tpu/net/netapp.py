"""NetApp: the per-node RPC hub (reference src/net/netapp.rs:65).

Owns the node's ed25519 identity, the TCP listener, the table of named
endpoints, and the pool of peer connections (one authenticated multiplexed
connection per peer, dialed lazily and shared).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, AsyncIterator, Awaitable, Callable

from .connection import Connection, RemoteError
from .handshake import HandshakeError, handshake, node_id_of
from .message import PRIO_NORMAL, Req, Resp

logger = logging.getLogger("garage.net")


def _set_nodelay(writer: asyncio.StreamWriter) -> None:
    """Disable Nagle on RPC sockets: a request/response pattern with
    small frames can otherwise stall on the delayed-ACK timer per round
    trip on real networks (loopback benches are unaffected)."""
    import socket

    sock = writer.get_extra_info("socket")
    if sock is not None:
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass


class RpcError(Exception):
    pass


class Endpoint:
    """A named RPC endpoint; register a handler or call remote peers."""

    def __init__(self, netapp: "NetApp", path: str):
        self.netapp = netapp
        self.path = path
        self.handler: Callable[[bytes, Req], Awaitable[Resp]] | None = None

    def set_handler(self, fn: Callable[[bytes, Req], Awaitable[Resp]]) -> None:
        """fn(from_node_id, req) -> resp"""
        self.handler = fn

    async def call(
        self,
        target: bytes,
        msg: Any,
        prio: int = PRIO_NORMAL,
        timeout: float | None = 30.0,
        stream: AsyncIterator[bytes] | None = None,
        order_tag=None,
    ) -> Resp:
        from ..utils.metrics import registry
        from ..utils.tracing import NOOP_SPAN, tracer

        lbl = (("endpoint", self.path),)
        registry.incr("rpc_request_counter", lbl + (("to", target.hex()[:16]),))
        # NOOP_SPAN when disabled: the hot path allocates no span, no
        # name string, no attr dict (asserted by test_observability.py)
        cm = (
            tracer.span("rpc:" + self.path, to=target.hex()[:16])
            if tracer.enabled
            else NOOP_SPAN
        )
        with cm:
            req = Req(msg, stream=stream, order_tag=order_tag)
            if tracer.enabled:
                # inside the rpc span: the remote handler becomes ITS child
                req.traceparent = tracer.inject()
            with registry.timer("rpc_request_duration", lbl):
                try:
                    return await self.netapp.call(
                        target, self.path, req,
                        prio=prio, timeout=timeout,
                    )
                except asyncio.TimeoutError:
                    # reference exports rpc_timeout_counter separately from
                    # generic errors (src/rpc/rpc_helper.rs:172-217)
                    registry.incr("rpc_timeout_counter", lbl)
                    raise
                except Exception:
                    registry.incr("rpc_error_counter", lbl)
                    raise


class NetApp:
    def __init__(self, network_key: bytes, node_privkey: bytes):
        self.network_key = network_key
        self.node_privkey = node_privkey
        self.id: bytes = node_id_of(node_privkey)
        self.endpoints: dict[str, Endpoint] = {}
        self.conns: dict[bytes, Connection] = {}
        # every live Connection, including ones displaced from `conns` by a
        # simultaneous dial in the other direction — needed for shutdown
        # (Server.wait_closed blocks until all accepted transports close)
        self.all_conns: set[Connection] = set()
        self._connecting: dict[bytes, asyncio.Lock] = {}
        self.server: asyncio.AbstractServer | None = None
        self.bind_addr: tuple[str, int] | None = None
        # fault-injection seam (chaos tests): peers in this set are
        # unreachable — calls fail fast, like a network partition
        self.blocked_peers: set[bytes] = set()
        # seedable deterministic fault plane (net/fault.py FaultPlan):
        # per-peer latency/jitter (also the bench seam for simulated
        # inter-node RTT), probabilistic drop (hang-to-timeout), and
        # response-stream truncation for outgoing + served traffic
        self.fault_plan = None
        self.on_connected: Callable[[bytes, bool], None] | None = None
        self.on_disconnected: Callable[[bytes], None] | None = None

    # --- endpoints -----------------------------------------------------------

    def endpoint(self, path: str) -> Endpoint:
        if path not in self.endpoints:
            self.endpoints[path] = Endpoint(self, path)
        return self.endpoints[path]

    async def _dispatch(self, path: str, from_id: bytes, req: Req) -> Resp:
        ep = self.endpoints.get(path)
        if ep is None or ep.handler is None:
            raise RpcError(f"no handler for endpoint {path!r}")
        from ..utils.metrics import registry
        from ..utils.tracing import NOOP_SPAN, tracer

        # remote-parent extraction: a request arriving over the wire joins
        # the caller's trace (one trace id per logical request across the
        # whole mesh); the local-shortcut path parents via contextvars
        cm = (
            tracer.span(
                "rpc-handle:" + path,
                remote_parent=tracer.extract(req.traceparent),
                from_=from_id.hex()[:16],
                node=self.id.hex()[:16],
            )
            if tracer.enabled
            else NOOP_SPAN
        )
        with cm:
            with registry.timer("rpc_handle_duration", (("endpoint", path),)):
                resp = await ep.handler(from_id, req)
        if (
            self.fault_plan is not None
            and from_id != self.id
            and resp.stream is not None
        ):
            # nemesis: this node's uplink may cut served streams short
            resp = Resp(
                resp.body,
                stream=self.fault_plan.maybe_truncate_stream(
                    from_id, resp.stream
                ),
                order_tag=resp.order_tag,
            )
        return resp

    # --- connections ---------------------------------------------------------

    async def listen(self, host: str, port: int) -> None:
        self.server = await asyncio.start_server(self._accept, host, port)
        self.bind_addr = (host, self.server.sockets[0].getsockname()[1])
        logger.info("%s listening on %s:%d", self.id.hex()[:8], host, self.bind_addr[1])

    async def _accept(self, reader, writer) -> None:
        _set_nodelay(writer)
        try:
            box = await asyncio.wait_for(
                handshake(
                    reader, writer, self.network_key, self.node_privkey,
                    is_server=True,
                ),
                timeout=10.0,
            )
        except (HandshakeError, asyncio.TimeoutError, OSError, EOFError,
                asyncio.IncompleteReadError) as e:
            logger.info("incoming handshake failed: %r", e)
            writer.close()
            return
        conn = Connection(
            box, self._dispatch, on_close=self._on_conn_close, initiator=False
        )
        self._install_conn(conn)
        if self.on_connected:
            self.on_connected(box.peer_id, True)

    async def connect(self, addr: tuple[str, int], peer_id: bytes | None = None) -> bytes:
        """Dial a peer; returns its node id.  Reuses an existing connection."""
        if peer_id is not None and peer_id in self.conns:
            return peer_id
        lock = self._connecting.setdefault(peer_id or b"?" + repr(addr).encode(), asyncio.Lock())
        async with lock:  # graft-lint: allow-lock-await(dial-dedup lock: holding it across the dial IS the mechanism that collapses concurrent connects to one)
            if peer_id is not None and peer_id in self.conns:
                return peer_id
            reader, writer = await asyncio.open_connection(addr[0], addr[1])
            _set_nodelay(writer)
            try:
                box = await asyncio.wait_for(
                    handshake(
                        reader, writer, self.network_key, self.node_privkey,
                        is_server=False, expected_peer_id=peer_id,
                    ),
                    timeout=10.0,
                )
            except BaseException:
                writer.close()
                raise
            conn = Connection(
                box, self._dispatch, on_close=self._on_conn_close, initiator=True
            )
            self._install_conn(conn)
            if self.on_connected:
                self.on_connected(box.peer_id, False)
            return box.peer_id

    def _install_conn(self, conn: Connection) -> None:
        old = self.conns.get(conn.peer_id)
        self.conns[conn.peer_id] = conn
        self.all_conns.add(conn)
        conn.start()
        if old is not None:
            # displaced by a reconnect or simultaneous dial: close the old
            # connection so its socket and tasks don't leak (supervised —
            # a failed close would otherwise vanish with the task handle)
            from ..utils.aio import spawn_supervised

            spawn_supervised(
                old.close(), name=f"conn-close-{conn.peer_id.hex()[:8]}"
            )

    def _on_conn_close(self, conn: Connection) -> None:
        self.all_conns.discard(conn)
        cur = self.conns.get(conn.peer_id)
        if cur is conn:
            del self.conns[conn.peer_id]
            if self.on_disconnected:
                self.on_disconnected(conn.peer_id)

    def is_connected(self, peer_id: bytes) -> bool:
        return peer_id in self.conns

    async def call(
        self,
        target: bytes,
        path: str,
        req: Req,
        prio: int = PRIO_NORMAL,
        timeout: float | None = 30.0,
    ) -> Resp:
        if target == self.id:
            # local shortcut (reference calls local handlers directly too)
            return await self._dispatch(path, self.id, req)
        if target in self.blocked_peers:
            raise RpcError(f"peer {target.hex()[:16]} unreachable (partition)")
        if self.fault_plan is not None:
            delay = self.fault_plan.rpc_delay(target)
            if delay:
                await asyncio.sleep(delay)
            if self.fault_plan.should_drop(target):
                # a lost request: hang until the caller's timeout fires,
                # like a real dropped packet (this is what exercises the
                # adaptive timeouts + circuit breaker, not a fast error)
                await asyncio.sleep(timeout if timeout is not None else 3600.0)
                raise asyncio.TimeoutError(
                    f"injected drop to {target.hex()[:16]}"
                )
        conn = self.conns.get(target)
        if conn is None:
            raise RpcError(f"not connected to {target.hex()[:16]}")
        return await conn.call(path, req, prio=prio, timeout=timeout)

    async def shutdown(self) -> None:
        # close connections first: Server.wait_closed (3.12+) blocks until
        # every accepted transport has disconnected
        for conn in list(self.all_conns):
            await conn.close()
        if self.server:
            self.server.close()
            await self.server.wait_closed()


__all__ = ["NetApp", "Endpoint", "RpcError", "RemoteError"]
