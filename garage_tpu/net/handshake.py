"""Connection handshake: mutual authentication + session encryption.

Same guarantees as the reference's kuska secret-handshake + box
(src/net/client.rs:55-74, server.rs:69-88) with a Noise-style construction
from the `cryptography` package primitives (NOT a port):

  1. Both sides exchange: version tag, 32-byte nonce, X25519 ephemeral
     public key, and an HMAC(network_key) over those — only holders of the
     cluster's shared network key produce a valid hello (the version tag
     gates incompatible protocol versions up front, reference
     netapp.rs:33-40).
  2. Session keys = HKDF(x25519_shared, salt=network_key, info=nonces):
     one ChaCha20-Poly1305 key per direction; forward secrecy from the
     ephemeral DH.
  3. Over the encrypted channel, each side sends its static ed25519 public
     key (= node id) and a signature over (role tag || its own static key
     || the handshake transcript), proving node identity.  Binding the
     signer's role and static key into the signed message (as the
     reference's secret-handshake does) prevents reflection: a peer that
     only knows the network key cannot echo our own auth frame back as its
     identity proof — the role tag differs per side, and an identical
     frame is rejected outright.  The client may pin an expected peer id.

Frames after the handshake: [u32 len][ChaCha20-Poly1305 ciphertext], nonce
= 4-byte direction tag + 8-byte counter.
"""

from __future__ import annotations

import hashlib
import hmac as hmac_mod
import os
import struct
from dataclasses import dataclass

from .crypto_compat import (
    HAVE_REAL_CRYPTO,
    ChaCha20Poly1305,
    Ed25519PrivateKey,
    Ed25519PublicKey,
    X25519PrivateKey,
    X25519PublicKey,
)

# protocol version gate (2: stream flow control).  The insecure stdlib
# fallback transport (crypto_compat.py) announces a DIFFERENT tag, so a
# fallback node and a real-crypto node refuse each other at the first
# hello instead of silently downgrading the cluster's transport security.
VERSION_TAG = b"grg_tpu2" if HAVE_REAL_CRYPTO else b"grg_tpuF"
MAX_FRAME = 20 * 1024


class HandshakeError(Exception):
    pass


@dataclass
class SessionKeys:
    send_key: bytes
    recv_key: bytes
    peer_id: bytes  # peer's ed25519 public key bytes


def _hkdf(key_material: bytes, salt: bytes, info: bytes, n: int) -> bytes:
    prk = hmac_mod.new(salt, key_material, hashlib.sha256).digest()
    out, t, i = b"", b"", 1
    while len(out) < n:
        t = hmac_mod.new(prk, t + info + bytes([i]), hashlib.sha256).digest()
        out += t
        i += 1
    return out[:n]


def gen_node_key() -> bytes:
    """Generate an ed25519 private key, returned as 32 raw bytes."""
    return Ed25519PrivateKey.generate().private_bytes_raw()


def node_id_of(privkey_raw: bytes) -> bytes:
    return (
        Ed25519PrivateKey.from_private_bytes(privkey_raw)
        .public_key()
        .public_bytes_raw()
    )


class FramedBox:
    """Length-prefixed AEAD framing over an asyncio stream pair."""

    def __init__(self, reader, writer, keys: SessionKeys):
        self.reader = reader
        self.writer = writer
        self.peer_id = keys.peer_id
        self._send = ChaCha20Poly1305(keys.send_key)
        self._recv = ChaCha20Poly1305(keys.recv_key)
        self._send_ctr = 0
        self._recv_ctr = 0

    def send_frame(self, plaintext: bytes) -> None:
        nonce = b"send" + struct.pack("<Q", self._send_ctr)
        self._send_ctr += 1
        ct = self._send.encrypt(nonce, plaintext, None)
        self.writer.write(struct.pack("<I", len(ct)) + ct)

    async def drain(self) -> None:
        await self.writer.drain()

    async def recv_frame(self) -> bytes:
        hdr = await self.reader.readexactly(4)
        (n,) = struct.unpack("<I", hdr)
        if n > MAX_FRAME + 256:
            raise HandshakeError(f"oversized frame {n}")
        ct = await self.reader.readexactly(n)
        nonce = b"send" + struct.pack("<Q", self._recv_ctr)
        self._recv_ctr += 1
        return self._recv.decrypt(nonce, ct, None)


async def handshake(
    reader,
    writer,
    network_key: bytes,
    node_privkey_raw: bytes,
    is_server: bool,
    expected_peer_id: bytes | None = None,
) -> FramedBox:
    """Run the 3-step handshake; returns the encrypted framed channel."""
    my_nonce = os.urandom(32)
    eph = X25519PrivateKey.generate()
    eph_pub = eph.public_key().public_bytes_raw()

    hello_body = VERSION_TAG + my_nonce + eph_pub
    mac = hmac_mod.new(network_key, hello_body, hashlib.sha256).digest()
    writer.write(hello_body + mac)
    await writer.drain()

    peer_hello = await reader.readexactly(len(hello_body) + 32)
    peer_body, peer_mac = peer_hello[:-32], peer_hello[-32:]
    if not hmac_mod.compare_digest(
        peer_mac, hmac_mod.new(network_key, peer_body, hashlib.sha256).digest()
    ):
        raise HandshakeError("peer does not know the network key")
    if peer_body[: len(VERSION_TAG)] != VERSION_TAG:
        raise HandshakeError(
            f"protocol version mismatch: {peer_body[:len(VERSION_TAG)]!r}"
        )
    peer_nonce = peer_body[len(VERSION_TAG) : len(VERSION_TAG) + 32]
    peer_eph = peer_body[len(VERSION_TAG) + 32 :]

    shared = eph.exchange(X25519PublicKey.from_public_bytes(peer_eph))
    # deterministic transcript ordering: server material first
    if is_server:
        info = my_nonce + peer_nonce
        k_server, k_client = (
            _hkdf(shared, network_key, info + b"s2c", 32),
            _hkdf(shared, network_key, info + b"c2s", 32),
        )
        send_key, recv_key = k_server, k_client
    else:
        info = peer_nonce + my_nonce
        k_server, k_client = (
            _hkdf(shared, network_key, info + b"s2c", 32),
            _hkdf(shared, network_key, info + b"c2s", 32),
        )
        send_key, recv_key = k_client, k_server

    keys = SessionKeys(send_key=send_key, recv_key=recv_key, peer_id=b"")
    box = FramedBox(reader, writer, keys)

    # step 3: prove static identity over the encrypted channel
    sk = Ed25519PrivateKey.from_private_bytes(node_privkey_raw)
    my_id = sk.public_key().public_bytes_raw()
    transcript = info + eph_pub + peer_eph if is_server else info + peer_eph + eph_pub
    my_role, peer_role = (b"server", b"client") if is_server else (b"client", b"server")
    sig = sk.sign(b"garage-tpu-auth" + my_role + my_id + transcript)
    my_auth = my_id + sig
    box.send_frame(my_auth)
    await box.drain()

    peer_auth = await box.recv_frame()
    if hmac_mod.compare_digest(peer_auth, my_auth):
        raise HandshakeError("peer echoed our own auth frame (reflection)")
    peer_id, peer_sig = peer_auth[:32], peer_auth[32:]
    try:
        Ed25519PublicKey.from_public_bytes(peer_id).verify(
            peer_sig, b"garage-tpu-auth" + peer_role + peer_id + transcript
        )
    except Exception as e:
        raise HandshakeError(f"peer identity signature invalid: {e}") from e
    if expected_peer_id is not None and peer_id != expected_peer_id:
        raise HandshakeError(
            f"peer id mismatch: expected {expected_peer_id.hex()[:16]}, "
            f"got {peer_id.hex()[:16]}"
        )
    keys.peer_id = peer_id
    box.peer_id = peer_id
    return box
