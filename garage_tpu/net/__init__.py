"""Authenticated RPC mesh over TCP (asyncio).

Capability-parity with the reference's netapp fork (src/net/, SURVEY.md
§2.2) re-designed for asyncio rather than translated:

  - node identity = ed25519 keypair; node id = 32-byte public key
    (reference src/net/netapp.rs:26-30)
  - connections authenticated against a cluster-wide network key and
    encrypted: X25519 ephemeral DH bound to the network key via HKDF,
    ed25519 transcript signatures, ChaCha20-Poly1305 frames
    (reference uses the kuska secret-handshake, src/net/client.rs:55-74)
  - typed endpoints addressed by path strings; msgpack message bodies
    (reference src/net/endpoint.rs:17-45, message.rs:96-99)
  - chunked multiplexing with 3-level priority QoS and round-robin
    chunk scheduling so background traffic never starves interactive
    RPC (reference src/net/send.rs:17-110)
  - request/response bodies may carry an attached byte stream, delivered
    incrementally (reference src/net/stream.rs:20)
  - PeeringManager: full mesh, periodic pings, peer-list exchange
    (reference src/net/peering.rs:23-50)
"""

from .fault import FaultPlan, FaultRule
from .message import PRIO_BACKGROUND, PRIO_HIGH, PRIO_NORMAL
from .netapp import NetApp, RpcError

__all__ = [
    "NetApp",
    "RpcError",
    "FaultPlan",
    "FaultRule",
    "PRIO_HIGH",
    "PRIO_NORMAL",
    "PRIO_BACKGROUND",
]
