"""Multiplexed RPC connection: chunked frames with priority QoS.

Wire protocol inside the encrypted channel (my design; the reference's
equivalent is src/net/send.rs:17-110 chunk framing + round-robin scheduler):

  frame = [kind u8][flags u8][id u32][payload...]      (<= 16 KiB payload)
  kinds: 1=REQ_META 2=RESP_META 3=BODY 4=STREAM 5=CANCEL
  flags: FIN=1 (last chunk of body/stream), ERR=2 (response is an error)

A message is sent as META, then BODY chunks (FIN on last), then — if a
byte stream is attached — STREAM chunks (FIN on last, possibly empty).

The send scheduler keeps one queue of in-flight message generators per
priority level and interleaves chunks round-robin within a level, always
draining higher-priority levels first: a huge BACKGROUND resync transfer
adds at most one chunk of latency to a HIGH quorum RPC on the same
connection — this is the QoS that keeps repair from starving PUT/GET.

Stream flow control is CREDIT-BASED (reference analog: kuska/netapp has
none; this mirrors HTTP/2 WINDOW_UPDATE): each attached stream starts
with STREAM_WINDOW bytes of send credit; the receiver grants more
(CREDIT frames, u32 bytes) as the consuming application actually reads.
A sender that runs out of credit PARKS its message — it stops occupying
the scheduler without blocking other messages — and resumes when credit
arrives, so a slow stream consumer backpressures its producer instead of
overflowing the receiver's buffer.
"""

from __future__ import annotations

import asyncio
import heapq
import logging
import struct
from typing import Any, AsyncIterator, Awaitable, Callable

from ..utils.serde import pack as _pack, unpack as _unpack
from .handshake import FramedBox
from .message import N_PRIO_LEVELS, PRIO_NORMAL, Req, Resp, prio_level
from .stream import StreamWriter

logger = logging.getLogger("garage.net")

CHUNK = 16 * 1024
STREAM_WINDOW = 1024 * 1024  # initial per-stream send credit
GRANT_BATCH = 256 * 1024  # receiver grants credit in batches this big

K_REQ_META = 1
K_RESP_META = 2
K_BODY = 3
K_STREAM = 4
K_CANCEL = 5
K_CREDIT = 6
K_WAIT = 0  # internal sentinel: generator parked awaiting stream credit

F_FIN = 1
F_ERR = 2


class RemoteError(Exception):
    pass


class ConnectionClosed(Exception):
    pass


class _Outgoing:
    """One message being sent: frames yielded chunk by chunk."""

    __slots__ = ("frames", "rid", "aborted", "owns_credit", "tag", "level")

    def __init__(
        self, frames, rid: int, owns_credit: bool = False,
        tag: tuple | None = None, level: int = 0,
    ):
        self.frames = frames  # async iterator of (kind, flags, id, payload)
        self.rid = rid
        self.aborted = False
        # True only for the message that registered _out_credit[rid]:
        # control frames (CREDIT grants, CANCELs) share the rid and must
        # not tear the credit down when they finish
        self.owns_credit = owns_credit
        # order-tag key + seq for sender-side stream serialization
        self.tag = tag  # ((mine, sid), seq) or None
        self.level = level


class _StreamCredit:
    """Sender-side credit for one attached stream."""

    __slots__ = ("avail", "parked")

    def __init__(self, initial: int = STREAM_WINDOW):
        self.avail = initial
        self.parked: tuple[int, _Outgoing] | None = None  # (level, out)

    def grant(self, n: int, conn: "Connection") -> None:
        self.avail += n
        if self.parked is not None and self.avail > 0:
            lvl, out = self.parked
            self.parked = None
            conn._send_queues[lvl].put_nowait(out)
            conn._send_wakeup.set()


async def _frames_of(
    kind_meta: int,
    rid: int,
    meta: dict,
    body: bytes,
    stream: AsyncIterator[bytes] | None,
    credit: _StreamCredit | None = None,
):
    """Async generator of frames for one message.  When stream credit is
    exhausted it yields a K_WAIT sentinel instead of blocking — the send
    loop parks the message so other traffic keeps flowing."""
    yield (kind_meta, 0, rid, _pack(meta))
    if body or stream is None:
        n = max(1, (len(body) + CHUNK - 1) // CHUNK)
        for i in range(n):
            part = body[i * CHUNK : (i + 1) * CHUNK]
            fin = F_FIN if i == n - 1 else 0
            yield (K_BODY, fin, rid, part)
    else:
        yield (K_BODY, F_FIN, rid, b"")
    if stream is not None:
        pending = b""
        async for chunk in stream:
            pending += chunk
            while len(pending) >= CHUNK:
                while credit is not None and credit.avail <= 0:
                    yield (K_WAIT, 0, rid, b"")
                if credit is not None:
                    credit.avail -= CHUNK
                yield (K_STREAM, 0, rid, pending[:CHUNK])
                pending = pending[CHUNK:]
        while credit is not None and pending and credit.avail <= 0:
            yield (K_WAIT, 0, rid, b"")
        if credit is not None:
            credit.avail -= len(pending)
        yield (K_STREAM, F_FIN, rid, pending)


class Connection:
    """One authenticated, multiplexed peer connection (either direction)."""

    def __init__(
        self,
        box: FramedBox,
        handler: Callable[[str, bytes, Req], Awaitable[Resp]] | None,
        on_close: Callable[["Connection"], None] | None = None,
        initiator: bool = False,
    ):
        self.box = box
        self.peer_id: bytes = box.peer_id
        self.handler = handler
        self.on_close = on_close
        # Request ids must not collide between the two directions of the
        # connection: the dialing side uses odd rids, the accepting side
        # even, and frames are routed by rid parity.
        self.initiator = initiator
        self._next_id = 1 if initiator else 2
        self._send_queues: list[asyncio.Queue] = [
            asyncio.Queue() for _ in range(N_PRIO_LEVELS)
        ]
        self._send_wakeup = asyncio.Event()
        # in-flight requests we sent: id -> (resp future, stream writer slot)
        self._pending: dict[int, dict] = {}
        # in-flight requests we are receiving: id -> partial state
        self._incoming: dict[int, dict] = {}
        # send credit for streams we are transmitting, by rid
        self._out_credit: dict[int, _StreamCredit] = {}
        # stream-bearing messages currently circulating in the send
        # queues, by rid — so a peer CANCEL can abort them mid-flight
        # (they are reachable neither via _pending nor via credit.parked)
        self._active_out: dict[int, _Outgoing] = {}
        # ordered sub-streams (reference src/net/message.rs:62-89): among
        # same-tag messages pending at once, transmit ONE at a time in
        # ascending seq order, so a prefetch pipeline's responses stream
        # back-to-back instead of interleaving.  Keyed by (mine, sid) —
        # our requests and our responses echoing the REMOTE's sids must
        # not share a namespace.  (mine, sid) -> {"active", "waiting"}
        self._order: dict[tuple, dict] = {}
        self._tasks: list[asyncio.Task] = []
        self._closed = False

    def start(self) -> None:
        self._tasks.append(asyncio.create_task(self._send_loop()))
        self._tasks.append(asyncio.create_task(self._recv_loop()))

    # --- sending -------------------------------------------------------------

    async def call(
        self,
        endpoint: str,
        req: Req,
        prio: int = PRIO_NORMAL,
        timeout: float | None = 30.0,
    ) -> Resp:
        """Send a request, await the response (body complete; stream may
        continue arriving afterwards)."""
        if self._closed:
            raise ConnectionClosed("connection closed")
        rid = self._next_id
        self._next_id += 2
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._pending[rid] = {"fut": fut}
        meta = {
            "ep": endpoint,
            "prio": prio,
            "hs": req.stream is not None,
            "ot": req.order_tag.to_obj() if req.order_tag else None,
        }
        if req.traceparent is not None:
            # distributed tracing: the serving node parents its handler
            # span under ours (absent when tracing is off — the wire
            # format is byte-identical to the untraced one)
            meta["tp"] = req.traceparent
        credit = None
        if req.stream is not None:
            credit = self._out_credit[rid] = _StreamCredit()
        frames = _frames_of(
            K_REQ_META, rid, meta, _pack(req.body), req.stream, credit
        )
        out = await self._enqueue(
            prio, frames, rid, owns_credit=credit is not None,
            order_tag=req.order_tag,
        )
        self._pending[rid]["out"] = out
        try:
            if timeout is not None:
                return await asyncio.wait_for(fut, timeout)
            return await fut
        except (asyncio.TimeoutError, asyncio.CancelledError):
            self._abort_out(rid)  # stop transmitting remaining chunks
            self._pending.pop(rid, None)
            await self._enqueue(0, _one_frame(K_CANCEL, 0, rid, b""), rid)
            raise

    def _rid_is_mine(self, rid: int) -> bool:
        return (rid & 1) == (1 if self.initiator else 0)

    def _abort_out(self, rid: int) -> None:
        """Stop transmitting rid's message (half-close): mark it aborted —
        whether it is a request we sent (_pending), a response stream
        mid-transmission (_active_out), or PARKED on stream credit (which
        needs a requeue so the send loop finalizes it) — otherwise the
        producer generator and credit entry leak until the connection
        closes."""
        credit = self._out_credit.get(rid)
        p = self._pending.get(rid)
        out = p.get("out") if p else None
        if out is not None:
            out.aborted = True
        active = self._active_out.get(rid)
        if active is not None:
            active.aborted = True
        if credit is not None and credit.parked is not None:
            lvl, parked_out = credit.parked
            credit.parked = None
            parked_out.aborted = True
            self._send_queues[lvl].put_nowait(parked_out)
            self._send_wakeup.set()

    async def _enqueue(
        self, prio: int, frames, rid: int, owns_credit: bool = False,
        order_tag=None,
    ) -> _Outgoing:
        lvl = prio_level(prio)
        tag = None
        if order_tag is not None:
            tag = ((self._rid_is_mine(rid), order_tag.stream), order_tag.seq)
        out = _Outgoing(frames, rid, owns_credit=owns_credit, tag=tag, level=lvl)
        if owns_credit:
            self._active_out[rid] = out
        if tag is not None:
            ent = self._order.setdefault(tag[0], {"active": False, "waiting": []})
            if ent["active"]:
                heapq.heappush(ent["waiting"], (tag[1], rid, out))
                return out
            ent["active"] = True
        self._send_queues[lvl].put_nowait(out)
        self._send_wakeup.set()
        return out

    def _order_release(self, out: _Outgoing) -> None:
        """The tagged message finished (sent fully, aborted, or errored):
        start the smallest-seq waiter, or retire the stream state.  Never
        waits for seqs that were never enqueued — a gap (cancelled
        request) cannot wedge the stream."""
        if out.tag is None:
            return
        out.tag, key = None, out.tag[0]  # guard double release
        ent = self._order.get(key)
        if ent is None:
            return
        if ent["waiting"]:
            _seq, _rid, nxt = heapq.heappop(ent["waiting"])
            self._send_queues[nxt.level].put_nowait(nxt)
            self._send_wakeup.set()
        else:
            del self._order[key]

    async def _send_loop(self) -> None:
        try:
            while not self._closed:
                out = None
                for q in self._send_queues:
                    if not q.empty():
                        out = q.get_nowait()
                        lvl = self._send_queues.index(q)
                        break
                if out is None:
                    self._send_wakeup.clear()
                    await self._send_wakeup.wait()
                    continue
                if out.aborted:
                    # caller gave up: drop remaining chunks and release the
                    # producer generator + its credit entry
                    try:
                        await out.frames.aclose()
                    except Exception as e:  # noqa: BLE001
                        logger.debug(
                            "closing aborted stream rid %d: %r", out.rid, e
                        )
                    if out.owns_credit:
                        self._out_credit.pop(out.rid, None)
                        self._active_out.pop(out.rid, None)
                    self._order_release(out)
                    continue
                # send ONE chunk of this message, then rotate it to the back
                # of its level queue (round-robin within priority)
                try:
                    frame = await out.frames.__anext__()
                except StopAsyncIteration:
                    if out.owns_credit:
                        self._out_credit.pop(out.rid, None)
                        self._active_out.pop(out.rid, None)
                    self._order_release(out)
                    continue
                except Exception as e:  # stream producer failed mid-message
                    logger.warning(
                        "stream producer error on rid %d: %r", out.rid, e
                    )
                    # terminate the half-sent message so the peer's handler
                    # isn't left waiting on a stream that never ends
                    self.box.send_frame(
                        struct.pack("<BBI", K_CANCEL, 0, out.rid)
                    )
                    await self.box.drain()
                    # if it was our own request, fail the caller immediately
                    p = self._pending.pop(out.rid, None)
                    if p:
                        fut = p.get("fut")
                        if fut and not fut.done():
                            fut.set_exception(e)
                        if p.get("writer"):
                            await p["writer"].close(f"request aborted: {e}")
                    # the message is dead: release its credit bookkeeping
                    # like the aborted/exhausted branches do
                    if out.owns_credit:
                        self._out_credit.pop(out.rid, None)
                        self._active_out.pop(out.rid, None)
                    self._order_release(out)
                    continue
                kind, flags, rid, payload = frame
                if kind == K_WAIT:
                    # out of stream credit: park; a CREDIT frame requeues it
                    credit = self._out_credit.get(rid)
                    if credit is None or credit.avail > 0:
                        self._send_queues[lvl].put_nowait(out)  # raced a grant
                    else:
                        credit.parked = (lvl, out)
                    continue
                self.box.send_frame(
                    struct.pack("<BBI", kind, flags, rid) + payload
                )
                await self.box.drain()
                if out.tag is not None:
                    # preemption (reference send.rs:135): if a SMALLER seq
                    # of this ordered stream arrived while we streamed,
                    # park this message and let the earlier one take over
                    ent = self._order.get(out.tag[0])
                    if (
                        ent is not None
                        and ent["waiting"]
                        and ent["waiting"][0][0] < out.tag[1]
                    ):
                        heapq.heappush(
                            ent["waiting"], (out.tag[1], out.rid, out)
                        )
                        _s, _r, nxt = heapq.heappop(ent["waiting"])
                        self._send_queues[nxt.level].put_nowait(nxt)
                        continue
                self._send_queues[lvl].put_nowait(out)
        except asyncio.CancelledError:
            # close() cancelled us: teardown runs in the finally, then
            # the cancel propagates so the task ends *cancelled* (a
            # swallowed cancel made close()'s reap believe the loop
            # finished on its own — graft-lint cancel-safety)
            raise
        except (ConnectionResetError, BrokenPipeError):
            pass
        except Exception as e:
            logger.warning("send loop error: %r", e)
        finally:
            # shielded: a cancel landing while teardown itself is
            # suspended must not abandon it half-way (pending RPC
            # futures would never resolve and breakers stay pinned
            # open for the whole adaptive timeout)
            await asyncio.shield(self._teardown())

    # --- receiving -----------------------------------------------------------

    async def _recv_loop(self) -> None:
        try:
            while not self._closed:
                frame = await self.box.recv_frame()
                kind, flags, rid = struct.unpack("<BBI", frame[:6])
                payload = frame[6:]
                if kind == K_REQ_META:
                    self._incoming[rid] = {
                        "meta": _unpack(payload),
                        "body": [],
                        "writer": None,
                    }
                elif kind == K_RESP_META:
                    p = self._pending.get(rid)
                    if p is not None:
                        p["meta"] = _unpack(payload)
                        p["body"] = []
                elif kind == K_BODY:
                    await self._on_body(rid, flags, payload)
                elif kind == K_STREAM:
                    await self._on_stream(rid, flags, payload)
                elif kind == K_CREDIT:
                    credit = self._out_credit.get(rid)
                    if credit is not None:
                        (n,) = struct.unpack("<I", payload)
                        credit.grant(n, self)
                elif kind == K_CANCEL:
                    self._abort_out(rid)  # stop any stream we send on rid
                    if self._rid_is_mine(rid):
                        # peer aborted its response (e.g. stream producer
                        # failed server-side)
                        p = self._pending.pop(rid, None)
                        if p:
                            fut = p.get("fut")
                            if fut and not fut.done():
                                fut.set_exception(RemoteError("cancelled by peer"))
                            if p.get("writer"):
                                await p["writer"].close("cancelled by peer")
                    else:
                        st = self._incoming.pop(rid, None)
                        if st:
                            # close the stream first so a handler blocked on
                            # it fails with a StreamError, then cancel
                            if st.get("writer"):
                                await st["writer"].close("cancelled by peer")
                            if st.get("task"):
                                st["task"].cancel()
        except asyncio.CancelledError:
            raise  # see _send_loop: teardown in finally, end cancelled
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        except Exception as e:
            logger.warning("recv loop error: %r", e)
        finally:
            # shielded for the same reason as _send_loop's teardown
            await asyncio.shield(self._teardown())

    async def _on_body(self, rid: int, flags: int, payload: bytes) -> None:
        if not self._rid_is_mine(rid):
            # request being received (we are the serving side of this rid)
            st = self._incoming.get(rid)
            if st is None:
                return
            st["body"].append(payload)
            if flags & F_FIN:
                body = _unpack(b"".join(st["body"]))
                writer = StreamWriter(on_consume=self._granter(rid))
                st["writer"] = writer
                if not st["meta"].get("hs"):
                    await writer.close()  # no attached stream coming
                req = Req(
                    body,
                    stream=writer.reader(),
                    traceparent=st["meta"].get("tp"),
                )
                st["task"] = asyncio.create_task(self._run_handler(rid, st, req))
            return
        p = self._pending.get(rid)  # response being received (calling side)
        if p is None:
            return
        p.setdefault("body", []).append(payload)
        if flags & F_FIN:
            body = _unpack(b"".join(p["body"]))
            writer = StreamWriter(on_consume=self._granter(rid))
            p["writer"] = writer
            meta = p.get("meta", {})
            fut: asyncio.Future = p["fut"]
            # half-close: once the peer has answered, any still-unsent tail
            # of OUR request stream is useless — stop transmitting it
            # (otherwise a handler that answered early leaves our producer
            # parked on credit forever)
            self._abort_out(rid)
            if meta.get("err"):
                if not fut.done():
                    fut.set_exception(RemoteError(meta["err"]))
                self._pending.pop(rid, None)
                return
            if not meta.get("hs"):
                await writer.close()
                self._pending.pop(rid, None)
            if not fut.done():
                fut.set_result(Resp(body, stream=writer.reader()))

    async def _on_stream(self, rid: int, flags: int, payload: bytes) -> None:
        if self._rid_is_mine(rid):
            p = self._pending.get(rid)
            target = p.get("writer") if p else None
        else:
            st = self._incoming.get(rid)
            target = st.get("writer") if st else None
        if target is None:
            return
        if payload:
            await target.feed(payload)
        if flags & F_FIN:
            await target.close()
            if self._rid_is_mine(rid):
                self._pending.pop(rid, None)  # response fully received

    def _granter(self, rid: int):
        """Batched credit grants for a stream we are receiving: called by
        the StreamWriter as the application consumes bytes."""
        acc = 0

        def on_consume(n: int) -> None:
            nonlocal acc
            acc += n
            if acc >= GRANT_BATCH and not self._closed:
                grant, acc = acc, 0
                self._send_queues[0].put_nowait(
                    _Outgoing(
                        _one_frame(K_CREDIT, 0, rid, struct.pack("<I", grant)),
                        rid,
                    )
                )
                self._send_wakeup.set()

        return on_consume

    async def _run_handler(self, rid: int, st: dict, req: Req) -> None:
        from .message import OrderTag

        meta = st["meta"]
        # response streams ride the request's order tag (or an explicit
        # one the handler sets): a tagged GET prefetch pipeline's blocks
        # transmit one at a time, in seq order
        ot = OrderTag.from_obj(meta.get("ot"))
        try:
            resp = await self.handler(meta["ep"], self.peer_id, req)
            if resp.order_tag is not None:
                ot = resp.order_tag
            rmeta = {
                "err": None,
                "hs": resp.stream is not None,
                "ot": ot.to_obj() if ot else None,
            }
            credit = None
            if resp.stream is not None:
                credit = self._out_credit[rid] = _StreamCredit()
            frames = _frames_of(
                K_RESP_META, rid, rmeta, _pack(resp.body), resp.stream, credit
            )
        except asyncio.CancelledError:
            # peer abort (K_CANCEL) or teardown cancelled the handler:
            # drop the request state, then end *cancelled* so the
            # supervisor sees a cancelled task, not a completed one
            self._incoming.pop(rid, None)
            raise
        except Exception as e:  # noqa: BLE001 — errors cross the wire
            logger.debug("handler error for %s: %r", meta.get("ep"), e)
            frames = _frames_of(
                K_RESP_META, rid, {"err": f"{type(e).__name__}: {e}"}, _pack(None), None
            )
        await self._enqueue(
            meta.get("prio", PRIO_NORMAL), frames, rid,
            owns_credit=rid in self._out_credit,
            order_tag=ot,
        )
        self._incoming.pop(rid, None)

    # --- teardown ------------------------------------------------------------

    async def _teardown(self) -> None:
        if self._closed:
            return
        self._closed = True
        for rid, p in list(self._pending.items()):
            fut = p.get("fut")
            if fut and not fut.done():
                fut.set_exception(ConnectionClosed("connection lost"))
            w = p.get("writer")
            if w:
                await w.close("connection lost")
        self._pending.clear()
        for rid, st in list(self._incoming.items()):
            if st.get("task"):
                st["task"].cancel()
            if st.get("writer"):
                await st["writer"].close("connection lost")
        self._incoming.clear()
        self._out_credit.clear()
        self._active_out.clear()
        self._send_wakeup.set()
        try:
            self.box.writer.close()
        except Exception as e:  # noqa: BLE001
            logger.debug("transport close during teardown: %r", e)
        if self.on_close:
            self.on_close(self)

    async def close(self) -> None:
        from ..utils.aio import reap

        for t in self._tasks:
            t.cancel()
        await self._teardown()
        # drain the send/recv loops, consuming their outcomes (a loop
        # that died of a real error logs it at debug instead of leaking
        # an unretrieved-exception warning)
        await reap(self._tasks, log=logger, what="connection loop")


async def _one_frame(kind, flags, rid, payload):
    yield (kind, flags, rid, payload)
