"""Full-mesh peering with ping-based failure detection.

Reference src/net/peering.rs:23-50: every node tries to keep a connection
to every known peer; pings every PING_INTERVAL, a peer is DOWN after
FAILED_PING_THRESHOLD consecutive misses; peer lists are exchanged so the
mesh closes transitively.
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from dataclasses import dataclass, field

from ..utils.aio import spawn_supervised
from ..utils.backoff import expo, jittered
from .message import PRIO_HIGH, Req, Resp
from .netapp import NetApp

logger = logging.getLogger("garage.peering")

PING_INTERVAL = 15.0
# a peer whose circuit breaker is not closed gets pinged at this much
# faster cadence: RPC traffic to it is being fast-failed, so these pings
# are the only probe that notices the peer healing — at 15 s, a healed
# peer could be fast-failed for up to 15 extra seconds while every
# sync/queue worker sinks deeper into its error backoff
SICK_PING_INTERVAL = 2.0
FAILED_PING_THRESHOLD = 4
PING_TIMEOUT = 10.0
CONNECT_RETRY_BASE = 1.0
CONNECT_RETRY_MAX = 60.0


@dataclass
class PeerInfo:
    id: bytes
    addr: tuple[str, int] | None = None
    state: str = "new"  # new | connecting | up | down
    last_seen: float = 0.0
    ping_rtt: float | None = None
    failed_pings: int = 0
    connect_failures: int = 0
    next_retry: float = 0.0
    rtts: list[float] = field(default_factory=list)
    # at most ONE ping in flight per peer: the sick-peer cadence (2 s) is
    # shorter than PING_TIMEOUT (10 s), so without this guard a dark peer
    # would accumulate ~5 concurrent hanging pings whose STALE failures
    # land after the peer heals and re-open its circuit breaker
    ping_inflight: bool = False


class PeeringManager:
    def __init__(
        self,
        netapp: NetApp,
        bootstrap: list[tuple[bytes, tuple[str, int]]],
        public_addr: tuple[str, int] | None = None,
    ):
        self.netapp = netapp
        # the address advertised to peers: a 0.0.0.0/:: bind address is not
        # dialable, so deployments must set rpc_public_addr (reference
        # config.rs rpc_public_addr); defaults to the bind address, which
        # is fine for loopback dev clusters and tests
        self.public_addr = public_addr
        # per-instance override of the module default (reference
        # config.rs rpc_ping_timeout_msec -> system.rs:269)
        self.ping_timeout = PING_TIMEOUT
        self.peers: dict[bytes, PeerInfo] = {}
        for pid, addr in bootstrap:
            if pid != netapp.id:
                self.peers[pid] = PeerInfo(id=pid, addr=addr)
        # optional rpc/peer_health.PeerHealth: ping outcomes feed the
        # same breaker/EWMA state the RpcHelper uses (wired by the
        # composition root); pings bypass the breaker on purpose — they
        # are the background probe that detects healing
        self.health = None
        self.ping_ep = netapp.endpoint("net/ping")
        self.ping_ep.set_handler(self._handle_ping)
        self.peerlist_ep = netapp.endpoint("net/peer_list")
        self.peerlist_ep.set_handler(self._handle_peer_list)
        netapp.on_connected = self._on_connected
        netapp.on_disconnected = self._on_disconnected
        self._task: asyncio.Task | None = None

    def start(self) -> None:
        self._task = spawn_supervised(self._loop(), name="peering-loop")

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass

    # --- handlers ------------------------------------------------------------

    async def _handle_ping(self, from_id: bytes, req: Req) -> Resp:
        return Resp(req.body)  # echo nonce

    async def _handle_peer_list(self, from_id: bytes, req: Req) -> Resp:
        self._learn(req.body or [], from_id=from_id)
        return Resp(self._known_list())

    def _known_list(self) -> list:
        my_addr = self.public_addr or self.netapp.bind_addr or ("", 0)
        out = [[self.netapp.id, list(my_addr)]]
        for p in self.peers.values():
            if p.addr:
                out.append([p.id, list(p.addr)])
        return out

    def _learn(self, peer_list, from_id: bytes | None = None) -> None:
        """Merge a peer-list exchange.  `from_id` is the reporting peer:
        its OWN entry is authoritative for its address — a peer that
        crashed and restarted on a new port (redeploy; the jepsen
        crash/restart nemesis) used to be unreachable forever once the
        connections it had dialed died, because the stale address was
        never overwritten and every redial backed off against a dead
        port.  Third-party entries only fill unknown addresses (gossip
        re-propagating a stale address must not clobber a fresh
        authoritative one)."""
        for item in peer_list:
            pid, addr = bytes(item[0]), (item[1][0], int(item[1][1]))
            if pid == self.netapp.id:
                continue
            p = self.peers.get(pid)
            if p is None:
                self.peers[pid] = PeerInfo(id=pid, addr=addr)
            elif p.addr is None:
                p.addr = addr
            elif (
                pid == from_id
                and p.addr != addr
                # a node without rpc_public_addr self-reports its BIND
                # address, which may be a wildcard — never overwrite a
                # dialable address with an undialable one
                and addr[0] not in ("", "0.0.0.0", "::")
                and addr[1] != 0
            ):
                p.addr = addr
                # the old address's connect backoff is meaningless for
                # the new one: redial promptly
                p.connect_failures = 0
                p.next_retry = 0.0

    def _on_connected(self, pid: bytes, incoming: bool) -> None:
        info = self.peers.setdefault(pid, PeerInfo(id=pid))
        info.state = "up"
        info.last_seen = time.monotonic()
        info.failed_pings = 0
        info.connect_failures = 0

    def _on_disconnected(self, pid: bytes) -> None:
        if pid in self.peers:
            self.peers[pid].state = "down"

    # --- main loop -----------------------------------------------------------

    async def _loop(self) -> None:
        while True:
            try:
                await self._tick()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001
                logger.warning("peering tick error: %r", e)
            await asyncio.sleep(1.0)

    async def _tick(self) -> None:
        now = time.monotonic()
        for p in list(self.peers.values()):
            if self.netapp.is_connected(p.id):
                interval = PING_INTERVAL
                if (
                    self.health is not None
                    and self.health.state_of(p.id) != "closed"
                ):
                    interval = SICK_PING_INTERVAL
                if now - p.last_seen >= interval:
                    # supervised: a crashed ping task must be logged, not
                    # silently dropped with the peer stuck "up" forever
                    spawn_supervised(
                        self._ping(p), name=f"ping-{p.id.hex()[:8]}"
                    )
            elif p.addr and now >= p.next_retry:
                p.state = "connecting"
                spawn_supervised(
                    self._try_connect(p), name=f"connect-{p.id.hex()[:8]}"
                )

    async def _ping(self, p: PeerInfo) -> None:
        if p.ping_inflight:
            return
        p.ping_inflight = True
        p.last_seen = time.monotonic()  # reset the cadence clock
        nonce = random.getrandbits(63)
        t0 = time.monotonic()
        try:
            resp = await self.ping_ep.call(
                p.id, nonce, prio=PRIO_HIGH, timeout=self.ping_timeout
            )
            if resp.body != nonce:
                raise ValueError("ping nonce mismatch")
            p.ping_rtt = time.monotonic() - t0
            p.rtts = (p.rtts + [p.ping_rtt])[-16:]
            p.failed_pings = 0
            p.state = "up"
            if self.health is not None:
                self.health.record_success(p.id, p.ping_rtt)
            # piggyback peer-list exchange on successful pings
            resp = await self.peerlist_ep.call(
                p.id, self._known_list(), prio=PRIO_HIGH,
                timeout=self.ping_timeout,
            )
            self._learn(resp.body or [], from_id=p.id)
        except Exception:  # noqa: BLE001
            p.failed_pings += 1
            if self.health is not None:
                self.health.record_failure(p.id)
            if p.failed_pings >= FAILED_PING_THRESHOLD:
                p.state = "down"
                conn = self.netapp.conns.get(p.id)
                if conn:
                    await conn.close()
        finally:
            p.ping_inflight = False

    async def _try_connect(self, p: PeerInfo) -> None:
        try:
            await self.netapp.connect(p.addr, p.id)
        except Exception as e:  # noqa: BLE001
            p.connect_failures += 1
            p.state = "down"
            delay = jittered(
                expo(p.connect_failures, CONNECT_RETRY_BASE, CONNECT_RETRY_MAX)
            )
            p.next_retry = time.monotonic() + delay
            logger.debug("connect to %s failed: %r", p.id.hex()[:8], e)

    # --- introspection --------------------------------------------------------

    def peer_avg_rtt(self, pid: bytes) -> float | None:
        p = self.peers.get(pid)
        if p and p.rtts:
            return sum(p.rtts) / len(p.rtts)
        return None

    def connected_peers(self) -> list[bytes]:
        return [pid for pid in self.peers if self.netapp.is_connected(pid)]

    def peer_states(self) -> dict[bytes, str]:
        return {
            pid: ("up" if self.netapp.is_connected(pid) else p.state)
            for pid, p in self.peers.items()
        }
