"""Global bucket aliases: name -> bucket id (full-copy;
reference src/model/bucket_alias_table.rs)."""

from __future__ import annotations

from typing import Any

from ..table.schema import TableSchema
from ..utils.crdt import Lww


class BucketAlias:
    def __init__(self, name: str, state: Lww):
        self.name = name
        self.state = state  # Lww[bucket_id bytes | None]

    @classmethod
    def new(cls, name: str, bucket_id: bytes | None) -> "BucketAlias":
        # syntax-only sanity check; the punycode POLICY gate lives in the
        # helper/admin layers (reference BucketAlias::new doesn't validate)
        if not valid_bucket_name(name, allow_punycode=True):
            raise ValueError(f"invalid bucket name {name!r}")
        return cls(name, Lww(bucket_id))

    def merge(self, other: "BucketAlias") -> None:
        self.state.merge(other.state)

    def to_obj(self) -> Any:
        return [self.name, self.state.to_obj()]


class BucketAliasTable(TableSchema):
    table_name = "bucket_alias"

    def entry_partition_key(self, e: BucketAlias) -> bytes:
        return e.name.encode()

    def entry_sort_key(self, e: BucketAlias) -> bytes:
        return b""

    def decode_entry(self, obj: Any) -> BucketAlias:
        v = Lww.from_obj(obj[1])
        if v.value is not None:
            v.value = bytes(v.value)
        return BucketAlias(obj[0], v)


def valid_bucket_name(name: str, allow_punycode: bool = False) -> bool:
    """AWS-compatible bucket naming (reference bucket_alias_table.rs:79-96):
    3-63 chars of [a-z0-9.-], no leading/trailing separator, not an IP
    address, no punycode labels unless `allow_punycode` (config knob), and
    never the reserved "-s3alias" suffix."""
    import ipaddress

    # ASCII-only, like the reference's 'a'..='z' | '0'..='9' ranges —
    # str.islower()/isdigit() accept Unicode (e.g. 'é', '¹'), which would
    # let raw-Unicode homographs bypass the punycode gate below
    if not (
        3 <= len(name) <= 63
        and all("a" <= c <= "z" or "0" <= c <= "9" or c in ".-" for c in name)
        and name[0] not in ".-"
        and name[-1] not in ".-"
        and ".." not in name
    ):
        return False
    try:
        ipaddress.ip_address(name)
        return False  # bucket names must not be formatted as an IP address
    except ValueError:
        pass
    if (name.startswith("xn--") or ".xn--" in name) and not allow_punycode:
        return False
    return not name.endswith("-s3alias")
