"""Global bucket aliases: name -> bucket id (full-copy;
reference src/model/bucket_alias_table.rs)."""

from __future__ import annotations

from typing import Any

from ..table.schema import TableSchema
from ..utils.crdt import Lww


class BucketAlias:
    def __init__(self, name: str, state: Lww):
        self.name = name
        self.state = state  # Lww[bucket_id bytes | None]

    @classmethod
    def new(cls, name: str, bucket_id: bytes | None) -> "BucketAlias":
        if not valid_bucket_name(name):
            raise ValueError(f"invalid bucket name {name!r}")
        return cls(name, Lww(bucket_id))

    def merge(self, other: "BucketAlias") -> None:
        self.state.merge(other.state)

    def to_obj(self) -> Any:
        return [self.name, self.state.to_obj()]


class BucketAliasTable(TableSchema):
    table_name = "bucket_alias"

    def entry_partition_key(self, e: BucketAlias) -> bytes:
        return e.name.encode()

    def entry_sort_key(self, e: BucketAlias) -> bytes:
        return b""

    def decode_entry(self, obj: Any) -> BucketAlias:
        v = Lww.from_obj(obj[1])
        if v.value is not None:
            v.value = bytes(v.value)
        return BucketAlias(obj[0], v)


def valid_bucket_name(name: str) -> bool:
    """AWS-compatible bucket naming (reference bucket_alias_table.rs)."""
    return (
        3 <= len(name) <= 63
        and all(c.islower() or c.isdigit() or c in ".-" for c in name)
        and name[0] not in ".-"
        and name[-1] not in ".-"
        and ".." not in name
        and not all(c.isdigit() or c == "." for c in name)
    )
