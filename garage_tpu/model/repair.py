"""Metadata repair workers (reference src/garage/repair/online.rs:29-95):
walk local table entries and fix dangling references.

  versions   — tombstone version entries whose object/upload no longer
               lists them (e.g. after an interrupted delete cascade)
  mpu        — tombstone multipart uploads whose object entry no longer
               has the matching uploading version
  block_refs — tombstone block refs whose version is deleted/missing

Each worker pages through the LOCAL copy of its table (repairs run on
every node; quorum writes propagate the fixes) and goes DONE at the end
of one pass.
"""

from __future__ import annotations

import logging

from ..utils.background import Worker, WorkerState

logger = logging.getLogger("garage.repair")

BATCH = 200


class _TableWalkWorker(Worker):
    """One pass over all local entries of a table, BATCH per work()."""

    def __init__(self, garage):
        self.garage = garage
        self.cursor = b""
        self.examined = 0
        self.fixed = 0

    def status(self):
        return {"examined": self.examined, "fixed": self.fixed}

    def _table(self):
        raise NotImplementedError

    async def _repair_one(self, entry) -> bool:
        raise NotImplementedError

    async def work(self):
        data = self._table().data
        batch = []
        for k, v in data.store.iter_range(start=self.cursor):
            batch.append((k, v))
            if len(batch) >= BATCH:
                break
        if not batch:
            return WorkerState.DONE
        for k, v in batch:
            self.examined += 1
            try:
                if await self._repair_one(data.decode(v)):
                    self.fixed += 1
            except Exception:  # noqa: BLE001 — keep walking
                logger.exception("repair step failed")
        self.cursor = batch[-1][0] + b"\x00"
        return WorkerState.BUSY

    async def wait_for_work(self):
        return


class VersionRepairWorker(_TableWalkWorker):
    """reference repair/online.rs RepairVersions."""

    def name(self) -> str:
        return "version repair"

    def _table(self):
        return self.garage.version_table

    async def _repair_one(self, ver) -> bool:
        if ver.deleted.get():
            return False
        g = self.garage
        obj = await g.object_table.get(ver.bucket_id, ver.key.encode())
        referenced = False
        upload_ids = []
        if obj is not None:
            for ov in obj.versions:
                if ov.state == "aborted":
                    continue
                if ov.uuid == ver.uuid or ov.data.get("vid") == ver.uuid:
                    referenced = True
                    break
                upload_ids.append(ov.uuid)
        if not referenced:
            # maybe an in-flight multipart part: referenced via mpu parts
            for uid in upload_ids:
                mpu = await g.mpu_table.get(bytes(uid), b"")
                if mpu is None or mpu.deleted.get():
                    continue
                if any(
                    bytes(p["vid"]) == ver.uuid
                    for p in mpu.latest_parts().values()
                ):
                    referenced = True
                    break
        if not referenced:
            from .s3.version_table import Version

            logger.info("version repair: deleting dangling %s", ver.uuid.hex()[:16])
            await g.version_table.insert(
                Version.deleted_marker(ver.uuid, ver.bucket_id, ver.key)
            )
            return True
        return False


class MpuRepairWorker(_TableWalkWorker):
    """reference repair/online.rs RepairMpu."""

    def name(self) -> str:
        return "mpu repair"

    def _table(self):
        return self.garage.mpu_table

    async def _repair_one(self, mpu) -> bool:
        if mpu.deleted.get():
            return False
        g = self.garage
        obj = await g.object_table.get(mpu.bucket_id, mpu.key.encode())
        alive = obj is not None and any(
            ov.uuid == mpu.upload_id and ov.state == "uploading"
            for ov in obj.versions
        )
        if not alive:
            from .s3.mpu_table import MultipartUpload

            logger.info("mpu repair: aborting dangling %s", mpu.upload_id.hex()[:16])
            dead = MultipartUpload(
                mpu.upload_id, mpu.bucket_id, mpu.key, timestamp=mpu.timestamp
            )
            dead.deleted.set()
            await g.mpu_table.insert(dead)
            return True
        return False


class BlockRefRepairWorker(_TableWalkWorker):
    """reference repair/online.rs RepairBlockRefs."""

    def name(self) -> str:
        return "block_ref repair"

    def _table(self):
        return self.garage.block_ref_table

    async def _repair_one(self, ref) -> bool:
        if ref.deleted.get():
            return False
        g = self.garage
        ver = await g.version_table.get(bytes(ref.version), b"")
        if ver is None or ver.deleted.get():
            from .s3.block_ref_table import BlockRef

            logger.info(
                "block_ref repair: dropping ref %s -> %s",
                ref.block.hex()[:16], bytes(ref.version).hex()[:16],
            )
            dead = BlockRef(ref.block, bytes(ref.version))
            dead.deleted.set()
            await g.block_ref_table.insert(dead)
            return True
        return False
