"""Data model: table schemas + the Garage composition root
(reference src/model/)."""

from .garage import Garage

__all__ = ["Garage"]
