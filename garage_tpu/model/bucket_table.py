"""Bucket table (full-copy; reference src/model/bucket_table.rs).

A bucket is identified by a random 32-byte id; human names are aliases
(global, or local to an access key).  All parameters are LWW registers so
concurrent admin edits converge.
"""

from __future__ import annotations

from typing import Any

from ..table.schema import TableSchema
from ..utils.crdt import Crdt, Deletable, Lww, LwwMap
from ..utils.time_util import now_msec


class BucketParams(Crdt):
    def __init__(
        self,
        creation_date: int | None = None,
        aliases: LwwMap | None = None,  # global alias name -> bool
        local_aliases: LwwMap | None = None,  # [key_id, name] -> bool
        website: Lww | None = None,  # None | {index_document, error_document}
        cors: Lww | None = None,  # None | list of cors rules
        lifecycle: Lww | None = None,  # None | list of lifecycle rules
        quotas: Lww | None = None,  # {max_size, max_objects}
    ):
        self.creation_date = creation_date if creation_date is not None else now_msec()
        self.aliases = aliases or LwwMap()
        self.local_aliases = local_aliases or LwwMap()
        self.website = website or Lww.raw(0, None)
        self.cors = cors or Lww.raw(0, None)
        self.lifecycle = lifecycle or Lww.raw(0, None)
        self.quotas = quotas or Lww.raw(0, {"max_size": None, "max_objects": None})

    def merge(self, other: "BucketParams") -> None:
        self.creation_date = min(self.creation_date, other.creation_date)
        self.aliases.merge(other.aliases)
        self.local_aliases.merge(other.local_aliases)
        self.website.merge(other.website)
        self.cors.merge(other.cors)
        self.lifecycle.merge(other.lifecycle)
        self.quotas.merge(other.quotas)

    def to_obj(self) -> Any:
        return {
            "cd": self.creation_date,
            "al": self.aliases.to_obj(),
            "la": self.local_aliases.to_obj(),
            "web": self.website.to_obj(),
            "cors": self.cors.to_obj(),
            "lc": self.lifecycle.to_obj(),
            "q": self.quotas.to_obj(),
        }

    @classmethod
    def from_obj(cls, obj: Any) -> "BucketParams":
        return cls(
            creation_date=obj["cd"],
            aliases=LwwMap.from_obj(obj["al"]),
            local_aliases=LwwMap.from_obj(obj["la"]),
            website=Lww.from_obj(obj["web"]),
            cors=Lww.from_obj(obj["cors"]),
            lifecycle=Lww.from_obj(obj["lc"]),
            quotas=Lww.from_obj(obj["q"]),
        )


class Bucket:
    def __init__(self, bucket_id: bytes, state: Deletable):
        self.id = bucket_id
        self.state = state  # Deletable[BucketParams]

    @classmethod
    def new(cls, bucket_id: bytes) -> "Bucket":
        return cls(bucket_id, Deletable.present(BucketParams()))

    def is_deleted(self) -> bool:
        return self.state.is_deleted()

    def params(self) -> BucketParams | None:
        return self.state.get()

    def merge(self, other: "Bucket") -> None:
        self.state.merge(other.state)

    def to_obj(self) -> Any:
        return [self.id, self.state.to_obj()]


class BucketTable(TableSchema):
    table_name = "bucket"

    def entry_partition_key(self, e: Bucket) -> bytes:
        return e.id

    def entry_sort_key(self, e: Bucket) -> bytes:
        return b""

    def decode_entry(self, obj: Any) -> Bucket:
        return Bucket(
            bytes(obj[0]), Deletable.from_obj(obj[1], BucketParams.from_obj)
        )

    def is_tombstone(self, e: Bucket) -> bool:
        return e.is_deleted()
