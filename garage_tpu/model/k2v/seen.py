"""RangeSeenMarker: which items of a polled range the client has seen
(reference src/model/k2v/seen.rs:1-105).

Two parts:
  - a vector clock: for each writer node, every item whose entry for that
    node is <= the clock value has been seen;
  - per-item causal contexts for items that are newer than the global
    clock (the "frontier" the clock can't express).

`canonicalize` drops per-item entries the global clock already covers, so
the marker stays small as the poller's view catches up.  Encoded
base64(zlib(msgpack)) — an opaque token to clients, like the reference's
base64(zstd(msgpack)).
"""

from __future__ import annotations

import base64
import zlib

from ...utils.serde import pack, unpack


def vclock_gt(a: dict[bytes, int], b: dict[bytes, int]) -> bool:
    """True iff `a` contains progress `b` hasn't seen."""
    return any(t > b.get(node, 0) for node, t in a.items())


def vclock_max(a: dict[bytes, int], b: dict[bytes, int]) -> dict[bytes, int]:
    out = dict(a)
    for node, t in b.items():
        if t > out.get(node, 0):
            out[node] = t
    return out


class RangeSeenMarker:
    def __init__(
        self,
        vector_clock: dict[bytes, int] | None = None,
        items: dict[str, dict[bytes, int]] | None = None,
    ):
        self.vector_clock = vector_clock or {}
        self.items = items or {}

    def restrict(self, start: str | None, end: str | None, prefix: str | None) -> None:
        """Drop per-item entries outside the polled range (seen.rs:36-46)."""
        self.items = {
            sk: vc
            for sk, vc in self.items.items()
            if (start is None or sk >= start)
            and (end is None or sk < end)
            and (prefix is None or sk.startswith(prefix))
        }

    def mark_seen_node_items(self, node: bytes, items) -> None:
        """Record a node's poll response: bump that node's clock entry to
        the max it reported, and pin still-unseen items individually
        (seen.rs:48-72)."""
        for item in items:
            vv = item.causal_context().vv
            if node in vv:
                self.vector_clock[node] = max(
                    self.vector_clock.get(node, 0), vv[node]
                )
            if vclock_gt(vv, self.vector_clock):
                cur = self.items.get(item.sort_key)
                self.items[item.sort_key] = (
                    vclock_max(cur, vv) if cur is not None else dict(vv)
                )

    def canonicalize(self) -> None:
        self.items = {
            sk: vc for sk, vc in self.items.items()
            if vclock_gt(vc, self.vector_clock)
        }

    def is_new_item(self, item) -> bool:
        vv = item.causal_context().vv
        if not vclock_gt(vv, self.vector_clock):
            return False
        pinned = self.items.get(item.sort_key)
        return pinned is None or vclock_gt(vv, pinned)

    def encode(self) -> str:
        self.canonicalize()
        payload = pack(
            [
                sorted([[n, t] for n, t in self.vector_clock.items()]),
                sorted(
                    [
                        [sk, sorted([[n, t] for n, t in vc.items()])]
                        for sk, vc in self.items.items()
                    ]
                ),
            ]
        )
        return base64.b64encode(zlib.compress(payload)).decode()

    @classmethod
    def decode(cls, s: str) -> "RangeSeenMarker | None":
        try:
            vc_rows, item_rows = unpack(zlib.decompress(base64.b64decode(s)))
            return cls(
                {bytes(n): int(t) for n, t in vc_rows},
                {
                    sk: {bytes(n): int(t) for n, t in vc}
                    for sk, vc in item_rows
                },
            )
        # graft-lint: allow-swallow(malformed client token decodes to None by contract)
        except Exception:  # noqa: BLE001 — any malformed token is invalid
            return None
