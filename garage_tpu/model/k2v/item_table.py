"""K2V items: a DVVS (dotted version vector set) CRDT register
(reference src/model/k2v/item_table.rs:28-117 + causality.rs:21-47).

An item at (bucket, partition_key, sort_key) holds, per writer node, a
discard horizon `t_discard` and the concurrent values written after it:

  items[node] = {"t": t_discard, "v": [[t, value | None], ...]}   t > t_discard

A write carries the causality token (vector clock {node: t}) of the state
it has seen; everything covered by the token is discarded, so the item
converges to exactly the set of concurrent (un-seen) writes — multiple
values survive iff they were truly concurrent.  None = tombstone value.
"""

from __future__ import annotations

import base64
from typing import Any

from ...table.schema import TableSchema
from ...utils.serde import pack, unpack


class CausalContext:
    """Vector clock {node_id: last_seen_t}, encoded base64(msgpack)."""

    def __init__(self, vv: dict[bytes, int] | None = None):
        self.vv = vv or {}

    def serialize(self) -> str:
        return base64.urlsafe_b64encode(
            pack(sorted([[n, t] for n, t in self.vv.items()]))
        ).decode()

    @classmethod
    def parse(cls, s: str) -> "CausalContext":
        try:
            rows = unpack(base64.urlsafe_b64decode(s.encode()))
            return cls({bytes(n): int(t) for n, t in rows})
        except Exception as e:
            raise ValueError(f"bad causality token: {e}") from e


class K2VItem:
    def __init__(
        self,
        bucket_id: bytes,
        partition_key: str,
        sort_key: str,
        items: dict[bytes, dict] | None = None,
    ):
        self.bucket_id = bucket_id
        self.partition_key = partition_key
        self.sort_key = sort_key
        self.items = items or {}

    # --- DVVS ops -------------------------------------------------------------

    def max_t(self) -> int:
        out = 0
        for e in self.items.values():
            out = max(out, e["t"], *[t for t, _v in e["v"]] or [0])
        return out

    def causal_context(self) -> CausalContext:
        vv = {}
        for node, e in self.items.items():
            vv[node] = max(e["t"], *[t for t, _v in e["v"]] or [0])
        return CausalContext(vv)

    def update(
        self,
        this_node: bytes,
        context: CausalContext | None,
        value: bytes | None,
        node_ts: int = 0,
    ) -> int:
        """Apply a write allocated on this_node (reference item_table.rs
        update()): discard everything the writer has seen, then append the
        new value with a fresh dot.

        `node_ts` is the writer node's GLOBAL monotonic timestamp floor
        (reference rpc.rs local_insert: max(persisted, now_msec)).  Dots
        must be monotonic per NODE — not just per item — because the
        PollRange seen-marker's vector clock asserts "every item this node
        produced with t <= clock has been seen" (seen.py).  Returns the
        allocated timestamp."""
        if context is not None:
            for node, seen_t in context.vv.items():
                # nodes we have no entry for yet STILL get their horizon
                # recorded (reference item_table.rs:79-91) — otherwise a
                # value synced in later would resurrect past the token
                e = self.items.setdefault(node, {"t": 0, "v": []})
                if seen_t > e["t"]:
                    e["t"] = seen_t
                    e["v"] = [[t, v] for t, v in e["v"] if t > seen_t]
        new_t = max(self.max_t(), node_ts) + 1
        e = self.items.setdefault(this_node, {"t": 0, "v": []})
        e["v"].append([new_t, value])
        return new_t

    def values(self) -> list[bytes | None]:
        out = []
        for _node, e in sorted(self.items.items()):
            for _t, v in sorted(e["v"]):
                out.append(bytes(v) if v is not None else None)
        return out

    def live_values(self) -> list[bytes]:
        return [v for v in self.values() if v is not None]

    def is_tombstone(self) -> bool:
        vals = self.values()
        return all(v is None for v in vals)

    # --- CRDT -----------------------------------------------------------------

    def merge(self, other: "K2VItem") -> None:
        for node, oe in other.items.items():
            e = self.items.get(node)
            if e is None:
                self.items[node] = {"t": oe["t"], "v": [list(x) for x in oe["v"]]}
                continue
            t_discard = max(e["t"], oe["t"])
            by_t = {t: v for t, v in e["v"]}
            for t, v in oe["v"]:
                by_t.setdefault(t, v)
            e["t"] = t_discard
            e["v"] = sorted([[t, v] for t, v in by_t.items() if t > t_discard])

    def counts(self) -> dict[str, int]:
        vals = self.values()
        live = [v for v in vals if v is not None]
        return {
            "items": 0 if self.is_tombstone() else 1,
            "conflicts": 1 if len(live) > 1 else 0,
            "values": len(live),
            "bytes": sum(len(v) for v in live),
        }

    def to_obj(self) -> Any:
        return [
            self.bucket_id,
            self.partition_key,
            self.sort_key,
            [[n, e["t"], e["v"]] for n, e in sorted(self.items.items())],
        ]


class K2VItemTable(TableSchema):
    table_name = "k2v_item"

    def __init__(self, counter=None, sub_manager=None):
        self.counter = counter
        self.sub_manager = sub_manager

    def entry_partition_key(self, e: K2VItem) -> bytes:
        # placement by (bucket, partition_key) — reference k2v partitioning
        return e.bucket_id + e.partition_key.encode()

    def entry_sort_key(self, e: K2VItem) -> bytes:
        return e.sort_key.encode()

    def decode_entry(self, obj: Any) -> K2VItem:
        return K2VItem(
            bytes(obj[0]),
            obj[1],
            obj[2],
            {
                bytes(n): {"t": int(t), "v": [[int(tt), bytes(v) if v is not None else None] for tt, v in vals]}
                for n, t, vals in obj[3]
            },
        )

    def merge_entries(self, a, b):
        a.merge(b)
        return a

    def is_tombstone(self, e: K2VItem) -> bool:
        return e.is_tombstone()

    def matches_filter(self, e, filt) -> bool:
        if filt == "conflicts":
            return len(e.live_values()) > 1
        if filt == "present":
            return not e.is_tombstone()
        return True

    def updated(self, tx, old, new) -> None:
        if self.counter is not None:
            oldc = old.counts() if old else {"items": 0, "conflicts": 0, "values": 0, "bytes": 0}
            newc = new.counts() if new else {"items": 0, "conflicts": 0, "values": 0, "bytes": 0}
            deltas = {k: newc[k] - oldc[k] for k in newc}
            ent = new or old
            # counter keyed (bucket, partition_key): all of a bucket's
            # counters share one placement partition, so ReadIndex is an
            # ordered distributed range read (reference index.rs)
            self.counter.count(
                tx, ent.bucket_id, ent.partition_key.encode(), deltas
            )
        if self.sub_manager is not None and new is not None:
            self.sub_manager.notify(new)