"""K2V RPC: causal-timestamp allocation + quorum insert + poll pub/sub.

Reference src/model/k2v/rpc.rs:74-205,373- — an insert is routed to ONE
storage node of the item's partition (the first reachable in latency
order), which allocates the DVVS dot under a local per-item lock and then
fans the merged item out to the other replicas through the normal table
path.  PollItem long-polls a local subscription until the item changes
past the polled causality token (reference sub.rs SubscriptionManager).
"""

from __future__ import annotations

import asyncio
import logging

from ...net.message import PRIO_HIGH, Req, Resp
from ...utils.error import Error
from .item_table import CausalContext, K2VItem

logger = logging.getLogger("garage.k2v")


class SubscriptionManager:
    def __init__(self):
        self.subs: dict[tuple, list[asyncio.Event]] = {}

    def _key(self, item: K2VItem) -> tuple:
        return (item.bucket_id, item.partition_key, item.sort_key)

    def notify(self, item: K2VItem) -> None:
        for ev in self.subs.pop(self._key(item), []):
            ev.set()

    async def wait(self, bucket_id, pk, sk, timeout: float) -> bool:
        ev = asyncio.Event()
        self.subs.setdefault((bucket_id, pk, sk), []).append(ev)
        try:
            await asyncio.wait_for(ev.wait(), timeout)
            return True
        except asyncio.TimeoutError:
            return False


class K2VRpcHandler:
    def __init__(self, garage):
        self.garage = garage
        self.sub = SubscriptionManager()
        garage.k2v_item_table.schema.sub_manager = self.sub
        self.endpoint = garage.netapp.endpoint("k2v/rpc")
        self.endpoint.set_handler(self._handle)
        # fixed-size lock pool: serializes dot allocation per item without
        # accumulating one lock per key forever
        self._locks = [asyncio.Lock() for _ in range(256)]

    # --- public API (called by the HTTP layer) --------------------------------

    async def insert(
        self,
        bucket_id: bytes,
        pk: str,
        sk: str,
        causal: CausalContext | None,
        value: bytes | None,
    ) -> None:
        """Route to a storage node of the partition for dot allocation."""
        h = self.garage.k2v_item_table.schema.partition_hash(
            bucket_id + pk.encode()
        )
        nodes = self.garage.helper_rpc.request_order(
            self.garage.k2v_item_table.replication.read_nodes(h)
        )
        errors = []
        msg = [
            "Insert",
            bucket_id,
            pk,
            sk,
            causal.serialize() if causal else None,
            value,
        ]
        for n in nodes:
            try:
                await self.endpoint.call(n, msg, prio=PRIO_HIGH)
                return
            except Exception as e:  # noqa: BLE001
                errors.append(f"{n.hex()[:8]}: {e!r}")
        raise Error(f"k2v insert failed on all nodes: {errors}")

    async def insert_batch(self, bucket_id: bytes, items: list) -> None:
        """items: [(pk, sk, causal | None, value | None)] — fanned out
        concurrently (bounded) instead of one round-trip per item."""
        sem = asyncio.Semaphore(16)

        async def one(pk, sk, causal, value):
            async with sem:
                await self.insert(bucket_id, pk, sk, causal, value)

        await asyncio.gather(*[one(*it) for it in items])

    async def poll_item(
        self, bucket_id: bytes, pk: str, sk: str, causal: CausalContext, timeout: float
    ) -> K2VItem | None:
        """Wait until the item advances past `causal`; None on timeout."""
        deadline = asyncio.get_event_loop().time() + timeout
        while True:
            item = await self.garage.k2v_item_table.get(
                bucket_id + pk.encode(), sk.encode()
            )
            if item is not None and _newer_than(item, causal):
                return item
            remaining = deadline - asyncio.get_event_loop().time()
            if remaining <= 0:
                return None
            await self.sub.wait(bucket_id, pk, sk, min(remaining, 5.0))

    # --- rpc ------------------------------------------------------------------

    async def _handle(self, from_id: bytes, req: Req) -> Resp:
        op = req.body
        if op[0] == "Insert":
            bucket_id, pk, sk = bytes(op[1]), op[2], op[3]
            causal = CausalContext.parse(op[4]) if op[4] else None
            value = bytes(op[5]) if op[5] is not None else None
            await self._local_insert(bucket_id, pk, sk, causal, value)
            return Resp(None)
        raise Error(f"unknown k2v rpc op {op[0]!r}")

    async def _local_insert(self, bucket_id, pk, sk, causal, value) -> None:
        table = self.garage.k2v_item_table
        key = bucket_id + pk.encode() + b"\x00" + sk.encode()
        from ...utils.data import blake2sum

        lock = self._locks[blake2sum(key)[0]]
        async with lock:
            existing = await table.get(bucket_id + pk.encode(), sk.encode())
            item = existing or K2VItem(bucket_id, pk, sk)
            item.update(self.garage.node_id, causal, value)
            await table.insert(item)


def _newer_than(item: K2VItem, causal: CausalContext) -> bool:
    vv = item.causal_context().vv
    for node, t in vv.items():
        if t > causal.vv.get(node, 0):
            return True
    return False