"""K2V RPC: causal-timestamp allocation + quorum insert + distributed polls.

Reference src/model/k2v/rpc.rs — an insert is routed to ONE storage node
of the item's partition (the first reachable in latency order), which
allocates the DVVS dot under a local per-item lock and then fans the
merged item out to the other replicas through the normal table path.

Polls are DISTRIBUTED (reference rpc.rs:206-262 poll_item, :264-367
poll_range): the poller fans the poll out to ALL storage nodes of the
partition and needs a read quorum of responses — a write that landed on a
different replica than the poller is still observed, because that replica
answers the poll directly; no anti-entropy round-trip is needed.  Range
polls carry a RangeSeenMarker (seen.py) so each node can compute which of
its items the client hasn't seen.
"""

from __future__ import annotations

import asyncio
import logging

from ...net.message import PRIO_HIGH, PRIO_NORMAL, Req, Resp
from ...utils.aio import reap
from ...utils.error import Error
from .item_table import CausalContext, K2VItem
from .seen import RangeSeenMarker

logger = logging.getLogger("garage.k2v")

POLL_RANGE_EXTRA_DELAY = 0.2  # wait a beat for stragglers after quorum


class SubscriptionManager:
    """Local pub/sub of item updates: per-item and per-partition channels
    (reference src/model/k2v/sub.rs)."""

    def __init__(self):
        self.item_subs: dict[tuple, list[asyncio.Queue]] = {}
        self.part_subs: dict[tuple, list[asyncio.Queue]] = {}

    def notify(self, item: K2VItem) -> None:
        ikey = (item.bucket_id, item.partition_key, item.sort_key)
        pkey = (item.bucket_id, item.partition_key)
        for q in self.item_subs.get(ikey, []):
            q.put_nowait(item)
        for q in self.part_subs.get(pkey, []):
            q.put_nowait(item)

    def subscribe_item(self, bucket_id, pk, sk) -> "_Sub":
        return _Sub(self.item_subs, (bucket_id, pk, sk))

    def subscribe_partition(self, bucket_id, pk) -> "_Sub":
        return _Sub(self.part_subs, (bucket_id, pk))


class _Sub:
    def __init__(self, registry: dict, key):
        self._registry = registry
        self._key = key
        self.queue: asyncio.Queue = asyncio.Queue()

    def __enter__(self) -> "_Sub":
        self._registry.setdefault(self._key, []).append(self.queue)
        return self

    def __exit__(self, *exc) -> None:
        subs = self._registry.get(self._key, [])
        if self.queue in subs:
            subs.remove(self.queue)
        if not subs:
            self._registry.pop(self._key, None)

    async def recv(self, deadline: float) -> K2VItem | None:
        remaining = deadline - asyncio.get_event_loop().time()
        if remaining <= 0:
            return None
        try:
            return await asyncio.wait_for(self.queue.get(), remaining)
        except asyncio.TimeoutError:
            return None


class K2VRpcHandler:
    def __init__(self, garage):
        self.garage = garage
        self.sub = SubscriptionManager()
        garage.k2v_item_table.schema.sub_manager = self.sub
        self.endpoint = garage.netapp.endpoint("k2v/rpc")
        self.endpoint.set_handler(self._handle)
        # node-global dot-allocation clock (reference rpc.rs TIMESTAMP_KEY)
        self._ts_tree = garage.k2v_item_table.data.db.open_tree("k2v_local_ts")
        # fixed-size lock pool: serializes dot allocation per item without
        # accumulating one lock per key forever
        self._locks = [asyncio.Lock() for _ in range(256)]

    # --- public API (called by the HTTP layer) --------------------------------

    def _storage_nodes(self, bucket_id: bytes, pk: str) -> list[bytes]:
        h = self.garage.k2v_item_table.schema.partition_hash(
            bucket_id + pk.encode()
        )
        return self.garage.k2v_item_table.replication.read_nodes(h)

    def _read_quorum(self) -> int:
        return self.garage.k2v_item_table.replication.read_quorum()

    async def insert(
        self,
        bucket_id: bytes,
        pk: str,
        sk: str,
        causal: CausalContext | None,
        value: bytes | None,
    ) -> None:
        """Route to a storage node of the partition for dot allocation."""
        nodes = self.garage.helper_rpc.request_order(
            self._storage_nodes(bucket_id, pk)
        )
        errors = []
        msg = [
            "Insert",
            bucket_id,
            pk,
            sk,
            causal.serialize() if causal else None,
            value,
        ]
        for n in nodes:
            try:
                await self.endpoint.call(n, msg, prio=PRIO_HIGH)
                return
            except Exception as e:  # noqa: BLE001
                errors.append(f"{n.hex()[:8]}: {e!r}")
        raise Error(f"k2v insert failed on all nodes: {errors}")

    async def insert_batch(self, bucket_id: bytes, items: list) -> None:
        """items: [(pk, sk, causal | None, value | None)] — fanned out
        concurrently (bounded) instead of one round-trip per item."""
        sem = asyncio.Semaphore(16)

        async def one(pk, sk, causal, value):
            async with sem:
                await self.insert(bucket_id, pk, sk, causal, value)

        await asyncio.gather(*[one(*it) for it in items])

    async def poll_item(
        self, bucket_id: bytes, pk: str, sk: str, causal: CausalContext, timeout: float
    ) -> K2VItem | None:
        """Fan the poll out to every replica of the partition; merge what
        comes back (reference rpc.rs:206-262).  None on timeout."""
        nodes = self._storage_nodes(bucket_id, pk)
        quorum = self._read_quorum()
        msg = ["PollItem", bucket_id, pk, sk, causal.serialize(), timeout]
        tasks = [
            asyncio.create_task(
                self.endpoint.call(n, msg, prio=PRIO_NORMAL, timeout=timeout + 10)
            )
            for n in nodes
        ]
        merged: K2VItem | None = None
        oks = errs = 0
        try:
            deadline = asyncio.get_event_loop().time() + timeout + 5
            pending = set(tasks)
            while pending:
                remaining = deadline - asyncio.get_event_loop().time()
                if remaining <= 0:
                    break
                done, pending = await asyncio.wait(
                    pending, timeout=remaining,
                    return_when=asyncio.FIRST_COMPLETED,
                )
                for t in done:
                    if t.exception():
                        errs += 1
                        continue
                    oks += 1
                    body = t.result().body
                    if body is not None:
                        item = self.garage.k2v_item_table.schema.decode_entry(body)
                        if merged is None:
                            merged = item
                        else:
                            merged.merge(item)
                # a positive answer means the item changed: return as soon
                # as a quorum confirms we polled enough replicas
                if merged is not None and oks >= quorum:
                    break
                if errs > len(nodes) - quorum:
                    raise Error(f"poll_item: {errs} replicas failed")
        finally:
            # cancel stragglers AND consume every outcome: a replica that
            # failed between our last wait and the cancel would otherwise
            # leak an unretrieved exception (graft-lint orphan-task triage)
            await reap(tasks, log=logger, what="poll_item rpc")
        if oks < quorum:
            # silently-hanging replicas count against quorum too: a
            # sub-quorum answer (or timeout) must not masquerade as an
            # authoritative "nothing changed"
            raise Error(
                f"poll_item: only {oks}/{quorum} replicas responded"
            )
        return merged

    async def poll_range(
        self,
        bucket_id: bytes,
        pk: str,
        start: str | None,
        end: str | None,
        prefix: str | None,
        seen_str: str | None,
        timeout: float,
    ) -> tuple[dict[str, K2VItem], str] | None:
        """Distributed range poll (reference rpc.rs:264-367).  Returns
        (new items by sort key, next seen marker), or None when nothing
        new arrived before the timeout (only possible with a marker)."""
        seen = RangeSeenMarker()
        if seen_str is not None:
            decoded = RangeSeenMarker.decode(seen_str)
            if decoded is None:
                raise ValueError("invalid seenMarker")
            seen = decoded
        seen.restrict(start, end, prefix)

        nodes = self._storage_nodes(bucket_id, pk)
        quorum = self._read_quorum()
        msg = ["PollRange", bucket_id, pk, start, end, prefix, seen_str, timeout]
        tasks = {
            asyncio.create_task(
                self.endpoint.call(n, msg, prio=PRIO_NORMAL, timeout=timeout + 10)
            )
            for n in nodes
        }

        resps: list[tuple[bytes, list[K2VItem]]] = []
        errors: list[str] = []
        loop = asyncio.get_event_loop()
        deadline = loop.time() + timeout + 2
        pending = set(tasks)
        try:
            while pending:
                remaining = deadline - loop.time()
                if remaining <= 0:
                    break
                done, pending = await asyncio.wait(
                    pending, timeout=remaining,
                    return_when=asyncio.FIRST_COMPLETED,
                )
                for t in done:
                    if t.exception():
                        errors.append(repr(t.exception()))
                        continue
                    node, rows = t.result().body
                    resps.append(
                        (
                            bytes(node),
                            [
                                self.garage.k2v_item_table.schema.decode_entry(r)
                                for r in rows
                            ],
                        )
                    )
                if len(resps) >= quorum:
                    # brief grace period for stragglers: their data shrinks
                    # the seen marker we hand back (reference rpc.rs:305-317)
                    deadline = min(
                        deadline, loop.time() + POLL_RANGE_EXTRA_DELAY
                    )
        finally:
            await reap(tasks, log=logger, what="poll_range rpc")
        if len(resps) < quorum:
            # errored AND silently-hanging replicas both count against the
            # read quorum — advancing the seen marker off a sub-quorum view
            # would skip writes held only by the unreachable replicas
            raise Error(
                f"poll_range: only {len(resps)}/{quorum} replicas "
                f"responded (errors: {errors})"
            )

        new_items: dict[str, K2VItem] = {}
        for node, items in resps:
            seen.mark_seen_node_items(node, items)
            for item in items:
                if item.sort_key in new_items:
                    new_items[item.sort_key].merge(item)
                else:
                    new_items[item.sort_key] = item
        if not new_items and seen_str is not None:
            return None
        return dict(sorted(new_items.items())), seen.encode()

    # --- rpc ------------------------------------------------------------------

    async def _handle(self, from_id: bytes, req: Req) -> Resp:
        op = req.body
        if op[0] == "Insert":
            bucket_id, pk, sk = bytes(op[1]), op[2], op[3]
            causal = CausalContext.parse(op[4]) if op[4] else None
            value = bytes(op[5]) if op[5] is not None else None
            await self._local_insert(bucket_id, pk, sk, causal, value)
            return Resp(None)
        if op[0] == "PollItem":
            bucket_id, pk, sk = bytes(op[1]), op[2], op[3]
            causal = CausalContext.parse(op[4])
            item = await self._local_poll_item(bucket_id, pk, sk, causal, float(op[5]))
            return Resp(item.to_obj() if item is not None else None)
        if op[0] == "PollRange":
            bucket_id, pk = bytes(op[1]), op[2]
            start, end, prefix, seen_str = op[3], op[4], op[5], op[6]
            items = await self._local_poll_range(
                bucket_id, pk, start, end, prefix, seen_str, float(op[7])
            )
            return Resp([self.garage.node_id, [i.to_obj() for i in items]])
        raise Error(f"unknown k2v rpc op {op[0]!r}")

    def _node_timestamp(self) -> int:
        """This node's persisted monotonic dot-allocation clock (reference
        rpc.rs local_timestamp_tree): max(persisted, wall clock ms)."""
        from ...utils.time_util import now_msec

        stored = self._ts_tree.get(b"ts")
        prev = int.from_bytes(stored, "big") if stored else 0
        return max(prev, now_msec())

    def _bump_node_timestamp(self, t: int) -> None:
        self._ts_tree.insert(b"ts", t.to_bytes(8, "big"))

    async def _local_insert(self, bucket_id, pk, sk, causal, value) -> None:
        table = self.garage.k2v_item_table
        key = bucket_id + pk.encode() + b"\x00" + sk.encode()
        from ...utils.data import blake2sum

        lock = self._locks[blake2sum(key)[0]]
        async with lock:  # graft-lint: allow-lock-await(causal RMW: the sharded item lock must span read-merge-write or concurrent inserts lose causality)
            existing = await table.get(bucket_id + pk.encode(), sk.encode())
            item = existing or K2VItem(bucket_id, pk, sk)
            new_t = item.update(
                self.garage.node_id, causal, value, self._node_timestamp()
            )
            self._bump_node_timestamp(new_t)
            await table.insert(item)

    async def _local_poll_item(
        self, bucket_id, pk, sk, causal: CausalContext, timeout: float
    ) -> K2VItem | None:
        """Replica-side poll: answer when the LOCAL copy advances past the
        token (reference rpc.rs:449-471)."""
        deadline = asyncio.get_event_loop().time() + min(timeout, 600.0)
        with self.sub.subscribe_item(bucket_id, pk, sk) as sub:
            item = await self.garage.k2v_item_table.get_local(
                bucket_id + pk.encode(), sk.encode()
            )
            while True:
                if item is not None and _newer_than(item, causal):
                    return item
                item = await sub.recv(deadline)
                if item is None:
                    return None

    async def _local_poll_range(
        self, bucket_id, pk, start, end, prefix, seen_str, timeout: float
    ) -> list[K2VItem]:
        """Replica-side range poll (reference rpc.rs:473-507): with a seen
        marker, block until something the client hasn't seen appears; with
        none, return the current state immediately (initial snapshot)."""
        if seen_str is None:
            return await self._range_snapshot(
                bucket_id, pk, start, end, prefix, RangeSeenMarker()
            )
        seen = RangeSeenMarker.decode(seen_str)
        if seen is None:
            raise Error("invalid seenMarker")
        deadline = asyncio.get_event_loop().time() + min(timeout, 600.0)
        with self.sub.subscribe_partition(bucket_id, pk) as sub:
            new_items = await self._range_snapshot(
                bucket_id, pk, start, end, prefix, seen
            )
            while not new_items:
                item = await sub.recv(deadline)
                if item is None:
                    return []
                if (
                    (start is None or item.sort_key >= start)
                    and (end is None or item.sort_key < end)
                    and (prefix is None or item.sort_key.startswith(prefix))
                    and seen.is_new_item(item)
                ):
                    new_items.append(item)
            return new_items

    async def _range_snapshot(
        self, bucket_id, pk, start, end, prefix, seen: RangeSeenMarker
    ) -> list[K2VItem]:
        """Items of the local range the marker hasn't seen (tombstones
        included — deletions are events too)."""
        out = []
        begin = max(start or "", prefix or "")
        cursor = begin.encode() if begin else None
        while True:
            batch = await self.garage.k2v_item_table.get_range_local(
                bucket_id + pk.encode(), cursor, None, 1000
            )
            if not batch:
                return out
            for item in batch:
                sk = item.sort_key
                if end is not None and sk >= end:
                    return out
                if prefix is not None and not sk.startswith(prefix):
                    if sk > prefix:
                        return out
                    continue
                if seen.is_new_item(item):
                    out.append(item)
            if len(batch) < 1000:
                return out
            cursor = batch[-1].sort_key.encode() + b"\x00"


def _newer_than(item: K2VItem, causal: CausalContext) -> bool:
    vv = item.causal_context().vv
    for node, t in vv.items():
        if t > causal.vv.get(node, 0):
            return True
    return False
