"""Metadata DB snapshots (reference src/model/snapshot.rs:17-35).

`garage meta snapshot` (admin op) and the optional automatic interval
produce consistent copies of the metadata database under
`<metadata_dir>/snapshots/<timestamp>/`; the two most recent are kept.
"""

from __future__ import annotations

import asyncio
import logging
import os
import re
import shutil
import time

from ..utils.background import Worker, WorkerState

logger = logging.getLogger("garage.snapshot")

KEEP = 2


def take_snapshot(garage) -> str:
    # metadata_snapshots_dir knob (reference config.rs:35): snapshots can
    # live on a different volume than the live metadata
    base = garage.config.metadata_snapshots_dir or os.path.join(
        garage.config.metadata_dir, "snapshots"
    )
    # db.snapshot below blocks by design — the engine's connection is not
    # thread-safe, so the whole snapshot pass runs on the loop; offloading
    # just the mkdir/rotation around it would be theater
    # graft-lint: allow-blocking(snapshot pass blocks by design, db conn not thread-safe)
    os.makedirs(base, exist_ok=True)
    name = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    dest = os.path.join(base, name)
    garage.db.snapshot(dest)
    # rotate: keep the most recent KEEP.  Only touch entries matching our
    # timestamp naming — metadata_snapshots_dir may be a shared volume and
    # rotation must never delete foreign data.
    snaps = sorted(
        e for e in os.listdir(base) if re.fullmatch(r"\d{8}T\d{6}Z", e)
    )
    for old in snaps[:-KEEP]:
        # graft-lint: allow-blocking(rotation rides the already-blocking snapshot pass)
        shutil.rmtree(os.path.join(base, old), ignore_errors=True)
    logger.info("metadata snapshot written to %s", dest)
    return dest


class SnapshotWorker(Worker):
    """Automatic periodic snapshots (metadata_auto_snapshot_interval)."""

    def __init__(self, garage):
        self.garage = garage
        self.interval_ms = garage.config.metadata_auto_snapshot_interval
        self.last = 0.0

    def name(self) -> str:
        return "meta_snapshot"

    async def work(self):
        if not self.interval_ms:
            return WorkerState.DONE
        now = time.monotonic()
        if now - self.last < max(self.interval_ms / 1000.0, 600):
            return WorkerState.IDLE
        self.last = now
        try:
            take_snapshot(self.garage)
        except NotImplementedError:
            return WorkerState.DONE  # memory engine: nothing to snapshot
        return WorkerState.IDLE

    async def wait_for_work(self) -> None:
        await asyncio.sleep(60.0)
