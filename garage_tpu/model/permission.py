"""Bucket-key permissions (reference src/model/permission.rs).

A timestamped allow/deny triple; merge keeps the newest decision per flag.
"""

from __future__ import annotations

from typing import Any

from ..utils.crdt import Crdt


class BucketKeyPerm(Crdt):
    __slots__ = ("ts", "allow_read", "allow_write", "allow_owner")

    NO_PERMISSIONS: "BucketKeyPerm"

    def __init__(self, ts: int = 0, allow_read=False, allow_write=False, allow_owner=False):
        self.ts = ts
        self.allow_read = bool(allow_read)
        self.allow_write = bool(allow_write)
        self.allow_owner = bool(allow_owner)

    def merge(self, other: "BucketKeyPerm") -> None:
        if other.ts > self.ts:
            self.ts = other.ts
            self.allow_read = other.allow_read
            self.allow_write = other.allow_write
            self.allow_owner = other.allow_owner
        elif other.ts == self.ts:
            # tie: union of permissions (deterministic, errs on permissive
            # like the reference's merge of equal timestamps)
            self.allow_read = self.allow_read or other.allow_read
            self.allow_write = self.allow_write or other.allow_write
            self.allow_owner = self.allow_owner or other.allow_owner

    def is_any(self) -> bool:
        return self.allow_read or self.allow_write or self.allow_owner

    def to_obj(self) -> Any:
        return [self.ts, self.allow_read, self.allow_write, self.allow_owner]

    @classmethod
    def from_obj(cls, obj: Any) -> "BucketKeyPerm":
        return cls(obj[0], obj[1], obj[2], obj[3])


BucketKeyPerm.NO_PERMISSIONS = BucketKeyPerm()
