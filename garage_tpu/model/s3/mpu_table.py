"""Multipart-upload table (reference src/model/s3/mpu_table.rs).

pk = upload id (a version uuid), sk = "".  Parts are a CRDT map keyed
[part_number, timestamp] -> {"vid": part version uuid, "etag", "size"};
re-uploading a part adds a newer (part_number, timestamp) entry and the
completion step picks the newest per part number (stale part versions are
tombstoned by the cascade).
"""

from __future__ import annotations

from typing import Any

from ...table.schema import TableSchema
from ...utils.crdt import Bool, CrdtMap


class MultipartUpload:
    def __init__(
        self,
        upload_id: bytes,
        bucket_id: bytes,
        key: str,
        timestamp: int = 0,
        parts: CrdtMap | None = None,
        deleted: Bool | None = None,
        enc: dict | None = None,
        hdrs: list | None = None,
    ):
        self.upload_id = upload_id
        self.bucket_id = bucket_id
        self.key = key
        self.timestamp = timestamp
        self.parts = parts or CrdtMap()
        self.deleted = deleted or Bool(False)
        self.enc = enc  # SSE-C {"alg","md5"} fixed at CreateMultipartUpload
        # object metadata headers fixed at CreateMultipartUpload; stored
        # here (not only on the uploading marker version) because a
        # concurrent complete PutObject prunes older marker versions
        self.hdrs = hdrs

    def merge(self, other: "MultipartUpload") -> None:
        self.deleted.merge(other.deleted)
        if self.deleted.get():
            self.parts = CrdtMap()
        else:
            self.parts.merge(other.parts)
        self.timestamp = max(self.timestamp, other.timestamp) if self.timestamp else other.timestamp
        if self.enc is None:
            self.enc = other.enc
        if self.hdrs is None:
            self.hdrs = other.hdrs

    def latest_parts(self) -> dict[int, dict]:
        """part_number -> newest {"vid","etag","size"}."""
        out: dict[int, tuple[int, dict]] = {}
        for k, v in self.parts.items():
            pn, ts = int(k[0]), int(k[1])
            if pn not in out or ts > out[pn][0]:
                out[pn] = (ts, v)
        return {pn: v for pn, (_ts, v) in out.items()}

    def all_part_vids(self) -> list[bytes]:
        return [bytes(v["vid"]) for _k, v in self.parts.items()]

    def to_obj(self) -> Any:
        return [
            self.upload_id,
            self.bucket_id,
            self.key,
            self.timestamp,
            self.parts.to_obj(),
            self.deleted.to_obj(),
            self.enc,
            self.hdrs,
        ]


class MpuTable(TableSchema):
    table_name = "multipart_upload"

    def __init__(self, version_table=None):
        self.version_table = version_table

    def entry_partition_key(self, e: MultipartUpload) -> bytes:
        return e.upload_id

    def entry_sort_key(self, e: MultipartUpload) -> bytes:
        return b""

    def decode_entry(self, obj: Any) -> MultipartUpload:
        parts = CrdtMap.from_obj(obj[4])
        for _k, v in parts.items():
            if "vid" in v:
                v["vid"] = bytes(v["vid"])
        return MultipartUpload(
            bytes(obj[0]), bytes(obj[1]), obj[2], int(obj[3]), parts,
            Bool.from_obj(obj[5]), obj[6] if len(obj) > 6 else None,
            obj[7] if len(obj) > 7 else None,
        )

    def merge_entries(self, a, b):
        a.merge(b)
        return a

    def is_tombstone(self, e: MultipartUpload) -> bool:
        return e.deleted.get()

    def updated(self, tx, old, new) -> None:
        """When the upload is deleted/aborted, tombstone every part
        version (cascades to block refs)."""
        if self.version_table is None:
            return
        from .version_table import Version

        was = old is not None and not old.deleted.get()
        now = new is not None and not new.deleted.get()
        if was and not now:
            for vid in old.all_part_vids():
                self.version_table.queue_insert(
                    Version.deleted_marker(vid, old.bucket_id, old.key), tx=tx
                )