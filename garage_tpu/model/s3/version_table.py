"""Version table: block lists of object versions
(reference src/model/s3/version_table.rs).

pk = version uuid (placement by uuid hash — spreads a big object's
metadata independently of the object entry), sk = "".

blocks: CrdtMap keyed [part_number, offset] -> {"h": hash, "s": size};
deleted: Bool tombstone.  The `updated()` hook cascades deletion to the
block_ref table (which decrements rc transactionally).
"""

from __future__ import annotations

from typing import Any

from ...table.schema import TableSchema
from ...utils.crdt import Bool, CrdtMap


class Version:
    def __init__(
        self,
        uuid: bytes,
        bucket_id: bytes,
        key: str,
        blocks: CrdtMap | None = None,
        parts_etags: CrdtMap | None = None,
        deleted: Bool | None = None,
    ):
        self.uuid = uuid
        self.bucket_id = bucket_id
        self.key = key
        self.blocks = blocks or CrdtMap()  # [part, offset] -> {"h","s"}
        self.parts_etags = parts_etags or CrdtMap()  # part -> etag (mpu)
        self.deleted = deleted or Bool(False)

    @classmethod
    def deleted_marker(cls, uuid: bytes, bucket_id: bytes, key: str) -> "Version":
        return cls(uuid, bucket_id, key, deleted=Bool(True))

    def merge(self, other: "Version") -> None:
        self.deleted.merge(other.deleted)
        if self.deleted.get():
            self.blocks = CrdtMap()
            self.parts_etags = CrdtMap()
        else:
            self.blocks.merge(other.blocks)
            self.parts_etags.merge(other.parts_etags)

    def sorted_blocks(self) -> list[tuple[tuple[int, int], dict]]:
        """Blocks in (part, offset) order — the object's byte stream."""
        return [((int(k[0]), int(k[1])), v) for k, v in self.blocks.items()]

    def total_size(self) -> int:
        return sum(v["s"] for _k, v in self.sorted_blocks())

    def to_obj(self) -> Any:
        return [
            self.uuid,
            self.bucket_id,
            self.key,
            self.blocks.to_obj(),
            self.parts_etags.to_obj(),
            self.deleted.to_obj(),
        ]


class VersionRowCache:
    """Per-node LRU of COMPLETE versions' rows, keyed by version uuid
    (ISSUE 15 metadata fast path).  Safety argument: a GET only looks
    up vids its quorum-fresh OBJECT row declares complete-and-visible,
    and such a version's block list is immutable — every block entry
    was quorum-committed before the complete object row was written
    (api/s3/objects.py, api/s3/multipart.py), and the row can only be
    tombstoned after the version stops being visible (the prune
    cascade), at which point no fresh object row resolves it.  So a
    cache hit can never serve a block list that differs from what a
    quorum read would return for a visible vid.  Overwrites/deletes
    need no invalidation (the object row gates visibility); the only
    consumer-side fallback is the escalation path, which bypasses the
    cache by construction.  Entry-bounded, per node — NEVER a process
    singleton (in-process multi-node tests)."""

    def __init__(self, max_entries: int = 1024):
        from collections import OrderedDict

        self.max_entries = int(max_entries)
        self._d: "OrderedDict[bytes, Version]" = OrderedDict()
        self.hits = 0
        self.misses = 0

    def get(self, vid: bytes) -> "Version | None":
        if self.max_entries <= 0:
            return None
        v = self._d.get(bytes(vid))
        if v is None:
            self.misses += 1
            return None
        self._d.move_to_end(bytes(vid))
        self.hits += 1
        return v

    def put(self, vid: bytes, ver: "Version") -> None:
        if self.max_entries <= 0 or ver.deleted.get():
            return
        self._d[bytes(vid)] = ver
        self._d.move_to_end(bytes(vid))
        while len(self._d) > self.max_entries:
            self._d.popitem(last=False)


class VersionTable(TableSchema):
    table_name = "version"

    def __init__(self, block_ref_table=None):
        self.block_ref_table = block_ref_table

    def entry_partition_key(self, e: Version) -> bytes:
        return e.uuid

    def entry_sort_key(self, e: Version) -> bytes:
        return b""

    def decode_entry(self, obj: Any) -> Version:
        blocks = CrdtMap.from_obj(obj[3])
        for _k, v in blocks.items():
            v["h"] = bytes(v["h"])
        return Version(
            bytes(obj[0]),
            bytes(obj[1]),
            obj[2],
            blocks,
            CrdtMap.from_obj(obj[4]),
            Bool.from_obj(obj[5]),
        )

    def merge_entries(self, a: Version, b: Version) -> Version:
        a.merge(b)
        return a

    def is_tombstone(self, e: Version) -> bool:
        return e.deleted.get()

    def updated(self, tx, old: Version | None, new: Version | None) -> None:
        if self.block_ref_table is None:
            return
        from .block_ref_table import BlockRef

        was_deleted = old is None or old.deleted.get()
        now_deleted = new is None or new.deleted.get()
        if not was_deleted and now_deleted:
            # deletion cascade: tombstone every block reference
            for _k, blk in old.sorted_blocks():
                self.block_ref_table.queue_insert(
                    BlockRef(blk["h"], old.uuid, deleted=Bool(True)), tx=tx
                )