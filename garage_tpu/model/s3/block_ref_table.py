"""Block reference table (reference src/model/s3/block_ref_table.rs).

pk = block hash (so refs of a block live WITH the block's storage nodes),
sk = version uuid.  The `updated()` hook adjusts the block manager's
refcounts inside the same transaction, and queues a resync check when a
block becomes needed or unneeded — this is the pivot between the metadata
plane and the data plane.
"""

from __future__ import annotations

from typing import Any

from ...table.schema import TableSchema
from ...utils.crdt import Bool


class BlockRef:
    def __init__(self, block: bytes, version: bytes, deleted: Bool | None = None):
        self.block = block
        self.version = version
        self.deleted = deleted or Bool(False)

    def merge(self, other: "BlockRef") -> None:
        self.deleted.merge(other.deleted)

    def to_obj(self) -> Any:
        return [self.block, self.version, self.deleted.to_obj()]


class BlockRefTable(TableSchema):
    table_name = "block_ref"

    def __init__(self, block_manager=None):
        self.block_manager = block_manager

    def entry_partition_key(self, e: BlockRef) -> bytes:
        return e.block

    def entry_sort_key(self, e: BlockRef) -> bytes:
        return e.version

    def partition_hash(self, pk: bytes) -> bytes:
        # the partition key IS the block hash: placement must match the
        # block's own placement, so no re-hashing (reference block_ref
        # sharding is by block hash directly)
        return pk

    def decode_entry(self, obj: Any) -> BlockRef:
        return BlockRef(bytes(obj[0]), bytes(obj[1]), Bool.from_obj(obj[2]))

    def merge_entries(self, a: BlockRef, b: BlockRef) -> BlockRef:
        a.merge(b)
        return a

    def is_tombstone(self, e: BlockRef) -> bool:
        return e.deleted.get()

    def updated(self, tx, old: BlockRef | None, new: BlockRef | None) -> None:
        if self.block_manager is None:
            return
        was_ref = old is not None and not old.deleted.get()
        now_ref = new is not None and not new.deleted.get()
        block = (new or old).block
        if not was_ref and now_ref:
            if self.block_manager.rc.incr(tx, block):
                # 0 -> 1: we may need to fetch this block
                self.block_manager.resync.queue_block(block, tx=tx)
        if was_ref and not now_ref:
            if self.block_manager.rc.decr(tx, block):
                # rc hit 0: deletion marker set; check after the delay
                from ...block.rc import BLOCK_GC_DELAY_MS

                self.block_manager.resync.queue_block(
                    block, delay_ms=BLOCK_GC_DELAY_MS + 1000, tx=tx
                )