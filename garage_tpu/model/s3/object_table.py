"""Object table: the S3 namespace (reference src/model/s3/object_table.rs).

One entry per (bucket_id, key).  An entry holds a list of ObjectVersions,
each identified by (uuid, timestamp):

  state: "uploading" | "complete" | "aborted"
  data:  {"t": "inline", "meta": {...}, "bytes": ...}
       | {"t": "first_block", "meta": {...}, "vid": version_uuid}
       | {"t": "delete_marker"}
  meta:  {"size": int, "etag": str, "headers": [[name, value]...]}

CRDT merge (object_table.rs:26-93): union of versions by (uuid, ts) with
per-version state merge (aborted wins over anything; complete wins over
uploading), then prune: drop everything strictly older than the newest
"complete-or-delete-marker" version except still-uploading versions (the
in-flight multipart uploads).  The `updated()` hook marks versions that
disappeared (or aborted) as deleted in the version table, cascading to
block refs -> rc decrements.
"""

from __future__ import annotations

from typing import Any

from ...table.schema import TableSchema

STATE_ORDER = {"uploading": 0, "complete": 1, "aborted": 2}  # aborted is terminal


class ObjectVersion:
    __slots__ = ("uuid", "timestamp", "state", "data")

    def __init__(self, uuid: bytes, timestamp: int, state: str, data: dict):
        self.uuid = uuid
        self.timestamp = timestamp
        self.state = state
        self.data = data

    def cmp_key(self) -> tuple[int, bytes]:
        return (self.timestamp, self.uuid)

    def is_complete_or_dm(self) -> bool:
        return self.state == "complete"

    def is_data_block(self) -> bool:
        return self.state == "complete" and self.data.get("t") == "first_block"

    def to_obj(self) -> Any:
        return [self.uuid, self.timestamp, self.state, self.data]

    @classmethod
    def from_obj(cls, obj: Any) -> "ObjectVersion":
        data = dict(obj[3])
        if "bytes" in data:
            data["bytes"] = bytes(data["bytes"])
        if "vid" in data:
            data["vid"] = bytes(data["vid"])
        return cls(bytes(obj[0]), int(obj[1]), obj[2], data)


class Object:
    def __init__(self, bucket_id: bytes, key: str, versions: list[ObjectVersion]):
        self.bucket_id = bucket_id
        self.key = key
        self.versions = sorted(versions, key=lambda v: v.cmp_key())

    def merge(self, other: "Object") -> None:
        byid: dict[bytes, ObjectVersion] = {v.uuid: v for v in self.versions}
        for v in other.versions:
            cur = byid.get(v.uuid)
            if cur is None:
                byid[v.uuid] = v
            elif STATE_ORDER[v.state] > STATE_ORDER[cur.state]:
                byid[v.uuid] = v
        versions = sorted(byid.values(), key=lambda v: v.cmp_key())
        # prune (object_table.rs:513-526): drop everything strictly older
        # than the newest complete version; keep the rest — INCLUDING
        # aborted versions, which persist as terminal CRDT tombstones so a
        # replica that missed the abort converges instead of resurrecting
        # the upload via anti-entropy (the cascade handles data cleanup).
        last_complete_idx = None
        for i, v in enumerate(versions):
            if v.is_complete_or_dm():
                last_complete_idx = i
        if last_complete_idx is not None:
            versions = versions[last_complete_idx:]
        self.versions = versions

    def last_complete(self) -> ObjectVersion | None:
        last = None
        for v in self.versions:
            if v.state == "complete":
                last = v
        return last

    def last_visible(self) -> ObjectVersion | None:
        """Newest complete version that is not a delete marker."""
        v = self.last_complete()
        if v is None or v.data.get("t") == "delete_marker":
            return None
        return v

    def to_obj(self) -> Any:
        return [self.bucket_id, self.key, [v.to_obj() for v in self.versions]]


def next_timestamp(existing: "Object | None") -> int:
    """Version timestamp for a new write: strictly after every version the
    key already has, even if a clock-skewed node wrote one in the future
    (reference put.rs:698 next_timestamp — without this, a delete issued
    after a future-dated write would lose the LWW race and the object
    would be undeletable until wall clocks catch up).  Shared by the API
    write paths, the lifecycle worker, and block purge."""
    from ...utils.time_util import now_msec

    ts = now_msec()
    if existing is not None and existing.versions:
        ts = max(ts, max(v.timestamp for v in existing.versions) + 1)
    return ts


def object_counts(e: "Object | None") -> dict[str, int]:
    """Counter deltas source (reference object_table.rs counts())."""
    if e is None:
        return {"objects": 0, "bytes": 0, "unfinished_uploads": 0}
    vis = e.last_visible()
    return {
        "objects": 1 if vis is not None else 0,
        "bytes": vis.data.get("meta", {}).get("size", 0) if vis else 0,
        "unfinished_uploads": sum(1 for v in e.versions if v.state == "uploading"),
    }


class ObjectTable(TableSchema):
    table_name = "object"

    def __init__(self, version_table=None, counter=None):
        self.version_table = version_table  # set by Garage after wiring
        self.counter = counter  # IndexCounter for per-bucket usage

    def entry_partition_key(self, e: Object) -> bytes:
        return e.bucket_id

    def entry_sort_key(self, e: Object) -> bytes:
        return e.key.encode()

    def decode_entry(self, obj: Any) -> Object:
        return Object(
            bytes(obj[0]), obj[1], [ObjectVersion.from_obj(v) for v in obj[2]]
        )

    def merge_entries(self, a: Object, b: Object) -> Object:
        a.merge(b)
        return a

    def is_tombstone(self, e: Object) -> bool:
        # an object whose only content is a delete marker is a tombstone
        return len(e.versions) == 1 and e.versions[0].data.get("t") == "delete_marker"

    def matches_filter(self, e: Object, filt) -> bool:
        if filt == "visible":
            return e.last_visible() is not None
        return True

    def updated(self, tx, old: Object | None, new: Object | None) -> None:
        """Cascade: versions that disappeared (pruned/aborted) get their
        data deleted via the version table (reference updated() hook)."""
        if self.counter is not None:
            oldc = object_counts(old)
            newc = object_counts(new)
            deltas = {k: newc[k] - oldc[k] for k in newc}
            pk = (new or old).bucket_id
            self.counter.count(tx, pk, b"", deltas)
        if self.version_table is None:
            return
        from .version_table import Version

        # a version's data is deleted when it disappeared from the merged
        # list OR it newly transitioned to aborted (object_table.rs:571-600)
        new_by_id = {v.uuid: v for v in new.versions} if new is not None else {}
        for v in old.versions if old is not None else []:
            nv = new_by_id.get(v.uuid)
            delete_version = (
                nv is None or (nv.state == "aborted" and v.state != "aborted")
            )
            if delete_version and v.data.get("t") != "delete_marker":
                # enqueue deletion (async local insert; the queue worker
                # fans it out with quorum)
                self.version_table.queue_insert(
                    Version.deleted_marker(v.uuid, old.bucket_id, old.key), tx=tx
                )