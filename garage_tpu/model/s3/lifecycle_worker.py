"""Lifecycle worker (reference src/model/s3/lifecycle_worker.rs).

Once per day (and on restart, resumable via a persisted cursor) walk the
LOCAL object table and apply each bucket's lifecycle rules:

  Expiration (Days | Date)             -> insert a delete marker
  AbortIncompleteMultipartUpload(Days) -> abort old in-flight uploads

Only the partitions this node stores are scanned; every storage node runs
the same pass, and the resulting delete markers converge by CRDT.
"""

from __future__ import annotations

import asyncio
import logging
from datetime import datetime, timezone
from typing import Any

from ...utils.background import Worker, WorkerState
from ...utils.data import gen_uuid
from ...utils.migrate import Migratable
from ...utils.persister import Persister
from ...utils.time_util import now_msec
from .object_table import Object, ObjectVersion

logger = logging.getLogger("garage.lifecycle")

BATCH = 64


class LifecycleState(Migratable):
    VERSION_MARKER = b"GT0lifecycle"

    def __init__(self, last_completed: str = "", cursor: bytes = b""):
        self.last_completed = last_completed  # YYYY-MM-DD of last full pass
        self.cursor = cursor

    def to_obj(self) -> Any:
        return [self.last_completed, self.cursor]

    @classmethod
    def from_obj(cls, obj: Any) -> "LifecycleState":
        return cls(obj[0], bytes(obj[1]))


def _today(use_local_tz: bool = False) -> str:
    """Current date for day-boundary decisions; `use_local_tz` shifts the
    boundary to local midnight (reference config.rs use_local_tz ->
    lifecycle_worker.rs:73,208-222 today()/midnight_ts)."""
    now = datetime.now().astimezone() if use_local_tz else datetime.now(timezone.utc)
    return now.strftime("%Y-%m-%d")


class LifecycleWorker(Worker):
    def __init__(self, garage, metadata_dir: str | None = None):
        self.garage = garage
        self.persister = (
            Persister(metadata_dir, "lifecycle_state", LifecycleState)
            if metadata_dir
            else None
        )
        self.state = (self.persister.load() if self.persister else None) or LifecycleState()
        self._bucket_cache: dict[bytes, list | None] = {}

    def name(self) -> str:
        return "lifecycle"

    def status(self):
        return {"last_completed": self.state.last_completed}

    async def work(self):
        use_local = self.garage.config.use_local_tz
        if self.state.last_completed == _today(use_local):
            return WorkerState.IDLE
        data = self.garage.object_table.data
        n = 0
        for key, value in data.store.iter_range(start=self.state.cursor):
            obj = data.decode(value)
            try:
                await self._apply(obj)
            except Exception as e:  # noqa: BLE001
                logger.warning("lifecycle apply failed for %s: %r", obj.key, e)
            self.state.cursor = key + b"\x00"
            n += 1
            if n >= BATCH:
                await self._save_async()
                return WorkerState.BUSY
        # pass complete
        self.state.last_completed = _today(use_local)
        self.state.cursor = b""
        self._bucket_cache.clear()
        await self._save_async()
        return WorkerState.IDLE

    async def wait_for_work(self) -> None:
        await asyncio.sleep(60.0)

    async def _rules_of(self, bucket_id: bytes):
        if bucket_id not in self._bucket_cache:
            try:
                b = await self.garage.helper.get_bucket(bucket_id)
                self._bucket_cache[bucket_id] = b.params().lifecycle.get()
            except Exception as e:  # noqa: BLE001
                logger.warning(
                    "lifecycle: cannot read bucket %s config, skipping: %r",
                    bucket_id.hex()[:16], e,
                )
                self._bucket_cache[bucket_id] = None
        return self._bucket_cache[bucket_id]

    async def _apply(self, obj: Object) -> None:
        rules = await self._rules_of(obj.bucket_id)
        if not rules:
            return
        now = now_msec()
        for rule in rules:
            if not rule.get("enabled", True):
                continue
            if rule.get("prefix") and not obj.key.startswith(rule["prefix"]):
                continue
            vis = obj.last_visible()
            if vis is not None:
                expired = False
                if rule.get("expiration_days") is not None:
                    age_days = (now - vis.timestamp) / 86_400_000
                    expired = age_days >= rule["expiration_days"]
                if rule.get("expiration_date"):
                    try:
                        # the rule date is a day boundary: local midnight
                        # when use_local_tz, else UTC midnight (reference
                        # lifecycle_worker.rs:389 midnight_ts)
                        tz = (
                            datetime.now().astimezone().tzinfo
                            if self.garage.config.use_local_tz
                            else timezone.utc
                        )
                        d = datetime.strptime(
                            rule["expiration_date"][:10], "%Y-%m-%d"
                        ).replace(tzinfo=tz)
                        expired = expired or now >= d.timestamp() * 1000
                    except ValueError:
                        pass
                if expired:
                    # strictly past every existing version, like the API
                    # delete path — a skew-dated version must not outrank
                    # its own expiration
                    from .object_table import next_timestamp

                    dm = ObjectVersion(
                        gen_uuid(), next_timestamp(obj), "complete",
                        {"t": "delete_marker"},
                    )
                    await self.garage.object_table.insert(
                        Object(obj.bucket_id, obj.key, [dm])
                    )
                    logger.info("lifecycle: expired %s", obj.key)
                    return
            if rule.get("abort_mpu_days") is not None:
                for v in obj.versions:
                    if v.state == "uploading":
                        age_days = (now - v.timestamp) / 86_400_000
                        if age_days >= rule["abort_mpu_days"]:
                            from .mpu_table import MultipartUpload

                            closed = MultipartUpload(
                                v.uuid, obj.bucket_id, obj.key, timestamp=v.timestamp
                            )
                            closed.deleted.set()
                            await self.garage.mpu_table.insert(closed)
                            aborted = ObjectVersion(
                                v.uuid, v.timestamp, "aborted", dict(v.data)
                            )
                            await self.garage.object_table.insert(
                                Object(obj.bucket_id, obj.key, [aborted])
                            )
                            logger.info("lifecycle: aborted stale mpu on %s", obj.key)

    async def _save_async(self):
        # work()-path checkpoints fsync off the event loop (loop-blocker)
        if self.persister:
            await self.persister.save_in_thread(self.state)
