"""Access-key table (full-copy; reference src/model/key_table.rs).

Key id format `GK` + hex (like the reference); the secret is a 64-hex
string.  Permissions live on the key side: authorized_buckets maps
bucket_id -> BucketKeyPerm.
"""

from __future__ import annotations

import os
from typing import Any

from ..table.schema import TableSchema
from ..utils.crdt import Crdt, Deletable, Lww, LwwMap
from .permission import BucketKeyPerm


class KeyParams(Crdt):
    def __init__(
        self,
        secret_key: str,
        name: Lww | None = None,
        allow_create_bucket: Lww | None = None,
        authorized_buckets: LwwMap | None = None,  # bucket_id -> perm obj
        local_aliases: LwwMap | None = None,  # name -> bucket_id | None
    ):
        self.secret_key = secret_key
        self.name = name or Lww.raw(0, "")
        self.allow_create_bucket = allow_create_bucket or Lww.raw(0, False)
        self.authorized_buckets = authorized_buckets or LwwMap()
        self.local_aliases = local_aliases or LwwMap()

    def merge(self, other: "KeyParams") -> None:
        self.name.merge(other.name)
        self.allow_create_bucket.merge(other.allow_create_bucket)
        self.authorized_buckets.merge(other.authorized_buckets)
        self.local_aliases.merge(other.local_aliases)

    def to_obj(self) -> Any:
        return {
            "sk": self.secret_key,
            "n": self.name.to_obj(),
            "acb": self.allow_create_bucket.to_obj(),
            "ab": self.authorized_buckets.to_obj(),
            "la": self.local_aliases.to_obj(),
        }

    @classmethod
    def from_obj(cls, obj: Any) -> "KeyParams":
        return cls(
            secret_key=obj["sk"],
            name=Lww.from_obj(obj["n"]),
            allow_create_bucket=Lww.from_obj(obj["acb"]),
            authorized_buckets=LwwMap.from_obj(obj["ab"]),
            local_aliases=LwwMap.from_obj(obj["la"]),
        )


class Key:
    def __init__(self, key_id: str, state: Deletable):
        self.key_id = key_id
        self.state = state  # Deletable[KeyParams]

    @classmethod
    def new(cls, name: str = "") -> "Key":
        key_id = "GK" + os.urandom(12).hex()
        secret = os.urandom(32).hex()
        params = KeyParams(secret)
        params.name.update(name)
        return cls(key_id, Deletable.present(params))

    def is_deleted(self) -> bool:
        return self.state.is_deleted()

    def params(self) -> KeyParams | None:
        return self.state.get()

    def secret(self) -> str | None:
        p = self.params()
        return p.secret_key if p else None

    def bucket_permissions(self, bucket_id: bytes) -> BucketKeyPerm:
        p = self.params()
        if p is None:
            return BucketKeyPerm.NO_PERMISSIONS
        perm = p.authorized_buckets.get(bucket_id)
        return BucketKeyPerm.from_obj(perm) if perm else BucketKeyPerm.NO_PERMISSIONS

    def merge(self, other: "Key") -> None:
        self.state.merge(other.state)

    def to_obj(self) -> Any:
        return [self.key_id, self.state.to_obj()]


class KeyTable(TableSchema):
    table_name = "key"

    def entry_partition_key(self, e: Key) -> bytes:
        return e.key_id.encode()

    def entry_sort_key(self, e: Key) -> bytes:
        return b""

    def decode_entry(self, obj: Any) -> Key:
        return Key(obj[0], Deletable.from_obj(obj[1], KeyParams.from_obj))

    def is_tombstone(self, e: Key) -> bool:
        return e.is_deleted()
