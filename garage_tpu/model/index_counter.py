"""Distributed index counters (reference src/model/index_counter.rs).

Every node that stores a table partition maintains, transactionally with
each entry write, a LOCAL count of items/bytes under each (pk, sk) counter
key.  It then publishes its count into a replicated counter table whose
entries map node -> (ts, value), merged per-node by newest timestamp.  The
aggregate value of a counter is the MAX over current layout nodes: every
replica counts the same logical set, so the freshest replica's number is
the truth — no cross-node transactions needed.

Used for per-bucket objects / bytes / unfinished-upload counts (quota
enforcement + bucket info).
"""

from __future__ import annotations

from typing import Any

from ..table.schema import TableSchema
from ..utils.serde import pack, unpack
from ..utils.time_util import now_msec


class CounterEntry:
    def __init__(self, pk: bytes, sk: bytes, values: dict[str, dict[bytes, list]]):
        self.pk = pk
        self.sk = sk
        # values[name][node] = [ts, value]
        self.values = values

    def merge(self, other: "CounterEntry") -> None:
        for name, nodes in other.values.items():
            mine = self.values.setdefault(name, {})
            for node, (ts, v) in nodes.items():
                if node not in mine or ts > mine[node][0]:
                    mine[node] = [ts, v]

    def aggregate(self, layout_nodes: list[bytes]) -> dict[str, int]:
        out = {}
        for name, nodes in self.values.items():
            vals = [v for n, (_ts, v) in nodes.items() if n in layout_nodes]
            if not vals:
                vals = [v for _n, (_ts, v) in nodes.items()]
            if vals:
                out[name] = max(vals)
        return out

    def to_obj(self) -> Any:
        return [
            self.pk,
            self.sk,
            {
                name: [[n, ts, v] for n, (ts, v) in nodes.items()]
                for name, nodes in self.values.items()
            },
        ]


class CounterTable(TableSchema):
    def __init__(self, table_name: str):
        self.table_name = table_name

    def entry_partition_key(self, e: CounterEntry) -> bytes:
        return e.pk

    def entry_sort_key(self, e: CounterEntry) -> bytes:
        return e.sk

    def decode_entry(self, obj: Any) -> CounterEntry:
        return CounterEntry(
            bytes(obj[0]),
            bytes(obj[1]),
            {
                name: {bytes(n): [int(ts), int(v)] for n, ts, v in rows}
                for name, rows in obj[2].items()
            },
        )


class IndexCounter:
    """One instance per counted table (reference IndexCounter<T>)."""

    def __init__(self, system, counter_table, db):
        self.system = system
        self.table = counter_table  # Table[CounterTable]
        self.local = db.open_tree(f"{counter_table.schema.table_name}:local")

    def count(self, tx, pk: bytes, sk: bytes, deltas: dict[str, int]) -> None:
        """Apply counter deltas transactionally; called from a table's
        updated() hook."""
        if not any(deltas.values()):
            return
        key = pk + b"\x00" + sk
        raw = tx.get(self.local, key)
        values: dict[str, list] = unpack(raw) if raw else {}
        now = now_msec()
        for name, d in deltas.items():
            ts, v = values.get(name, [0, 0])
            values[name] = [max(ts + 1, now), v + d]
        tx.insert(self.local, key, pack(values))
        entry = CounterEntry(
            pk, sk,
            {
                name: {self.system.id: [ts, v]}
                for name, (ts, v) in values.items()
            },
        )
        self.table.queue_insert(entry, tx=tx)

    async def get_values(self, pk: bytes, sk: bytes = b"") -> dict[str, int]:
        entry = await self.table.get(pk, sk)
        if entry is None:
            return {}
        nodes = self.system.layout_manager.history.current().storage_nodes()
        return entry.aggregate(nodes)