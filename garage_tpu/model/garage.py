"""Garage: the composition root wiring every subsystem
(reference src/model/garage.rs:95-320).

Boot order: config -> db -> netapp -> layout manager -> system -> block
manager -> tables (with their reactive cross-links) -> background workers.
"""

from __future__ import annotations

import asyncio
import logging
import os

from ..block.codec import get_codec
from ..block.manager import BlockManager
from ..db import open_db
from ..net.handshake import gen_node_key, node_id_of
from ..net.netapp import NetApp
from ..rpc.layout.manager import LayoutManager, PersistedLayout
from ..rpc.replication_mode import ReplicationMode
from ..rpc.rpc_helper import RpcHelper
from ..rpc.system import PersistedPeers, System
from ..table.replication import (
    TableFullReplication,
    TableMetaReplication,
    TableStripeSyncedReplication,
)
from ..table.table import Table
from ..utils.background import BackgroundRunner
from ..utils.config import Config
from ..utils.persister import Persister
from .bucket_alias_table import BucketAliasTable
from .bucket_table import BucketTable
from .key_table import KeyTable
from .s3.block_ref_table import BlockRefTable
from .s3.object_table import ObjectTable
from .s3.version_table import VersionTable

logger = logging.getLogger("garage")


def network_key_from_secret(secret: str) -> bytes:
    """rpc_secret (hex) -> the 32-byte cluster network key."""
    return bytes.fromhex(secret.ljust(64, "0"))[:32]


def _parse_addr(s: str) -> tuple[str, int]:
    host, _, port = s.rpartition(":")
    return (host.strip("[]") or "0.0.0.0", int(port))


def _public_addr_from_subnet(subnet: str, port: int) -> tuple[str, int] | None:
    """First local interface address inside `subnet` (CIDR), with the RPC
    bind port — reference system.rs:885-935 get_rpc_public_addr /
    get_default_ip filtered by rpc_public_addr_subnet."""
    import ipaddress
    import socket

    net = ipaddress.ip_network(subnet, strict=False)
    candidates: list[str] = []
    # the default-route address (UDP connect performs no I/O) ...
    probe = "8.8.8.8" if net.version == 4 else "2001:4860:4860::8888"
    fam = socket.AF_INET if net.version == 4 else socket.AF_INET6
    try:
        s = socket.socket(fam, socket.SOCK_DGRAM)
        try:
            s.connect((probe, 9))
            candidates.append(s.getsockname()[0])
        finally:
            s.close()
    except OSError:
        pass
    # ... plus everything the hostname resolves to
    try:
        for info in socket.getaddrinfo(socket.gethostname(), None, fam):
            candidates.append(info[4][0])
    except OSError:
        pass
    for ip in candidates:
        try:
            if ipaddress.ip_address(ip) in net:
                return (ip, port)
        except ValueError:
            continue
    logger.warning(
        "rpc_public_addr_subnet %s matches no local address (candidates: %s)",
        subnet, candidates,
    )
    return None


def _parse_bootstrap(entries: list[str]) -> list[tuple[bytes, tuple[str, int]]]:
    """'hexid@host:port' entries (reference: node id @ address)."""
    out = []
    for e in entries:
        nid, _, addr = e.partition("@")
        out.append((bytes.fromhex(nid), _parse_addr(addr)))
    return out


class Garage:
    def __init__(self, config: Config):
        self.config = config
        meta = config.metadata_dir
        os.makedirs(meta, exist_ok=True)

        # node identity persists across restarts
        keyfile = os.path.join(meta, "node_key")
        if os.path.exists(keyfile):
            with open(keyfile, "rb") as f:
                node_key = f.read()
        else:
            node_key = gen_node_key()
            with open(keyfile, "wb") as f:
                f.write(node_key)
            os.chmod(keyfile, 0o600)
        self.node_id = node_id_of(node_key)

        if not config.rpc_secret:
            raise ValueError("rpc_secret is required")
        network_key = network_key_from_secret(config.rpc_secret)

        self.db = open_db(
            os.path.join(meta, "db"),
            engine=config.db_engine,
            fsync=config.metadata_fsync,
        )
        self.netapp = NetApp(network_key, node_key)

        self.replication_mode = ReplicationMode(
            config.replication_factor, config.consistency_mode
        )
        # the SECOND quorum tuple (ISSUE 15): metadata tables replicate
        # at their own factor — O(1) in EC stripe width — on the meta
        # ring (table/replication.py TableMetaReplication).  Effective
        # factor is min(meta rf, layout rf); config load validated an
        # explicit meta rf against the cluster's minimum size.
        self.meta_replication_mode = ReplicationMode(
            min(config.meta.replication_factor, config.replication_factor),
            config.consistency_mode,
        )
        self.layout_manager = LayoutManager(
            self.node_id,
            config.replication_factor,
            persister=Persister(meta, "cluster_layout", PersistedLayout),
        )
        public_addr = (
            _parse_addr(config.rpc_public_addr) if config.rpc_public_addr else None
        )
        if public_addr is None and config.rpc_public_addr_subnet:
            public_addr = _public_addr_from_subnet(
                config.rpc_public_addr_subnet,
                _parse_addr(config.rpc_bind_addr)[1],
            )
        from ..rpc.discovery import discovery_from_config

        self.system = System(
            self.netapp,
            self.layout_manager,
            self.replication_mode,
            bootstrap=_parse_bootstrap(config.bootstrap_peers),
            peer_persister=Persister(meta, "peer_list", PersistedPeers),
            metadata_dir=meta,
            data_dirs=[d.path for d in config.data_dir],
            public_addr=public_addr,
            discovery=discovery_from_config(config),
        )
        # one PeerHealth instance shared by the RPC helper (call outcomes,
        # breaker gating) and the peering layer (ping outcomes): pings are
        # the background probe that detects a sick peer healing
        from ..rpc.peer_health import PeerHealth

        self.peer_health = PeerHealth(self.node_id)
        self.helper_rpc = RpcHelper(
            self.node_id, self.system.peering,
            default_timeout=config.rpc_timeout_msec / 1000.0,
            health=self.peer_health,
        )
        self.system.peering.health = self.peer_health

        def _zone_of(nid: bytes) -> str | None:
            for v in reversed(self.layout_manager.history.versions):
                role = v.roles.get(nid)
                if role is not None:
                    return role.zone
            return None

        self.helper_rpc.zone_of = _zone_of
        if config.rpc_ping_timeout_msec:
            # reference system.rs:269 set_ping_timeout_millis
            self.system.peering.ping_timeout = config.rpc_ping_timeout_msec / 1000.0

        codec = get_codec(
            config.ec_params(),
            tpu_enable=config.tpu.enable,
            platform=config.tpu.platform,
        )
        self.block_manager = BlockManager(
            self.system,
            self.helper_rpc,
            self.db,
            config.data_dir,
            meta,
            compression_level=config.compression_level,
            codec=codec,
            data_fsync=config.data_fsync,
            ram_buffer_max=config.block_ram_buffer_max,
            disable_scrub=config.disable_scrub,
            block_config=config.block,
        )

        # tables, wired with their reactive cross-links.  Sharded model
        # tables place entries on the META ring (first meta_rf distinct
        # nodes of the partition's node list) — block placement alone
        # spans the full stripe.
        sharded = TableMetaReplication(self.system, self.meta_replication_mode)
        # block_ref: same meta-ring quorums, but anti-entropy spans the
        # full stripe — its updated() hook feeds every piece holder's rc
        # tree (resync/scrub/GC/durability all walk it locally)
        ref_sharded = TableStripeSyncedReplication(
            self.system, self.meta_replication_mode
        )
        fullcopy = TableFullReplication(self.system)

        self.block_ref_schema = BlockRefTable(self.block_manager)
        self.block_ref_table = Table(
            self.system, self.helper_rpc, self.db, self.block_ref_schema,
            ref_sharded,
        )
        self.version_schema = VersionTable(self.block_ref_table)
        self.version_table = Table(
            self.system, self.helper_rpc, self.db, self.version_schema, sharded
        )
        # metadata fast path (ISSUE 15): per-node cache of complete
        # versions' rows — repeat GETs skip the version quorum read
        from .s3.version_table import VersionRowCache

        self.version_cache = VersionRowCache(config.meta.version_cache_entries)
        self.object_schema = ObjectTable(self.version_table)
        self.object_table = Table(
            self.system, self.helper_rpc, self.db, self.object_schema, sharded
        )
        from .s3.mpu_table import MpuTable

        self.mpu_table = Table(
            self.system, self.helper_rpc, self.db, MpuTable(self.version_table), sharded
        )
        from .index_counter import CounterTable, IndexCounter

        self.object_counter_table = Table(
            self.system, self.helper_rpc, self.db,
            CounterTable("bucket_object_counter"), sharded,
        )
        self.object_counter = IndexCounter(
            self.system, self.object_counter_table, self.db
        )
        self.object_schema.counter = self.object_counter
        from .index_counter import CounterTable as _CT, IndexCounter as _IC
        from .k2v.item_table import K2VItemTable

        self.k2v_counter_table = Table(
            self.system, self.helper_rpc, self.db, _CT("k2v_index_counter"), sharded
        )
        self.k2v_counter = _IC(self.system, self.k2v_counter_table, self.db)
        self.k2v_item_schema = K2VItemTable(counter=self.k2v_counter)
        self.k2v_item_table = Table(
            self.system, self.helper_rpc, self.db, self.k2v_item_schema, sharded
        )
        self.bucket_table = Table(
            self.system, self.helper_rpc, self.db, BucketTable(), fullcopy
        )
        self.bucket_alias_table = Table(
            self.system, self.helper_rpc, self.db, BucketAliasTable(), fullcopy
        )
        self.key_table = Table(
            self.system, self.helper_rpc, self.db, KeyTable(), fullcopy
        )
        self.tables = [
            self.k2v_counter_table,
            self.k2v_item_table,
            self.object_counter_table,
            self.object_table,
            self.version_table,
            self.block_ref_table,
            self.mpu_table,
            self.bucket_table,
            self.bucket_alias_table,
            self.key_table,
        ]
        # coalesced table write path ([meta] coalesce_*): the sharded
        # (meta-ring) tables are the hot commit path — object/version/
        # blockref rows from concurrent requests share RPCs
        if config.meta.coalesce_enabled:
            for t in self.tables:
                if isinstance(t.replication, TableMetaReplication):
                    t.enable_coalescing(
                        linger_msec=config.meta.coalesce_linger_msec,
                        max_entries=config.meta.coalesce_max_entries,
                    )

        from .helper import GarageHelper
        from .k2v.rpc import K2VRpcHandler

        self.helper = GarageHelper(self)
        self.k2v_rpc = K2VRpcHandler(self)

        # runtime-tunable variables (reference util/background/vars.rs,
        # `garage worker get/set`)
        from ..utils.background import BgVars

        self.bg_vars = BgVars()
        resync = self.block_manager.resync
        self.bg_vars.register_rw(
            "resync-tranquility",
            lambda: str(resync.tranquility),
            lambda v: setattr(resync, "tranquility", max(0, int(v))),
        )
        self.bg_vars.register_rw(
            "resync-worker-count",
            lambda: str(resync.n_workers),
            lambda v: setattr(resync, "n_workers", max(1, min(8, int(v)))),
        )

        # codec batcher ([block] knobs): live-tuned on the running
        # batcher — the flusher reads them on every flush cycle
        def _batcher():
            b = self.block_manager.batcher
            if b is None:
                raise ValueError("codec batcher not active (replica codec?)")
            return b

        self.bg_vars.register_rw(
            "codec-batch-linger-msec",
            lambda: str(_batcher().linger_msec),
            lambda v: setattr(_batcher(), "linger_msec", max(0.0, float(v))),
        )
        self.bg_vars.register_rw(
            "codec-batch-max-blocks",
            lambda: str(_batcher().max_blocks),
            lambda v: setattr(_batcher(), "max_blocks", max(1, int(v))),
        )

        # read path (ISSUE 13): hot-block cache budget resizes live
        # (shrinking evicts immediately); the hedge-delay floor applies
        # to the next read (the manager reads block_config per GET)
        self.bg_vars.register_rw(
            "read-cache-bytes",
            lambda: str(self.block_manager.read_cache.max_bytes),
            lambda v: self.block_manager.read_cache.set_max_bytes(int(v)),
        )
        self.bg_vars.register_rw(
            "read-hedge-min-msec",
            lambda: str(self.block_manager.block_config.read_hedge_min_msec),
            lambda v: setattr(
                self.block_manager.block_config,
                "read_hedge_min_msec",
                max(0.0, float(v)),
            ),
        )

        def _scrub_worker():
            sw = getattr(self.block_manager, "scrub_worker", None)
            if sw is None:
                raise ValueError("scrub worker not running")
            return sw

        self.bg_vars.register_rw(
            "scrub-tranquility",
            lambda: str(_scrub_worker().state.tranquility),
            lambda v: _scrub_worker().cmd_set_tranquility(int(v)),
        )

        def _set_sync_interval(v: str) -> None:
            secs = float(v)
            if secs <= 0:
                raise ValueError("sync-interval-secs must be > 0")
            for t in self.tables:
                t.syncer.anti_entropy_interval = secs

        self.bg_vars.register_rw(
            "sync-interval-secs",
            lambda: str(self.tables[0].syncer.anti_entropy_interval),
            _set_sync_interval,
        )

        # table insert coalescer ([meta] knobs): live-tuned on every
        # enabled table — the flusher reads them each flush cycle
        def _coalescers():
            cs = [t.coalescer for t in self.tables if t.coalescer is not None]
            if not cs:
                raise ValueError("insert coalescing not enabled ([meta])")
            return cs

        def _set_coalesce_linger(v: str) -> None:
            msec = float(v)
            if msec < 0:
                raise ValueError("meta-coalesce-linger-msec must be >= 0")
            for c in _coalescers():
                c.linger_msec = msec

        def _set_coalesce_max(v: str) -> None:
            n = int(v)
            if n < 1:
                raise ValueError("meta-coalesce-max-entries must be >= 1")
            for c in _coalescers():
                c.max_entries = n

        self.bg_vars.register_rw(
            "meta-coalesce-linger-msec",
            lambda: str(_coalescers()[0].linger_msec),
            _set_coalesce_linger,
        )
        self.bg_vars.register_rw(
            "meta-coalesce-max-entries",
            lambda: str(_coalescers()[0].max_entries),
            _set_coalesce_max,
        )

        # repair plane (block/repair_plan.py): knob object shared with a
        # running planner so `worker set` changes apply on the next round
        from ..block.repair_plan import PlanParams

        self.repair_params = PlanParams(
            tranquility=config.repair.tranquility,
            bytes_in_flight=config.repair.bytes_in_flight,
            batch_blocks=config.repair.batch_blocks,
        )
        self.repair_planner = None
        self.bg_vars.register_rw(
            "repair-tranquility",
            lambda: str(self.repair_params.tranquility),
            lambda v: setattr(
                self.repair_params, "tranquility", max(0, int(v))
            ),
        )
        self.bg_vars.register_rw(
            "repair-bytes-in-flight",
            lambda: str(self.repair_params.bytes_in_flight),
            lambda v: setattr(
                self.repair_params, "bytes_in_flight", max(1, int(v))
            ),
        )
        # durability observatory (block/durability.py): always
        # constructed — the telemetry digest and the admin endpoint read
        # it — spawned as a worker only when [durability] enabled
        from ..block.durability import DurabilityScanner, ScanParams

        self.durability_scanner = DurabilityScanner(
            self.block_manager,
            params=ScanParams(
                tranquility=config.durability.tranquility,
                scan_batch=config.durability.scan_batch,
                interval_secs=config.durability.interval_secs,
                stuck_error_secs=config.durability.stuck_error_secs,
            ),
            planner_fn=lambda: self.repair_planner,
        )
        self.bg_vars.register_rw(
            "durability-tranquility",
            lambda: str(self.durability_scanner.params.tranquility),
            lambda v: setattr(
                self.durability_scanner.params, "tranquility", max(0, int(v))
            ),
        )
        self.bg_vars.register_rw(
            "durability-interval-secs",
            lambda: str(self.durability_scanner.params.interval_secs),
            lambda v: setattr(
                self.durability_scanner.params,
                "interval_secs",
                max(0.05, float(v)),
            ),
        )
        # overload-control plane (api/overload.py + rpc/shedding.py):
        # the admission controller exists from construction (the S3
        # server reads it per request); the shedding controller spawns
        # with the other workers
        from ..api.overload import AdmissionController

        self.overload = AdmissionController(config.overload)
        self.shedder = None
        self.bg_vars.register_rw(
            "overload-max-in-flight",
            lambda: str(self.config.overload.max_in_flight),
            lambda v: setattr(
                self.config.overload, "max_in_flight", max(1, int(v))
            ),
        )
        self.bg = BackgroundRunner()
        # flight recorder plane (utils/flight.py), wired in start()
        self.flight_recorder = None
        self.watchdog = None
        # stall auto-capture (utils/profiler.py), opt-in via [admin] stall_profile
        self.stall_profiler = None
        # latency X-ray + canary prober (utils/latency.py, api/s3/canary.py)
        self._latency_enabled = False
        # traffic observatory (rpc/traffic.py), enabled in start()
        self._traffic_enabled = False
        # tenant observatory (rpc/tenant.py), enabled in start()
        self._tenant_enabled = False
        self.canary = None

        # cluster telemetry plane (rpc/telemetry_digest.py): local digest
        # collection piggybacked on the status gossip + S3 SLO budgets
        from ..rpc.telemetry_digest import DigestCollector, SloTracker

        self.telemetry = DigestCollector(self)
        self.system.telemetry_collector = self.telemetry.collect
        # rebalance observatory (rpc/transition.py): layout-transition
        # flight deck + federated event timeline.  The events collector
        # reads flight_recorder at call time — it is wired in start().
        from ..rpc.transition import TransitionTracker, local_events

        self.transition_tracker = TransitionTracker(self)
        self.system.transition_tracker = self.transition_tracker
        self.system.events_collector = lambda since, min_severity: (
            local_events(self.flight_recorder, since, min_severity)
        )
        self.slo_tracker = SloTracker(
            availability_target=config.admin.slo_availability_target,
            latency_target_msec=config.admin.slo_latency_p99_target_msec,
            window_secs=config.admin.slo_window_secs,
        )
        self._started = False

    def ec_layout_warning(self, lv) -> str | None:
        """EC(k,m) places k+m distinct pieces per block, so every active
        layout version needs >= k+m storage nodes; an applied version
        below that makes EC PUTs error until a wider layout lands (reads
        and repair of existing blocks keep working — any k surviving
        pieces decode).  Returns an operator warning string, or None.
        See doc/ec-placement.md §"Shrinking below k+m"; reference
        philosophy: src/rpc/layout/version.rs:177-249 invariant checks."""
        npieces = self.block_manager.codec.n_pieces
        if npieces <= 1:
            return None
        storage = [n for n, r in lv.roles.items() if r.capacity]
        if len(storage) >= npieces:
            return None
        k = self.block_manager.codec.min_pieces
        return (
            f"WARNING: layout v{lv.version} has {len(storage)} storage "
            f"node(s) but EC({k},{npieces - k}) needs {npieces} per block; "
            f"EC writes will FAIL until a layout with >= {npieces} storage "
            "nodes is applied (existing blocks stay readable/repairable "
            "from any surviving k pieces)"
        )

    # --- lifecycle -----------------------------------------------------------

    async def start(self) -> None:
        host, port = _parse_addr(self.config.rpc_bind_addr)
        await self.netapp.listen(host, port)
        await self.system.start()
        from ..utils.tracing import tracer

        if self.config.admin.trace_sink:
            tracer.configure(self.config.admin.trace_sink)
            await tracer.start()
        from ..utils import flight

        adm = self.config.admin
        if adm.flight_recorder:
            self.flight_recorder = flight.SlowRequestRecorder(
                threshold_ms=adm.slow_request_threshold_msec,
                top_k=adm.slow_request_top_k,
            )
            # shared fanout, NOT a per-node tracer hook: several
            # in-process nodes would otherwise buffer + serialize every
            # span once per node (utils/flight.py _SharedSpanFanout)
            flight.attach_recorder(self.flight_recorder)
        if adm.event_loop_watchdog_threshold_msec:
            self.watchdog = flight.EventLoopWatchdog(
                threshold=adm.event_loop_watchdog_threshold_msec / 1000.0
            )
            if adm.stall_profile:
                # stall auto-capture: every counted stall episode samples
                # the wedged process from the watchdog thread and records
                # a `loop-stall-profile` flight event (utils/profiler.py)
                from ..utils.profiler import StallProfiler

                self.stall_profiler = StallProfiler()
                self.watchdog.on_stall = self.stall_profiler.on_stall
            self.watchdog.start()
        if adm.latency_xray:
            # latency X-ray (utils/latency.py): phase attribution via a
            # span-end hook — like the flight recorder, attaching it
            # turns span creation on with no OTLP sink
            from ..utils import latency

            latency.enable()
            self._latency_enabled = True
        if adm.traffic_observatory:
            # traffic observatory (rpc/traffic.py): refcounted singleton
            # like the latency aggregator — the S3 request path records
            # into it only while at least one node has it enabled
            from ..rpc import traffic

            traffic.enable(
                topk=adm.traffic_topk,
                halflife=adm.traffic_halflife_secs,
            )
            self._traffic_enabled = True
        if adm.tenant_observatory:
            # tenant observatory (rpc/tenant.py): per-authenticated-key
            # usage + per-class SLO burn — same refcounted-singleton
            # discipline as the traffic observatory
            from ..rpc import tenant

            tenant.enable(topk=adm.tenant_topk)
            # pre-auth sheds carry only a claimed key id; resolve its
            # class against THIS node's live config for the per-class
            # shed counter (last in-process node to start wins — the
            # config is shared in practice)
            tenant.observatory.class_resolver = (
                lambda kid: tenant.class_for(self.config, kid)[0]
            )
            self._tenant_enabled = True
        self._register_gauges()
        # uptime measures SERVING time: restamp at start(), not object
        # construction (recovery work can run between the two)
        self.telemetry.started_at = self.telemetry.clock()
        self._started = True

    def _register_gauges(self) -> None:
        """Backlog/queue gauges, polled at scrape time (reference
        src/block/metrics.rs, src/table/metrics.rs)."""
        from ..utils.metrics import registry

        # preserve keys tracked before start() (a canary spawned early):
        # reassigning would orphan their registry entries at stop()
        self._gauge_keys: list[tuple] = getattr(self, "_gauge_keys", [])

        def reg(name: str, labels: tuple, fn) -> None:
            registry.register_gauge(name, labels, fn)
            self._gauge_keys.append((name, labels))

        resync = self.block_manager.resync
        reg("block_resync_queue_length", (), lambda: len(resync.queue))
        reg("block_resync_errored_blocks", (), lambda: len(resync.errors))
        # error AGE: transient blip vs stuck block (0 when the error set
        # is empty or predates age tracking)
        reg(
            "block_resync_oldest_error_age_seconds", (),
            lambda: float(resync.oldest_error_age_secs() or 0.0),
        )
        # durability observatory (block/durability.py): ledger classes,
        # backlog, ETA, zone exposure, layout-sync progress.  `id` is
        # process-unique (in-process multi-node registry sharing); fns
        # raise before the first completed pass so samples are dropped,
        # never fabricated.
        from ..block.durability import DUR_CLASSES

        sc = self.durability_scanner
        gid = (("id", sc.gauge_id),)
        for cls in DUR_CLASSES:
            reg(
                "durability_blocks",
                (("class", cls),) + gid,
                lambda c=cls: sc.published_class(c),
            )
        reg(
            "durability_missing_pieces", gid,
            lambda: sc.published_value("missingPieces"),
        )
        reg(
            "durability_repair_eta_seconds", gid,
            # float(None) raises on unknown ETA -> sample dropped
            lambda: float(sc.repair_eta_secs()),
        )
        reg("durability_backlog_bytes", gid, lambda: sc.backlog_bytes())
        reg(
            "durability_zone_exposed_blocks", gid,
            lambda: sc.worst_zone_exposed(),
        )
        reg(
            "durability_layout_sync_fraction", gid,
            lambda: sc.layout_sync_fraction(),
        )
        reg("durability_scan_age_seconds", gid, lambda: sc.scan_age_secs())
        reg(
            "block_ram_buffer_bytes", (),
            lambda: self.block_manager.buffers.used,
        )
        for t in self.tables:
            lbl = (("table_name", t.schema.table_name),)
            reg(
                "table_merkle_updater_todo_queue_length", lbl,
                lambda d=t.data: len(d.merkle_todo),
            )
            reg(
                "table_gc_todo_queue_length", lbl,
                lambda d=t.data: len(d.gc_todo),
            )
        reg(
            "cluster_connected_nodes", (),
            lambda: len(self.system.peering.connected_peers()),
        )
        # overload-control plane: current degradation-ladder level (0 =
        # healthy) and live in-flight admitted requests
        reg(
            "overload_ladder_level", (),
            lambda: float(self.shedder.level if self.shedder else 0),
        )
        reg("api_in_flight_requests", (), lambda: float(self.overload.in_flight))
        # SLO error budgets (rpc/telemetry_digest.py SloTracker), scrape-
        # time so the rolling window advances even without digest traffic
        for kind in ("availability", "latency_p99"):
            lbl = (("slo", kind),)
            reg(
                "slo_error_budget_remaining", lbl,
                lambda k=kind: self.slo_tracker.compute()[k]["budget_remaining"],
            )
            reg(
                "slo_burn_rate", lbl,
                lambda k=kind: self.slo_tracker.compute()[k]["burn_rate"],
            )

    def spawn_workers(self) -> None:
        for t in self.tables:
            t.spawn_workers(self.bg)
        self.block_manager.spawn_workers(self.bg)
        from .s3.lifecycle_worker import LifecycleWorker
        from .snapshot import SnapshotWorker

        self.bg.spawn(LifecycleWorker(self, metadata_dir=self.config.metadata_dir))
        if self.config.metadata_auto_snapshot_interval:
            self.bg.spawn(SnapshotWorker(self))
        if self.config.overload.enabled:
            # SLO-driven shedding controller (rpc/shedding.py): walks
            # the degradation ladder off the local burn-rate/loop-lag
            # signals, acting through the live BgVars + admission tiers
            from ..rpc.shedding import SheddingController

            self.shedder = SheddingController(self)
            self.bg.spawn(self.shedder)
        if self.config.durability.enabled:
            # durability observatory (block/durability.py): tranquilized
            # rc-tree walk feeding the redundancy ledger + digest
            self.bg.spawn(self.durability_scanner)
        # restart-safe repair plane: a plan checkpointed mid-flight by a
        # previous process resumes (ledger + cursor intact) instead of
        # rescanning the cluster
        from ..block.repair_plan import RepairPlanner

        if (
            self.config.repair.auto_resume
            and self.block_manager.codec.n_pieces > 1
            and RepairPlanner.resumable(self.config.metadata_dir)
        ):
            self.launch_repair_plan()

    # --- canary prober --------------------------------------------------------

    def spawn_canary(self, endpoint: str):
        """Start the background canary prober against this node's own S3
        frontend (`endpoint`).  Called by the daemon once the S3 server
        is listening; tests call it directly.  Registers the
        `canary_healthy{id}` gauge at spawn (unregistered at stop() via
        _gauge_keys, process-unique id) and the `canary-*` live BgVars."""
        from ..api.s3.canary import CanaryWorker
        from ..utils.metrics import registry

        adm = self.config.admin
        w = CanaryWorker(
            self,
            endpoint,
            interval=adm.canary_interval_secs,
            object_bytes=adm.canary_object_bytes,
            bucket=adm.canary_bucket,
        )
        self.canary = w
        self.bg.spawn(w)
        self.bg_vars.register_rw(
            "canary-interval-secs",
            lambda: str(w.interval),
            lambda v: setattr(w, "interval", max(0.05, float(v))),
        )
        self.bg_vars.register_rw(
            "canary-object-bytes",
            lambda: str(w.object_bytes),
            lambda v: setattr(w, "object_bytes", max(1, int(v))),
        )
        lbl = (("id", w.gauge_id),)
        # fn raising on None (no cycle yet) drops the sample at scrape
        registry.register_gauge(
            "canary_healthy", lbl, lambda: float(w.healthy)
        )
        # _gauge_keys normally exists by now (start() ran); a canary
        # spawned before start() must not crash, just track its key
        self._gauge_keys = getattr(self, "_gauge_keys", [])
        self._gauge_keys.append(("canary_healthy", lbl))
        return w

    # --- repair plane ---------------------------------------------------------

    def launch_repair_plan(self, fresh: bool = False):
        """Start (or resume) the batched-reconstruction planner; admin
        `POST /v1/repair/plan/launch` and `cli repair plan launch`."""
        from ..block.repair_plan import RepairPlanner

        if self.block_manager.codec.n_pieces <= 1:
            raise ValueError(
                "repair planner requires an erasure-coded block codec "
                "(replication_mode = ec:k:m)"
            )
        if self.repair_planner is not None and not self.repair_planner.finished:
            raise ValueError("a repair plan is already running")
        planner = RepairPlanner(
            self.block_manager,
            metadata_dir=self.config.metadata_dir,
            params=self.repair_params,
            fresh=fresh,
        )
        self.repair_planner = planner
        self.bg.spawn(planner)
        return planner

    def repair_plan_status(self) -> dict:
        from ..block.repair_plan import RepairPlanner

        p = self.repair_planner
        out: dict = {"running": p is not None and not p.finished}
        if p is not None:
            out.update(p.status_full())
            out["resumed"] = p.resumed
        else:
            out["resumable"] = RepairPlanner.resumable(self.config.metadata_dir)
        out["params"] = {
            "tranquility": self.repair_params.tranquility,
            "bytesInFlight": self.repair_params.bytes_in_flight,
            "batchBlocks": self.repair_params.batch_blocks,
        }
        return out

    def overload_status(self) -> dict:
        """Admission + ladder state (admin GET /v1/overload, admin-RPC
        `overload status`, `cli overload status`)."""
        out = {
            "node": self.node_id.hex(),
            "admission": self.overload.status(),
            "ladder": (
                self.shedder.status_full() if self.shedder is not None else None
            ),
        }
        return out

    async def stop(self) -> None:
        from ..utils.tracing import tracer

        if self.watchdog is not None:
            self.watchdog.stop()
            self.watchdog = None
        self.stall_profiler = None
        if self.flight_recorder is not None:
            from ..utils import flight

            flight.detach_recorder(self.flight_recorder)
            self.flight_recorder = None
        if self._latency_enabled:
            from ..utils import latency

            latency.disable()
            self._latency_enabled = False
        if self._traffic_enabled:
            from ..rpc import traffic

            traffic.disable()
            self._traffic_enabled = False
        if self._tenant_enabled:
            from ..rpc import tenant

            tenant.disable()
            self._tenant_enabled = False
        await self.bg.shutdown()
        # after bg.shutdown(): the insert-queue workers are cancelled,
        # nothing new enters the coalescers
        for t in self.tables:
            await t.close()
        await self.block_manager.close()
        if self.canary is not None:
            # after bg.shutdown(): the worker is cancelled, nothing is
            # mid-probe on this session anymore
            await self.canary.stop_client()
            self.canary = None
        await self.system.stop()
        await self.netapp.shutdown()
        if self.config.admin.trace_sink:
            await tracer.stop()
        from ..utils.metrics import registry

        for name, labels in getattr(self, "_gauge_keys", []):
            registry.unregister_gauge(name, labels)
        self.overload.close()  # per-tenant token gauges
        self.db.close()
