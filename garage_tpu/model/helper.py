"""Admin helpers: bucket/key lifecycle with invariant checks.

Reference src/model/helper/{bucket,key,locked}.rs — admin mutations that
touch several entries (bucket + alias + key permissions) are serialized
through one lock per node; cross-node races converge by CRDT (two
concurrent create-bucket calls for the same alias: the LWW alias points to
one winner, the loser's bucket remains unaliased and can be cleaned up).
"""

from __future__ import annotations

import asyncio

from ..utils.crdt import Deletable, Lww
from ..utils.data import gen_uuid
from ..utils.error import Error
from .bucket_alias_table import BucketAlias, valid_bucket_name
from .bucket_table import Bucket
from .key_table import Key
from .permission import BucketKeyPerm
from .s3.object_table import Object


class GarageHelper:
    def __init__(self, garage):
        self.garage = garage
        self.lock = asyncio.Lock()

    # --- resolution -----------------------------------------------------------

    async def resolve_bucket(self, name: str, key: Key | None = None) -> bytes:
        """Bucket name -> id: local alias of the key first, then global
        alias (reference helper/bucket.rs resolve_bucket)."""
        if key is not None and key.params() is not None:
            local = key.params().local_aliases.get(name)
            if local:
                return bytes(local)
        alias = await self.garage.bucket_alias_table.get(name.encode(), b"")
        if alias is not None and alias.state.get() is not None:
            return bytes(alias.state.get())
        raise Error(f"bucket {name!r} not found")

    async def get_bucket(self, bucket_id: bytes) -> Bucket:
        b = await self.garage.bucket_table.get(bucket_id, b"")
        if b is None or b.is_deleted():
            raise Error(f"bucket {bucket_id.hex()[:16]} not found")
        return b

    async def get_key(self, key_id: str) -> Key:
        k = await self.garage.key_table.get(key_id.encode(), b"")
        if k is None or k.is_deleted():
            raise Error(f"key {key_id} not found")
        return k

    # --- bucket lifecycle -----------------------------------------------------

    async def create_bucket(self, name: str) -> bytes:
        if not valid_bucket_name(name, self.garage.config.allow_punycode):
            raise Error(f"invalid bucket name {name!r}")
        async with self.lock:  # graft-lint: allow-lock-await(admin-plane RMW serialization: the global helper lock must span the table quorum ops; no nested locks, RPC timeouts bound the hold)
            existing = await self.garage.bucket_alias_table.get(name.encode(), b"")
            if existing is not None and existing.state.get() is not None:
                raise Error(f"bucket {name!r} already exists")
            bucket = Bucket.new(gen_uuid())
            bucket.params().aliases.update_in_place(name, True)
            await self.garage.bucket_table.insert(bucket)
            if existing is not None:
                existing.state.update(bucket.id)
                await self.garage.bucket_alias_table.insert(existing)
            else:
                await self.garage.bucket_alias_table.insert(
                    BucketAlias.new(name, bucket.id)
                )
            return bucket.id

    async def delete_bucket(self, bucket_id: bytes) -> None:
        """Delete an EMPTY bucket and its aliases."""
        async with self.lock:  # graft-lint: allow-lock-await(admin-plane RMW serialization: the global helper lock must span the table quorum ops; no nested locks, RPC timeouts bound the hold)
            bucket = await self.get_bucket(bucket_id)
            objs = await self.garage.object_table.get_range(
                bucket_id, None, "visible", 1
            )
            if objs:
                raise Error("bucket is not empty")
            params = bucket.params()
            for name, v in params.aliases.items():
                if v:
                    alias = await self.garage.bucket_alias_table.get(name.encode(), b"")
                    if alias and alias.state.get() == bucket_id:
                        alias.state.update(None)
                        await self.garage.bucket_alias_table.insert(alias)
            bucket.state = Deletable.deleted()
            await self.garage.bucket_table.insert(bucket)

    async def list_buckets(self) -> list[Bucket]:
        out = []
        aliases = await self.garage.bucket_alias_table.get_all_local()
        seen = set()
        for a in aliases:
            bid = a.state.get()
            if bid is not None and bytes(bid) not in seen:
                seen.add(bytes(bid))
                try:
                    out.append(await self.get_bucket(bytes(bid)))
                except Error:
                    pass
        return out

    # --- aliases (reference helper/locked.rs alias ops) -----------------------

    async def set_global_alias(self, bucket_id: bytes, alias: str) -> None:
        if not valid_bucket_name(alias, self.garage.config.allow_punycode):
            raise Error(f"invalid alias {alias!r}")
        async with self.lock:  # graft-lint: allow-lock-await(admin-plane RMW serialization: the global helper lock must span the table quorum ops; no nested locks, RPC timeouts bound the hold)
            bucket = await self.get_bucket(bucket_id)
            existing = await self.garage.bucket_alias_table.get(alias.encode(), b"")
            if (
                existing is not None
                and existing.state.get() is not None
                and bytes(existing.state.get()) != bucket_id
            ):
                raise Error(f"alias {alias!r} already points to another bucket")
            if existing is not None:
                existing.state.update(bucket_id)
                await self.garage.bucket_alias_table.insert(existing)
            else:
                await self.garage.bucket_alias_table.insert(
                    BucketAlias.new(alias, bucket_id)
                )
            bucket.params().aliases.update_in_place(alias, True)
            await self.garage.bucket_table.insert(bucket)

    async def unset_global_alias(self, bucket_id: bytes, alias: str) -> None:
        async with self.lock:  # graft-lint: allow-lock-await(admin-plane RMW serialization: the global helper lock must span the table quorum ops; no nested locks, RPC timeouts bound the hold)
            bucket = await self.get_bucket(bucket_id)
            params = bucket.params()
            live = [n for n, v in params.aliases.items() if v]
            has_local = any(
                True
                for k in await self.list_keys()
                for n, b in k.params().local_aliases.items()
                if b is not None and bytes(b) == bucket_id
            )
            if live == [alias] and not has_local:
                raise Error(
                    f"{alias!r} is the bucket's last alias; removing it would "
                    "make the bucket unreachable"
                )
            a = await self.garage.bucket_alias_table.get(alias.encode(), b"")
            if a is None or a.state.get() is None or bytes(a.state.get()) != bucket_id:
                raise Error(f"alias {alias!r} does not point to this bucket")
            a.state.update(None)
            await self.garage.bucket_alias_table.insert(a)
            params.aliases.update_in_place(alias, False)
            await self.garage.bucket_table.insert(bucket)

    async def set_local_alias(self, bucket_id: bytes, key_id: str, alias: str) -> None:
        if not valid_bucket_name(alias, self.garage.config.allow_punycode):
            raise Error(f"invalid alias {alias!r}")
        async with self.lock:  # graft-lint: allow-lock-await(admin-plane RMW serialization: the global helper lock must span the table quorum ops; no nested locks, RPC timeouts bound the hold)
            await self.get_bucket(bucket_id)
            key = await self.get_key(key_id)
            cur = key.params().local_aliases.get(alias)
            if cur is not None and bytes(cur) != bucket_id:
                raise Error(f"key already uses alias {alias!r} for another bucket")
            key.params().local_aliases.update_in_place(alias, bucket_id)
            await self.garage.key_table.insert(key)

    async def unset_local_alias(self, bucket_id: bytes, key_id: str, alias: str) -> None:
        async with self.lock:  # graft-lint: allow-lock-await(admin-plane RMW serialization: the global helper lock must span the table quorum ops; no nested locks, RPC timeouts bound the hold)
            key = await self.get_key(key_id)
            cur = key.params().local_aliases.get(alias)
            if cur is None or bytes(cur) != bucket_id:
                raise Error(f"alias {alias!r} does not point to this bucket")
            key.params().local_aliases.update_in_place(alias, None)
            await self.garage.key_table.insert(key)

    # --- key lifecycle --------------------------------------------------------

    async def create_key(self, name: str = "") -> Key:
        key = Key.new(name)
        await self.garage.key_table.insert(key)
        return key

    async def delete_key(self, key_id: str) -> None:
        async with self.lock:  # graft-lint: allow-lock-await(admin-plane RMW serialization: the global helper lock must span the table quorum ops; no nested locks, RPC timeouts bound the hold)
            key = await self.get_key(key_id)
            key.state = Deletable.deleted()
            await self.garage.key_table.insert(key)

    async def list_keys(self) -> list[Key]:
        ks = await self.garage.key_table.get_all_local()
        return [k for k in ks if not k.is_deleted()]

    async def update_key(
        self,
        key_id: str,
        name: str | None = None,
        allow_create_bucket: bool | None = None,
    ) -> Key:
        async with self.lock:  # graft-lint: allow-lock-await(admin-plane RMW serialization: the global helper lock must span the table quorum ops; no nested locks, RPC timeouts bound the hold)
            key = await self.get_key(key_id)
            if name is not None:
                key.params().name.update(name)
            if allow_create_bucket is not None:
                key.params().allow_create_bucket.update(allow_create_bucket)
            await self.garage.key_table.insert(key)
            return key

    async def import_key(self, key_id: str, secret: str, name: str = "") -> Key:
        """Import an existing credential pair (reference key import)."""
        from .key_table import KeyParams
        from ..utils.crdt import Deletable

        if not key_id.startswith("GK") or len(secret) != 64:
            raise Error("malformed key id or secret")
        async with self.lock:  # graft-lint: allow-lock-await(admin-plane RMW serialization: the global helper lock must span the table quorum ops; no nested locks, RPC timeouts bound the hold)
            existing = await self.garage.key_table.get(key_id.encode(), b"")
            if existing is not None:
                # a deleted key leaves a delete-wins CRDT tombstone: an
                # import under the same id would silently converge back to
                # deleted — refuse instead of lying
                raise Error(
                    f"key {key_id} already exists"
                    if not existing.is_deleted()
                    else f"key id {key_id} was deleted and cannot be reused"
                )
            params = KeyParams(secret)
            params.name.update(name)
            key = Key(key_id, Deletable.present(params))
            await self.garage.key_table.insert(key)
            return key

    async def set_bucket_key_permissions(
        self, bucket_id: bytes, key_id: str, read: bool, write: bool, owner: bool
    ) -> None:
        from ..utils.time_util import now_msec

        async with self.lock:  # graft-lint: allow-lock-await(admin-plane RMW serialization: the global helper lock must span the table quorum ops; no nested locks, RPC timeouts bound the hold)
            key = await self.get_key(key_id)
            await self.get_bucket(bucket_id)  # must exist
            perm = BucketKeyPerm(now_msec(), read, write, owner)
            key.params().authorized_buckets.update_in_place(bucket_id, perm.to_obj())
            await self.garage.key_table.insert(key)

    # --- object listing (used by delete_bucket and the CLI) -------------------

    async def bucket_is_empty(self, bucket_id: bytes) -> bool:
        objs = await self.garage.object_table.get_range(bucket_id, None, "visible", 1)
        return not objs
