from .web_server import WebServer

__all__ = ["WebServer"]
