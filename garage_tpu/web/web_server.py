"""Static-website server (reference src/web/web_server.rs:70).

Serves buckets whose website config is enabled, vhost-style: Host
`<bucket>.<root_domain>` (or an alias matching the Host exactly).  Reuses
the S3 GET path without authentication; index documents for directory
paths, error documents for 404s, CORS headers from the bucket config.
"""

from __future__ import annotations

import logging

from aiohttp import web

from ..api.common.error import ApiError
from ..api.s3.bucket_config import add_cors_headers, find_matching_cors_rule
from ..api.s3.objects import handle_get_object
from ..utils.error import Error

logger = logging.getLogger("garage.web")


class WebServer:
    def __init__(self, garage):
        self.garage = garage
        self.root_domain = garage.config.s3_web.root_domain
        self.app = web.Application()
        self.app.router.add_route("*", "/{tail:.*}", self._entry)
        self.runner: web.AppRunner | None = None

    async def start(self, host: str, port: int) -> None:
        self.runner = web.AppRunner(self.app, access_log=None)
        await self.runner.setup()
        site = web.TCPSite(self.runner, host, port)
        await site.start()
        logger.info("web server listening on %s:%d", host, port)

    async def stop(self) -> None:
        if self.runner:
            await self.runner.cleanup()

    def _bucket_name(self, request) -> str:
        host = request.headers.get("Host", "").split(":")[0]
        rd = (self.root_domain or "").lstrip(".")
        if rd and host != rd and host.endswith("." + rd):
            return host[: -(len(rd) + 1)]
        return host  # a global alias can be a bare domain name

    async def _entry(self, request: web.Request) -> web.StreamResponse:
        from ..utils.metrics import request_metrics

        try:
            with request_metrics(
                "web", request.method, "web", host=self._bucket_name(request)
            ):
                return await self._serve(request)
        except (ApiError, Error) as e:
            status = getattr(e, "status", 404)
            return web.Response(status=status if status != 403 else 404, text=str(e))

    async def _serve(self, request: web.Request) -> web.StreamResponse:
        bucket_name = self._bucket_name(request)
        bucket_id = await self.garage.helper.resolve_bucket(bucket_name)
        bucket = await self.garage.helper.get_bucket(bucket_id)
        params = bucket.params()
        website = params.website.get()
        if not website:
            raise ApiError("bucket is not a website", code="Forbidden", status=403)

        origin = request.headers.get("Origin", "")
        if request.method == "OPTIONS":
            rule = find_matching_cors_rule(
                params, origin, request.headers.get("Access-Control-Request-Method", "GET")
            )
            resp = web.Response(status=200 if rule else 403)
            if rule:
                add_cors_headers(resp, rule, origin)
            return resp
        if request.method not in ("GET", "HEAD"):
            raise ApiError("method not allowed", code="MethodNotAllowed", status=405)

        key = request.path.lstrip("/")
        if not key or key.endswith("/"):
            key = key + website["index_document"]
        try:
            resp = await handle_get_object(
                self.garage, bucket_id, key, request,
                head_only=(request.method == "HEAD"),
                allow_overrides=False,  # anonymous path: no response-* rewrites
            )
        except ApiError as e:
            if e.status == 404 and website.get("error_document"):
                try:
                    resp = await handle_get_object(
                        self.garage, bucket_id, website["error_document"],
                        request, allow_overrides=False,
                    )
                    if not resp.prepared:
                        resp.set_status(404)
                except ApiError:
                    raise e from None
            else:
                raise
        if origin and not resp.prepared:
            # streamed (multi-block) responses are already on the wire;
            # CORS headers can only be added to buffered ones
            rule = find_matching_cors_rule(params, origin, request.method)
            if rule:
                add_cors_headers(resp, rule, origin)
        return resp
