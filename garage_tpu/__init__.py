"""garage_tpu — a TPU-native, S3-compatible, geo-distributed object store.

Re-architecture of the capability surface of Garage (reference:
/root/reference, deuxfleurs-org/garage): no-consensus placement from a
CRDT-replicated cluster layout, read/write-quorum consistency, CRDT merge +
Merkle anti-entropy convergence, content-addressed block storage — plus a
TPU-native compute plane: batched GF(2^8) Reed-Solomon erasure coding and
batched BLAKE3 integrity hashing running on XLA/TPU behind a BlockCodec
interface (`replication_mode = "ec:k:m"`).

Layer map (mirrors reference workspace crates, SURVEY.md §1):
  utils/   — ids, hashes, CRDTs, versioned migration, config, workers
  db/      — metadata KV abstraction (sqlite / memory engines)
  net/     — authenticated asyncio TCP mesh with typed RPC + streams + QoS
  rpc/     — membership, cluster layout (min-cost-flow assignment), quorum RPC
  table/   — replicated CRDT table engine (merkle anti-entropy, GC)
  block/   — content-addressed block store, resync/scrub, BlockCodec seam
  model/   — table schemas + composition root (S3, K2V, buckets, keys)
  api/     — S3 / K2V / admin HTTP APIs, SigV4
  web/     — static-website server
  cli/     — daemon + operator CLI
  ops/     — JAX/XLA kernels: GF(2^8) bitplane matmul EC, batched BLAKE3
  parallel/— device-mesh sharding for pod-level repair fan-out
  models/  — flagship compute pipelines (scrub+repair) used by bench/entry
"""

__version__ = "0.1.0"

# Optional-dependency fallbacks (zlib-backed `zstandard` shim, etc.) must
# be installed before any submodule import pulls the real names.
from .utils import depcompat as _depcompat  # noqa: E402,F401
