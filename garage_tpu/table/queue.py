"""Insert-queue worker: batches async local inserts into quorum writes
(reference src/table/queue.rs:15-44)."""

from __future__ import annotations

import asyncio
import logging

from ..utils.background import Worker, WorkerState

logger = logging.getLogger("garage.table.queue")

BATCH = 100


class InsertQueueWorker(Worker):
    def __init__(self, table):
        self.table = table

    def name(self) -> str:
        return f"queue:{self.table.schema.table_name}"

    def status(self):
        return {"queued": len(self.table.data.insert_queue)}

    async def work(self):
        keys, entries = [], []
        for k, v in self.table.data.insert_queue.iter_range():
            keys.append(k)
            entries.append(self.table.data.decode(v))
            if len(entries) >= BATCH:
                break
        if not entries:
            return WorkerState.IDLE
        await self.table.insert_many(entries)  # errors => supervisor backoff
        for k in keys:
            self.table.data.insert_queue.remove(k)
        return WorkerState.BUSY

    async def wait_for_work(self) -> None:
        await asyncio.sleep(1.0)
