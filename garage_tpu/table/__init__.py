"""Replicated CRDT table engine (reference src/table/).

A Table stores CRDT entries keyed by (partition key, sort key), replicated
to the nodes the cluster layout designates for hash(partition key):

  - writes CRDT-merge into local storage transactionally and fan out with
    try_write_many_sets (quorum in every active layout version)
  - reads are quorum reads with CRDT merge of the replies + background
    read-repair of stale nodes
  - convergence without coordination: a per-partition Merkle trie is
    maintained incrementally and anti-entropy syncs diverging subtrees
  - tombstones are garbage-collected with the 3-phase protocol (replicate
    tombstone everywhere, then delete-if-equal-hash) after a 24 h delay
"""

from .schema import TableSchema
from .table import Table

__all__ = ["Table", "TableSchema"]
