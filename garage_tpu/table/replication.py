"""Replication strategies (reference src/table/replication/).

TableShardedReplication — data tables: each entry lives on the rf nodes
the layout assigns to hash(pk); quorums from the replication mode
(sharded.rs:16-50).

TableFullReplication — control-plane tables (buckets, keys): every node
stores everything; reads are local; writes go to all nodes with a majority
quorum (fullcopy.rs:21-55).
"""

from __future__ import annotations

from ..rpc.layout.types import N_PARTITIONS
from ..rpc.system import System


class TableReplication:
    # full-copy tables sync as one partition covering the whole keyspace
    full_copy = False

    def read_nodes(self, hash32: bytes) -> list[bytes]:
        raise NotImplementedError

    def read_quorum(self) -> int:
        raise NotImplementedError

    def write_sets(self, hash32: bytes) -> list[list[bytes]]:
        raise NotImplementedError

    def write_quorum(self) -> int:
        raise NotImplementedError

    def storage_nodes(self, hash32: bytes) -> list[bytes]:
        """All nodes that should (eventually) store this hash."""
        raise NotImplementedError

    def local_partitions(self, node: bytes) -> list[tuple[int, bytes]]:
        """(partition index, first hash of partition) stored by `node`."""
        raise NotImplementedError

    def partition_of(self, hash32: bytes) -> int:
        """Merkle/sync partition for a placement hash."""
        raise NotImplementedError


def partition_first_hash(p: int) -> bytes:
    return bytes([p]) + b"\x00" * 31


class TableShardedReplication(TableReplication):
    def __init__(self, system: System):
        self.system = system

    @property
    def _layout(self):
        return self.system.layout_manager.history

    def read_nodes(self, hash32: bytes) -> list[bytes]:
        return self._layout.read_nodes_of(hash32)

    def read_quorum(self) -> int:
        return self.system.replication_mode.read_quorum()

    def write_sets(self, hash32: bytes) -> list[list[bytes]]:
        return self._layout.write_sets_of(hash32)

    def write_quorum(self) -> int:
        return self.system.replication_mode.write_quorum()

    def storage_nodes(self, hash32: bytes) -> list[bytes]:
        nodes: list[bytes] = []
        for s in self._layout.write_sets_of(hash32):
            for n in s:
                if n not in nodes:
                    nodes.append(n)
        return nodes

    def partition_of(self, hash32: bytes) -> int:
        return hash32[0]

    def local_partitions(self, node: bytes) -> list[tuple[int, bytes]]:
        out = []
        for p in range(N_PARTITIONS):
            fh = partition_first_hash(p)
            if any(node in v.nodes_of_partition(p) for v in self._layout.versions if v.ring_assignment):
                out.append((p, fh))
        return out


class TableFullReplication(TableReplication):
    full_copy = True

    def __init__(self, system: System):
        self.system = system

    def _all_nodes(self) -> list[bytes]:
        nodes = self.system.layout_manager.history.all_nodes()
        if not nodes:
            nodes = [self.system.id]
        return nodes

    def read_nodes(self, hash32: bytes) -> list[bytes]:
        return [self.system.id]  # always readable locally

    def read_quorum(self) -> int:
        return 1

    def write_sets(self, hash32: bytes) -> list[list[bytes]]:
        return [self._all_nodes()]

    def write_quorum(self) -> int:
        n = len(self._all_nodes())
        return n // 2 + 1

    def storage_nodes(self, hash32: bytes) -> list[bytes]:
        return self._all_nodes()

    def partition_of(self, hash32: bytes) -> int:
        return 0

    def local_partitions(self, node: bytes) -> list[tuple[int, bytes]]:
        # full-copy tables sync as a single partition 0
        return [(0, partition_first_hash(0))]
