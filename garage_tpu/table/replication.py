"""Replication strategies (reference src/table/replication/).

TableShardedReplication — data tables: each entry lives on the rf nodes
the layout assigns to hash(pk); quorums from the replication mode
(sharded.rs:16-50).

TableMetaReplication — ISSUE 15: the `model/` sharded tables carry
their own (small) replication factor instead of inheriting the block
plane's stripe width.  Entries live on the METADATA RING: the first
`meta_rf` distinct nodes of the partition's layout node list, derived
per active layout version — so an ec:8:3 cluster quorums object/
version/blockref rows over 3 nodes while blocks still fan to all 11.
Quorums come from the meta replication mode at the EFFECTIVE factor
min(meta_rf, layout rf) (replica modes "1"/"2" fall back to the full
partition node list), keeping `read_q + write_q > effective_rf`
(read-your-writes) by the same arithmetic as the block plane.  Sync,
GC and offload all go through this interface, so anti-entropy follows
the meta ring and never repairs to a node that no longer stores the
partition.

TableFullReplication — control-plane tables (buckets, keys): every node
stores everything; reads are local; writes go to all nodes with a majority
quorum (fullcopy.rs:21-55).
"""

from __future__ import annotations

from ..rpc.layout.types import N_PARTITIONS
from ..rpc.replication_mode import (
    ReplicationMode,
    read_quorum_for,
    write_quorum_for,
)
from ..rpc.system import System


class TableReplication:
    # full-copy tables sync as one partition covering the whole keyspace
    full_copy = False

    def read_nodes(self, hash32: bytes) -> list[bytes]:
        raise NotImplementedError

    def read_quorum(self) -> int:
        raise NotImplementedError

    def write_sets(self, hash32: bytes) -> list[list[bytes]]:
        raise NotImplementedError

    def write_quorum(self) -> int:
        raise NotImplementedError

    def storage_nodes(self, hash32: bytes) -> list[bytes]:
        """All nodes that should (eventually) store this hash."""
        raise NotImplementedError

    def background_nodes(self, hash32: bytes) -> list[bytes]:
        """Nodes that should eventually store this hash but take no part
        in quorum accounting: inserts send them best-effort background
        copies, anti-entropy is the backstop.  Empty for every strategy
        whose quorum set IS its storage set."""
        return []

    def local_partitions(self, node: bytes) -> list[tuple[int, bytes]]:
        """(partition index, first hash of partition) stored by `node`."""
        raise NotImplementedError

    def partition_of(self, hash32: bytes) -> int:
        """Merkle/sync partition for a placement hash."""
        raise NotImplementedError


def partition_first_hash(p: int) -> bytes:
    return bytes([p]) + b"\x00" * 31


class TableShardedReplication(TableReplication):
    def __init__(self, system: System):
        self.system = system

    @property
    def _layout(self):
        return self.system.layout_manager.history

    def read_nodes(self, hash32: bytes) -> list[bytes]:
        return self._layout.read_nodes_of(hash32)

    def read_quorum(self) -> int:
        return self.system.replication_mode.read_quorum()

    def write_sets(self, hash32: bytes) -> list[list[bytes]]:
        return self._layout.write_sets_of(hash32)

    def write_quorum(self) -> int:
        return self.system.replication_mode.write_quorum()

    def storage_nodes(self, hash32: bytes) -> list[bytes]:
        # union over self.write_sets (NOT the raw layout sets) so the
        # meta subclass's ring subsetting applies to sync/GC/offload too
        nodes: list[bytes] = []
        for s in self.write_sets(hash32):
            for n in s:
                if n not in nodes:
                    nodes.append(n)
        return nodes

    def partition_of(self, hash32: bytes) -> int:
        return hash32[0]

    def _partition_nodes_of(self, v, p: int) -> list[bytes]:
        """One layout version's storage set for partition `p` — the seam
        the meta subclass narrows to its ring."""
        return v.nodes_of_partition(p)

    def local_partitions(self, node: bytes) -> list[tuple[int, bytes]]:
        out = []
        for p in range(N_PARTITIONS):
            fh = partition_first_hash(p)
            if any(
                node in self._partition_nodes_of(v, p)
                for v in self._layout.versions
                if v.ring_assignment
            ):
                out.append((p, fh))
        return out


class TableMetaReplication(TableShardedReplication):
    """The metadata ring (module docstring): first `meta_rf` distinct
    nodes of each partition's node list, per active layout version.

    Ring properties the tier-1 tests pin down:
      - distinctness: the subset inherits the layout invariant that a
        partition's replicas are distinct nodes (and dedupes
        defensively, so a corrupt assignment can't shrink a quorum
        silently);
      - stability: the layout orders a partition's nodes previous-
        holders-first (version.py compute_assignment), so the meta
        subset only changes when the partition's placement actually
        changes — tracker gossip never moves it;
      - transitions: one subset per ACTIVE version, so writes quorum in
        every active version's meta set and a read from the newest
        synced version intersects the write set of the same version
        (`read_q + write_q > effective_rf`);
      - fallback: a layout whose own rf is below meta_rf (replica
        modes "1"/"2") keeps the full partition node list, with quorums
        at that smaller effective factor.
    """

    def __init__(self, system: System, mode: ReplicationMode):
        super().__init__(system)
        # `mode` carries the CONFIGURED [meta] replication_factor +
        # consistency mode; the effective factor follows the live layout
        self.mode = mode

    def effective_rf(self) -> int:
        return min(
            self.mode.replication_factor, self._layout.replication_factor
        )

    def meta_nodes_of(self, nodes: list[bytes]) -> list[bytes]:
        rf = self.mode.replication_factor
        out: list[bytes] = []
        for n in nodes:
            if n not in out:
                out.append(n)
                if len(out) >= rf:
                    break
        return out

    def read_nodes(self, hash32: bytes) -> list[bytes]:
        return self.meta_nodes_of(self._layout.read_nodes_of(hash32))

    def read_quorum(self) -> int:
        return read_quorum_for(self.effective_rf(), self.mode.consistency_mode)

    def write_sets(self, hash32: bytes) -> list[list[bytes]]:
        return [
            self.meta_nodes_of(s) for s in self._layout.write_sets_of(hash32)
        ]

    def write_quorum(self) -> int:
        return write_quorum_for(
            self.effective_rf(), self.mode.consistency_mode
        )

    def _partition_nodes_of(self, v, p: int) -> list[bytes]:
        return self.meta_nodes_of(v.nodes_of_partition(p))


class TableStripeSyncedReplication(TableMetaReplication):
    """block_ref only: meta-ring QUORUMS, full-stripe ANTI-ENTROPY.

    The block_ref table is the pivot between the metadata and data
    planes: its `updated()` hook feeds each node's local rc tree, and
    the rc tree is what resync, scrub, the durability ledger and block
    GC walk — so every node holding a PIECE of block h must eventually
    hold h's ref rows, even though the foreground insert only needs a
    small quorum.  This strategy therefore keeps the fast path on the
    meta ring (insert/get fan to meta_rf nodes, same quorum arithmetic
    as TableMetaReplication — read-your-writes holds because reads and
    writes use the same per-version subsets) while `storage_nodes` /
    `local_partitions` span the FULL stripe: the Merkle syncer treats
    every piece holder as a replica, so refs reach rank >= meta_rf
    holders within one anti-entropy round (<= sync interval, immediate
    on layout change), and the 3-phase tombstone GC still requires
    every holder's ack before a deletion marker may disappear (any
    holder could otherwise resurrect the ref).  The lag is benign: rc
    on a high-rank holder arriving late only delays background heal/
    scrub/ledger visibility of a young block — piece durability comes
    from the direct block-plane write, and deletion keeps the rc GC
    delay on top.  See doc/metadata-replication.md."""

    def storage_nodes(self, hash32: bytes) -> list[bytes]:
        nodes: list[bytes] = []
        for s in self._layout.write_sets_of(hash32):
            for n in s:
                if n not in nodes:
                    nodes.append(n)
        return nodes

    def background_nodes(self, hash32: bytes) -> list[bytes]:
        """The stripe holders beyond the meta ring: they receive
        foreground best-effort copies so a young block's refs (and the
        rc entries they feed) appear on its piece holders immediately
        instead of at the next anti-entropy round."""
        quorum: set[bytes] = set()
        for s in self.write_sets(hash32):
            quorum.update(s)
        return [n for n in self.storage_nodes(hash32) if n not in quorum]

    def _partition_nodes_of(self, v, p: int) -> list[bytes]:
        return v.nodes_of_partition(p)


class TableFullReplication(TableReplication):
    full_copy = True

    def __init__(self, system: System):
        self.system = system

    def _all_nodes(self) -> list[bytes]:
        nodes = self.system.layout_manager.history.all_nodes()
        if not nodes:
            nodes = [self.system.id]
        return nodes

    def read_nodes(self, hash32: bytes) -> list[bytes]:
        return [self.system.id]  # always readable locally

    def read_quorum(self) -> int:
        return 1

    def write_sets(self, hash32: bytes) -> list[list[bytes]]:
        return [self._all_nodes()]

    def write_quorum(self) -> int:
        n = len(self._all_nodes())
        return n // 2 + 1

    def storage_nodes(self, hash32: bytes) -> list[bytes]:
        return self._all_nodes()

    def partition_of(self, hash32: bytes) -> int:
        return 0

    def local_partitions(self, node: bytes) -> list[tuple[int, bytes]]:
        # full-copy tables sync as a single partition 0
        return [(0, partition_first_hash(0))]
