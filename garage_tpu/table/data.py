"""TableData: local storage of one table + its auxiliary queues.

Reference src/table/data.rs.  Trees:
  <name>            entries, keyed hash(pk) || sk, values = versioned msgpack
  <name>:merkle_todo   key -> new value hash (or b"" for deletion)
  <name>:merkle_tree   merkle trie nodes (see merkle.py)
  <name>:gc_todo       [deadline_ms || key] -> value hash, tombstone queue
  <name>:insert_queue  async local insert batching

`update_entry` is THE mutation path: CRDT merge inside a transaction,
merkle_todo enqueue, and the schema's `updated()` cascade — all atomic.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Iterator

from ..db import Db, Tx, TxAbort
from ..utils.data import blake2sum
from ..utils.serde import pack, unpack
from ..utils.time_util import now_msec
from .replication import TableReplication
from .schema import TableSchema

logger = logging.getLogger("garage.table")

GC_DELAY_MS = 24 * 3600 * 1000  # tombstones wait 24 h (reference gc.rs:33)


class TableData:
    def __init__(self, db: Db, schema: TableSchema, replication: TableReplication):
        self.db = db
        self.schema = schema
        self.replication = replication
        name = schema.table_name
        self.store = db.open_tree(name)
        self.merkle_todo = db.open_tree(f"{name}:merkle_todo")
        self.merkle_tree = db.open_tree(f"{name}:merkle_tree")
        self.gc_todo = db.open_tree(f"{name}:gc_todo")
        self.insert_queue = db.open_tree(f"{name}:insert_queue")
        # notified on local changes (merkle worker, insert queue worker)
        self.change_waiters: list[Callable[[], None]] = []

    # --- reads ---------------------------------------------------------------

    def read_entry(self, pk: bytes, sk: bytes) -> bytes | None:
        return self.store.get(self.schema.tree_key(pk, sk))

    def read_range(
        self,
        pk: bytes,
        start_sk: bytes | None,
        filt: Any,
        limit: int,
        reverse: bool = False,
    ) -> list[bytes]:
        ph = self.schema.partition_hash(pk)
        out: list[bytes] = []
        if reverse:
            # reverse enumeration starts AT start_sk (inclusive) and walks
            # down; with no start_sk it covers the whole partition,
            # including sort keys made of 0xff bytes
            end = ph + start_sk + b"\x00" if start_sk is not None else _prefix_end(ph)
            it = self.store.iter_range(ph, end, reverse=True)
        else:
            it = self.store.iter_range(ph + (start_sk or b""), _prefix_end(ph))
        for k, v in it:
            if not k.startswith(ph):
                break
            ent = self.decode(v)
            if self.schema.matches_filter(ent, filt):
                out.append(v)
            if len(out) >= limit:
                break
        return out

    def decode(self, value: bytes):
        return self.schema.decode_entry(unpack(value))

    def encode(self, entry) -> bytes:
        return pack(self.schema.encode_entry(entry))

    # --- writes --------------------------------------------------------------

    def update_entry(self, entry_value: bytes) -> bool:
        """CRDT-merge a serialized entry into local storage.
        Returns True if the stored value changed."""
        entry = self.decode(entry_value)
        pk = self.schema.entry_partition_key(entry)
        sk = self.schema.entry_sort_key(entry)
        key = self.schema.tree_key(pk, sk)

        def txf(tx: Tx) -> bool:
            old_v = tx.get(self.store, key)
            if old_v is not None:
                old = self.decode(old_v)
                new = self.schema.merge_entries(self.decode(old_v), self.decode(entry_value))
            else:
                old = None
                new = self.decode(entry_value)
            new_v = self.encode(new)
            if old_v == new_v:
                raise TxAbort(value=False)
            tx.insert(self.store, key, new_v)
            tx.insert(self.merkle_todo, key, blake2sum(new_v))
            if self.schema.is_tombstone(new):
                deadline = now_msec() + GC_DELAY_MS
                tx.insert(
                    self.gc_todo,
                    deadline.to_bytes(8, "big") + key,
                    blake2sum(new_v),
                )
            self.schema.updated(tx, old, new)
            return True

        changed = self.db.transaction(txf)
        if changed:
            self._notify()
        return changed

    def delete_if_equal_hash(self, key: bytes, vhash: bytes) -> bool:
        """Phase-3 GC deletion: remove the entry only if its value still
        hashes to vhash (reference gc.rs DeleteIfEqualHash)."""

        def txf(tx: Tx) -> bool:
            cur = tx.get(self.store, key)
            if cur is None or blake2sum(cur) != vhash:
                raise TxAbort(value=False)
            old = self.decode(cur)
            tx.remove(self.store, key)
            tx.insert(self.merkle_todo, key, b"")  # b"" = deleted
            self.schema.updated(tx, old, None)
            return True

        changed = self.db.transaction(txf)
        if changed:
            self._notify()
        return changed

    # --- insert queue (reference table/queue.rs) ------------------------------

    def queue_insert(self, entry, tx: Tx | None = None) -> None:
        """Cheap local enqueue; the InsertQueueWorker batches these into
        real quorum inserts.  Pass `tx` when called from an updated() hook
        so the enqueue commits atomically with the triggering write."""
        v = self.encode(entry)
        k = now_msec().to_bytes(8, "big") + blake2sum(v)[:8]
        if tx is not None:
            tx.insert(self.insert_queue, k, v)
        else:
            self.insert_queue.insert(k, v)
        self._notify()

    # --- iteration (sync / gc workers) ---------------------------------------

    def iter_partition(self, partition_idx: int) -> Iterator[tuple[bytes, bytes]]:
        """All entries whose tree key falls in this sync partition."""
        start, end = self.partition_range(partition_idx)
        yield from self.store.iter_range(start, end)

    def partition_range(self, partition_idx: int) -> tuple[bytes, bytes | None]:
        if getattr(self.replication, "full_copy", False):
            return (b"", None)  # single partition covers all keys
        start = bytes([partition_idx])
        end = bytes([partition_idx + 1]) if partition_idx < 255 else None
        return (start, end)

    def _notify(self) -> None:
        for fn in self.change_waiters:
            try:
                fn()
            except Exception:  # noqa: BLE001 — one broken waiter must not
                # starve the rest, but a raising callback is a real bug
                logger.exception("table change waiter failed")


def _prefix_end(prefix: bytes) -> bytes | None:
    p = bytearray(prefix)
    while p:
        if p[-1] != 0xFF:
            p[-1] += 1
            return bytes(p)
        p.pop()
    return None
