"""Incremental per-partition Merkle trie (reference src/table/merkle.rs).

A sparse 256-ary patricia-style trie over entry tree-keys, one root per
sync partition.  Nodes (stored in the `<name>:merkle_tree` db tree, keyed
`[partition u8] || prefix bytes`):

  None                       empty
  ["L", key, value_hash]     leaf: entry `key` with blake2(serialized value)
  ["I", [[byte, child_hash], ...], term]
      intermediate: children at prefix+byte, plus an optional `term` =
      [key, value_hash] for the single key that ENDS exactly at this
      prefix (sort keys have variable length, so one tree key may be a
      strict prefix of another)

Canonical shape invariant (content-addressed: equal key sets => equal
trees): a prefix holding 0 keys stores nothing, 1 key stores a leaf,
>= 2 keys stores an intermediate.

node_hash = blake2(msgpack(node)); parent references child by hash so any
difference propagates to the root — two replicas with equal roots hold
bit-identical partitions.  The MerkleWorker consumes `merkle_todo`
(key -> new value hash, b"" = deleted) in batches: up to 100 items are
applied in one transaction, then their todos cleared (supersession-
checked) in a second — per-commit cost, not the trie walk, dominates.
"""

from __future__ import annotations

import logging
from typing import Any

from ..db import Tx
from ..utils.background import Worker, WorkerState
from ..utils.data import blake2sum
from ..utils.serde import pack, unpack
from .data import TableData

logger = logging.getLogger("garage.table.merkle")

EMPTY_HASH = b"\x00" * 32


def node_hash(node: Any) -> bytes:
    if node is None:
        return EMPTY_HASH
    return blake2sum(pack(node))


class MerkleUpdater:
    def __init__(self, data: TableData):
        self.data = data

    # --- node storage ---------------------------------------------------------

    def _nk(self, partition: int, prefix: bytes) -> bytes:
        return bytes([partition]) + prefix

    def get_node(self, partition: int, prefix: bytes, tx: Tx | None = None) -> Any:
        raw = (
            tx.get(self.data.merkle_tree, self._nk(partition, prefix))
            if tx
            else self.data.merkle_tree.get(self._nk(partition, prefix))
        )
        return None if raw is None else unpack(raw)

    def _put_node(self, tx: Tx, partition: int, prefix: bytes, node: Any) -> bytes:
        k = self._nk(partition, prefix)
        if node is None:
            tx.remove(self.data.merkle_tree, k)
            return EMPTY_HASH
        tx.insert(self.data.merkle_tree, k, pack(node))
        return node_hash(node)

    def root_hash(self, partition: int) -> bytes:
        return node_hash(self.get_node(partition, b""))

    # --- incremental update ----------------------------------------------------

    def update_item(self, key: bytes, value_hash: bytes) -> None:
        """Apply one merkle_todo item (value_hash = b'' means deleted)."""
        self.update_batch([(key, value_hash)])

    def update_batch(self, items: list[tuple[bytes, bytes]]) -> None:
        """Apply a batch of todo items in ONE transaction: the per-commit
        cost (sqlite journal round-trip, native/log WAL frame + fsync)
        dominates the trie walk, so draining 100 items per commit instead
        of one is a ~100x cut in commit overhead under write load."""

        def txf(tx: Tx):
            for key, value_hash in items:
                partition = self.data.replication.partition_of(key[:32])
                self._update_rec(tx, partition, b"", key, value_hash or None)
            return None

        self.data.db.transaction(txf)

    def _update_rec(
        self, tx: Tx, partition: int, prefix: bytes, key: bytes, vhash: bytes | None
    ) -> bytes:
        """Insert/update/delete `key` under node at `prefix`; returns the
        node's new hash."""
        node = self.get_node(partition, prefix, tx)
        depth = len(prefix)
        if node is None:
            if vhash is None:
                return EMPTY_HASH
            return self._put_node(tx, partition, prefix, ["L", key, vhash])
        if node[0] == "L":
            lkey, lhash = bytes(node[1]), bytes(node[2])
            if lkey == key:
                if vhash is None:
                    return self._put_node(tx, partition, prefix, None)
                return self._put_node(tx, partition, prefix, ["L", key, vhash])
            if vhash is None:
                return node_hash(node)  # deleting an absent key: no-op
            # split: push the existing leaf down (or into the term slot if
            # it ends here), then insert the new key
            if len(lkey) == depth:
                inter = ["I", [], [lkey, lhash]]
            else:
                cb = lkey[depth]
                ch = self._put_node(
                    tx, partition, prefix + bytes([cb]), ["L", lkey, lhash]
                )
                inter = ["I", [[cb, ch]], None]
            self._put_node(tx, partition, prefix, inter)
            return self._update_rec(tx, partition, prefix, key, vhash)
        # intermediate
        children = {int(c): bytes(h) for c, h in node[1]}
        term = node[2]
        if len(key) == depth:
            term = None if vhash is None else [key, vhash]
        else:
            b = key[depth]
            ch = self._update_rec(tx, partition, prefix + bytes([b]), key, vhash)
            if ch == EMPTY_HASH:
                children.pop(b, None)
            else:
                children[b] = ch
        # restore the canonical-shape invariant (0 keys -> empty, 1 -> leaf)
        if not children:
            if term is None:
                return self._put_node(tx, partition, prefix, None)
            return self._put_node(
                tx, partition, prefix, ["L", bytes(term[0]), bytes(term[1])]
            )
        if len(children) == 1 and term is None:
            ((only_b, _h),) = children.items()
            child = self.get_node(partition, prefix + bytes([only_b]), tx)
            if child is not None and child[0] == "L":
                self._put_node(tx, partition, prefix + bytes([only_b]), None)
                return self._put_node(
                    tx, partition, prefix, ["L", bytes(child[1]), bytes(child[2])]
                )
        return self._put_node(
            tx,
            partition,
            prefix,
            ["I", [[c, children[c]] for c in sorted(children)], term],
        )


class MerkleWorker(Worker):
    """Drains merkle_todo into the trie (reference merkle.rs:79-)."""

    def __init__(self, updater: MerkleUpdater):
        self.updater = updater
        self.data = updater.data

    def name(self) -> str:
        return f"merkle:{self.data.schema.table_name}"

    def status(self):
        return {"todo": len(self.data.merkle_todo)}

    async def work(self) -> WorkerState:
        batch: list[tuple[bytes, bytes]] = []
        for key, vhash in self.data.merkle_todo.iter_range():
            batch.append((key, vhash))
            if len(batch) >= 100:
                break
        if not batch:
            return WorkerState.IDLE
        self.updater.update_batch(batch)
        todo = self.data.merkle_todo

        def clear(tx):
            # only clear todos that weren't superseded while we applied
            for key, vhash in batch:
                if tx.get(todo, key) == vhash:
                    tx.remove(todo, key)

        self.data.db.transaction(clear)
        return WorkerState.BUSY
