"""Incremental per-partition Merkle trie (reference src/table/merkle.rs).

A sparse 256-ary patricia-style trie over entry tree-keys, one root per
sync partition.  Nodes (stored in the `<name>:merkle_tree` db tree, keyed
`[partition u8] || prefix bytes`):

  None                       empty
  ["L", key, value_hash]     leaf: entry `key` with blake2(serialized value)
  ["I", [[byte, child_hash], ...], term]
      intermediate: children at prefix+byte, plus an optional `term` =
      [key, value_hash] for the single key that ENDS exactly at this
      prefix (sort keys have variable length, so one tree key may be a
      strict prefix of another)

Canonical shape invariant (content-addressed: equal key sets => equal
trees): a prefix holding 0 keys stores nothing, 1 key stores a leaf,
>= 2 keys stores an intermediate.

node_hash = blake2(msgpack(node)); parent references child by hash so any
difference propagates to the root — two replicas with equal roots hold
bit-identical partitions.  The MerkleWorker consumes `merkle_todo`
(key -> new value hash, b"" = deleted) in batches: up to 100 items are
applied in one transaction, then their todos cleared (supersession-
checked) in a second — per-commit cost, not the trie walk, dominates.
"""

from __future__ import annotations

import logging
from typing import Any

from ..db import Tx
from ..utils.background import Worker, WorkerState
from ..utils.data import blake2sum
from ..utils.serde import pack, unpack
from .data import TableData

logger = logging.getLogger("garage.table.merkle")

EMPTY_HASH = b"\x00" * 32


def node_hash(node: Any) -> bytes:
    if node is None:
        return EMPTY_HASH
    return blake2sum(pack(node))


class MerkleUpdater:
    def __init__(self, data: TableData):
        self.data = data

    # --- node storage ---------------------------------------------------------

    def _nk(self, partition: int, prefix: bytes) -> bytes:
        return bytes([partition]) + prefix

    def get_node(self, partition: int, prefix: bytes, tx: Tx | None = None) -> Any:
        raw = (
            tx.get(self.data.merkle_tree, self._nk(partition, prefix))
            if tx
            else self.data.merkle_tree.get(self._nk(partition, prefix))
        )
        return None if raw is None else unpack(raw)

    def root_hash(self, partition: int) -> bytes:
        return node_hash(self.get_node(partition, b""))

    # --- incremental update ----------------------------------------------------

    def update_item(self, key: bytes, value_hash: bytes) -> None:
        """Apply one merkle_todo item (value_hash = b'' means deleted)."""
        self.update_batch([(key, value_hash)])

    def drain_batch(self, items: list[tuple[bytes, bytes]]) -> None:
        """update_batch + supersession-checked todo clearing in the SAME
        transaction (ISSUE 15): the worker used to commit twice per
        batch — once to apply, once to clear — and on the sqlite engine
        the per-commit cost (WAL frame + journal round-trip) is the
        dominant term once the trie walk itself is batched.  Clearing
        inside the apply transaction halves the commits; the
        supersession check (only remove a todo whose value is still the
        one we applied) keeps the contract that a concurrent
        update_entry's newer todo survives the drain."""

        def txf(tx: Tx):
            self._apply_in_tx(tx, items)
            todo = self.data.merkle_todo
            for key, value_hash in items:
                if tx.get(todo, key) == value_hash:
                    tx.remove(todo, key)
            return None

        self.data.db.transaction(txf)

    def _apply_in_tx(self, tx: Tx, items: list[tuple[bytes, bytes]]) -> None:
        ctx = _BatchCtx(self, tx)
        for key, value_hash in items:
            partition = self.data.replication.partition_of(key[:32])
            ctx.apply(partition, b"", key, value_hash or None)
        ctx.flush()

    def update_batch(self, items: list[tuple[bytes, bytes]]) -> None:
        """Apply a batch of todo items in ONE transaction, hashing each
        touched node ONCE at the end.

        Two costs dominated the naive per-item walk: the per-commit cost
        (sqlite journal round-trip, WAL frame + fsync), and the trie walk
        itself — keys of one bucket share their full 32-byte partition
        hash, so every update descends a ~35-deep single-child chain and
        the per-item version re-packed + re-hashed that whole chain per
        item (~42 node visits each).  Here all items are first applied
        STRUCTURALLY against an in-memory node cache (child hashes marked
        dirty, not recomputed), then one bottom-up flush pack+hashes each
        dirty node exactly once — a 100-item single-bucket batch does
        ~135 hashes instead of ~4200."""

        def txf(tx: Tx):
            self._apply_in_tx(tx, items)
            return None

        self.data.db.transaction(txf)

_DIRTY = object()  # child-hash sentinel: recomputed at flush


def _term_eq(a, b) -> bool:
    if a is None or b is None:
        return a is None and b is None
    return bytes(a[0]) == bytes(b[0]) and bytes(a[1]) == bytes(b[1])


class _BatchCtx:
    """Structural batch application over a node cache.

    Working nodes are mutable: ["L", key, vhash] or ["I", {byte: hash or
    _DIRTY}, term].  `apply` edits structure only, marking touched child
    hashes _DIRTY; `flush` then walks dirty prefixes longest-first so
    every node is packed + hashed exactly once, children before parents.
    The on-disk encoding (and therefore every node hash and root hash) is
    bit-identical to what per-item application produces — a mixed-version
    cluster syncs cleanly."""

    def __init__(self, updater: MerkleUpdater, tx: Tx):
        self.u = updater
        self.tx = tx
        self.nodes: dict[tuple[int, bytes], Any] = {}
        self.dirty: set[tuple[int, bytes]] = set()
        self.hashes: dict[tuple[int, bytes], bytes] = {}

    def get(self, partition: int, prefix: bytes) -> Any:
        k = (partition, prefix)
        if k in self.nodes:
            return self.nodes[k]
        node = self.u.get_node(partition, prefix, self.tx)
        if node is not None and node[0] == "I":
            node = ["I", {int(c): bytes(h) for c, h in node[1]}, node[2]]
        elif node is not None:
            node = ["L", bytes(node[1]), bytes(node[2])]
        self.nodes[k] = node
        return node

    def set(self, partition: int, prefix: bytes, node: Any) -> None:
        k = (partition, prefix)
        self.nodes[k] = node
        self.dirty.add(k)

    def apply(
        self, partition: int, prefix: bytes, key: bytes, vhash: bytes | None
    ) -> tuple[bool, bool]:
        """Insert/update/delete `key` under `prefix`; returns
        (non-empty-afterwards, changed).  `changed=False` paths — deletes
        of absent keys, idempotent re-applies — must not dirty the node:
        a dirtied-but-never-set child would crash flush's hash lookup,
        and a no-op delete would otherwise re-pack+re-hash the whole
        ~35-deep shared-prefix chain for nothing."""
        node = self.get(partition, prefix)
        depth = len(prefix)
        if node is None:
            if vhash is None:
                return (False, False)
            self.set(partition, prefix, ["L", key, vhash])
            return (True, True)
        if node[0] == "L":
            lkey, lhash = node[1], node[2]
            if lkey == key:
                if vhash is None:
                    self.set(partition, prefix, None)
                    return (False, True)
                if vhash == lhash:
                    return (True, False)  # idempotent re-apply
                self.set(partition, prefix, ["L", key, vhash])
                return (True, True)
            if vhash is None:
                return (True, False)  # deleting an absent key: no-op
            # split: push the existing leaf down (or into the term slot if
            # it ends here), then insert the new key
            if len(lkey) == depth:
                inter = ["I", {}, [lkey, lhash]]
            else:
                cb = lkey[depth]
                self.set(partition, prefix + bytes([cb]), ["L", lkey, lhash])
                inter = ["I", {cb: _DIRTY}, None]
            self.set(partition, prefix, inter)
            self.apply(partition, prefix, key, vhash)
            return (True, True)
        # intermediate
        children, term = node[1], node[2]
        changed = False
        if len(key) == depth:
            new_term = None if vhash is None else [key, vhash]
            if _term_eq(term, new_term):
                return (True, False)
            term = new_term
            changed = True
        else:
            b = key[depth]
            nonempty, child_changed = self.apply(
                partition, prefix + bytes([b]), key, vhash
            )
            if not nonempty:
                if b in children:
                    del children[b]
                    changed = True
            elif child_changed:
                children[b] = _DIRTY
                changed = True
        if not changed:
            return (True, False)
        # restore the canonical-shape invariant (0 keys -> empty, 1 -> leaf)
        if not children:
            if term is None:
                self.set(partition, prefix, None)
                return (False, True)
            self.set(partition, prefix, ["L", bytes(term[0]), bytes(term[1])])
            return (True, True)
        if len(children) == 1 and term is None:
            (only_b,) = children.keys()
            child = self.get(partition, prefix + bytes([only_b]))
            if child is not None and child[0] == "L":
                self.set(partition, prefix + bytes([only_b]), None)
                self.set(partition, prefix, ["L", child[1], child[2]])
                return (True, True)
        self.set(partition, prefix, ["I", children, term])
        return (True, True)

    def _child_hash(self, partition: int, prefix: bytes, stored) -> bytes:
        if stored is not _DIRTY:
            return stored
        # dirty children sort after their parent in the flush order, so
        # their hash is always computed by the time the parent packs
        return self.hashes[(partition, prefix)]

    def flush(self) -> None:
        """Write + hash every dirty node once, children before parents."""
        for part, prefix in sorted(
            self.dirty, key=lambda k: len(k[1]), reverse=True
        ):
            node = self.nodes[(part, prefix)]
            k = self.u._nk(part, prefix)
            if node is None:
                self.tx.remove(self.u.data.merkle_tree, k)
                self.hashes[(part, prefix)] = EMPTY_HASH
                continue
            if node[0] == "I":
                enc = [
                    "I",
                    [
                        [b, self._child_hash(part, prefix + bytes([b]), node[1][b])]
                        for b in sorted(node[1])
                    ],
                    node[2],
                ]
            else:
                enc = node
            packed = pack(enc)
            self.tx.insert(self.u.data.merkle_tree, k, packed)
            self.hashes[(part, prefix)] = blake2sum(packed)


class MerkleWorker(Worker):
    """Drains merkle_todo into the trie (reference merkle.rs:79-)."""

    def __init__(self, updater: MerkleUpdater):
        self.updater = updater
        self.data = updater.data

    def name(self) -> str:
        return f"merkle:{self.data.schema.table_name}"

    def status(self):
        return {"todo": len(self.data.merkle_todo)}

    BATCH = 256  # todo items drained per transaction (one trie flush)

    async def work(self) -> WorkerState:
        batch: list[tuple[bytes, bytes]] = []
        for key, vhash in self.data.merkle_todo.iter_range():
            batch.append((key, vhash))
            if len(batch) >= self.BATCH:
                break
        if not batch:
            return WorkerState.IDLE
        # one transaction: structural batch apply, single bottom-up
        # hash flush, supersession-checked todo clear
        self.updater.drain_batch(batch)
        return WorkerState.BUSY
