"""Table: quorum reads/writes over TableData + RPC endpoint.

Reference src/table/table.rs:36-139.  RPC ops (one endpoint per table):
  ["U",  [values...]]                       replicate serialized entries
  ["RE", pk, sk]                            read one entry
  ["RR", pk, start_sk, filt, limit, rev]    read a range
"""

from __future__ import annotations

import logging
from typing import Any

from ..db import Db
from ..net.message import PRIO_BACKGROUND, PRIO_NORMAL, Req, Resp
from ..rpc.rpc_helper import RpcHelper
from ..rpc.system import System
from ..utils.background import BackgroundRunner, spawn
from ..utils.error import Quorum
from ..utils.metrics import registry
from ..utils.serde import pack
from .coalesce import InsertCoalescer
from .data import TableData
from .gc import TableGc
from .merkle import MerkleUpdater, MerkleWorker
from .queue import InsertQueueWorker
from .replication import TableReplication
from .schema import TableSchema
from .sync import TableSyncer

logger = logging.getLogger("garage.table")


class Table:
    def __init__(
        self,
        system: System,
        helper: RpcHelper,
        db: Db,
        schema: TableSchema,
        replication: TableReplication,
    ):
        self.system = system
        self.helper = helper
        self.schema = schema
        self.replication = replication
        self.data = TableData(db, schema, replication)
        self.merkle = MerkleUpdater(self.data)
        self.endpoint = system.netapp.endpoint(f"table/{schema.table_name}")
        self.endpoint.set_handler(self._handle)
        self.syncer = TableSyncer(self)
        self.gc = TableGc(self)
        # cross-caller insert coalescing (ISSUE 15, table/coalesce.py):
        # None = direct per-call quorum writes.  The composition root
        # enables it from `[meta] coalesce_*` via enable_coalescing().
        self.coalescer: InsertCoalescer | None = None
        # per-table op metrics (reference src/table/metrics.rs:
        # table_get/put_request_counter+duration, internal update counter)
        self._mlbl = (("table_name", schema.table_name),)

    def enable_coalescing(
        self, *, linger_msec: float = 1.0, max_entries: int = 256
    ) -> InsertCoalescer:
        self.coalescer = InsertCoalescer(
            self, linger_msec=linger_msec, max_entries=max_entries
        )
        return self.coalescer

    async def close(self) -> None:
        if self.coalescer is not None:
            await self.coalescer.close()

    def spawn_workers(self, bg: BackgroundRunner) -> None:
        bg.spawn(MerkleWorker(self.merkle))
        bg.spawn(self.syncer.worker())
        bg.spawn(self.gc.worker())
        bg.spawn(InsertQueueWorker(self))

    # --- writes ---------------------------------------------------------------

    async def insert(self, entry) -> None:
        await self.insert_many([entry])

    async def insert_many(self, entries: list) -> None:
        """Quorum write: group by placement hash, write each group to every
        active layout version's node set (reference table.rs:106-139)."""
        from ..utils.tracing import span

        registry.incr("table_put_request_counter", self._mlbl)
        with span("table:insert", table=self.schema.table_name, n=len(entries)):
            with registry.timer("table_put_request_duration", self._mlbl):
                await self._insert_many(entries)

    async def _insert_many(self, entries: list) -> None:
        by_sets: dict[
            bytes, tuple[list[list[bytes]], list[bytes], set[bytes]]
        ] = {}
        for e in entries:
            pk = self.schema.entry_partition_key(e)
            h = self.schema.partition_hash(pk)
            v = pack(self.schema.encode_entry(e))
            write_sets = self.replication.write_sets(h)
            # group by the exact per-version sets (not their union): quorum
            # is accounted per set, so two hashes may only share a batch if
            # their sets are identical
            key = pack([sorted(s) for s in write_sets])
            if key not in by_sets:
                by_sets[key] = (write_sets, [], set())
            by_sets[key][1].append(v)
            # non-quorum stripe holders (block_ref only): best-effort
            # background copies so their rc trees see the block promptly
            by_sets[key][2].update(self.replication.background_nodes(h))
        if self.coalescer is not None:
            # cross-caller path: same-destination groups from concurrent
            # insert_many calls share one ["U", values] RPC per node
            await self.coalescer.submit(
                [
                    (k, ws, vals, extra)
                    for k, (ws, vals, extra) in by_sets.items()
                ]
            )
            return
        for write_sets, values, extra in by_sets.values():
            await self.helper.try_write_many_sets(
                self.endpoint,
                write_sets,
                ["U", values],
                quorum=self.replication.write_quorum(),
            )
            self.replicate_background(extra, values)

    def replicate_background(
        self, nodes: set[bytes] | list[bytes], values: list[bytes]
    ) -> None:
        """Fire-and-forget ["U", values] to non-quorum storage nodes
        (TableReplication.background_nodes).  call_many returns per-node
        exceptions as data, so a dead holder costs nothing; anti-entropy
        repairs whatever these misses leave behind."""
        if not nodes:
            return
        registry.incr(
            "table_background_replicate_total", self._mlbl, by=len(nodes)
        )
        spawn(
            self.helper.call_many(
                self.endpoint, list(nodes), ["U", values], prio=PRIO_BACKGROUND
            )
        )

    def queue_insert(self, entry, tx=None) -> None:
        """Asynchronous local insert (reference table/queue.rs): cheap,
        batched into quorum writes by the InsertQueueWorker."""
        self.data.queue_insert(entry, tx=tx)

    # --- reads ----------------------------------------------------------------

    async def get(self, pk: bytes, sk: bytes):
        from ..utils.tracing import span

        registry.incr("table_get_request_counter", self._mlbl)
        with span("table:get", table=self.schema.table_name):
            with registry.timer("table_get_request_duration", self._mlbl):
                return await self._get(pk, sk)

    def _race_reads(self, nodes: list[bytes], quorum: int) -> bool:
        """Meta-ring reads (3 candidates, quorum 2) RACE the whole
        ring: the surplus request is one tiny frame, and the quorum
        completes on the FASTEST repliers instead of the ones the
        preference order happened to pick — a straight latency cut on
        the index_read path.  Wide candidate sets keep the staggered
        probe, which exists to keep read traffic off far nodes."""
        return len(nodes) <= quorum + 1

    async def _get(self, pk: bytes, sk: bytes):
        h = self.schema.partition_hash(pk)
        nodes = self.replication.read_nodes(h)
        quorum = self.replication.read_quorum()
        resps = await self.helper.try_call_many(
            self.endpoint,
            nodes,
            ["RE", pk, sk],
            quorum=quorum,
            all_at_once=self._race_reads(nodes, quorum),
        )
        values = [r.body for r in resps]
        ent = None
        n_some = 0
        for v in values:
            if v is not None:
                n_some += 1
                dec = self.data.decode(v)
                ent = dec if ent is None else self.schema.merge_entries(ent, dec)
        if ent is not None and (n_some < len(values) or _differ(values)):
            # read-repair: push the merged value back to stale replicas
            spawn(self._repair([ent], nodes))
        return ent

    async def get_merged_all(self, pk: bytes, sk: bytes):
        """Inconsistency-escalation read: merge THIS key from EVERY
        reachable replica — no quorum short-circuit — and read-repair
        the merge back.  Used when a quorum read surfaced a state that
        contradicts another table (e.g. an object row resolving a
        tombstoned version, tests/test_put_abort_race.py): the row that
        explains it may exist only on the replica the staggered quorum
        read never consulted.  Requires at least read_quorum replies (a
        weaker answer could go BACKWARD vs. the quorum read that
        triggered the escalation)."""
        registry.incr("table_get_request_counter", self._mlbl)
        h = self.schema.partition_hash(pk)
        nodes = self.replication.read_nodes(h)
        results = await self.helper.call_many(
            self.endpoint, nodes, ["RE", pk, sk]
        )
        values = [r.body for _n, r in results if not isinstance(r, Exception)]
        if len(values) < self.replication.read_quorum():
            errs = [
                f"{n.hex()[:8]}: {r!r}"
                for n, r in results
                if isinstance(r, Exception)
            ]
            raise Quorum(self.replication.read_quorum(), len(values), errs)
        ent = None
        n_some = 0
        for v in values:
            if v is not None:
                n_some += 1
                dec = self.data.decode(v)
                ent = dec if ent is None else self.schema.merge_entries(ent, dec)
        if ent is not None and (n_some < len(values) or _differ(values)):
            spawn(self._repair([ent], nodes))
        return ent

    async def get_range(
        self,
        pk: bytes,
        start_sk: bytes | None = None,
        filt: Any = None,
        limit: int = 1000,
        reverse: bool = False,
    ) -> list:
        registry.incr("table_range_request_counter", self._mlbl)
        h = self.schema.partition_hash(pk)
        nodes = self.replication.read_nodes(h)
        quorum = self.replication.read_quorum()
        with registry.timer("table_range_request_duration", self._mlbl):
            resps = await self.helper.try_call_many(
                self.endpoint,
                nodes,
                ["RR", pk, start_sk, filt, limit, reverse],
                quorum=quorum,
                all_at_once=self._race_reads(nodes, quorum),
            )
        merged: dict[bytes, Any] = {}
        seen_values: dict[bytes, set[bytes]] = {}
        for r in resps:
            for v in r.body:
                ent = self.data.decode(v)
                sk = self.schema.entry_sort_key(ent)
                if sk in merged:
                    merged[sk] = self.schema.merge_entries(merged[sk], ent)
                else:
                    merged[sk] = ent
                seen_values.setdefault(sk, set()).add(bytes(v))
        if len(resps) > 1:
            to_repair = [
                merged[sk]
                for sk, vals in seen_values.items()
                if len(vals) > 1
            ]
            if to_repair:
                spawn(self._repair(to_repair, nodes))
        out = sorted(merged.items(), key=lambda kv: kv[0], reverse=reverse)
        ents = [e for _sk, e in out if self.schema.matches_filter(e, filt)]
        return ents[:limit]

    async def get_all_local(self, filt: Any = None, limit: int = 100_000) -> list:
        """Enumerate ALL local entries across partitions.  Correct for
        full-copy tables (every node holds everything) — the control-plane
        list operations (buckets, keys, aliases) use this; a per-partition
        get_range cannot enumerate tables whose partition key is the
        entry id itself."""
        out = []
        for _k, v in self.data.store.iter_range():
            ent = self.data.decode(v)
            if self.schema.matches_filter(ent, filt):
                out.append(ent)
                if len(out) >= limit:
                    break
        return out

    async def get_local(self, pk: bytes, sk: bytes):
        """Read THIS replica's copy only — no quorum, no read-repair.
        For replica-side handlers (e.g. K2V polls) where this node is
        itself one of the replicas being polled."""
        v = self.data.read_entry(pk, sk)
        return self.data.decode(v) if v is not None else None

    async def get_range_local(
        self,
        pk: bytes,
        start_sk: bytes | None = None,
        filt: Any = None,
        limit: int = 1000,
    ) -> list:
        vals = self.data.read_range(pk, start_sk, filt, limit, False)
        return [self.data.decode(v) for v in vals]

    async def _repair(self, entries: list, nodes: list[bytes]) -> None:
        try:
            values = [pack(self.schema.encode_entry(e)) for e in entries]
            await self.helper.try_call_many(
                self.endpoint,
                nodes,
                ["U", values],
                quorum=len(nodes),
                prio=PRIO_NORMAL,
            )
        except Exception as e:  # noqa: BLE001
            logger.debug("read-repair failed: %r", e)

    # --- rpc handler ----------------------------------------------------------

    async def _handle(self, from_id: bytes, req: Req) -> Resp:
        op = req.body
        if op[0] == "U":
            registry.incr(
                "table_internal_update_counter", self._mlbl, by=len(op[1])
            )
            for v in op[1]:
                self.data.update_entry(bytes(v))
            return Resp(None)
        if op[0] == "RE":
            return Resp(self.data.read_entry(bytes(op[1]), bytes(op[2])))
        if op[0] == "RR":
            vals = self.data.read_range(
                bytes(op[1]),
                bytes(op[2]) if op[2] is not None else None,
                op[3],
                int(op[4]),
                bool(op[5]),
            )
            return Resp(vals)
        raise ValueError(f"unknown table op {op[0]!r}")


def _differ(values: list) -> bool:
    norm = {bytes(v) for v in values if v is not None}
    return len(norm) > 1
