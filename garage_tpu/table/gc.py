"""3-phase tombstone garbage collection.

Reference src/table/gc.rs:33-120 and the safety argument in
doc/book/design/internals.md:79-128: a tombstone may only disappear once
every storage node holds it (otherwise a node that missed the deletion
could resurrect the entry via anti-entropy).  Therefore, after a 24 h
delay (tombstone was quorum-written long ago):

  1. push the tombstone value to ALL storage nodes — all must ack
  2. send DeleteIfEqualHash(key, value_hash) to ALL storage nodes — the
     delete is skipped anywhere the value changed in the meantime
  3. drop the gc_todo entry

RPC ops on `table/<name>/gc`:
  ["U", [values...]]   apply tombstone values
  ["D", [[key, value_hash]...]]   delete-if-equal-hash
"""

from __future__ import annotations

import asyncio
import logging

from ..net.message import PRIO_BACKGROUND, Req, Resp
from ..utils.background import Worker, WorkerState
from ..utils.data import blake2sum
from ..utils.time_util import now_msec

logger = logging.getLogger("garage.table.gc")

GC_BATCH = 32
RETRY_DELAY_MS = 10 * 60 * 1000  # failed GC retries in 10 min


class TableGc:
    def __init__(self, table):
        self.table = table
        self.data = table.data
        self.endpoint = table.system.netapp.endpoint(
            f"table/{table.schema.table_name}/gc"
        )
        self.endpoint.set_handler(self._handle)

    async def _handle(self, from_id: bytes, req: Req) -> Resp:
        op = req.body
        if op[0] == "U":
            for v in op[1]:
                self.data.update_entry(bytes(v))
            return Resp(None)
        if op[0] == "D":
            for k, vh in op[1]:
                self.data.delete_if_equal_hash(bytes(k), bytes(vh))
            return Resp(None)
        raise ValueError(f"unknown gc op {op[0]!r}")

    async def gc_round(self) -> int:
        """Collect one batch of due tombstones; returns number collected."""
        now = now_msec()
        batch: list[tuple[bytes, bytes, bytes]] = []  # (todo_key, key, vhash)
        for tk, vhash in self.data.gc_todo.iter_range():
            deadline = int.from_bytes(tk[:8], "big")
            if deadline > now:
                break
            key = tk[8:]
            cur = self.data.store.get(key)
            if cur is None or blake2sum(cur) != vhash:
                # entry changed or already gone: obsolete todo item
                self.data.gc_todo.remove(tk)
                continue
            batch.append((tk, key, bytes(vhash)))
            if len(batch) >= GC_BATCH:
                break
        if not batch:
            return 0

        # group by storage node set
        by_nodes: dict[tuple, list[tuple[bytes, bytes, bytes]]] = {}
        for tk, key, vhash in batch:
            nodes = tuple(self.table.replication.storage_nodes(key[:32]))
            by_nodes.setdefault(nodes, []).append((tk, key, vhash))

        collected = 0
        for nodes, items in by_nodes.items():
            values = [self.data.store.get(k) for _tk, k, _vh in items]
            values = [v for v in values if v is not None]
            try:
                # phase 1: every storage node must hold the tombstone
                await self._call_all(list(nodes), ["U", values])
                # phase 2: delete everywhere (incl. locally) if unchanged
                await self._call_all(
                    list(nodes), ["D", [[k, vh] for _tk, k, vh in items]]
                )
            except Exception as e:  # noqa: BLE001
                logger.debug("gc round failed, will retry: %r", e)
                for tk, key, vhash in items:
                    self.data.gc_todo.remove(tk)
                    retry_at = now_msec() + RETRY_DELAY_MS
                    self.data.gc_todo.insert(
                        retry_at.to_bytes(8, "big") + key, vhash
                    )
                continue
            # phase 3: forget
            for tk, _k, _vh in items:
                self.data.gc_todo.remove(tk)
            collected += len(items)
        return collected

    async def _call_all(self, nodes: list[bytes], msg) -> None:
        """All nodes must succeed (GC requires full acknowledgement)."""
        results = await self.table.helper.call_many(
            self.endpoint, nodes, msg, prio=PRIO_BACKGROUND, timeout=60.0
        )
        errs = [r for _n, r in results if isinstance(r, Exception)]
        if errs:
            raise errs[0]

    def worker(self) -> Worker:
        return _GcWorker(self)


class _GcWorker(Worker):
    def __init__(self, gc: TableGc):
        self.gc = gc

    def name(self) -> str:
        return f"gc:{self.gc.table.schema.table_name}"

    def status(self):
        return {"queued": len(self.gc.data.gc_todo)}

    async def work(self):
        n = await self.gc.gc_round()
        return WorkerState.BUSY if n else WorkerState.IDLE

    async def wait_for_work(self) -> None:
        await asyncio.sleep(60.0)
