"""InsertCoalescer: cross-caller coalescing of table quorum writes.

ISSUE 15, the second half of the metadata tentpole: once the meta ring
shrinks a table write to 3 nodes, the per-RPC fixed cost (frame
serialization, endpoint dispatch, per-peer health accounting) dominates
a burst of small inserts — N concurrent PUTs each commit an object row,
a version row and a block ref, and until now each row was its own
`try_write_many_sets` fan-out.  This module coalesces them the way the
CodecBatcher (block/codec_batch.py) coalesces codec dispatches:

  - concurrent `insert_many` calls queue their serialized entries keyed
    by DESTINATION — the exact per-version write-set list — and share
    ONE ``["U", values]`` RPC per node per flush window.  Same-key
    grouping is what makes this safe: quorum is accounted per layout
    version's node set, so only entries with identical write sets may
    share a dispatch (the same rule Table._insert_many always applied
    within one call; the coalescer extends it across callers);

  - a lone insert flushes after a bounded linger
    (``[meta] coalesce_linger_msec``, default 1 ms — noise against a
    quorum round-trip), while ``coalesce_max_entries`` flushes
    immediately; both live-tunable (`worker set meta-coalesce-*`);

  - a dispatch error fails every waiter that contributed to it (each
    caller sees the same Quorum error it would have seen alone); a
    cancelled caller abandons its entries without poisoning the batch.

Entries are CRDT values — merge is commutative and idempotent — so
batching across callers cannot change any merge outcome, only the RPC
count.  The caller-side wait until the dispatch launches is attributed
to the `meta_coalesce_wait` phase (utils/latency.py catalogue); the
dispatch itself stays inside the caller's enclosing `meta_commit` span
via the returned future.

Metric families (doc/monitoring.md):

  table_coalesce_batch_entries{table_name}     entries per dispatch (H)
  table_coalesce_dispatch_total{table_name,flush}  dispatches by flush
                                               reason (full | linger)
  table_coalesce_coalesced_total{table_name}   entries that shared a
                                               dispatch with another
                                               caller's entries
"""

from __future__ import annotations

import asyncio
import logging
import time

from ..utils.aio import reap, spawn_supervised
from ..utils.latency import phase_span
from ..utils.metrics import SIZE_BUCKETS, registry

logger = logging.getLogger("garage.table.coalesce")

registry.set_buckets("table_coalesce_batch_entries", SIZE_BUCKETS)


class _Group:
    """Entries bound for one exact write-set list, across callers."""

    __slots__ = (
        "write_sets", "values", "waiters", "arrived", "started", "extra",
    )

    def __init__(self, write_sets: list[list[bytes]]):
        self.write_sets = write_sets
        self.values: list[bytes] = []
        # one (future, n_entries) per contributing submit call
        self.waiters: list[tuple[asyncio.Future, int]] = []
        self.arrived = time.monotonic()
        # set when the dispatch launches (ends meta_coalesce_wait)
        self.started = asyncio.Event()
        # non-quorum stripe holders (background best-effort copies)
        self.extra: set[bytes] = set()


class InsertCoalescer:
    """One per Table.  The flusher task spawns lazily on first use and
    is reaped by `close()` (Garage.stop()); knobs are read on every
    flush cycle so `worker set` changes apply live."""

    def __init__(
        self,
        table,
        *,
        linger_msec: float = 1.0,
        max_entries: int = 256,
    ):
        self.table = table
        self.linger_msec = float(linger_msec)
        self.max_entries = int(max_entries)
        self.pending: dict[bytes, _Group] = {}
        self.wake = asyncio.Event()
        self.task: asyncio.Task | None = None
        self._dispatches: set[asyncio.Task] = set()
        self._closed = False
        self._lbl = (("table_name", table.schema.table_name),)

    # --- submit side ----------------------------------------------------------

    async def submit(
        self,
        groups: list[
            tuple[bytes, list[list[bytes]], list[bytes], set[bytes]]
        ],
    ) -> None:
        """`groups`: (destination key, write_sets, serialized values,
        background nodes) tuples from one insert_many call.  Returns once
        EVERY group's coalesced dispatch reached quorum; raises the
        first failure."""
        if self._closed:
            raise RuntimeError("insert coalescer is closed")
        loop = asyncio.get_running_loop()
        waits: list[tuple[_Group, asyncio.Future]] = []
        for key, write_sets, values, extra in groups:
            g = self.pending.get(key)
            if g is None:
                g = self.pending[key] = _Group(write_sets)
            fut = loop.create_future()
            g.values.extend(values)
            g.extra.update(extra)
            g.waiters.append((fut, len(values)))
            waits.append((g, fut))
        self.wake.set()
        if self.task is None:
            self.task = spawn_supervised(
                self._run(),
                name=f"table-coalesce:{self.table.schema.table_name}",
            )
        try:
            with phase_span("meta_coalesce_wait"):
                for g, _fut in waits:
                    await g.started.wait()
            # the dispatch itself: stays in the caller's enclosing
            # phase (meta_commit), like a direct quorum write would
            await asyncio.gather(*[f for _g, f in waits])
        except asyncio.CancelledError:
            # abandon: the dispatch (if launched) completes for the
            # other contributors; _dispatch skips finished futures.
            # A future that already FAILED must have its exception
            # retrieved here (cancel() is a no-op on a done future, and
            # an unretrieved exception logs noise at GC).
            for _g, f in waits:
                if f.done():
                    if not f.cancelled():
                        f.exception()
                else:
                    f.cancel()
            raise

    # --- flusher --------------------------------------------------------------

    def _due(self, g: _Group, now: float) -> bool:
        return (
            len(g.values) >= self.max_entries
            or now - g.arrived >= self.linger_msec / 1e3
        )

    async def _run(self) -> None:
        while not self._closed:
            if not self.pending:
                self.wake.clear()
                if not self.pending:  # re-check after the clear
                    await self.wake.wait()
                continue
            now = time.monotonic()
            due = [k for k, g in self.pending.items() if self._due(g, now)]
            for k in due:
                g = self.pending.pop(k)
                flush = (
                    "full" if len(g.values) >= self.max_entries else "linger"
                )
                # dispatches run concurrently per destination group; the
                # flusher never awaits one (a slow quorum must not stall
                # the next window's coalescing).  Handles are kept so
                # close() can reap an in-flight dispatch.
                t = spawn_supervised(
                    self._dispatch(g, flush),
                    name=f"table-coalesce-rpc:{self.table.schema.table_name}",
                )
                self._dispatches.add(t)
                t.add_done_callback(self._dispatches.discard)
            if self.pending:
                head = min(g.arrived for g in self.pending.values())
                delay = max(0.0, head + self.linger_msec / 1e3 - now)
                self.wake.clear()
                try:
                    await asyncio.wait_for(self.wake.wait(), delay)
                except asyncio.TimeoutError:
                    pass

    async def _dispatch(self, g: _Group, flush: str) -> None:
        g.started.set()
        live = [(f, n) for f, n in g.waiters if not f.done()]
        registry.observe(
            "table_coalesce_batch_entries", self._lbl, float(len(g.values))
        )
        registry.incr(
            "table_coalesce_dispatch_total", self._lbl + (("flush", flush),)
        )
        if len(live) > 1:
            registry.incr(
                "table_coalesce_coalesced_total", self._lbl,
                by=len(g.values),
            )
        table = self.table
        try:
            await table.helper.try_write_many_sets(
                table.endpoint,
                g.write_sets,
                ["U", g.values],
                quorum=table.replication.write_quorum(),
            )
        except Exception as e:  # noqa: BLE001 — fails THIS batch's waiters
            for f, _n in g.waiters:
                if not f.done():
                    f.set_exception(e)
            return
        except BaseException:
            # dispatch task cancelled mid-quorum (close() during node
            # stop): this group already left `pending`, so close() can't
            # fail its futures — do it here or every contributing caller
            # hangs forever on its future
            for f, _n in g.waiters:
                if not f.done():
                    f.set_exception(
                        RuntimeError("insert coalescer closed mid-dispatch")
                    )
            raise
        for f, _n in g.waiters:
            if not f.done():
                f.set_result(None)
        # the quorum held: ship the non-quorum stripe holders their
        # best-effort copies (block_ref rc feed; anti-entropy backstop)
        table.replicate_background(g.extra, g.values)

    async def close(self) -> None:
        """Fail pending waiters and reap the flusher (codec-batcher
        close contract: resources registered at creation are released
        here)."""
        self._closed = True
        self.wake.set()
        for g in self.pending.values():
            g.started.set()
            for f, _n in g.waiters:
                if not f.done():
                    f.set_exception(RuntimeError("insert coalescer closed"))
        self.pending.clear()
        if self.task is not None:
            await reap(
                [self.task], log=logger,
                what=f"table-coalesce {self.table.schema.table_name} flusher",
            )
            self.task = None
        if self._dispatches:
            await reap(
                list(self._dispatches), log=logger,
                what=f"table-coalesce {self.table.schema.table_name} dispatch",
            )
            self._dispatches.clear()
