"""Table schema: how entries are keyed, encoded, merged and reacted to.

Reference src/table/schema.rs:72-93.  Entries are CRDT objects; the
`updated` hook runs INSIDE the storage transaction that changed the entry,
so reactive cascades (object -> version -> block_ref -> rc) are atomic
with the write that triggered them.
"""

from __future__ import annotations

from typing import Any

from ..db import Tx
from ..utils.data import blake2sum


class TableSchema:
    table_name: str = ""

    # --- keys ---------------------------------------------------------------

    def entry_partition_key(self, entry) -> bytes:
        raise NotImplementedError

    def entry_sort_key(self, entry) -> bytes:
        raise NotImplementedError

    def partition_hash(self, pk: bytes) -> bytes:
        """Placement hash of a partition key."""
        return blake2sum(pk)

    def tree_key(self, pk: bytes, sk: bytes) -> bytes:
        """Local storage key: hash(pk) || sk (reference table/data.rs)."""
        return self.partition_hash(pk) + sk

    # --- encoding -----------------------------------------------------------

    def encode_entry(self, entry) -> Any:
        return entry.to_obj()

    def decode_entry(self, obj: Any):
        raise NotImplementedError

    # --- semantics ----------------------------------------------------------

    def merge_entries(self, a, b):
        """CRDT merge (in place on a, returns a)."""
        a.merge(b)
        return a

    def is_tombstone(self, entry) -> bool:
        """Tombstones are GC'd by the 3-phase protocol."""
        return False

    def matches_filter(self, entry, filt) -> bool:
        return True

    def updated(self, tx: Tx, old, new) -> None:
        """Reactive hook, called inside the update transaction."""
