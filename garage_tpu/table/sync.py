"""Anti-entropy: Merkle-diff sync + partition offload.

Reference src/table/sync.rs:31-627.  Periodically (and on layout change),
for every partition this node stores, compare Merkle roots with the other
storage nodes and push items under diverging subtrees.  Partitions this
node no longer owns are fully pushed to their new owners, then deleted
locally ("offload").

RPC ops on `table/<name>/sync`:
  ["Root", partition]          -> root hash
  ["Node", partition, prefix]  -> merkle node
  ["Items", [values...]]       -> CRDT-apply serialized entries
"""

from __future__ import annotations

import asyncio
import logging
import time

from ..net.message import PRIO_BACKGROUND, Req, Resp
from ..utils.background import Worker, WorkerState
from ..utils.metrics import registry

logger = logging.getLogger("garage.table.sync")

ANTI_ENTROPY_INTERVAL = 600.0  # 10 min (reference sync.rs:31)
ITEMS_BATCH = 64


class TableSyncer:
    def __init__(self, table):
        self.table = table
        self.data = table.data
        self.merkle = table.merkle
        self.endpoint = table.system.netapp.endpoint(
            f"table/{table.schema.table_name}/sync"
        )
        self.endpoint.set_handler(self._handle)
        self._layout_changed = asyncio.Event()
        # runtime-tunable via `worker set sync-interval-secs` (BgVars)
        self.anti_entropy_interval = ANTI_ENTROPY_INTERVAL
        table.system.layout_manager.subscribe(self._on_layout_change)
        table.system.layout_manager.register_sync_component(
            f"table:{table.schema.table_name}"
        )

    def _on_layout_change(self) -> None:
        self._layout_changed.set()

    # --- rpc ------------------------------------------------------------------

    async def _handle(self, from_id: bytes, req: Req) -> Resp:
        op = req.body
        if op[0] == "Root":
            return Resp(self.merkle.root_hash(int(op[1])))
        if op[0] == "Node":
            return Resp(self.merkle.get_node(int(op[1]), bytes(op[2])))
        if op[0] == "Items":
            registry.incr(
                "table_sync_items_received",
                (("table_name", self.table.schema.table_name),),
                by=len(op[1]),
            )
            for v in op[1]:
                self.data.update_entry(bytes(v))
            return Resp(None)
        raise ValueError(f"unknown sync op {op[0]!r}")

    # --- sync round -----------------------------------------------------------

    async def sync_all_partitions(self) -> dict:
        """One full anti-entropy round; returns stats."""
        me = self.table.system.id
        stats = {"partitions": 0, "pushed": 0, "offloaded": 0, "errors": 0}
        owned = {p for p, _ in self.table.replication.local_partitions(me)}
        for p in sorted(owned):
            stats["partitions"] += 1
            nodes = self._partition_nodes(p)
            for node in nodes:
                if node == me:
                    continue
                try:
                    stats["pushed"] += await self._sync_with(p, node)
                except Exception as e:  # noqa: BLE001
                    stats["errors"] += 1
                    logger.debug("sync p%d with %s failed: %r", p, node.hex()[:8], e)
        # offload: local data in partitions we don't own
        await self._offload(owned, stats)
        return stats

    def _partition_nodes(self, p: int) -> list[bytes]:
        from .replication import partition_first_hash

        return self.table.replication.storage_nodes(partition_first_hash(p))

    async def _sync_with(self, p: int, node: bytes) -> int:
        my_root = self.merkle.root_hash(p)
        resp = await self.endpoint.call(
            node, ["Root", p], prio=PRIO_BACKGROUND, timeout=60.0
        )
        if bytes(resp.body or b"") == my_root:
            return 0
        return await self._push_diff(p, node, b"")

    async def _push_diff(self, p: int, node: bytes, prefix: bytes) -> int:
        """Push every local item under `prefix` whose remote counterpart is
        missing or different."""
        local = self.merkle.get_node(p, prefix)
        if local is None:
            return 0
        resp = await self.endpoint.call(
            node, ["Node", p, prefix], prio=PRIO_BACKGROUND, timeout=60.0
        )
        remote = resp.body
        from .merkle import node_hash

        if remote is not None and node_hash(remote) == node_hash(local):
            return 0
        if local[0] == "L":
            return await self._push_items(node, [bytes(local[1])])
        # intermediate: recurse into children; push term item if present
        pushed = 0
        if local[2] is not None:
            pushed += await self._push_items(node, [bytes(local[2][0])])
        for b, _h in local[1]:
            pushed += await self._push_diff(p, node, prefix + bytes([int(b)]))
        return pushed

    async def _push_items(self, node: bytes, keys: list[bytes]) -> int:
        values = []
        for k in keys:
            v = self.data.store.get(k)
            if v is not None:
                values.append(v)
        for i in range(0, len(values), ITEMS_BATCH):
            batch = values[i : i + ITEMS_BATCH]
            await self.endpoint.call(
                node, ["Items", batch], prio=PRIO_BACKGROUND, timeout=60.0
            )
            # count per delivered batch, so a push that dies midway still
            # reports the items that actually reached the peer
            registry.incr(
                "table_sync_items_sent",
                (("table_name", self.table.schema.table_name),),
                by=len(batch),
            )
        return len(values)

    async def _offload(self, owned: set[int], stats: dict) -> None:
        """Push partitions we no longer own to their owners, delete local
        copy afterwards (reference sync.rs offload path)."""
        from .replication import partition_first_hash

        seen_parts: set[int] = set()
        for key, _v in self.data.store.iter_range():
            part = self.data.replication.partition_of(key[:32])
            if part in owned or part in seen_parts:
                continue
            seen_parts.add(part)
        from ..utils.data import blake2sum

        for p in sorted(seen_parts):
            nodes = self._partition_nodes(p)
            if not nodes:
                continue
            snapshot: list[tuple[bytes, bytes, bytes]] = []  # (key, value, vhash)
            start, end = self.data.partition_range(p)
            for k, v in self.data.store.iter_range(start, end):
                snapshot.append((k, v, blake2sum(v)))
            values = [v for _k, v, _h in snapshot]
            ok = True
            for node in nodes:
                try:
                    for i in range(0, len(values), ITEMS_BATCH):
                        await self.endpoint.call(
                            node,
                            ["Items", values[i : i + ITEMS_BATCH]],
                            prio=PRIO_BACKGROUND,
                            timeout=60.0,
                        )
                except Exception as e:  # noqa: BLE001
                    ok = False
                    stats["errors"] += 1
                    logger.debug("offload p%d to %s failed: %r", p, node.hex()[:8], e)
            if ok:
                # hash-checked transactional delete: an entry updated while
                # we were pushing (its value hash changed) is NOT deleted —
                # the new value was never pushed and would be lost; it goes
                # in the next offload round instead (reference
                # sync.rs offload_items / delete_if_equal)
                n_del = 0
                for k, _v, vh in snapshot:
                    if self.data.delete_if_equal_hash(k, vh):
                        n_del += 1
                stats["offloaded"] += n_del

    # --- worker ---------------------------------------------------------------

    def worker(self) -> Worker:
        return _SyncWorker(self)


class _SyncWorker(Worker):
    def __init__(self, syncer: TableSyncer):
        self.syncer = syncer
        self.last_sync = 0.0
        self.last_stats: dict = {}
        self._last_placement: bytes | None = None
        self._retry_backoff = 0.0  # grows while rounds keep failing

    def name(self) -> str:
        return f"sync:{self.syncer.table.schema.table_name}"

    def status(self):
        return dict(self.last_stats, last=self.last_sync)

    async def work(self):
        now = time.monotonic()
        lm = self.syncer.table.system.layout_manager
        due = now - self.last_sync >= self.syncer.anti_entropy_interval
        # placement digest captured BEFORE the round: a version applied
        # mid-round changes the live digest, so the next wakeup re-rounds
        placement = lm.history.placement_digest()
        if self.syncer._layout_changed.is_set():
            self.syncer._layout_changed.clear()
        # layout notifications also fire for tracker-only gossip
        # (ack/sync movement), which happens constantly under write
        # load; a full root-compare round (~512 RPCs/table) is only
        # warranted when the PLACEMENT changed.  Checked OUTSIDE the
        # event gate: a failed round leaves _last_placement stale, so
        # wakeups keep retrying until a round completes cleanly — with
        # exponential backoff so a long peer outage doesn't amplify
        # into back-to-back full rounds against the dead node
        if (
            placement != self._last_placement
            and now - self.last_sync >= self._retry_backoff
        ):
            due = True
        if not due:
            return WorkerState.IDLE
        self.last_sync = now
        # the round guarantees convergence only up to the version current
        # when it STARTED; a layout applied mid-round re-triggers via
        # _layout_changed, and the next round reports the newer version
        v0 = lm.history.current().version
        self.last_stats = await self.syncer.sync_all_partitions()
        if self.last_stats.get("errors", 0) == 0:
            # only a CLEAN round retires the trigger — a failed round
            # (partitioned peer) keeps retrying on subsequent wakeups
            # instead of stalling until the 10-minute interval
            self._last_placement = placement
            self._retry_backoff = 0.0
            lm.component_synced(
                f"table:{self.syncer.table.schema.table_name}", v0
            )
        else:
            self._retry_backoff = min(
                self._retry_backoff * 2 or 10.0, self.syncer.anti_entropy_interval
            )
        return WorkerState.IDLE

    async def wait_for_work(self) -> None:
        try:
            await asyncio.wait_for(self.syncer._layout_changed.wait(), timeout=10.0)
        except asyncio.TimeoutError:
            pass
