"""Async K2V client with SigV4 signing (reference src/k2v-client/)."""

from __future__ import annotations

import base64
import json
import urllib.parse

import aiohttp

from ..api.common.signature import sign_request_headers

TOKEN_HEADER = "X-Garage-Causality-Token"


class K2VError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(f"{status}: {message}")
        self.status = status


class K2VClient:
    def __init__(self, endpoint: str, bucket: str, key_id: str, secret: str, region="garage"):
        self.endpoint = endpoint.rstrip("/")
        self.bucket = bucket
        self.key_id = key_id
        self.secret = secret
        self.region = region
        self.host = urllib.parse.urlparse(self.endpoint).netloc
        self._session: aiohttp.ClientSession | None = None

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None

    def _sess(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession()
        return self._session

    async def _req(self, method, path, query=None, body=b"", headers=None, timeout=300):
        query = query or []
        h = dict(headers or {})
        h["host"] = self.host
        signed = sign_request_headers(
            method, path, query, h, body, self.key_id, self.secret, self.region
        )
        qs = urllib.parse.urlencode(query)
        url = self.endpoint + path + ("?" + qs if qs else "")
        async with self._sess().request(
            method, url, data=body, headers=signed,
            timeout=aiohttp.ClientTimeout(total=timeout),
        ) as resp:
            data = await resp.read()
            return resp.status, resp.headers.copy(), data

    # --- item ops -------------------------------------------------------------

    async def read_item(self, pk: str, sk: str) -> tuple[list[bytes], str]:
        """-> (values, causality_token)"""
        st, h, data = await self._req(
            "GET", f"/{self.bucket}/{urllib.parse.quote(pk, safe='')}/{urllib.parse.quote(sk, safe='')}", headers={"accept": "application/json"}
        )
        if st == 404:
            raise K2VError(404, "not found")
        if st != 200:
            raise K2VError(st, data.decode(errors="replace"))
        vals = [base64.b64decode(v) for v in json.loads(data)]
        return vals, h.get(TOKEN_HEADER, "")

    async def insert_item(self, pk: str, sk: str, value: bytes, token: str | None = None):
        headers = {TOKEN_HEADER.lower(): token} if token else {}
        st, _h, data = await self._req(
            "PUT", f"/{self.bucket}/{urllib.parse.quote(pk, safe='')}/{urllib.parse.quote(sk, safe='')}", body=value, headers=headers
        )
        if st not in (200, 204):
            raise K2VError(st, data.decode(errors="replace"))

    async def delete_item(self, pk: str, sk: str, token: str):
        st, _h, data = await self._req(
            "DELETE", f"/{self.bucket}/{urllib.parse.quote(pk, safe='')}/{urllib.parse.quote(sk, safe='')}", headers={TOKEN_HEADER.lower(): token}
        )
        if st not in (200, 204):
            raise K2VError(st, data.decode(errors="replace"))

    async def poll_item(self, pk: str, sk: str, token: str, timeout: float = 60):
        st, h, data = await self._req(
            "GET",
            f"/{self.bucket}/{urllib.parse.quote(pk, safe='')}/{urllib.parse.quote(sk, safe='')}",
            query=[("poll", ""), ("causality_token", token), ("timeout", str(timeout))],
            timeout=timeout + 30,
        )
        if st == 304:
            return None
        if st != 200:
            raise K2VError(st, data.decode(errors="replace"))
        return [base64.b64decode(v) for v in json.loads(data)], h.get(TOKEN_HEADER, "")

    async def poll_range(
        self,
        pk: str,
        seen_marker: str | None = None,
        start: str | None = None,
        end: str | None = None,
        prefix: str | None = None,
        timeout: float = 60,
    ):
        """-> ({sk: {"ct":…, "v":[bytes|None]}}, seen_marker) or None (304)."""
        body = {"timeout": timeout}
        if seen_marker is not None:
            body["seenMarker"] = seen_marker
        for k, v in (("start", start), ("end", end), ("prefix", prefix)):
            if v is not None:
                body[k] = v
        st, _h, data = await self._req(
            "POST",
            f"/{self.bucket}/{urllib.parse.quote(pk, safe='')}",
            query=[("poll_range", "")],
            body=json.dumps(body).encode(),
            timeout=timeout + 30,
        )
        if st == 304:
            return None
        if st != 200:
            raise K2VError(st, data.decode(errors="replace"))
        res = json.loads(data)
        items = {
            it["sk"]: {
                "ct": it["ct"],
                "v": [
                    base64.b64decode(v) if v is not None else None
                    for v in it["v"]
                ],
            }
            for it in res["items"]
        }
        return items, res["seenMarker"]

    # --- index + batch --------------------------------------------------------

    async def read_index(self, prefix: str = "", limit: int = 1000) -> dict:
        q = [("limit", str(limit))]
        if prefix:
            q.append(("prefix", prefix))
        st, _h, data = await self._req("GET", f"/{self.bucket}", query=q)
        if st != 200:
            raise K2VError(st, data.decode(errors="replace"))
        return json.loads(data)

    async def insert_batch(self, items: list[tuple[str, str, bytes, str | None]]):
        """items: [(pk, sk, value, token|None)]"""
        body = json.dumps(
            [
                {
                    "pk": pk,
                    "sk": sk,
                    "ct": token,
                    "v": base64.b64encode(value).decode(),
                }
                for pk, sk, value, token in items
            ]
        ).encode()
        st, _h, data = await self._req("POST", f"/{self.bucket}", body=body)
        if st not in (200, 204):
            raise K2VError(st, data.decode(errors="replace"))

    async def read_batch(self, searches: list[dict]) -> list[dict]:
        body = json.dumps(searches).encode()
        st, _h, data = await self._req(
            "POST", f"/{self.bucket}", query=[("search", "")], body=body
        )
        if st != 200:
            raise K2VError(st, data.decode(errors="replace"))
        return json.loads(data)

    async def delete_batch(self, deletes: list[dict]) -> list[dict]:
        body = json.dumps(deletes).encode()
        st, _h, data = await self._req(
            "POST", f"/{self.bucket}", query=[("delete", "")], body=body
        )
        if st != 200:
            raise K2VError(st, data.decode(errors="replace"))
        return json.loads(data)