"""k2v CLI (reference src/k2v-client/bin/k2v-cli.rs).

    python -m garage_tpu.k2v_client --endpoint URL --bucket B \
        --key-id GK.. --secret .. <command> ...

Commands: insert, read, delete, poll-item, poll-range, read-index,
read-range, delete-range.  Credentials may also come from the
AWS_ACCESS_KEY_ID / AWS_SECRET_ACCESS_KEY / K2V_ENDPOINT / K2V_BUCKET
environment variables.
"""

from __future__ import annotations

import argparse
import asyncio
import base64
import json
import os
import sys

from .client import K2VClient, K2VError


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="k2v-cli")
    ap.add_argument("--endpoint", default=os.environ.get("K2V_ENDPOINT"))
    ap.add_argument("--bucket", default=os.environ.get("K2V_BUCKET"))
    ap.add_argument("--key-id", default=os.environ.get("AWS_ACCESS_KEY_ID"))
    ap.add_argument("--secret", default=os.environ.get("AWS_SECRET_ACCESS_KEY"))
    ap.add_argument("--region", default="garage")
    sub = ap.add_subparsers(dest="cmd", required=True)

    ins = sub.add_parser("insert")
    ins.add_argument("partition_key")
    ins.add_argument("sort_key")
    ins.add_argument("value", help="literal value, or @file, or - for stdin")
    ins.add_argument("-c", "--causality")

    rd = sub.add_parser("read")
    rd.add_argument("partition_key")
    rd.add_argument("sort_key")
    rd.add_argument("--json", action="store_true", help="values base64 + token")

    dl = sub.add_parser("delete")
    dl.add_argument("partition_key")
    dl.add_argument("sort_key")
    dl.add_argument("-c", "--causality", required=True)

    pi = sub.add_parser("poll-item")
    pi.add_argument("partition_key")
    pi.add_argument("sort_key")
    pi.add_argument("-c", "--causality", required=True)
    pi.add_argument("-T", "--timeout", type=float, default=60.0)

    pr = sub.add_parser("poll-range")
    pr.add_argument("partition_key")
    pr.add_argument("-S", "--seen-marker")
    pr.add_argument("--prefix")
    pr.add_argument("--start")
    pr.add_argument("--end")
    pr.add_argument("-T", "--timeout", type=float, default=60.0)

    ri = sub.add_parser("read-index")
    ri.add_argument("--prefix", default="")
    ri.add_argument("--limit", type=int, default=1000)

    rr = sub.add_parser("read-range")
    rr.add_argument("partition_key")
    rr.add_argument("--start")
    rr.add_argument("--end")
    rr.add_argument("--limit", type=int, default=1000)

    dr = sub.add_parser("delete-range")
    dr.add_argument("partition_key")
    dr.add_argument("--start")
    dr.add_argument("--end")

    args = ap.parse_args(argv)
    for req in ("endpoint", "bucket", "key_id", "secret"):
        if not getattr(args, req):
            ap.error(f"--{req.replace('_', '-')} required (or env var)")
    return asyncio.run(run(args))


def _read_value(spec: str) -> bytes:
    if spec == "-":
        return sys.stdin.buffer.read()
    if spec.startswith("@"):
        # graft-lint: allow-blocking(one-shot CLI client, loop not shared)
        with open(spec[1:], "rb") as f:
            return f.read()
    return spec.encode()


async def run(args) -> int:
    client = K2VClient(
        args.endpoint, args.bucket, args.key_id, args.secret, region=args.region
    )
    try:
        if args.cmd == "insert":
            await client.insert_item(
                args.partition_key, args.sort_key,
                _read_value(args.value), token=args.causality,
            )
            print("ok")
        elif args.cmd == "read":
            vals, tok = await client.read_item(args.partition_key, args.sort_key)
            if args.json:
                print(json.dumps(
                    {"causality": tok,
                     "values": [base64.b64encode(v).decode() for v in vals]}
                ))
            else:
                print(f"causality: {tok}", file=sys.stderr)
                for v in vals:
                    sys.stdout.buffer.write(v + b"\n")
        elif args.cmd == "delete":
            await client.delete_item(
                args.partition_key, args.sort_key, args.causality
            )
            print("deleted")
        elif args.cmd == "poll-item":
            res = await client.poll_item(
                args.partition_key, args.sort_key, args.causality,
                timeout=args.timeout,
            )
            if res is None:
                print("timeout (not modified)", file=sys.stderr)
                return 1
            vals, tok = res
            print(json.dumps(
                {"causality": tok,
                 "values": [base64.b64encode(v).decode() for v in vals]}
            ))
        elif args.cmd == "poll-range":
            res = await client.poll_range(
                args.partition_key, seen_marker=args.seen_marker,
                start=args.start, end=args.end, prefix=args.prefix,
                timeout=args.timeout,
            )
            if res is None:
                print("timeout (not modified)", file=sys.stderr)
                return 1
            items, marker = res
            print(json.dumps(
                {
                    "seenMarker": marker,
                    "items": {
                        sk: {
                            "causality": it["ct"],
                            "values": [
                                base64.b64encode(v).decode()
                                if v is not None else None
                                for v in it["v"]
                            ],
                        }
                        for sk, it in items.items()
                    },
                }
            ))
        elif args.cmd == "read-index":
            idx = await client.read_index(prefix=args.prefix, limit=args.limit)
            print(json.dumps(idx))
        elif args.cmd == "read-range":
            res = await client.read_batch(
                [{"partitionKey": args.partition_key, "start": args.start,
                  "end": args.end, "limit": args.limit}]
            )
            print(json.dumps(res[0]))
        elif args.cmd == "delete-range":
            res = await client.delete_batch(
                [{"partitionKey": args.partition_key, "start": args.start,
                  "end": args.end}]
            )
            print(json.dumps(res[0]))
        return 0
    except K2VError as e:
        print(f"error {e.status}: {e}", file=sys.stderr)
        return 1
    finally:
        # graft-lint: allow-cancel(one-shot CLI: process exits right after teardown)
        await client.close()


if __name__ == "__main__":
    sys.exit(main())
