"""K2V client library (reference src/k2v-client/lib.rs:67-341)."""

from .client import K2VClient, K2VError

__all__ = ["K2VClient", "K2VError"]
