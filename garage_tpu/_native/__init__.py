"""Native (C++) host-side hot paths, loaded via ctypes.

The reference's data path is native end to end (Rust); here the TPU runs
the batched math and this extension covers the per-request host paths:
GF(2^8) coding for single blocks and BLAKE3 hashing.  Built on demand with
g++ (`python -m garage_tpu._native` or first import); every caller has a
pure-Python/numpy fallback, so a missing toolchain degrades performance,
never correctness.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess

import numpy as np

logger = logging.getLogger("garage.native")

_DIR = os.path.dirname(os.path.abspath(__file__))
_DEFAULT_SO = os.path.join(_DIR, "libgarage_native.so")
_SOURCES = ["gf8.cpp", "blake3.cpp", "kvlog.cpp"]

_lib: ctypes.CDLL | None = None
_tried = False


def _host_tag() -> str | None:
    """Fingerprint of the build host's ISA: -march=native binaries are
    host-specific, so a cached .so from another machine must be rebuilt
    (loading it could SIGILL on the first AVX instruction).  None when the
    host exposes no fingerprint — the build then drops -march=native and
    produces a portable (cacheable everywhere) binary instead."""
    import hashlib
    import platform

    try:
        with open("/proc/cpuinfo") as f:
            flags = next(
                (line for line in f if line.startswith("flags")), None
            )
    except OSError:
        flags = None
    if flags is None:
        return None
    return hashlib.sha256(
        (platform.machine() + flags).encode()
    ).hexdigest()[:16]


def build(force: bool = False) -> str | None:
    """Compile the extension into the package-default path; returns the
    .so path or None on failure.  Never touches a GARAGE_NATIVE_SO
    override — that env var points at an externally-built (e.g.
    sanitizer-instrumented) library which must not be overwritten with an
    uninstrumented one."""
    return _compile(
        [os.path.join(_DIR, s) for s in _SOURCES],
        _DEFAULT_SO,
        extra_flags=["-pthread"],
        force=force,
    )


def _compile(
    srcs: list[str],
    out_so: str,
    extra_flags: list[str],
    tag_extra: str = "",
    force: bool = False,
) -> str | None:
    """Shared compile-and-cache: rebuild out_so when a source is newer or
    the host tag changed (-march=native binaries are host-specific; no
    ISA fingerprint -> portable build, cacheable anywhere)."""
    tag_file = out_so + ".host"
    host = _host_tag()
    want_tag = (host if host is not None else "portable") + tag_extra
    if not force and os.path.exists(out_so):
        newest = max(os.path.getmtime(s) for s in srcs)
        try:
            with open(tag_file) as f:
                tag_ok = f.read().strip() == want_tag
        except OSError:
            tag_ok = False
        if os.path.getmtime(out_so) >= newest and tag_ok:
            return out_so
    march = ["-march=native"] if host is not None else []
    cmd = [
        "g++", "-O3", *march, *extra_flags, "-shared", "-fPIC",
        "-std=c++17", "-o", out_so, *srcs,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        with open(tag_file, "w") as f:
            f.write(want_tag)
        return out_so
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired, FileNotFoundError) as e:
        err = getattr(e, "stderr", b"")
        logger.warning(
            "native build of %s failed (%r): %s", os.path.basename(out_so),
            e, err.decode(errors="replace")[:500] if err else "",
        )
        return None


_KV_SO = os.path.join(_DIR, "garage_kv.so")
_kv_mod = None
_kv_tried = False


def build_kv(force: bool = False) -> str | None:
    """Compile the CPython C-API binding of the metadata engine
    (kvpy.cpp + kvlog.cpp -> garage_kv.so).  Separate from the ctypes
    .so: it needs Python.h and a matching interpreter ABI."""
    import sysconfig

    inc = sysconfig.get_paths().get("include")
    if inc is None or not os.path.exists(os.path.join(inc, "Python.h")):
        return None
    return _compile(
        [os.path.join(_DIR, s) for s in ("kvpy.cpp", "kvlog.cpp")],
        _KV_SO,
        extra_flags=[f"-I{inc}", "-pthread"],
        tag_extra=":" + str(sysconfig.get_config_var("SOABI")),
        force=force,
    )


def kv_module():
    """The garage_kv extension module, building it on first use; None if
    unavailable (db/native_engine.py then uses the ctypes path)."""
    global _kv_mod, _kv_tried
    if _kv_mod is not None or _kv_tried:
        return _kv_mod
    _kv_tried = True
    so = build_kv()
    if so is None:
        return None
    import importlib.util

    spec = importlib.util.spec_from_file_location("garage_kv", so)
    try:
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _kv_mod = mod
    except Exception as e:  # noqa: BLE001
        logger.warning("cannot load garage_kv module: %r", e)
    return _kv_mod


def lib() -> ctypes.CDLL | None:
    """The loaded library, building it on first use; None if unavailable.
    GARAGE_NATIVE_SO loads an external build as-is (no rebuild)."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    override = os.environ.get("GARAGE_NATIVE_SO")
    so = override if override else build()
    if so is None or not os.path.exists(so):
        return None
    try:
        l = ctypes.CDLL(so)
        l.gf8_apply.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t,
        ]
        l.blake3_hash.argtypes = [ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p]
        l.blake3_batch.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_size_t, ctypes.c_char_p
        ]
        # kvlog: native metadata engine (db/native_engine.py)
        l.kv_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
        l.kv_open.restype = ctypes.c_void_p
        l.kv_close.argtypes = [ctypes.c_void_p]
        l.kv_commit.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t]
        l.kv_get.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
            ctypes.c_char_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_size_t),
        ]
        l.kv_tree_len.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t]
        l.kv_tree_len.restype = ctypes.c_uint64
        l.kv_tree_names.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t]
        l.kv_tree_names.restype = ctypes.c_size_t
        l.kv_iter_chunk.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int,
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int, ctypes.c_int,
            ctypes.c_uint32, ctypes.c_char_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_int),
        ]
        l.kv_iter_chunk.restype = ctypes.c_size_t
        l.kv_compact_now.argtypes = [ctypes.c_void_p]
        l.kv_sync_barrier.argtypes = [ctypes.c_void_p]
        l.kv_log_bytes.argtypes = [ctypes.c_void_p]
        l.kv_log_bytes.restype = ctypes.c_uint64
        l.kv_live_bytes.argtypes = [ctypes.c_void_p]
        l.kv_live_bytes.restype = ctypes.c_uint64
        if hasattr(l, "kv_sync_failures"):
            # telemetry-only symbol, absent from externally-built .so's
            # (GARAGE_NATIVE_SO) predating it — optional, never a reason
            # to reject the whole library
            l.kv_sync_failures.argtypes = [ctypes.c_void_p]
            l.kv_sync_failures.restype = ctypes.c_uint64
        _lib = l
    except (OSError, AttributeError) as e:
        # AttributeError: an externally-built .so (GARAGE_NATIVE_SO) from
        # before a symbol was added — degrade to the Python fallbacks
        # rather than crashing available() callers
        logger.warning("cannot load native library: %r", e)
    return _lib


def available() -> bool:
    return lib() is not None


# --- typed wrappers ----------------------------------------------------------


def gf8_apply(mat: np.ndarray, shards: np.ndarray) -> np.ndarray | None:
    """out (r, s) = mat (r, q) @ shards (q, s) over GF(2^8); None if the
    native library is unavailable."""
    l = lib()
    if l is None:
        return None
    r, q = mat.shape
    q2, s = shards.shape
    assert q == q2
    mat_c = np.ascontiguousarray(mat, dtype=np.uint8)
    sh_c = np.ascontiguousarray(shards, dtype=np.uint8)
    out = np.zeros((r, s), dtype=np.uint8)
    l.gf8_apply(
        mat_c.ctypes.data_as(ctypes.c_char_p), r, q,
        sh_c.ctypes.data_as(ctypes.c_char_p),
        out.ctypes.data_as(ctypes.c_char_p), s,
    )
    return out


def blake3(data: bytes) -> bytes | None:
    l = lib()
    if l is None:
        return None
    out = ctypes.create_string_buffer(32)
    l.blake3_hash(data, len(data), out)
    return out.raw


def blake3_batch(x: np.ndarray) -> np.ndarray | None:
    """x (n, each_len) uint8 -> (n, 32) digests; None if unavailable."""
    l = lib()
    if l is None:
        return None
    n, each = x.shape
    x_c = np.ascontiguousarray(x, dtype=np.uint8)
    out = np.zeros((n, 32), dtype=np.uint8)
    l.blake3_batch(
        x_c.ctypes.data_as(ctypes.c_char_p), n, each,
        out.ctypes.data_as(ctypes.c_char_p),
    )
    return out


if __name__ == "__main__":
    print(build(force=True) or "BUILD FAILED")
