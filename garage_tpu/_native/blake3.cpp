// Portable BLAKE3 (default hash mode) — host-side shard-integrity hashing.
//
// Written from the BLAKE3 specification (same construction as the Python
// oracle in ops/blake3_ref.py, which is validated against the official
// test vectors; the native/python pair are cross-checked in tests).
//
// Exported C ABI (ctypes):
//   blake3_hash(in, len, out32)
//   blake3_batch(in, n, each_len, out)   n inputs of each_len bytes

#include <cstdint>
#include <cstddef>
#include <cstring>
#include "parallel.h"

#if defined(__AVX2__)
#include <immintrin.h>
#endif

namespace {

constexpr uint32_t IV[8] = {
    0x6A09E667u, 0xBB67AE85u, 0x3C6EF372u, 0xA54FF53Au,
    0x510E527Fu, 0x9B05688Cu, 0x1F83D9ABu, 0x5BE0CD19u,
};
constexpr int MSG_PERM[16] = {2, 6, 3, 10, 7, 0, 4, 13, 1, 11, 12, 5, 9, 14, 15, 8};

constexpr uint32_t CHUNK_START = 1 << 0;
constexpr uint32_t CHUNK_END = 1 << 1;
constexpr uint32_t PARENT = 1 << 2;
constexpr uint32_t ROOT = 1 << 3;

constexpr size_t BLOCK_LEN = 64;
constexpr size_t CHUNK_LEN = 1024;

inline uint32_t rotr(uint32_t x, int n) { return (x >> n) | (x << (32 - n)); }

inline void g(uint32_t* st, int a, int b, int c, int d, uint32_t mx, uint32_t my) {
    st[a] = st[a] + st[b] + mx;
    st[d] = rotr(st[d] ^ st[a], 16);
    st[c] = st[c] + st[d];
    st[b] = rotr(st[b] ^ st[c], 12);
    st[a] = st[a] + st[b] + my;
    st[d] = rotr(st[d] ^ st[a], 8);
    st[c] = st[c] + st[d];
    st[b] = rotr(st[b] ^ st[c], 7);
}

void compress(const uint32_t cv[8], const uint32_t block[16], uint64_t counter,
              uint32_t block_len, uint32_t flags, uint32_t out[16]) {
    uint32_t st[16] = {
        cv[0], cv[1], cv[2], cv[3], cv[4], cv[5], cv[6], cv[7],
        IV[0], IV[1], IV[2], IV[3],
        (uint32_t)counter, (uint32_t)(counter >> 32), block_len, flags,
    };
    uint32_t m[16];
    memcpy(m, block, sizeof(m));
    for (int r = 0; r < 7; r++) {
        g(st, 0, 4, 8, 12, m[0], m[1]);
        g(st, 1, 5, 9, 13, m[2], m[3]);
        g(st, 2, 6, 10, 14, m[4], m[5]);
        g(st, 3, 7, 11, 15, m[6], m[7]);
        g(st, 0, 5, 10, 15, m[8], m[9]);
        g(st, 1, 6, 11, 12, m[10], m[11]);
        g(st, 2, 7, 8, 13, m[12], m[13]);
        g(st, 3, 4, 9, 14, m[14], m[15]);
        if (r < 6) {
            uint32_t p[16];
            for (int i = 0; i < 16; i++) p[i] = m[MSG_PERM[i]];
            memcpy(m, p, sizeof(m));
        }
    }
    for (int i = 0; i < 8; i++) {
        out[i] = st[i] ^ st[i + 8];
        out[i + 8] = st[i + 8] ^ cv[i];
    }
}

void load_block(const uint8_t* p, size_t len, uint32_t out[16]) {
    uint8_t buf[BLOCK_LEN] = {0};
    memcpy(buf, p, len);
    for (int i = 0; i < 16; i++) {
        out[i] = (uint32_t)buf[4 * i] | ((uint32_t)buf[4 * i + 1] << 8) |
                 ((uint32_t)buf[4 * i + 2] << 16) | ((uint32_t)buf[4 * i + 3] << 24);
    }
}

// chunk -> (cv, last_block, last_len, base_flags); ROOT added by caller
struct ChunkOut {
    uint32_t cv[8];
    uint32_t last_block[16];
    uint32_t last_len;
    uint32_t flags;
};

ChunkOut chunk_state(const uint8_t* p, size_t len, uint64_t counter) {
    ChunkOut out;
    memcpy(out.cv, IV, sizeof(IV));
    size_t n_blocks = len == 0 ? 1 : (len + BLOCK_LEN - 1) / BLOCK_LEN;
    for (size_t i = 0; i + 1 < n_blocks; i++) {
        uint32_t block[16], res[16];
        load_block(p + i * BLOCK_LEN, BLOCK_LEN, block);
        uint32_t flags = (i == 0) ? CHUNK_START : 0;
        compress(out.cv, block, counter, BLOCK_LEN, flags, res);
        memcpy(out.cv, res, sizeof(out.cv));
    }
    size_t last_off = (n_blocks - 1) * BLOCK_LEN;
    out.last_len = (uint32_t)(len - last_off);
    load_block(p + last_off, out.last_len, out.last_block);
    out.flags = ((n_blocks == 1) ? CHUNK_START : 0) | CHUNK_END;
    return out;
}

#if defined(__AVX2__)
// --- 8-way chunk hashing: one AVX2 lane per chunk ---------------------------
// Chunks are independent until the parent fold, and every FULL chunk runs
// the identical 16-block schedule — so 8 of them execute in lockstep with
// the 32-bit state held as one __m256i per state word.

inline __m256i rotr_v(__m256i x, int n) {
    return _mm256_or_si256(_mm256_srli_epi32(x, n), _mm256_slli_epi32(x, 32 - n));
}

inline void g_v(__m256i* st, int a, int b, int c, int d, __m256i mx, __m256i my) {
    st[a] = _mm256_add_epi32(_mm256_add_epi32(st[a], st[b]), mx);
    st[d] = rotr_v(_mm256_xor_si256(st[d], st[a]), 16);
    st[c] = _mm256_add_epi32(st[c], st[d]);
    st[b] = rotr_v(_mm256_xor_si256(st[b], st[c]), 12);
    st[a] = _mm256_add_epi32(_mm256_add_epi32(st[a], st[b]), my);
    st[d] = rotr_v(_mm256_xor_si256(st[d], st[a]), 8);
    st[c] = _mm256_add_epi32(st[c], st[d]);
    st[b] = rotr_v(_mm256_xor_si256(st[b], st[c]), 7);
}

// hash 8 consecutive FULL chunks at p (stride CHUNK_LEN), chunk counters
// counter0..counter0+7; writes 8 CVs chunk-major into out_cvs (8*8 words)
void chunks8(const uint8_t* p, uint64_t counter0, uint32_t* out_cvs) {
    const __m256i byte_off = _mm256_setr_epi32(
        0, 1 * CHUNK_LEN, 2 * CHUNK_LEN, 3 * CHUNK_LEN,
        4 * CHUNK_LEN, 5 * CHUNK_LEN, 6 * CHUNK_LEN, 7 * CHUNK_LEN);
    __m256i cv[8];
    for (int w = 0; w < 8; w++) cv[w] = _mm256_set1_epi32((int)IV[w]);
    alignas(32) uint32_t clo[8], chi[8];
    for (int l = 0; l < 8; l++) {
        uint64_t c = counter0 + (uint64_t)l;
        clo[l] = (uint32_t)c;
        chi[l] = (uint32_t)(c >> 32);
    }
    const __m256i vclo = _mm256_load_si256((const __m256i*)clo);
    const __m256i vchi = _mm256_load_si256((const __m256i*)chi);
    const int blocks_per_chunk = (int)(CHUNK_LEN / BLOCK_LEN);
    for (int b = 0; b < blocks_per_chunk; b++) {
        __m256i m[16];
        const uint8_t* base = p + (size_t)b * BLOCK_LEN;
        for (int w = 0; w < 16; w++) {
            m[w] = _mm256_i32gather_epi32(
                (const int*)(base + 4 * w), byte_off, 1);
        }
        uint32_t flags = (b == 0 ? CHUNK_START : 0) |
                         (b == blocks_per_chunk - 1 ? CHUNK_END : 0);
        __m256i st[16];
        for (int w = 0; w < 8; w++) st[w] = cv[w];
        for (int w = 0; w < 4; w++) st[8 + w] = _mm256_set1_epi32((int)IV[w]);
        st[12] = vclo;
        st[13] = vchi;
        st[14] = _mm256_set1_epi32((int)BLOCK_LEN);
        st[15] = _mm256_set1_epi32((int)flags);
        for (int r = 0; r < 7; r++) {
            g_v(st, 0, 4, 8, 12, m[0], m[1]);
            g_v(st, 1, 5, 9, 13, m[2], m[3]);
            g_v(st, 2, 6, 10, 14, m[4], m[5]);
            g_v(st, 3, 7, 11, 15, m[6], m[7]);
            g_v(st, 0, 5, 10, 15, m[8], m[9]);
            g_v(st, 1, 6, 11, 12, m[10], m[11]);
            g_v(st, 2, 7, 8, 13, m[12], m[13]);
            g_v(st, 3, 4, 9, 14, m[14], m[15]);
            if (r < 6) {
                __m256i pmt[16];
                for (int i = 0; i < 16; i++) pmt[i] = m[MSG_PERM[i]];
                memcpy(m, pmt, sizeof(m));
            }
        }
        for (int w = 0; w < 8; w++)
            cv[w] = _mm256_xor_si256(st[w], st[w + 8]);
    }
    // transpose lanes out: out_cvs[l*8 + w] = lane l of cv[w]
    alignas(32) uint32_t tmp[8][8];
    for (int w = 0; w < 8; w++)
        _mm256_store_si256((__m256i*)tmp[w], cv[w]);
    for (int l = 0; l < 8; l++)
        for (int w = 0; w < 8; w++)
            out_cvs[l * 8 + w] = tmp[w][l];
}
#endif  // __AVX2__

void merge_tree(const uint32_t* cvs, size_t n, uint32_t out_pair[16]);

// reduce a group of chunk CVs to a single CV (non-root parent)
void reduce_group(const uint32_t* cvs, size_t n, uint32_t out_cv[8]) {
    if (n == 1) {
        memcpy(out_cv, cvs, 8 * sizeof(uint32_t));
        return;
    }
    uint32_t pair[16], res[16];
    merge_tree(cvs, n, pair);
    compress(IV, pair, 0, BLOCK_LEN, PARENT, res);
    memcpy(out_cv, res, 8 * sizeof(uint32_t));
}

// produce the final parent block (left_cv || right_cv) for n >= 2 CVs
void merge_tree(const uint32_t* cvs, size_t n, uint32_t out_pair[16]) {
    if (n == 2) {
        memcpy(out_pair, cvs, 16 * sizeof(uint32_t));
        return;
    }
    // left subtree = largest power of two < n
    size_t split = 1;
    while (split * 2 < n) split *= 2;
    reduce_group(cvs, split, out_pair);
    reduce_group(cvs + split * 8, n - split, out_pair + 8);
}

}  // namespace

extern "C" {

void blake3_hash(const uint8_t* in, size_t len, uint8_t out[32]) {
    size_t n_chunks = len == 0 ? 1 : (len + CHUNK_LEN - 1) / CHUNK_LEN;
    uint32_t root[16];
    if (n_chunks == 1) {
        ChunkOut c = chunk_state(in, len, 0);
        compress(c.cv, c.last_block, 0, c.last_len, c.flags | ROOT, root);
    } else {
        uint32_t* cvs = new uint32_t[n_chunks * 8];
        size_t i = 0;
#if defined(__AVX2__)
        // full chunks run 8 at a time, one AVX2 lane each
        size_t n_full = len / CHUNK_LEN;
        for (; i + 8 <= n_full; i += 8)
            chunks8(in + i * CHUNK_LEN, (uint64_t)i, cvs + i * 8);
#endif
        for (; i < n_chunks; i++) {
            size_t off = i * CHUNK_LEN;
            size_t clen = (off + CHUNK_LEN <= len) ? CHUNK_LEN : len - off;
            ChunkOut c = chunk_state(in + off, clen, (uint64_t)i);
            uint32_t res[16];
            compress(c.cv, c.last_block, (uint64_t)i, c.last_len, c.flags, res);
            memcpy(cvs + i * 8, res, 8 * sizeof(uint32_t));
        }
        uint32_t pair[16];
        merge_tree(cvs, n_chunks, pair);
        compress(IV, pair, 0, BLOCK_LEN, PARENT | ROOT, root);
        delete[] cvs;
    }
    for (int i = 0; i < 8; i++) {
        out[4 * i] = (uint8_t)root[i];
        out[4 * i + 1] = (uint8_t)(root[i] >> 8);
        out[4 * i + 2] = (uint8_t)(root[i] >> 16);
        out[4 * i + 3] = (uint8_t)(root[i] >> 24);
    }
}

void blake3_batch(const uint8_t* in, size_t n, size_t each_len, uint8_t* out) {
    // items are independent: split the batch across threads when there is
    // enough work to amortize spawn cost (~scrub batches are MBs)
    garage_native::parallel_ranges(
        n, each_len, (size_t)1 << 18,
        [=](size_t i0, size_t i1) {
            for (size_t i = i0; i < i1; i++)
                blake3_hash(in + i * each_len, each_len, out + i * 32);
        });
}

}  // extern "C"
