// Shared thread-partitioning for the native hot paths.  One definition so
// gf8.cpp and blake3.cpp cannot drift, and so no exception ever crosses
// the ctypes FFI boundary (this code's contract is "degrades performance,
// never correctness" — thread-resource exhaustion falls back to serial).
#pragma once

#include <cstddef>
#include <thread>
#include <vector>

namespace garage_native {

// Split [0, n) into contiguous ranges across up to 8 threads and run
// fn(begin, end) on each.  `work_per_item` scales the serial-fallback
// threshold by how expensive one item is (bytes hashed, r*q table ops,
// ...): threads only spawn when each would get >= min_work work units.
template <typename F>
inline void parallel_ranges(size_t n, size_t work_per_item, size_t min_work,
                            F fn) {
    unsigned hw = std::thread::hardware_concurrency();
    size_t nthreads = hw ? hw : 1;
    if (nthreads > 8) nthreads = 8;
    size_t total = n * (work_per_item ? work_per_item : 1);
    if (nthreads > 1 && total / nthreads < min_work)
        nthreads = total / min_work ? total / min_work : 1;
    if (nthreads <= 1 || n < 2) {
        fn((size_t)0, n);
        return;
    }
    size_t step = (n + nthreads - 1) / nthreads;
    std::vector<std::thread> workers;
    size_t spawned_to = 0;
    try {
        for (size_t k = 0; k < nthreads; k++) {
            size_t b0 = k * step;
            size_t b1 = b0 + step < n ? b0 + step : n;
            if (b0 >= b1) break;
            workers.emplace_back([=, &fn] { fn(b0, b1); });
            spawned_to = b1;
        }
    } catch (...) {
        // std::thread construction failed (pids/thread limit): finish the
        // rest serially instead of letting the exception cross the FFI
        for (auto& w : workers) w.join();
        if (spawned_to < n) fn(spawned_to, n);
        return;
    }
    for (auto& w : workers) w.join();
}

}  // namespace garage_native
