// GF(2^8) Reed-Solomon data path — the host-side (CPU) codec core.
//
// The TPU kernel (ops/ec_tpu.py) is the batched fast path; this native
// implementation serves the per-block paths (single PUT/GET encode/decode,
// small repairs) where device dispatch latency would dominate.  Same field
// as ops/gf.py: polynomial x^8+x^4+x^3+x^2+1 (0x11d), Cauchy matrices.
//
// Exported C ABI (ctypes):
//   gf8_mul_table()                      -> const uint8_t* (256*256)
//   gf8_apply(mat, r, q, shards, out, s) out[i] = sum_j mat[i,j]*shards[j]
//
// Fast path: split-nibble multiplication (y = LO[c][x & 15] ^ HI[c][x>>4])
// — 16-entry tables fit a pshufb/vpshufb register, so SSSE3/AVX2 multiply
// 16/32 bytes per instruction (the ISA-L technique).  Wide shards also
// split across threads.  Scalar 256-byte-LUT fallback for other ISAs.

#include <cstdint>
#include <cstddef>
#include <cstring>
#include "parallel.h"

#if defined(__SSSE3__)
#include <immintrin.h>
#endif

namespace {

struct Tables {
    uint8_t mul[256][256];
    // split-nibble tables: mul[c][x] == lo[c][x & 15] ^ hi[c][x >> 4]
    // (GF multiply is linear over the XOR decomposition x = lo ^ (hi<<4))
    alignas(32) uint8_t lo[256][16];
    alignas(32) uint8_t hi[256][16];
    Tables() {
        uint8_t exp_[512];
        int log_[256] = {0};
        int x = 1;
        for (int i = 0; i < 255; i++) {
            exp_[i] = (uint8_t)x;
            log_[x] = i;
            x <<= 1;
            if (x & 0x100) x ^= 0x11d;
        }
        for (int i = 255; i < 510; i++) exp_[i] = exp_[i - 255];
        for (int a = 0; a < 256; a++) {
            for (int b = 0; b < 256; b++) {
                mul[a][b] = (a && b) ? exp_[log_[a] + log_[b]] : 0;
            }
            for (int n = 0; n < 16; n++) {
                lo[a][n] = mul[a][n];
                hi[a][n] = mul[a][n << 4];
            }
        }
    }
};

const Tables& tables() {
    static Tables t;
    return t;
}

// multiply-accumulate one coefficient over the byte range [b0, b1)
void mac_range(const Tables& t, uint8_t c, const uint8_t* src, uint8_t* dst,
               size_t b0, size_t b1) {
    if (c == 1) {
        size_t b = b0;
#if defined(__AVX2__)
        for (; b + 32 <= b1; b += 32) {
            __m256i d = _mm256_loadu_si256((const __m256i*)(dst + b));
            __m256i x = _mm256_loadu_si256((const __m256i*)(src + b));
            _mm256_storeu_si256((__m256i*)(dst + b), _mm256_xor_si256(d, x));
        }
#endif
        for (; b < b1; b++) dst[b] ^= src[b];
        return;
    }
    size_t b = b0;
#if defined(__AVX2__)
    const __m256i vlo = _mm256_broadcastsi128_si256(
        _mm_load_si128((const __m128i*)t.lo[c]));
    const __m256i vhi = _mm256_broadcastsi128_si256(
        _mm_load_si128((const __m128i*)t.hi[c]));
    const __m256i mask = _mm256_set1_epi8(0x0f);
    for (; b + 32 <= b1; b += 32) {
        __m256i x = _mm256_loadu_si256((const __m256i*)(src + b));
        __m256i l = _mm256_shuffle_epi8(vlo, _mm256_and_si256(x, mask));
        __m256i h = _mm256_shuffle_epi8(
            vhi, _mm256_and_si256(_mm256_srli_epi64(x, 4), mask));
        __m256i d = _mm256_loadu_si256((const __m256i*)(dst + b));
        _mm256_storeu_si256(
            (__m256i*)(dst + b),
            _mm256_xor_si256(d, _mm256_xor_si256(l, h)));
    }
#elif defined(__SSSE3__)
    const __m128i vlo = _mm_load_si128((const __m128i*)t.lo[c]);
    const __m128i vhi = _mm_load_si128((const __m128i*)t.hi[c]);
    const __m128i mask = _mm_set1_epi8(0x0f);
    for (; b + 16 <= b1; b += 16) {
        __m128i x = _mm_loadu_si128((const __m128i*)(src + b));
        __m128i l = _mm_shuffle_epi8(vlo, _mm_and_si128(x, mask));
        __m128i h = _mm_shuffle_epi8(
            vhi, _mm_and_si128(_mm_srli_epi64(x, 4), mask));
        __m128i d = _mm_loadu_si128((const __m128i*)(dst + b));
        _mm_storeu_si128((__m128i*)(dst + b),
                         _mm_xor_si128(d, _mm_xor_si128(l, h)));
    }
#endif
    const uint8_t* row = t.mul[c];
    for (; b < b1; b++) dst[b] ^= row[src[b]];
}

void apply_range(const Tables& t, const uint8_t* mat, int r, int q,
                 const uint8_t* shards, uint8_t* out, size_t s,
                 size_t b0, size_t b1) {
    // L1 cache blocking: with full rows, every MAC streams the whole
    // dst row through L1 (r*q row-sized passes of L2 traffic per
    // apply).  Processing a column chunk at a time keeps the q src
    // chunks + dst chunk L1-resident across the i,j loops: the q=8,
    // r=3, 128 KiB-shard encode drops from ~9 MB to ~2 MB of L2
    // traffic per 1 MiB block (measured 3.8 -> 6.5 GB/s single-core;
    // BLK swept 1-16 KiB, 4 KiB best).
    constexpr size_t BLK = 4096;
    for (size_t c0 = b0; c0 < b1; c0 += BLK) {
        size_t c1 = c0 + BLK < b1 ? c0 + BLK : b1;
        for (int i = 0; i < r; i++) {
            uint8_t* dst = out + (size_t)i * s;
            memset(dst + c0, 0, c1 - c0);
            for (int j = 0; j < q; j++) {
                uint8_t c = mat[(size_t)i * q + j];
                if (c == 0) continue;
                mac_range(t, c, shards + (size_t)j * s, dst, c0, c1);
            }
        }
    }
}

}  // namespace

extern "C" {

const uint8_t* gf8_mul_table() { return &tables().mul[0][0]; }

// out (r x s) = mat (r x q) * shards (q x s) over GF(2^8)
void gf8_apply(const uint8_t* mat, int r, int q,
               const uint8_t* shards, uint8_t* out, size_t s) {
    const Tables& t = tables();
    // wide shards split by column range across threads (each range is an
    // independent slice of every row); per-column work scales with r*q,
    // so the serial threshold does too
    garage_native::parallel_ranges(
        s, (size_t)r * (size_t)q, (size_t)1 << 19,
        [&](size_t b0, size_t b1) {
            apply_range(t, mat, r, q, shards, out, s, b0, b1);
        });
}

}  // extern "C"
