// GF(2^8) Reed-Solomon data path — the host-side (CPU) codec core.
//
// The TPU kernel (ops/ec_tpu.py) is the batched fast path; this native
// implementation serves the per-block paths (single PUT/GET encode/decode,
// small repairs) where device dispatch latency would dominate.  Same field
// as ops/gf.py: polynomial x^8+x^4+x^3+x^2+1 (0x11d), Cauchy matrices.
//
// Exported C ABI (ctypes):
//   gf8_mul_table()                      -> const uint8_t* (256*256)
//   gf8_apply(mat, r, q, shards, out, s) out[i] = sum_j mat[i,j]*shards[j]
//
// The inner loop processes 8 bytes at a time through a per-coefficient
// 256-byte lookup row; with -O3 g++ vectorizes the gather-free XOR chain.

#include <cstdint>
#include <cstddef>
#include <cstring>

namespace {

struct Tables {
    uint8_t mul[256][256];
    Tables() {
        uint8_t exp_[512];
        int log_[256] = {0};
        int x = 1;
        for (int i = 0; i < 255; i++) {
            exp_[i] = (uint8_t)x;
            log_[x] = i;
            x <<= 1;
            if (x & 0x100) x ^= 0x11d;
        }
        for (int i = 255; i < 510; i++) exp_[i] = exp_[i - 255];
        for (int a = 0; a < 256; a++) {
            for (int b = 0; b < 256; b++) {
                mul[a][b] = (a && b) ? exp_[log_[a] + log_[b]] : 0;
            }
        }
    }
};

const Tables& tables() {
    static Tables t;
    return t;
}

}  // namespace

extern "C" {

const uint8_t* gf8_mul_table() { return &tables().mul[0][0]; }

// out (r x s) = mat (r x q) * shards (q x s) over GF(2^8)
void gf8_apply(const uint8_t* mat, int r, int q,
               const uint8_t* shards, uint8_t* out, size_t s) {
    const Tables& t = tables();
    memset(out, 0, (size_t)r * s);
    for (int i = 0; i < r; i++) {
        uint8_t* dst = out + (size_t)i * s;
        for (int j = 0; j < q; j++) {
            uint8_t c = mat[(size_t)i * q + j];
            if (c == 0) continue;
            const uint8_t* row = t.mul[c];
            const uint8_t* src = shards + (size_t)j * s;
            if (c == 1) {
                for (size_t b = 0; b < s; b++) dst[b] ^= src[b];
            } else {
                size_t b = 0;
                for (; b + 8 <= s; b += 8) {
                    dst[b]     ^= row[src[b]];
                    dst[b + 1] ^= row[src[b + 1]];
                    dst[b + 2] ^= row[src[b + 2]];
                    dst[b + 3] ^= row[src[b + 3]];
                    dst[b + 4] ^= row[src[b + 4]];
                    dst[b + 5] ^= row[src[b + 5]];
                    dst[b + 6] ^= row[src[b + 6]];
                    dst[b + 7] ^= row[src[b + 7]];
                }
                for (; b < s; b++) dst[b] ^= row[src[b]];
            }
        }
    }
}

}  // extern "C"
