// Native metadata KV engine: the C++ core of db_engine = "native".
//
// Same role as the reference's LMDB adapter (src/db/lmdb_adapter.rs): the
// fast durable engine behind the generic Db/Tree/Tx abstraction.  Design
// is the repo's log-structured engine (db/log_engine.py) re-done native:
//
//   - full keyspace in RAM as ordered maps (std::map per tree): O(log n)
//     point ops and ordered range scans at native speed — fixing the
//     Python engine's O(n) sorted-list inserts, which degrade badly past
//     ~100k keys;
//   - every commit appends ONE crc-framed batch to the write-ahead log;
//     recovery replays frames until the first bad/short one and truncates
//     the torn tail (atomicity = frame integrity);
//   - compaction rewrites live state to <path>.new, fsyncs, renames.
//
// The on-disk format is BYTE-IDENTICAL to db/log_engine.py (frame =
// [u32 len][u32 crc32][payload]; record = [u8 op][u16 tlen][tree]
// [u32 klen][k]([u32 vlen][v] if put)), so a store written by either
// engine opens in the other — convert-db not required to switch.
//
// Sync modes (kv_open's second arg):
//   0 = none   : no per-commit sync (compact/close still fsync)
//   1 = full   : fdatasync inside every kv_commit (strict durability)
//   2 = group  : classic group commit — kv_commit appends + applies and
//                returns immediately; a dedicated flusher thread runs
//                fdatasync continuously while commits are pending, so
//                every commit becomes durable within ~one fdatasync
//                (fsync absorption: all frames appended while a sync is
//                in flight are covered by the next one).  This matches
//                sqlite WAL + synchronous=NORMAL and the reference's
//                default metadata_fsync=false LMDB posture, at a
//                bounded (~200 us) window.  kv_sync_barrier() waits for
//                full durability (used by snapshot/close).
//
// Thread-safety contract: a handle's MAPS serve exactly one caller
// thread at a time (the daemon's asyncio loop under the GIL) — reads and
// iteration take no locks.  db->mu protects only what the internal
// flusher thread shares with callers: the fd, the byte/seq counters, and
// fd swaps during compaction.  The flusher itself never touches the maps.

#include <algorithm>
#include <cerrno>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kCompactRatio = 3;
constexpr uint64_t kCompactMinBytes = 4ull * 1024 * 1024;
constexpr uint8_t kOpPut = 1;
constexpr uint8_t kOpDel = 2;

// zlib-compatible crc32 (poly 0xEDB88320), table built on first use.
uint32_t crc32_of(const uint8_t* data, size_t len) {
  static uint32_t table[256];
  static bool init = false;
  if (!init) {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int b = 0; b < 8; b++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      table[i] = c;
    }
    init = true;
  }
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < len; i++) c = table[(c ^ data[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

inline uint32_t rd_u32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;  // little-endian hosts only (x86/arm64), same as struct '<I'
}

inline void put_u32(std::string& out, uint32_t v) {
  out.append(reinterpret_cast<const char*>(&v), 4);
}

using TreeMap = std::map<std::string, std::string>;

struct KvDb {
  std::string path;
  int sync_mode = 1;  // 0 none, 1 full, 2 group
  int fd = -1;
  uint64_t log_bytes = 0;
  uint64_t live_bytes = 0;
  std::map<std::string, TreeMap> trees;

  // group-commit machinery (sync_mode == 2 only)
  std::mutex mu;
  std::condition_variable cv;
  std::thread flusher;
  uint64_t seq_committed = 0;  // frames appended
  uint64_t seq_durable = 0;    // frames covered by an fdatasync
  bool stop_flusher = false;
  // errno of the last failed flusher sync (0 = healthy).  While nonzero,
  // seq_durable is frozen and kv_sync_barrier fails fast instead of
  // waiting on durability that is not being achieved.
  int sync_err = 0;
  uint64_t sync_failures = 0;  // cumulative failed flusher sync attempts

  ~KvDb() {
    if (fd >= 0) ::close(fd);
  }
};

bool write_all(int fd, const void* buf, size_t len) {
  const char* p = static_cast<const char*>(buf);
  while (len > 0) {
    ssize_t n = ::write(fd, p, len);
    if (n < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    p += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

// Structural check of one frame payload WITHOUT mutating state.  Commit
// validates before writing/applying so a malformed batch can never leave
// memory and disk divergent (a partial apply would make this process see
// keys the post-restart replay silently drops).
bool validate_payload(const uint8_t* p, size_t len) {
  size_t pos = 0;
  while (pos < len) {
    if (pos + 3 > len) return false;
    uint8_t op = p[pos];
    uint16_t tlen;
    std::memcpy(&tlen, p + pos + 1, 2);
    pos += 3 + tlen;
    if (pos + 4 > len) return false;
    uint32_t klen = rd_u32(p + pos);
    pos += 4 + klen;
    if (pos > len) return false;
    if (op == kOpPut) {
      if (pos + 4 > len) return false;
      uint32_t vlen = rd_u32(p + pos);
      pos += 4 + vlen;
      if (pos > len) return false;
    } else if (op != kOpDel) {
      return false;
    }
  }
  return true;
}

// Apply one frame payload to the in-memory state.  Returns false on a
// malformed record (treated like a corrupt frame by the replay caller).
bool apply_payload(KvDb* db, const uint8_t* p, size_t len) {
  size_t pos = 0;
  while (pos < len) {
    if (pos + 3 > len) return false;
    uint8_t op = p[pos];
    uint16_t tlen;
    std::memcpy(&tlen, p + pos + 1, 2);
    pos += 3;
    if (pos + tlen + 4 > len) return false;
    std::string tree(reinterpret_cast<const char*>(p + pos), tlen);
    pos += tlen;
    uint32_t klen = rd_u32(p + pos);
    pos += 4;
    if (pos + klen > len) return false;
    std::string key(reinterpret_cast<const char*>(p + pos), klen);
    pos += klen;
    TreeMap& t = db->trees[tree];
    auto it = t.find(key);
    if (op == kOpPut) {
      if (pos + 4 > len) return false;
      uint32_t vlen = rd_u32(p + pos);
      pos += 4;
      if (pos + vlen > len) return false;
      if (it != t.end())
        db->live_bytes -= key.size() + it->second.size();
      t[std::move(key)] =
          std::string(reinterpret_cast<const char*>(p + pos), vlen);
      db->live_bytes += klen + vlen;
      pos += vlen;
    } else if (op == kOpDel) {
      if (it != t.end()) {
        db->live_bytes -= key.size() + it->second.size();
        t.erase(it);
      }
    } else {
      return false;
    }
  }
  return true;
}

void enc_record(std::string& out, uint8_t op, const std::string& tree,
                const std::string& k, const std::string* v) {
  out.push_back(static_cast<char>(op));
  uint16_t tlen = static_cast<uint16_t>(tree.size());
  out.append(reinterpret_cast<const char*>(&tlen), 2);
  out.append(tree);
  put_u32(out, static_cast<uint32_t>(k.size()));
  out.append(k);
  if (op == kOpPut) {
    put_u32(out, static_cast<uint32_t>(v->size()));
    out.append(*v);
  }
}

// Replay the log; truncate a torn/corrupt tail in place.
bool replay(KvDb* db) {
  FILE* f = std::fopen(db->path.c_str(), "rb");
  if (f == nullptr) return errno == ENOENT;  // no log yet: fine
  std::fseek(f, 0, SEEK_END);
  long fsize = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  std::vector<uint8_t> buf(static_cast<size_t>(fsize));
  if (fsize > 0 && std::fread(buf.data(), 1, buf.size(), f) != buf.size()) {
    std::fclose(f);
    return false;
  }
  std::fclose(f);
  size_t pos = 0, valid_end = 0;
  while (pos + 8 <= buf.size()) {
    uint32_t plen = rd_u32(buf.data() + pos);
    uint32_t crc = rd_u32(buf.data() + pos + 4);
    if (pos + 8 + plen > buf.size()) break;  // torn tail
    const uint8_t* payload = buf.data() + pos + 8;
    if (crc32_of(payload, plen) != crc) break;  // corrupt: stop here
    if (!apply_payload(db, payload, plen)) break;
    pos += 8 + plen;
    valid_end = pos;
  }
  if (valid_end < buf.size()) {
    if (::truncate(db->path.c_str(), static_cast<off_t>(valid_end)) != 0)
      return false;
  }
  db->log_bytes = valid_end;
  return true;
}

int compact(KvDb* db) {
  std::string tmp = db->path + ".new";
  int tfd = ::open(tmp.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (tfd < 0) return -1;
  uint64_t total = 0;
  std::string payload, frame;
  for (const auto& [name, t] : db->trees) {
    if (t.empty()) continue;
    payload.clear();
    for (const auto& [k, v] : t) enc_record(payload, kOpPut, name, k, &v);
    frame.clear();
    put_u32(frame, static_cast<uint32_t>(payload.size()));
    put_u32(frame, crc32_of(reinterpret_cast<const uint8_t*>(payload.data()),
                            payload.size()));
    frame += payload;
    if (!write_all(tfd, frame.data(), frame.size())) {
      ::close(tfd);
      ::unlink(tmp.c_str());
      return -1;
    }
    total += frame.size();
  }
  int frc = ::fsync(tfd);
  int crc = ::close(tfd);  // close unconditionally: no fd leak on fsync fail
  if (frc != 0 || crc != 0) {
    ::unlink(tmp.c_str());
    return -1;
  }
  // Open the append fd to the NEW inode BEFORE the rename: the fd stays
  // valid across rename (same inode), so there is no window where db->fd
  // is closed/-1 and a failure can strand the handle.  Every early return
  // below leaves db->fd and the old log fully intact (true best-effort).
  int nfd = ::open(tmp.c_str(), O_WRONLY | O_APPEND, 0644);
  if (nfd < 0) {
    ::unlink(tmp.c_str());
    return -1;
  }
  if (::rename(tmp.c_str(), db->path.c_str()) != 0) {
    ::close(nfd);
    ::unlink(tmp.c_str());
    return -1;
  }
  {
    // fd swap + counters under mu: the flusher dups db->fd under this
    // lock.  Everything written so far is durable in the new inode
    // (fsynced before rename), so the durable seq catches up.
    std::lock_guard<std::mutex> lk(db->mu);
    if (db->fd >= 0) ::close(db->fd);
    db->fd = nfd;
    db->log_bytes = total;
    db->seq_durable = db->seq_committed;
  }
  db->cv.notify_all();
  // best-effort: persist the rename itself (directory entry)
  std::string dir = db->path.substr(0, db->path.find_last_of('/'));
  if (dir.empty()) dir = ".";
  int dfd = ::open(dir.c_str(), O_RDONLY);
  if (dfd >= 0) {
    ::fsync(dfd);
    ::close(dfd);
  }
  return 0;
}

void maybe_compact(KvDb* db) {
  uint64_t live = db->live_bytes > 0 ? db->live_bytes : 1;
  if (db->log_bytes > kCompactMinBytes && db->log_bytes > kCompactRatio * live)
    compact(db);  // best-effort: a failed compaction keeps the long log
}

// Group-commit flusher: fdatasync continuously while commits are pending.
// Syncs on a dup of the current fd OUTSIDE the lock, so appenders are
// never blocked by a sync in flight (absorption: frames appended during
// a sync are covered by the next loop turn).
void flusher_main(KvDb* db) {
  std::unique_lock<std::mutex> lk(db->mu);
  for (;;) {
    db->cv.wait(lk, [db] {
      return db->stop_flusher || db->seq_committed > db->seq_durable;
    });
    if (db->seq_committed <= db->seq_durable) {
      if (db->stop_flusher) return;
      continue;
    }
    uint64_t target = db->seq_committed;
    int sfd = ::dup(db->fd);
    int err = sfd < 0 ? errno : 0;
    lk.unlock();
    int rc = -1;
    if (sfd >= 0) {
      rc = ::fdatasync(sfd);
      if (rc != 0) err = errno;  // capture before close() can clobber it
      ::close(sfd);
    }
    lk.lock();
    if (rc == 0) {
      db->sync_err = 0;
      // a concurrent compact may have advanced seq_durable past target
      if (target > db->seq_durable) db->seq_durable = target;
      db->cv.notify_all();
    } else {
      // dup or fdatasync failed: seq_durable must NOT advance — doing so
      // would make kv_sync_barrier() report unsynced commits as durable.
      // Surface the error (barrier waiters fail fast on sync_err) and
      // pace the retry with a bounded wait instead of busy-spinning on
      // the still-true wait predicate; a later successful sync (e.g.
      // after a compaction swapped in a fresh fd) clears the state.
      db->sync_err = err ? err : EIO;
      db->sync_failures++;
      db->cv.notify_all();
      db->cv.wait_for(lk, std::chrono::milliseconds(50),
                      [db] { return db->stop_flusher; });
      if (db->stop_flusher) return;
    }
  }
}

}  // namespace

extern "C" {

void* kv_open(const char* path, int sync_mode) {
  KvDb* db = new KvDb();
  db->path = path;
  db->sync_mode = sync_mode;
  if (!replay(db)) {
    delete db;
    return nullptr;
  }
  db->fd = ::open(path, O_WRONLY | O_APPEND | O_CREAT, 0644);
  if (db->fd < 0) {
    delete db;
    return nullptr;
  }
  if (db->sync_mode == 2) db->flusher = std::thread(flusher_main, db);
  return db;
}

int kv_close(void* h) {
  KvDb* db = static_cast<KvDb*>(h);
  if (db->flusher.joinable()) {
    {
      std::lock_guard<std::mutex> lk(db->mu);
      db->stop_flusher = true;
    }
    db->cv.notify_all();
    db->flusher.join();
  }
  int rc = compact(db);  // rewrites + fsyncs live state
  delete db;
  return rc;
}

// Durability barrier: returns once every commit acknowledged so far is
// on stable storage (group mode waits for the flusher; other modes
// fdatasync inline).  Used by snapshot and by operators wanting an
// explicit sync point.
int kv_sync_barrier(void* h) {
  KvDb* db = static_cast<KvDb*>(h);
  std::unique_lock<std::mutex> lk(db->mu);
  if (db->sync_mode == 2 && db->flusher.joinable()) {
    uint64_t target = db->seq_committed;
    db->cv.notify_all();
    // a failing flusher (sync_err set) must surface here, not hang the
    // barrier forever on durability the disk is refusing to provide
    db->cv.wait(lk, [&] {
      return db->seq_durable >= target || db->sync_err != 0;
    });
    return db->seq_durable >= target ? 0 : -1;
  }
  return ::fdatasync(db->fd) == 0 ? 0 : -1;
}

// Flusher health introspection: cumulative failed sync attempts (for
// metrics/tests; 0 on a healthy handle, or for non-group sync modes).
uint64_t kv_sync_failures(void* h) {
  KvDb* db = static_cast<KvDb*>(h);
  std::lock_guard<std::mutex> lk(db->mu);
  return db->sync_failures;
}

// Commit one batch: payload is the concatenated record encoding (exactly
// what goes inside the frame).  Appends the frame, fsyncs if configured,
// applies to memory, maybe compacts.
int kv_commit(void* h, const uint8_t* payload, size_t len) {
  KvDb* db = static_cast<KvDb*>(h);
  // Validate BEFORE writing or applying: a malformed batch is rejected
  // with no disk write and no memory mutation, so the -2 path can never
  // leave an acked-in-memory key that a post-restart replay would drop.
  if (!validate_payload(payload, len)) return -2;
  std::string frame;
  frame.reserve(len + 8);
  put_u32(frame, static_cast<uint32_t>(len));
  put_u32(frame, crc32_of(payload, len));
  frame.append(reinterpret_cast<const char*>(payload), len);
  {
    std::lock_guard<std::mutex> lk(db->mu);  // fd/counters vs flusher
    if (!write_all(db->fd, frame.data(), frame.size()) ||
        (db->sync_mode == 1 && ::fdatasync(db->fd) != 0)) {
      // A partial frame left in the log would make the NEXT replay stop
      // at its bad crc and discard every later acknowledged commit.
      // Roll the failed commit off the file so later appends start at a
      // clean frame boundary (best-effort: if even truncate fails the fd
      // is hosed and every later commit errors too).
      ::ftruncate(db->fd, static_cast<off_t>(db->log_bytes));
      return -1;
    }
  }
  if (!apply_payload(db, payload, len)) {
    // Unreachable after the validate above (apply's structural checks
    // are a subset) — kept as a belt-and-braces guard: roll the (not yet
    // counted) frame off the file so replay never stops at it.
    std::lock_guard<std::mutex> lk(db->mu);
    ::ftruncate(db->fd, static_cast<off_t>(db->log_bytes));
    return -2;
  }
  {
    std::lock_guard<std::mutex> lk(db->mu);
    db->log_bytes += frame.size();
    db->seq_committed++;
  }
  if (db->sync_mode == 2) db->cv.notify_all();
  maybe_compact(db);
  return 0;
}

// Point read.  *out points into internal storage — valid until the next
// mutation of this key; the (GIL-holding) caller copies immediately.
int kv_get(void* h, const char* tree, size_t tlen, const uint8_t* k,
           size_t klen, const uint8_t** out, size_t* outlen) {
  KvDb* db = static_cast<KvDb*>(h);
  auto ti = db->trees.find(std::string(tree, tlen));
  if (ti == db->trees.end()) return 0;
  auto it = ti->second.find(std::string(reinterpret_cast<const char*>(k), klen));
  if (it == ti->second.end()) return 0;
  *out = reinterpret_cast<const uint8_t*>(it->second.data());
  *outlen = it->second.size();
  return 1;
}

uint64_t kv_tree_len(void* h, const char* tree, size_t tlen) {
  KvDb* db = static_cast<KvDb*>(h);
  auto ti = db->trees.find(std::string(tree, tlen));
  return ti == db->trees.end() ? 0 : ti->second.size();
}

// Packed tree-name list: [u16 len][name]... — returns bytes needed; only
// writes when cap suffices (caller retries with a larger buffer).
size_t kv_tree_names(void* h, uint8_t* buf, size_t cap) {
  KvDb* db = static_cast<KvDb*>(h);
  size_t need = 0;
  for (const auto& [name, t] : db->trees) need += 2 + name.size();
  if (need > cap) return need;
  size_t pos = 0;
  for (const auto& [name, t] : db->trees) {
    uint16_t n = static_cast<uint16_t>(name.size());
    std::memcpy(buf + pos, &n, 2);
    std::memcpy(buf + pos + 2, name.data(), name.size());
    pos += 2 + name.size();
  }
  return need;
}

// Ordered range scan, one chunk per call.  Writes up to max_items (0 =
// no limit) packed [u32 klen][k][u32 vlen][v] entries of the range
// [start, end) — descending from end when reverse — into buf, stopping
// before an entry that would overflow cap.  Returns bytes written;
// *done = 1 when the range is exhausted.  The caller resumes with
// start = last_key + '\0' (forward) or end = last_key (reverse); a chunk
// of 0 bytes with *done == 0 means one entry exceeds cap — grow and retry.
size_t kv_iter_chunk(void* h, const char* tree, size_t tlen,
                     const uint8_t* start, size_t slen, int has_start,
                     const uint8_t* end, size_t elen, int has_end, int reverse,
                     uint32_t max_items, uint8_t* buf, size_t cap, int* done) {
  KvDb* db = static_cast<KvDb*>(h);
  *done = 1;
  auto ti = db->trees.find(std::string(tree, tlen));
  if (ti == db->trees.end()) return 0;
  TreeMap& t = ti->second;
  std::string skey(reinterpret_cast<const char*>(start), has_start ? slen : 0);
  std::string ekey(reinterpret_cast<const char*>(end), has_end ? elen : 0);
  auto lo = has_start ? t.lower_bound(skey) : t.begin();
  auto hi = has_end ? t.lower_bound(ekey) : t.end();
  size_t pos = 0;
  uint32_t items = 0;
  auto emit = [&](const std::string& k, const std::string& v) -> bool {
    size_t need = 8 + k.size() + v.size();
    if (pos + need > cap) {
      *done = 0;
      return false;
    }
    uint32_t n = static_cast<uint32_t>(k.size());
    std::memcpy(buf + pos, &n, 4);
    std::memcpy(buf + pos + 4, k.data(), k.size());
    n = static_cast<uint32_t>(v.size());
    std::memcpy(buf + pos + 4 + k.size(), &n, 4);
    std::memcpy(buf + pos + 8 + k.size(), v.data(), v.size());
    pos += need;
    items++;
    if (max_items != 0 && items >= max_items) {
      *done = 0;
      return false;
    }
    return true;
  };
  if (!reverse) {
    for (auto it = lo; it != hi; ++it)
      if (!emit(it->first, it->second)) {
        return pos;
      }
  } else {
    auto it = hi;
    while (it != lo) {
      --it;
      if (!emit(it->first, it->second)) return pos;
    }
  }
  return pos;
}

int kv_compact_now(void* h) { return compact(static_cast<KvDb*>(h)); }

uint64_t kv_log_bytes(void* h) { return static_cast<KvDb*>(h)->log_bytes; }
uint64_t kv_live_bytes(void* h) { return static_cast<KvDb*>(h)->live_bytes; }

}  // extern "C"
