// CPython C-API binding for the native metadata engine (kvlog.cpp).
//
// The ctypes FFI costs ~3 us per call — more than the engine's own
// std::map lookup — so the hot point ops (get/commit/len) go through a
// real extension module instead (~100 ns call overhead).  Compiled
// together with kvlog.cpp into garage_kv.so by _native.build_kv();
// db/native_engine.py falls back to the ctypes path when this module
// can't be built.
//
// All functions take the db handle as an int (the pointer from kv_open);
// handles are created/destroyed only via this module or the ctypes path,
// never mixed on one db.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>

extern "C" {
void* kv_open(const char* path, int sync_mode);
int kv_close(void* h);
int kv_sync_barrier(void* h);
int kv_commit(void* h, const uint8_t* payload, size_t len);
int kv_get(void* h, const char* tree, size_t tlen, const uint8_t* k,
           size_t klen, const uint8_t** out, size_t* outlen);
uint64_t kv_tree_len(void* h, const char* tree, size_t tlen);
size_t kv_tree_names(void* h, uint8_t* buf, size_t cap);
size_t kv_iter_chunk(void* h, const char* tree, size_t tlen,
                     const uint8_t* start, size_t slen, int has_start,
                     const uint8_t* end, size_t elen, int has_end, int reverse,
                     uint32_t max_items, uint8_t* buf, size_t cap, int* done);
int kv_compact_now(void* h);
uint64_t kv_log_bytes(void* h);
uint64_t kv_live_bytes(void* h);
uint64_t kv_sync_failures(void* h);
}

namespace {

void* handle_of(PyObject* obj) {
  return PyLong_AsVoidPtr(obj);  // sets an exception on junk input
}

PyObject* py_open(PyObject*, PyObject* args) {
  const char* path;
  int sync_mode;  // 0 none, 1 full, 2 group
  if (!PyArg_ParseTuple(args, "si", &path, &sync_mode)) return nullptr;
  void* h = kv_open(path, sync_mode);
  if (h == nullptr) {
    PyErr_Format(PyExc_OSError, "cannot open native kv log at '%s'", path);
    return nullptr;
  }
  return PyLong_FromVoidPtr(h);
}

PyObject* py_close(PyObject*, PyObject* args) {
  PyObject* hobj;
  if (!PyArg_ParseTuple(args, "O", &hobj)) return nullptr;
  void* h = handle_of(hobj);
  if (h == nullptr && PyErr_Occurred()) return nullptr;
  kv_close(h);
  Py_RETURN_NONE;
}

PyObject* py_commit(PyObject*, PyObject* args) {
  PyObject* hobj;
  Py_buffer payload;
  if (!PyArg_ParseTuple(args, "Oy*", &hobj, &payload)) return nullptr;
  void* h = handle_of(hobj);
  if (h == nullptr && PyErr_Occurred()) {
    PyBuffer_Release(&payload);
    return nullptr;
  }
  int rc = kv_commit(h, static_cast<const uint8_t*>(payload.buf),
                     static_cast<size_t>(payload.len));
  PyBuffer_Release(&payload);
  if (rc != 0) {
    PyErr_Format(PyExc_OSError, "native kv commit failed (rc=%d)", rc);
    return nullptr;
  }
  Py_RETURN_NONE;
}

PyObject* py_get(PyObject*, PyObject* args) {
  PyObject* hobj;
  Py_buffer tree, key;
  if (!PyArg_ParseTuple(args, "Oy*y*", &hobj, &tree, &key)) return nullptr;
  void* h = handle_of(hobj);
  const uint8_t* out = nullptr;
  size_t outlen = 0;
  int found =
      (h != nullptr)
          ? kv_get(h, static_cast<const char*>(tree.buf),
                   static_cast<size_t>(tree.len),
                   static_cast<const uint8_t*>(key.buf),
                   static_cast<size_t>(key.len), &out, &outlen)
          : 0;
  PyBuffer_Release(&tree);
  PyBuffer_Release(&key);
  if (h == nullptr && PyErr_Occurred()) return nullptr;
  if (!found) Py_RETURN_NONE;
  return PyBytes_FromStringAndSize(reinterpret_cast<const char*>(out),
                                   static_cast<Py_ssize_t>(outlen));
}

PyObject* py_tree_len(PyObject*, PyObject* args) {
  PyObject* hobj;
  Py_buffer tree;
  if (!PyArg_ParseTuple(args, "Oy*", &hobj, &tree)) return nullptr;
  void* h = handle_of(hobj);
  uint64_t n = (h != nullptr)
                   ? kv_tree_len(h, static_cast<const char*>(tree.buf),
                                 static_cast<size_t>(tree.len))
                   : 0;
  PyBuffer_Release(&tree);
  if (h == nullptr && PyErr_Occurred()) return nullptr;
  return PyLong_FromUnsignedLongLong(n);
}

PyObject* py_tree_names(PyObject*, PyObject* args) {
  PyObject* hobj;
  if (!PyArg_ParseTuple(args, "O", &hobj)) return nullptr;
  void* h = handle_of(hobj);
  if (h == nullptr && PyErr_Occurred()) return nullptr;
  size_t need = kv_tree_names(h, nullptr, 0);
  PyObject* out = PyBytes_FromStringAndSize(nullptr, (Py_ssize_t)need);
  if (out == nullptr) return nullptr;
  if (need > 0)
    kv_tree_names(h, reinterpret_cast<uint8_t*>(PyBytes_AS_STRING(out)), need);
  return out;
}

// iter_chunk(h, tree, start|None, end|None, reverse, max_items, cap)
//   -> (chunk: bytes, done: bool)
PyObject* py_iter_chunk(PyObject*, PyObject* args) {
  PyObject* hobj;
  Py_buffer tree;
  PyObject *startobj, *endobj;
  int reverse;
  unsigned int max_items;
  Py_ssize_t cap;
  if (!PyArg_ParseTuple(args, "Oy*OOpIn", &hobj, &tree, &startobj, &endobj,
                        &reverse, &max_items, &cap))
    return nullptr;
  void* h = handle_of(hobj);
  if (h == nullptr && PyErr_Occurred()) {
    PyBuffer_Release(&tree);
    return nullptr;
  }
  Py_buffer start{}, end{};
  int has_start = 0, has_end = 0;
  if (startobj != Py_None) {
    if (PyObject_GetBuffer(startobj, &start, PyBUF_SIMPLE) != 0) {
      PyBuffer_Release(&tree);
      return nullptr;
    }
    has_start = 1;
  }
  if (endobj != Py_None) {
    if (PyObject_GetBuffer(endobj, &end, PyBUF_SIMPLE) != 0) {
      if (has_start) PyBuffer_Release(&start);
      PyBuffer_Release(&tree);
      return nullptr;
    }
    has_end = 1;
  }
  PyObject* buf = PyBytes_FromStringAndSize(nullptr, cap);
  if (buf == nullptr) {
    if (has_start) PyBuffer_Release(&start);
    if (has_end) PyBuffer_Release(&end);
    PyBuffer_Release(&tree);
    return nullptr;
  }
  int done = 0;
  size_t n = kv_iter_chunk(
      h, static_cast<const char*>(tree.buf), static_cast<size_t>(tree.len),
      has_start ? static_cast<const uint8_t*>(start.buf) : nullptr,
      has_start ? static_cast<size_t>(start.len) : 0, has_start,
      has_end ? static_cast<const uint8_t*>(end.buf) : nullptr,
      has_end ? static_cast<size_t>(end.len) : 0, has_end, reverse, max_items,
      reinterpret_cast<uint8_t*>(PyBytes_AS_STRING(buf)),
      static_cast<size_t>(cap), &done);
  if (has_start) PyBuffer_Release(&start);
  if (has_end) PyBuffer_Release(&end);
  PyBuffer_Release(&tree);
  if (_PyBytes_Resize(&buf, static_cast<Py_ssize_t>(n)) != 0) return nullptr;
  PyObject* ret = Py_BuildValue("(NO)", buf, done ? Py_True : Py_False);
  return ret;
}

PyObject* py_compact(PyObject*, PyObject* args) {
  PyObject* hobj;
  if (!PyArg_ParseTuple(args, "O", &hobj)) return nullptr;
  void* h = handle_of(hobj);
  if (h == nullptr && PyErr_Occurred()) return nullptr;
  if (kv_compact_now(h) != 0) {
    PyErr_SetString(PyExc_OSError, "native kv compaction failed");
    return nullptr;
  }
  Py_RETURN_NONE;
}

PyObject* py_sync_barrier(PyObject*, PyObject* args) {
  PyObject* hobj;
  if (!PyArg_ParseTuple(args, "O", &hobj)) return nullptr;
  void* h = handle_of(hobj);
  if (h == nullptr && PyErr_Occurred()) return nullptr;
  int rc;
  Py_BEGIN_ALLOW_THREADS  // may block on the flusher's fdatasync
  rc = kv_sync_barrier(h);
  Py_END_ALLOW_THREADS
  if (rc != 0) {
    PyErr_SetString(PyExc_OSError, "native kv sync barrier failed");
    return nullptr;
  }
  Py_RETURN_NONE;
}

PyObject* py_log_bytes(PyObject*, PyObject* args) {
  PyObject* hobj;
  if (!PyArg_ParseTuple(args, "O", &hobj)) return nullptr;
  void* h = handle_of(hobj);
  if (h == nullptr && PyErr_Occurred()) return nullptr;
  return PyLong_FromUnsignedLongLong(kv_log_bytes(h));
}

PyObject* py_live_bytes(PyObject*, PyObject* args) {
  PyObject* hobj;
  if (!PyArg_ParseTuple(args, "O", &hobj)) return nullptr;
  void* h = handle_of(hobj);
  if (h == nullptr && PyErr_Occurred()) return nullptr;
  return PyLong_FromUnsignedLongLong(kv_live_bytes(h));
}

PyObject* py_sync_failures(PyObject*, PyObject* args) {
  PyObject* hobj;
  if (!PyArg_ParseTuple(args, "O", &hobj)) return nullptr;
  void* h = handle_of(hobj);
  if (h == nullptr && PyErr_Occurred()) return nullptr;
  return PyLong_FromUnsignedLongLong(kv_sync_failures(h));
}

PyMethodDef methods[] = {
    {"open", py_open, METH_VARARGS, "open(path, fsync) -> handle"},
    {"close", py_close, METH_VARARGS, "close(handle)"},
    {"commit", py_commit, METH_VARARGS, "commit(handle, payload)"},
    {"get", py_get, METH_VARARGS, "get(handle, tree, key) -> bytes | None"},
    {"tree_len", py_tree_len, METH_VARARGS, "tree_len(handle, tree) -> int"},
    {"tree_names", py_tree_names, METH_VARARGS, "tree_names(handle) -> bytes"},
    {"iter_chunk", py_iter_chunk, METH_VARARGS,
     "iter_chunk(handle, tree, start, end, reverse, max_items, cap) -> "
     "(bytes, done)"},
    {"compact", py_compact, METH_VARARGS, "compact(handle)"},
    {"sync_barrier", py_sync_barrier, METH_VARARGS,
     "sync_barrier(handle) — wait until all acked commits are durable"},
    {"log_bytes", py_log_bytes, METH_VARARGS, "log_bytes(handle) -> int"},
    {"live_bytes", py_live_bytes, METH_VARARGS, "live_bytes(handle) -> int"},
    {"sync_failures", py_sync_failures, METH_VARARGS,
     "sync_failures(handle) -> int — cumulative failed flusher syncs"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef module = {
    PyModuleDef_HEAD_INIT, "garage_kv",
    "Native metadata KV engine (C-API binding over kvlog.cpp)", -1, methods,
};

}  // namespace

PyMODINIT_FUNC PyInit_garage_kv(void) { return PyModule_Create(&module); }
