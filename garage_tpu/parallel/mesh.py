"""Device-mesh sharding for pod-level EC repair fan-out.

The storage protocol itself (quorums, gossip, anti-entropy) runs host-side
over DCN — the reference has no NCCL/MPI analog to port (SURVEY.md §2.3).
The TPU mesh is used where the math is: batched erasure coding and scrub
hashing shard embarrassingly over blocks ("blocks" axis = the DP analog),
with a small `psum` only for fleet-wide scrub statistics.  Laid out so all
collectives ride ICI.
"""

from __future__ import annotations


def make_mesh(n_devices: int | None = None, axis: str = "blocks"):
    """1-D mesh over the first n devices (or all)."""
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            # dry-run path: fall back to the virtual CPU devices
            # (--xla_force_host_platform_device_count)
            try:
                cpus = jax.devices("cpu")
            except RuntimeError:
                cpus = []
            if len(cpus) >= n_devices:
                devs = cpus
            else:
                raise RuntimeError(
                    f"need {n_devices} devices, jax sees {len(devs)} "
                    f"(+{len(cpus)} cpu); set "
                    "--xla_force_host_platform_device_count for CPU dry-runs"
                )
        devs = devs[:n_devices]
    import numpy as np

    return Mesh(np.array(devs), (axis,))


def block_sharding(mesh, axis: str = "blocks"):
    """Shard the leading (block-batch) dimension across the mesh."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P(axis))


def replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P())
