from .mesh import block_sharding, make_mesh, replicated

__all__ = ["make_mesh", "block_sharding", "replicated"]
