"""Admin RPC: the operator control plane over the netapp mesh.

Reference src/garage/admin/mod.rs:38-88 — the CLI connects to the daemon
as an ephemeral authenticated peer and issues AdminRpc commands; the
daemon executes them against its Garage instance.  Ops are msgpack
["name", {args}] pairs on endpoint `admin/rpc`.
"""

from __future__ import annotations

import logging
from typing import Any

from ..net.message import Req, Resp
from ..rpc.layout.types import NodeRole
from ..utils.data import hex_of

logger = logging.getLogger("garage.admin")


def _probe_summary():
    from ..ops.telemetry import probe_failure_summary

    return probe_failure_summary()


class AdminRpcHandler:
    def __init__(self, garage):
        self.garage = garage
        ep = garage.netapp.endpoint("admin/rpc")
        ep.set_handler(self._handle)

    async def _handle(self, from_id: bytes, req: Req) -> Resp:
        op, args = req.body[0], req.body[1] or {}
        fn = getattr(self, f"op_{op.replace('-', '_')}", None)
        if fn is None:
            raise ValueError(f"unknown admin op {op!r}")
        return Resp(await fn(args))

    # --- cluster --------------------------------------------------------------

    async def op_status(self, args) -> Any:
        sysd = self.garage.system
        h = sysd.health()
        peers = []
        for pid, state in sysd.peering.peer_states().items():
            st = sysd.node_status.get(pid)
            peers.append(
                {
                    "id": hex_of(pid),
                    "state": state,
                    "hostname": st[0].hostname if st else "?",
                }
            )
        layout = self.garage.layout_manager.history
        cur = layout.current()
        roles = {
            hex_of(n): {
                "zone": r.zone,
                "capacity": r.capacity,
                "tags": r.tags,
            }
            for n, r in cur.roles.items()
        }
        return {
            "node_id": hex_of(sysd.id),
            "health": h.__dict__,
            "peers": peers,
            "layout_version": cur.version,
            "roles": roles,
            "staged": [
                [hex_of(bytes(k)), v]
                for k, v in layout.staging.roles.items()
            ],
        }

    async def op_connect(self, args) -> Any:
        nid = bytes.fromhex(args["node"])
        addr = (args["host"], int(args["port"]))
        await self.garage.netapp.connect(addr, nid)
        return "connected"

    # --- layout ---------------------------------------------------------------

    async def op_layout_assign(self, args) -> Any:
        node = bytes.fromhex(args["node"])
        if args.get("gateway"):
            role = NodeRole(zone=args["zone"], capacity=None, tags=args.get("tags", []))
        else:
            role = NodeRole(
                zone=args["zone"],
                capacity=int(args["capacity"]),
                tags=args.get("tags", []),
            )
        self.garage.layout_manager.stage_role(node, role)
        return "staged"

    async def op_layout_remove(self, args) -> Any:
        self.garage.layout_manager.stage_role(bytes.fromhex(args["node"]), None)
        return "staged removal"

    async def op_layout_apply(self, args) -> Any:
        lv, report = self.garage.layout_manager.apply_staged(args.get("version"))
        warn = self.garage.ec_layout_warning(lv)
        if warn:
            report = list(report) + [warn]
        return {"version": lv.version, "report": report}

    async def op_layout_revert(self, args) -> Any:
        self.garage.layout_manager.revert_staged()
        return "reverted"

    async def op_layout_show(self, args) -> Any:
        layout = self.garage.layout_manager.history
        cur = layout.current()
        return {
            "version": cur.version,
            "roles": {
                hex_of(n): [r.zone, r.capacity, r.tags]
                for n, r in cur.roles.items()
            },
            "staged": [
                [hex_of(bytes(k)), v] for k, v in layout.staging.roles.items()
            ],
            "partition_size": cur.partition_size,
        }

    async def op_layout_config(self, args) -> Any:
        """Stage layout parameters (reference cli layout config -r):
        zone_redundancy = "maximum" or an integer."""
        zr = args.get("zone_redundancy")
        if zr is None:
            raise ValueError("zone_redundancy required")
        from ..rpc.layout.types import ZoneRedundancy

        val = ZoneRedundancy.MAXIMUM if zr == "maximum" else int(zr)
        self.garage.layout_manager.local_update(
            lambda h: h.staging.parameters.update({"zone_redundancy": val})
        )
        return f"staged zone_redundancy = {zr}"

    async def op_layout_history(self, args) -> Any:
        """Layout version history + per-node update trackers (reference
        cli layout history)."""
        h = self.garage.layout_manager.history
        nodes = h.all_nodes()
        return {
            "current_version": h.current().version,
            "min_stored": h.min_stored(),
            "versions": [
                {
                    "version": v.version,
                    "status": "current" if v is h.current() else "draining",
                    "storage_nodes": len(v.storage_nodes()),
                    "gateway_nodes": len(v.all_nodes()) - len(v.storage_nodes()),
                }
                for v in h.versions
            ],
            "trackers": {
                hex_of(n): {
                    "ack": h.ack.get(n),
                    "sync": h.sync.get(n),
                    "sync_ack": h.sync_ack.get(n),
                }
                for n in nodes
            },
        }

    async def op_layout_skip_dead_nodes(self, args) -> Any:
        """Force dead nodes' trackers forward so a stuck layout transition
        can complete without them (reference cli layout skip-dead-nodes
        --version N [--allow-missing-data])."""
        version = args.get("version")
        allow_missing = bool(args.get("allow_missing_data"))
        lm = self.garage.layout_manager
        h = lm.history
        if version is None:
            version = h.current().version
        if version > h.current().version:
            raise ValueError(f"version {version} does not exist yet")
        skipped = []

        def mutate(hist):
            for n in hist.all_nodes():
                if self.garage.netapp.is_connected(n) or n == self.garage.node_id:
                    continue
                changed = hist.ack.set_max(n, version)
                if allow_missing:
                    changed = hist.sync.set_max(n, version) or changed
                    changed = hist.sync_ack.set_max(n, version) or changed
                if changed:
                    skipped.append(hex_of(n))

        lm.local_update(mutate)  # persists + gossips to connected peers
        return {"version": version, "skipped_nodes": skipped}

    # --- block operations (reference src/garage/cli block subcommands) --------

    async def op_block_list_errors(self, args) -> Any:
        from ..block.resync import unpack_error
        from ..utils.time_util import now_msec

        resync = self.garage.block_manager.resync
        out = []
        for h, v in resync.errors.iter_range():
            count, next_try, first = unpack_error(v)
            out.append(
                {
                    "hash": h.hex(),
                    "failures": count,
                    "next_try_in_secs": max(0, (next_try - now_msec()) // 1000),
                    # error AGE: transient blip vs stuck block (None for
                    # entries written before age tracking)
                    "age_secs": (
                        max(0, (now_msec() - first) // 1000)
                        if first is not None
                        else None
                    ),
                }
            )
        return out

    def _resolve_block_hash(self, prefix_hex: str) -> bytes:
        """Accept a full hash or an unambiguous hex prefix."""
        bm = self.garage.block_manager
        prefix = bytes.fromhex(
            prefix_hex if len(prefix_hex) % 2 == 0 else prefix_hex[:-1]
        )
        matches = []
        for h, _v in bm.rc.tree.iter_range(start=prefix):
            if not h.startswith(prefix):
                break
            if not h.hex().startswith(prefix_hex):
                continue  # odd-length prefix: half-byte mismatch, keep scanning
            matches.append(h)
            if len(matches) > 2:
                break
        if not matches:
            raise ValueError(f"no block with hash prefix {prefix_hex}")
        if len(matches) > 1:
            raise ValueError(f"ambiguous hash prefix {prefix_hex}")
        return matches[0]

    async def op_block_info(self, args) -> Any:
        g = self.garage
        bm = g.block_manager
        h = self._resolve_block_hash(args["hash"])
        refs = []
        truncated = False
        async for ref in self._iter_block_refs(h):
            if ref.deleted.get():
                continue
            if len(refs) >= 1000:
                truncated = True
                break
            ver = await g.version_table.get_local(bytes(ref.version), b"")
            refs.append(
                {
                    "version": bytes(ref.version).hex(),
                    "bucket_id": hex_of(ver.bucket_id) if ver else None,
                    "key": ver.key if ver else None,
                    "deleted": ver.deleted.get() if ver else None,
                }
            )
        from ..utils.serde import unpack

        err = bm.resync.errors.get(h)
        return {
            "hash": h.hex(),
            "refcount": bm.rc.get(h),
            "needed": bm.rc.is_needed(h),
            "stored_locally": bm.find_block_file(h) is not None
            or bool(bm.local_pieces(h)),
            "error_count": unpack(err)[0] if err else 0,
            "refs": refs,
            "refs_truncated": truncated,
        }

    async def _iter_block_refs(self, h: bytes):
        """Page through ALL local refs of a block (no silent 1000 cap)."""
        cursor = None
        while True:
            batch = await self.garage.block_ref_table.get_range_local(
                h, cursor, None, 1000
            )
            for ref in batch:
                yield ref
            if len(batch) < 1000:
                return
            cursor = bytes(batch[-1].version) + b"\x00"

    async def op_block_retry_now(self, args) -> Any:
        resync = self.garage.block_manager.resync
        if args.get("all"):
            hashes = [h for h, _v in resync.errors.iter_range()]
        else:
            hashes = [self._resolve_block_hash(args["hash"])]
        for h in hashes:
            resync.errors.remove(h)
            resync.queue_block(h)
        return f"{len(hashes)} blocks requeued for immediate resync"

    async def op_block_purge(self, args) -> Any:
        """Delete every object version referencing a block — the way out
        when a block is irrecoverably lost (reference block purge)."""
        if not args.get("yes"):
            raise ValueError("refusing to purge without yes=true")
        g = self.garage
        h = self._resolve_block_hash(args["hash"])
        from ..model.s3.object_table import Object, ObjectVersion, next_timestamp
        from ..model.s3.version_table import Version
        from ..utils.data import gen_uuid

        versions = objects = 0
        async for ref in self._iter_block_refs(h):
            if ref.deleted.get():
                continue
            ver = await g.version_table.get(bytes(ref.version), b"")
            if ver is None:
                continue
            if not ver.deleted.get():
                await g.version_table.insert(
                    Version.deleted_marker(ver.uuid, ver.bucket_id, ver.key)
                )
                versions += 1
            obj = await g.object_table.get(ver.bucket_id, ver.key.encode())
            if obj is not None and any(
                v.uuid == ver.uuid or v.data.get("vid") == ver.uuid
                for v in obj.versions
            ):
                dm = ObjectVersion(
                    gen_uuid(), next_timestamp(obj), "complete",
                    {"t": "delete_marker"},
                )
                await g.object_table.insert(
                    Object(ver.bucket_id, ver.key, [dm])
                )
                objects += 1
        return {"hash": h.hex(), "versions_deleted": versions, "objects_deleted": objects}

    # --- buckets --------------------------------------------------------------

    async def op_bucket_list(self, args) -> Any:
        out = []
        for b in await self.garage.helper.list_buckets():
            names = [n for n, v in b.params().aliases.items() if v]
            out.append({"id": hex_of(b.id), "aliases": names})
        return out

    async def op_bucket_create(self, args) -> Any:
        bid = await self.garage.helper.create_bucket(args["name"])
        return {"id": hex_of(bid)}

    async def op_bucket_delete(self, args) -> Any:
        bid = await self.garage.helper.resolve_bucket(args["name"])
        await self.garage.helper.delete_bucket(bid)
        return "deleted"

    async def op_bucket_info(self, args) -> Any:
        bid = await self.garage.helper.resolve_bucket(args["name"])
        b = await self.garage.helper.get_bucket(bid)
        p = b.params()
        return {
            "id": hex_of(bid),
            "aliases": [n for n, v in p.aliases.items() if v],
            "website": p.website.get(),
            "quotas": p.quotas.get(),
        }

    async def op_bucket_allow(self, args) -> Any:
        bid = await self.garage.helper.resolve_bucket(args["bucket"])
        await self.garage.helper.set_bucket_key_permissions(
            bid,
            args["key"],
            bool(args.get("read")),
            bool(args.get("write")),
            bool(args.get("owner")),
        )
        return "granted"

    async def op_bucket_deny(self, args) -> Any:
        bid = await self.garage.helper.resolve_bucket(args["bucket"])
        await self.garage.helper.set_bucket_key_permissions(
            bid, args["key"], False, False, False
        )
        return "revoked"

    async def op_bucket_website(self, args) -> Any:
        bid = await self.garage.helper.resolve_bucket(args["bucket"])
        b = await self.garage.helper.get_bucket(bid)
        if args.get("allow"):
            b.params().website.update(
                {
                    "index_document": args.get("index_document") or "index.html",
                    "error_document": args.get("error_document"),
                }
            )
        else:
            b.params().website.update(None)
        await self.garage.bucket_table.insert(b)
        return "website " + ("enabled" if args.get("allow") else "disabled")

    async def op_bucket_quota(self, args) -> Any:
        """Only the quotas present in `args` change; absent keys keep their
        current value (None clears one explicitly)."""
        bid = await self.garage.helper.resolve_bucket(args["bucket"])
        b = await self.garage.helper.get_bucket(bid)
        q = dict(b.params().quotas.get() or {})
        for field in ("max_size", "max_objects"):
            if field in args:
                q[field] = args[field]
        b.params().quotas.update(q)
        await self.garage.bucket_table.insert(b)
        return "quotas updated"

    async def op_bucket_alias(self, args) -> Any:
        bid = await self.garage.helper.resolve_bucket(args["bucket"])
        if args.get("local_key"):
            await self.garage.helper.set_local_alias(
                bid, args["local_key"], args["alias"]
            )
        else:
            await self.garage.helper.set_global_alias(bid, args["alias"])
        return "alias added"

    async def op_bucket_unalias(self, args) -> Any:
        bid = await self.garage.helper.resolve_bucket(args["bucket"])
        if args.get("local_key"):
            await self.garage.helper.unset_local_alias(
                bid, args["local_key"], args["alias"]
            )
        else:
            await self.garage.helper.unset_global_alias(bid, args["alias"])
        return "alias removed"

    # --- keys -----------------------------------------------------------------

    async def op_key_new(self, args) -> Any:
        key = await self.garage.helper.create_key(args.get("name", ""))
        if args.get("allow_create_bucket"):
            key.params().allow_create_bucket.update(True)
            await self.garage.key_table.insert(key)
        return {"key_id": key.key_id, "secret_key": key.secret()}

    async def op_key_list(self, args) -> Any:
        return [
            {"key_id": k.key_id, "name": k.params().name.get()}
            for k in await self.garage.helper.list_keys()
        ]

    async def op_key_info(self, args) -> Any:
        k = await self.garage.helper.get_key(args["key"])
        p = k.params()
        return {
            "key_id": k.key_id,
            "name": p.name.get(),
            "secret_key": p.secret_key if args.get("show_secret") else "(hidden)",
            "buckets": [
                hex_of(bytes(b)) for b, _perm in p.authorized_buckets.items()
            ],
        }

    async def op_key_delete(self, args) -> Any:
        await self.garage.helper.delete_key(args["key"])
        return "deleted"

    async def op_key_import(self, args) -> Any:
        k = await self.garage.helper.import_key(
            args["key_id"], args["secret"], args.get("name", "")
        )
        return {"key_id": k.key_id}

    async def op_key_set(self, args) -> Any:
        k = await self.garage.helper.update_key(
            args["key"],
            name=args.get("name"),
            allow_create_bucket=args.get("allow_create_bucket"),
        )
        return {
            "key_id": k.key_id,
            "name": k.params().name.get(),
            "allow_create_bucket": bool(k.params().allow_create_bucket.get()),
        }

    # --- workers / repair -----------------------------------------------------

    async def op_worker_list(self, args) -> Any:
        return [
            {
                "id": wid,
                "name": info.name,
                "state": info.state,
                "errors": info.errors,
                "consecutive_errors": info.consecutive_errors,
                "last_error": info.last_error,
                "tranquility": info.tranquility,
                "iterations": info.iterations,
                "last_duration_secs": info.last_duration_secs,
                "duration_ewma_secs": info.duration_ewma_secs,
                "throughput": info.throughput,
                "last_completed": info.last_completed,
                "info": info.progress,
            }
            for wid, info in self.garage.bg.worker_info().items()
        ]

    async def op_worker_get(self, args) -> Any:
        if args.get("var"):
            return {args["var"]: self.garage.bg_vars.get(args["var"])}
        return self.garage.bg_vars.all()

    async def op_worker_set(self, args) -> Any:
        self.garage.bg_vars.set(args["var"], args["value"])
        return {args["var"]: self.garage.bg_vars.get(args["var"])}

    async def op_repair(self, args) -> Any:
        what = args.get("what", "blocks")
        from ..block.repair import RebalanceWorker, RepairWorker
        from ..model.repair import (
            BlockRefRepairWorker,
            MpuRepairWorker,
            VersionRepairWorker,
        )

        if what == "blocks":
            self.garage.bg.spawn(RepairWorker(self.garage.block_manager))
        elif what == "rebalance":
            self.garage.bg.spawn(RebalanceWorker(self.garage.block_manager))
        elif what == "tables":
            for t in self.garage.tables:
                await t.syncer.sync_all_partitions()
        elif what == "versions":
            self.garage.bg.spawn(VersionRepairWorker(self.garage))
        elif what == "mpu":
            self.garage.bg.spawn(MpuRepairWorker(self.garage))
        elif what == "block-refs":
            self.garage.bg.spawn(BlockRefRepairWorker(self.garage))
        elif what == "scrub":
            sw = getattr(self.garage.block_manager, "scrub_worker", None)
            if sw is None:
                raise ValueError("scrub worker not running")
            cmd = args.get("cmd", "start")
            if cmd == "start":
                sw.cmd_start()
            elif cmd == "pause":
                sw.cmd_pause()
            elif cmd == "resume":
                sw.cmd_resume()
            elif cmd == "cancel":
                sw.cmd_cancel()
            elif cmd == "set-tranquility":
                sw.cmd_set_tranquility(int(args["value"]))
            else:
                raise ValueError(f"unknown scrub command {cmd!r}")
            return {"scrub": sw.status()}
        elif what == "plan":
            # repair plane (block/repair_plan.py): status/launch/cancel
            cmd = args.get("cmd", "status")
            if cmd == "status":
                return self.garage.repair_plan_status()
            if cmd == "launch":
                self.garage.launch_repair_plan(fresh=bool(args.get("fresh")))
                return self.garage.repair_plan_status()
            if cmd == "cancel":
                p = self.garage.repair_planner
                if p is None or p.finished:
                    raise ValueError("no repair plan running")
                p.cmd_cancel()
                return "repair plan cancelled"
            raise ValueError(f"unknown plan command {cmd!r}")
        else:
            raise ValueError(f"unknown repair target {what!r}")
        return f"repair {what} launched"

    # --- flight recorder (debug profile/slow, utils/flight.py) ----------------

    async def op_debug_profile(self, args) -> Any:
        from ..utils import flight

        prof = await flight.profile(
            args.get("seconds") or 2.0, hz=args.get("hz") or 100
        )
        out: dict[str, Any] = {"samples": prof.samples}
        if args.get("format") == "speedscope":
            out["speedscope"] = prof.speedscope()
        else:
            out["folded"] = prof.folded()
        return out

    async def op_debug_slow(self, args) -> Any:
        from ..utils import flight

        return flight.slow_response(getattr(self.garage, "flight_recorder", None))

    async def op_debug_latency(self, args) -> Any:
        from ..utils.latency import latency_response

        return latency_response()

    async def op_meta_snapshot(self, args) -> Any:
        from ..model.snapshot import take_snapshot

        return {"snapshot": take_snapshot(self.garage)}

    async def op_stats(self, args) -> Any:
        g = self.garage
        return {
            "db_engine": g.db.engine,
            "tables": {
                t.schema.table_name: {
                    "entries": len(t.data.store),
                    "merkle_todo": len(t.data.merkle_todo),
                    "gc_todo": len(t.data.gc_todo),
                }
                for t in g.tables
            },
            "blocks": {
                "rc_entries": len(g.block_manager.rc.tree),
                "resync_queue": g.block_manager.resync.queue_len(),
                "resync_errors": g.block_manager.resync.errors_len(),
            },
            # local telemetry digest (rpc/telemetry_digest.py) — the same
            # row this node gossips to its peers
            "telemetry": g.telemetry.collect(),
            # newest banked TPU probe wedge verdict (bench.py
            # phased_probe, ISSUE 11) — null on boxes that never wedged
            "tpuProbe": _probe_summary(),
        }

    async def op_overload_status(self, args) -> Any:
        """Overload-control plane state (admission + shedding ladder) —
        `cli overload status`."""
        return self.garage.overload_status()

    async def op_cluster_telemetry(self, args) -> Any:
        """The cluster rollup (per-node digests + aggregates + outliers
        + SLO) over the admin mesh — `cluster top` / `cluster telemetry`."""
        from ..rpc.telemetry_digest import rollup

        return rollup(self.garage)

    async def op_durability(self, args) -> Any:
        """Durability observatory (block/durability.py): redundancy
        ledger + zone exposure + repair ETA — `cluster durability`."""
        from ..block.durability import durability_response

        return durability_response(self.garage)

    async def op_codec(self, args) -> Any:
        """Codec X-ray (ops/telemetry.py): per-kernel pad accounting,
        compile events, overlap efficiency, lane linger + the cluster
        view from the gossiped codec.* keys — `cluster codec` /
        `codec top`."""
        from ..rpc.telemetry_digest import codec_response

        return codec_response(self.garage)

    async def op_transition(self, args) -> Any:
        """Rebalance observatory (rpc/transition.py): layout-transition
        flight deck + cluster version spread — `cluster transition`."""
        from ..rpc.transition import transition_response

        return transition_response(self.garage)

    async def op_cluster_events(self, args) -> Any:
        """Federated event timeline (rpc/transition.py): skew-corrected
        merge of every node's flight events — `cluster events`."""
        from ..rpc.transition import cluster_events_response

        return await cluster_events_response(
            self.garage,
            since=float(args.get("since") or 0.0),
            min_severity=str(args.get("min_severity") or "info"),
        )

    async def op_tenants(self, args) -> Any:
        """Tenant observatory (rpc/tenant.py): cluster-summed per-tenant
        consumption, fairness stats, per-tenant SLO burn — `cluster
        tenants`."""
        from ..rpc.tenant import tenants_response

        return tenants_response(self.garage)

    async def op_traffic(self, args) -> Any:
        """Traffic observatory (rpc/traffic.py): hot objects/buckets,
        op mix, skew, slow-peer ranking, cluster rollup — `cluster hot`."""
        from ..rpc.traffic import traffic_response

        return traffic_response(self.garage)

    async def op_traffic_profile(self, args) -> Any:
        """Replayable workload profile — `cluster hot --profile`."""
        from ..rpc.traffic import profile_response

        return profile_response(self.garage)
