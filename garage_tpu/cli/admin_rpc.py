"""Admin RPC: the operator control plane over the netapp mesh.

Reference src/garage/admin/mod.rs:38-88 — the CLI connects to the daemon
as an ephemeral authenticated peer and issues AdminRpc commands; the
daemon executes them against its Garage instance.  Ops are msgpack
["name", {args}] pairs on endpoint `admin/rpc`.
"""

from __future__ import annotations

import logging
from typing import Any

from ..net.message import Req, Resp
from ..rpc.layout.types import NodeRole
from ..utils.data import hex_of

logger = logging.getLogger("garage.admin")


class AdminRpcHandler:
    def __init__(self, garage):
        self.garage = garage
        ep = garage.netapp.endpoint("admin/rpc")
        ep.set_handler(self._handle)

    async def _handle(self, from_id: bytes, req: Req) -> Resp:
        op, args = req.body[0], req.body[1] or {}
        fn = getattr(self, f"op_{op.replace('-', '_')}", None)
        if fn is None:
            raise ValueError(f"unknown admin op {op!r}")
        return Resp(await fn(args))

    # --- cluster --------------------------------------------------------------

    async def op_status(self, args) -> Any:
        sysd = self.garage.system
        h = sysd.health()
        peers = []
        for pid, state in sysd.peering.peer_states().items():
            st = sysd.node_status.get(pid)
            peers.append(
                {
                    "id": hex_of(pid),
                    "state": state,
                    "hostname": st[0].hostname if st else "?",
                }
            )
        layout = self.garage.layout_manager.history
        cur = layout.current()
        roles = {
            hex_of(n): {
                "zone": r.zone,
                "capacity": r.capacity,
                "tags": r.tags,
            }
            for n, r in cur.roles.items()
        }
        return {
            "node_id": hex_of(sysd.id),
            "health": h.__dict__,
            "peers": peers,
            "layout_version": cur.version,
            "roles": roles,
            "staged": [
                [hex_of(bytes(k)), v]
                for k, v in layout.staging.roles.items()
            ],
        }

    async def op_connect(self, args) -> Any:
        nid = bytes.fromhex(args["node"])
        addr = (args["host"], int(args["port"]))
        await self.garage.netapp.connect(addr, nid)
        return "connected"

    # --- layout ---------------------------------------------------------------

    async def op_layout_assign(self, args) -> Any:
        node = bytes.fromhex(args["node"])
        if args.get("gateway"):
            role = NodeRole(zone=args["zone"], capacity=None, tags=args.get("tags", []))
        else:
            role = NodeRole(
                zone=args["zone"],
                capacity=int(args["capacity"]),
                tags=args.get("tags", []),
            )
        self.garage.layout_manager.stage_role(node, role)
        return "staged"

    async def op_layout_remove(self, args) -> Any:
        self.garage.layout_manager.stage_role(bytes.fromhex(args["node"]), None)
        return "staged removal"

    async def op_layout_apply(self, args) -> Any:
        lv, report = self.garage.layout_manager.apply_staged(args.get("version"))
        return {"version": lv.version, "report": report}

    async def op_layout_revert(self, args) -> Any:
        self.garage.layout_manager.revert_staged()
        return "reverted"

    async def op_layout_show(self, args) -> Any:
        layout = self.garage.layout_manager.history
        cur = layout.current()
        return {
            "version": cur.version,
            "roles": {
                hex_of(n): [r.zone, r.capacity, r.tags]
                for n, r in cur.roles.items()
            },
            "staged": [
                [hex_of(bytes(k)), v] for k, v in layout.staging.roles.items()
            ],
            "partition_size": cur.partition_size,
        }

    # --- buckets --------------------------------------------------------------

    async def op_bucket_list(self, args) -> Any:
        out = []
        for b in await self.garage.helper.list_buckets():
            names = [n for n, v in b.params().aliases.items() if v]
            out.append({"id": hex_of(b.id), "aliases": names})
        return out

    async def op_bucket_create(self, args) -> Any:
        bid = await self.garage.helper.create_bucket(args["name"])
        return {"id": hex_of(bid)}

    async def op_bucket_delete(self, args) -> Any:
        bid = await self.garage.helper.resolve_bucket(args["name"])
        await self.garage.helper.delete_bucket(bid)
        return "deleted"

    async def op_bucket_info(self, args) -> Any:
        bid = await self.garage.helper.resolve_bucket(args["name"])
        b = await self.garage.helper.get_bucket(bid)
        p = b.params()
        return {
            "id": hex_of(bid),
            "aliases": [n for n, v in p.aliases.items() if v],
            "website": p.website.get(),
            "quotas": p.quotas.get(),
        }

    async def op_bucket_allow(self, args) -> Any:
        bid = await self.garage.helper.resolve_bucket(args["bucket"])
        await self.garage.helper.set_bucket_key_permissions(
            bid,
            args["key"],
            bool(args.get("read")),
            bool(args.get("write")),
            bool(args.get("owner")),
        )
        return "granted"

    async def op_bucket_deny(self, args) -> Any:
        bid = await self.garage.helper.resolve_bucket(args["bucket"])
        await self.garage.helper.set_bucket_key_permissions(
            bid, args["key"], False, False, False
        )
        return "revoked"

    # --- keys -----------------------------------------------------------------

    async def op_key_new(self, args) -> Any:
        key = await self.garage.helper.create_key(args.get("name", ""))
        if args.get("allow_create_bucket"):
            key.params().allow_create_bucket.update(True)
            await self.garage.key_table.insert(key)
        return {"key_id": key.key_id, "secret_key": key.secret()}

    async def op_key_list(self, args) -> Any:
        return [
            {"key_id": k.key_id, "name": k.params().name.get()}
            for k in await self.garage.helper.list_keys()
        ]

    async def op_key_info(self, args) -> Any:
        k = await self.garage.helper.get_key(args["key"])
        p = k.params()
        return {
            "key_id": k.key_id,
            "name": p.name.get(),
            "secret_key": p.secret_key if args.get("show_secret") else "(hidden)",
            "buckets": [
                hex_of(bytes(b)) for b, _perm in p.authorized_buckets.items()
            ],
        }

    async def op_key_delete(self, args) -> Any:
        await self.garage.helper.delete_key(args["key"])
        return "deleted"

    # --- workers / repair -----------------------------------------------------

    async def op_worker_list(self, args) -> Any:
        return [
            {
                "id": wid,
                "name": info.name,
                "state": info.state,
                "errors": info.errors,
                "info": info.progress,
            }
            for wid, info in self.garage.bg.worker_info().items()
        ]

    async def op_worker_get(self, args) -> Any:
        if args.get("var"):
            return {args["var"]: self.garage.bg_vars.get(args["var"])}
        return self.garage.bg_vars.all()

    async def op_worker_set(self, args) -> Any:
        self.garage.bg_vars.set(args["var"], args["value"])
        return {args["var"]: self.garage.bg_vars.get(args["var"])}

    async def op_repair(self, args) -> Any:
        what = args.get("what", "blocks")
        from ..block.repair import RebalanceWorker, RepairWorker

        if what == "blocks":
            self.garage.bg.spawn(RepairWorker(self.garage.block_manager))
        elif what == "rebalance":
            self.garage.bg.spawn(RebalanceWorker(self.garage.block_manager))
        elif what == "tables":
            for t in self.garage.tables:
                await t.syncer.sync_all_partitions()
        else:
            raise ValueError(f"unknown repair target {what!r}")
        return f"repair {what} launched"

    async def op_meta_snapshot(self, args) -> Any:
        from ..model.snapshot import take_snapshot

        return {"snapshot": take_snapshot(self.garage)}

    async def op_stats(self, args) -> Any:
        g = self.garage
        return {
            "db_engine": g.db.engine,
            "tables": {
                t.schema.table_name: {
                    "entries": len(t.data.store),
                    "merkle_todo": len(t.data.merkle_todo),
                    "gc_todo": len(t.data.gc_todo),
                }
                for t in g.tables
            },
            "blocks": {
                "rc_entries": len(g.block_manager.rc.tree),
                "resync_queue": g.block_manager.resync.queue_len(),
                "resync_errors": g.block_manager.resync.errors_len(),
            },
        }
