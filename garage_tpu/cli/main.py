"""garage-tpu CLI + daemon (reference src/garage/main.rs + cli/).

    python -m garage_tpu.cli server -c garage.toml
    python -m garage_tpu.cli -c garage.toml status
    python -m garage_tpu.cli -c garage.toml node id
    python -m garage_tpu.cli -c garage.toml layout assign <node> -z dc1 -c 100G
    python -m garage_tpu.cli -c garage.toml layout apply / show / revert
    python -m garage_tpu.cli -c garage.toml bucket create/list/info/delete/allow/deny
    python -m garage_tpu.cli -c garage.toml key new/list/info/delete
    python -m garage_tpu.cli -c garage.toml worker list
    python -m garage_tpu.cli -c garage.toml repair blocks|rebalance|tables
    python -m garage_tpu.cli -c garage.toml stats

Non-server commands connect to the running daemon as an ephemeral
authenticated peer (reference main.rs:281-324) and issue AdminRpc ops.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import signal
import sys
import time

from ..format_table import format_table
from ..model.garage import Garage, _parse_addr, network_key_from_secret
from ..net.handshake import gen_node_key
from ..net.netapp import NetApp
from ..utils.config import read_config


def main(argv=None):
    ap = argparse.ArgumentParser(prog="garage-tpu")
    ap.add_argument(
        "-c", "--config",
        default=os.environ.get("GARAGE_CONFIG_FILE", "/etc/garage.toml"),
    )
    ap.add_argument("--json", action="store_true", help="raw JSON output")
    sub = ap.add_subparsers(dest="cmd", required=True)

    sub.add_parser("server", help="run the storage daemon")
    sub.add_parser("status")
    sub.add_parser("stats")
    node = sub.add_parser("node")
    node.add_argument("node_cmd", choices=["id", "connect"])
    node.add_argument("arg", nargs="?")

    lay = sub.add_parser("layout")
    lay_sub = lay.add_subparsers(dest="layout_cmd", required=True)
    asg = lay_sub.add_parser("assign")
    asg.add_argument("node")
    asg.add_argument("-z", "--zone", required=True)
    asg.add_argument("-s", "--capacity", help="e.g. 100G (omit for gateway)")
    asg.add_argument("-g", "--gateway", action="store_true")
    asg.add_argument("-t", "--tags", nargs="*", default=[])
    rmv = lay_sub.add_parser("remove")
    rmv.add_argument("node")
    app = lay_sub.add_parser("apply")
    app.add_argument("--version", type=int)
    lay_sub.add_parser("show")
    lay_sub.add_parser("revert")
    lcfg = lay_sub.add_parser("config")
    lcfg.add_argument(
        "-r", "--zone-redundancy", required=True,
        help='"maximum" or an integer number of distinct zones per partition',
    )
    lay_sub.add_parser("history")
    skd = lay_sub.add_parser("skip-dead-nodes")
    skd.add_argument("--version", type=int)
    skd.add_argument(
        "--allow-missing-data", action="store_true",
        help="also mark dead nodes as synced (data they held is abandoned)",
    )

    blk = sub.add_parser("block")
    blk_sub = blk.add_subparsers(dest="block_cmd", required=True)
    blk_sub.add_parser("list-errors")
    binf = blk_sub.add_parser("info")
    binf.add_argument("hash")
    brn = blk_sub.add_parser("retry-now")
    brn.add_argument("hash", nargs="?")
    brn.add_argument("--all", action="store_true")
    bpg = blk_sub.add_parser("purge")
    bpg.add_argument("hash")
    bpg.add_argument("--yes", action="store_true", required=True)

    bkt = sub.add_parser("bucket")
    bkt_sub = bkt.add_subparsers(dest="bucket_cmd", required=True)
    for c in ["create", "delete", "info"]:
        p = bkt_sub.add_parser(c)
        p.add_argument("name")
    bkt_sub.add_parser("list")
    alw = bkt_sub.add_parser("allow")
    alw.add_argument("bucket")
    alw.add_argument("--key", required=True)
    alw.add_argument("--read", action="store_true")
    alw.add_argument("--write", action="store_true")
    alw.add_argument("--owner", action="store_true")
    dny = bkt_sub.add_parser("deny")
    dny.add_argument("bucket")
    dny.add_argument("--key", required=True)
    web_p = bkt_sub.add_parser("website")
    web_p.add_argument("bucket")
    grp = web_p.add_mutually_exclusive_group(required=True)
    grp.add_argument("--allow", action="store_true")
    grp.add_argument("--deny", action="store_true")
    web_p.add_argument("--index-document", default="index.html")
    web_p.add_argument("--error-document")
    quo = bkt_sub.add_parser("quota")
    quo.add_argument("bucket")
    quo.add_argument("--max-size", help="bytes or 100G etc; 'none' clears")
    quo.add_argument("--max-objects", help="count; 'none' clears")
    ali = bkt_sub.add_parser("alias")
    ali.add_argument("bucket")
    ali.add_argument("alias")
    ali.add_argument("--local", help="key id: make a key-local alias")
    una = bkt_sub.add_parser("unalias")
    una.add_argument("bucket")
    una.add_argument("alias")
    una.add_argument("--local", help="key id: remove a key-local alias")

    key = sub.add_parser("key")
    key_sub = key.add_subparsers(dest="key_cmd", required=True)
    knew = key_sub.add_parser("new")
    knew.add_argument("--name", default="")
    knew.add_argument("--allow-create-bucket", action="store_true")
    key_sub.add_parser("list")
    kinf = key_sub.add_parser("info")
    kinf.add_argument("key")
    kinf.add_argument("--show-secret", action="store_true")
    kdel = key_sub.add_parser("delete")
    kdel.add_argument("key")
    kimp = key_sub.add_parser("import")
    kimp.add_argument("key_id")
    kimp.add_argument("secret")
    kimp.add_argument("--name", default="imported")
    kset = key_sub.add_parser("set")
    kset.add_argument("key")
    kset.add_argument("--name")
    acb = kset.add_mutually_exclusive_group()
    acb.add_argument("--allow-create-bucket", action="store_true", default=None)
    acb.add_argument("--deny-create-bucket", action="store_true", default=None)

    clu = sub.add_parser(
        "cluster", help="cluster-wide telemetry from the gossiped digests"
    )
    clu_sub = clu.add_subparsers(dest="cluster_cmd", required=True)
    ctop = clu_sub.add_parser(
        "top", help="live per-node table (any node answers for all)"
    )
    ctop.add_argument(
        "-n", "--interval", type=float, default=2.0,
        help="refresh interval in seconds",
    )
    ctop.add_argument(
        "--once", action="store_true", help="render one frame and exit"
    )
    clu_sub.add_parser("telemetry", help="raw cluster rollup JSON")
    chot = clu_sub.add_parser(
        "hot", help="traffic observatory: hot objects/buckets, op mix, "
        "slow peers (rpc/traffic.py)",
    )
    chot.add_argument(
        "--profile", action="store_true",
        help="print the replayable workload profile JSON instead",
    )
    chot.add_argument(
        "--top", type=int, default=10, help="hot-object rows to show"
    )
    clu_sub.add_parser(
        "durability",
        help="redundancy ledger: blocks by class, zone-loss exposure, "
        "repair ETA (block/durability.py)",
    )
    clu_sub.add_parser(
        "codec",
        help="codec X-ray: dispatch pad waste, compile events, overlap "
        "efficiency, batcher lane linger (ops/telemetry.py)",
    )
    clu_sub.add_parser(
        "transition",
        help="rebalance observatory: layout-transition flight deck, "
        "version spread, per-pair bytes moved (rpc/transition.py)",
    )
    cten = clu_sub.add_parser(
        "tenants",
        help="tenant observatory: cluster-summed per-tenant consumption, "
        "SLO burn, fairness (rpc/tenant.py)",
    )
    cten.add_argument(
        "--sort", choices=["ops", "rps", "bytes", "shed", "burn"],
        default="ops", help="cluster tenant table sort key",
    )
    cten.add_argument(
        "--top", type=int, default=10, help="tenant rows to show"
    )
    cev = clu_sub.add_parser(
        "events",
        help="federated cluster event timeline: every node's flight "
        "events merged skew-corrected (rpc/transition.py)",
    )
    cev.add_argument(
        "--since", type=float, default=0.0,
        help="only events after this epoch timestamp (seconds)",
    )
    cev.add_argument(
        "--min-severity", choices=["info", "warn", "critical"],
        default="info", help="severity floor for the timeline",
    )
    cev.add_argument(
        "--follow", action="store_true",
        help="poll and stream new events until interrupted",
    )
    cev.add_argument(
        "-n", "--interval", type=float, default=2.0,
        help="poll interval in seconds with --follow",
    )

    cdx = sub.add_parser(
        "codec", help="codec X-ray: local accelerator dispatch economics"
    )
    cdx_sub = cdx.add_subparsers(dest="codec_cmd", required=True)
    cdx_sub.add_parser(
        "top", help="per-kernel breakdown: pad waste, overlap, compile cost, "
        "batcher lane linger",
    )

    ovl = sub.add_parser(
        "overload", help="overload-control plane: admission + shedding ladder"
    )
    ovl.add_argument("overload_cmd", choices=["status"])

    wrk = sub.add_parser("worker")
    wrk.add_argument("worker_cmd", choices=["list", "get", "set"])
    wrk.add_argument("var", nargs="?")
    wrk.add_argument("value", nargs="?")

    dbg = sub.add_parser("debug", help="flight recorder: node self-diagnostics")
    dbg_sub = dbg.add_subparsers(dest="debug_cmd", required=True)
    dpr = dbg_sub.add_parser(
        "profile", help="sample the daemon's stacks (folded/speedscope)"
    )
    dpr.add_argument("--seconds", type=float, default=2.0)
    dpr.add_argument("--hz", type=int, default=100)
    dpr.add_argument(
        "--speedscope", action="store_true",
        help="emit speedscope JSON instead of folded stacks",
    )
    dpr.add_argument("-o", "--output", help="write to a file instead of stdout")
    dbg_sub.add_parser("slow", help="slowest recent requests (span trees)")
    dbg_sub.add_parser(
        "latency",
        help="latency X-ray: rolling per-phase waterfall per S3 op",
    )
    rep = sub.add_parser("repair")
    rep.add_argument(
        "what",
        choices=["blocks", "rebalance", "tables", "versions", "mpu",
                 "block-refs", "scrub", "plan"],
    )
    rep.add_argument(
        "sub_cmd", nargs="?",
        choices=["start", "pause", "resume", "cancel", "set-tranquility",
                 "status", "launch"],
        help="scrub: start|pause|resume|cancel|set-tranquility; "
             "plan: status|launch|cancel",
    )
    rep.add_argument("sub_value", nargs="?")
    rep.add_argument(
        "--fresh", action="store_true",
        help="plan launch: discard a checkpointed plan and rescan",
    )
    meta = sub.add_parser("meta")
    meta.add_argument("meta_cmd", choices=["snapshot"])
    cdb = sub.add_parser("convert-db", help="copy the metadata db between engines")
    cdb.add_argument("--input", required=True, help="src db path")
    cdb.add_argument("--input-engine", default="sqlite")
    cdb.add_argument("--output", required=True, help="dst db path")
    cdb.add_argument("--output-engine", default="sqlite")
    orep = sub.add_parser(
        "offline-repair", help="run repairs without a running daemon"
    )
    orep.add_argument("what", choices=["tables", "blocks", "rebalance"])

    args = ap.parse_args(argv)

    from ..utils.log_fmt import setup_logging

    # trace-correlated logging (utils/log_fmt.py): every record under an
    # active span carries its trace/span ids; GARAGE_LOG_FORMAT=json for
    # JSON lines.  run_server re-applies this once the config is read.
    setup_logging(
        fmt=os.environ.get("GARAGE_LOG_FORMAT", "text"),
        level=os.environ.get("GARAGE_LOG", "INFO"),
    )

    if args.cmd == "server":
        return asyncio.run(run_server(args.config))
    if args.cmd == "convert-db":
        return convert_db(args)
    if args.cmd == "offline-repair":
        return asyncio.run(offline_repair(args))
    return asyncio.run(run_cli(args))


def convert_db(args) -> None:
    """Copy every tree between db engines (reference cli/convert_db.rs)."""
    from ..db import open_db

    src = open_db(args.input, engine=args.input_engine)
    dst = open_db(args.output, engine=args.output_engine, fsync=False)
    total = 0
    for name in src.list_trees():
        st, dt = src.open_tree(name), dst.open_tree(name)
        n = 0
        batch: list[tuple[bytes, bytes]] = []

        def flush(items=None):
            items = batch if items is None else items
            if items:
                dst.transaction(
                    lambda tx: [tx.insert(dt, k, v) for k, v in items] and None
                )
                items.clear()

        for k, v in st.iter_range():
            batch.append((k, v))
            n += 1
            if len(batch) >= 1000:
                flush()
        flush()
        total += n
        print(f"  {name}: {n} entries")
    src.close()
    dst.close()
    print(f"converted {total} entries")


async def offline_repair(args) -> None:
    """Boot Garage WITHOUT network servers and run a repair pass
    (reference src/garage/repair/offline.rs:11-40)."""
    from ..block.repair import RebalanceWorker, RepairWorker
    from ..utils.background import WorkerState

    config = read_config(args.config)
    garage = Garage(config)
    # no garage.start(): no listener, no peering — local-only repairs
    try:
        if args.what == "tables":
            for t in garage.tables:
                # rebuild merkle trees from scratch locally, chunked into
                # batched transactions (2 commits per 100 items, not 2
                # commits per item — a large backlog would otherwise pay
                # millions of journal round-trips)
                todo = list(t.data.merkle_todo.iter_range())
                for i in range(0, len(todo), 100):
                    chunk = todo[i : i + 100]
                    t.merkle.update_batch(chunk)
                    t.data.db.transaction(
                        lambda tx, c=chunk: [
                            tx.remove(t.data.merkle_todo, key)
                            for key, _vh in c
                        ]
                        and None
                    )
                print(f"{t.schema.table_name}: {len(todo)} merkle items")
        else:
            w = (
                RepairWorker(garage.block_manager)
                if args.what == "blocks"
                else RebalanceWorker(garage.block_manager)
            )
            while await w.work() != WorkerState.DONE:
                pass
            # replica-mode repair enqueues into the resync queue: drain it
            # here (no background workers run offline); peers are
            # unreachable, so only local work (deletes, verifies) succeeds
            # and the rest stays queued for the next daemon start
            drained = 0
            while await garage.block_manager.resync.resync_iter():
                drained += 1
            print(
                f"offline {args.what} repair done: {w.status()}, "
                f"{drained} resync items processed "
                f"({garage.block_manager.resync.queue_len()} left for the "
                "running daemon)"
            )
    finally:
        # graft-lint: allow-cancel(one-shot CLI: process exits right after; a ctrl-C mid-teardown is an acceptable partial stop)
        await garage.stop()


async def run_server(config_path: str) -> None:
    """Daemon boot (reference src/garage/server.rs:30)."""
    from ..api.s3.api_server import S3ApiServer
    from .admin_rpc import AdminRpcHandler

    config = read_config(config_path)
    if "GARAGE_LOG_FORMAT" not in os.environ:
        from ..utils.log_fmt import setup_logging

        setup_logging(
            fmt=config.log_format, level=os.environ.get("GARAGE_LOG", "INFO")
        )
    garage = Garage(config)
    await garage.start()
    AdminRpcHandler(garage)
    garage.spawn_workers()

    servers = []
    if config.s3_api.api_bind_addr:
        s3 = S3ApiServer(garage)
        host, port = _parse_addr(config.s3_api.api_bind_addr)
        await s3.start(host, port)
        servers.append(s3)
        if config.admin.canary_enabled:
            # canary prober (api/s3/canary.py): probe through this
            # node's own S3 frontend; a wildcard bind probes loopback
            probe_host = host if host not in ("0.0.0.0", "::") else "127.0.0.1"
            bound_port = s3.runner.addresses[0][1]
            garage.spawn_canary(f"http://{probe_host}:{bound_port}")
    if config.k2v_api.api_bind_addr:
        from ..api.k2v.api_server import K2VApiServer

        k2v = K2VApiServer(garage)
        host, port = _parse_addr(config.k2v_api.api_bind_addr)
        await k2v.start(host, port)
        servers.append(k2v)
    if config.s3_web.bind_addr:
        from ..web.web_server import WebServer

        webs = WebServer(garage)
        host, port = _parse_addr(config.s3_web.bind_addr)
        await webs.start(host, port)
        servers.append(webs)
    if config.admin.api_bind_addr:
        from ..api.admin.api_server import AdminApiServer

        adm = AdminApiServer(garage)
        host, port = _parse_addr(config.admin.api_bind_addr)
        await adm.start(host, port)
        servers.append(adm)

    print(f"garage-tpu node {garage.node_id.hex()} up", flush=True)
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    print("shutting down...", flush=True)
    for s in servers:
        await s.stop()
    await garage.stop()


async def run_cli(args) -> None:
    config = read_config(args.config)
    if args.cmd == "node" and args.node_cmd == "id":
        # local: read the node key from metadata_dir
        from ..net.handshake import node_id_of

        # graft-lint: allow-blocking(one-shot CLI command, loop not shared)
        with open(os.path.join(config.metadata_dir, "node_key"), "rb") as f:
            nid = node_id_of(f.read())
        addr = config.rpc_public_addr or config.rpc_bind_addr
        print(f"{nid.hex()}@{addr}")
        return

    # connect to the daemon as an ephemeral peer
    network_key = network_key_from_secret(config.rpc_secret)
    app = NetApp(network_key, gen_node_key())
    addr = _parse_addr(config.rpc_public_addr or config.rpc_bind_addr)
    if addr[0] == "0.0.0.0":
        addr = ("127.0.0.1", addr[1])
    daemon_id = await app.connect(addr)
    ep = app.endpoint("admin/rpc")

    async def call(op, op_args=None):
        resp = await ep.call(daemon_id, [op, op_args or {}], timeout=120.0)
        return resp.body

    try:
        out = await dispatch(args, call, config)
        if out is not None:
            print(out)
    finally:
        # graft-lint: allow-cancel(one-shot CLI: process exits right after; a ctrl-C mid-teardown is an acceptable partial stop)
        await app.shutdown()


def _ms(secs) -> str:
    return "-" if secs is None else f"{float(secs) * 1000:.1f}ms"


def _render_cluster_top(r: dict) -> str:
    """One frame of `cluster top`: cluster header + SLO line + one row
    per node from the gossiped digests (rpc/telemetry_digest.py)."""
    h = r.get("clusterHealth") or {}
    agg = r.get("aggregate") or {}
    outliers = r.get("outliers") or {}
    head = [
        f"cluster health\t{h.get('status', '?')}",
        f"nodes\t{h.get('connected_nodes', '?')}/{h.get('known_nodes', '?')}"
        f" connected, {r.get('nodesReporting', 0)} reporting digests",
        f"s3\t{agg.get('s3RequestsPerSec', 0):.2f} req/s, "
        f"{agg.get('s3ErrorsPerSec', 0):.2f} 5xx/s",
        f"backlogs\tresync {agg.get('resyncQueue', 0):g}, "
        f"repair {agg.get('repairBacklog', 0):g}",
        f"outliers\t{', '.join(o[:16] for o in sorted(outliers)) or '(none)'}",
    ]
    slo = r.get("slo")
    if slo:
        head.append(
            "slo budget\t"
            f"avail {slo['availability']['budgetRemaining'] * 100:.1f}% "
            f"(burn {slo['availability']['burnRate']:.2f}), "
            f"p99 {slo['latencyP99']['budgetRemaining'] * 100:.1f}% "
            f"(burn {slo['latencyP99']['burnRate']:.2f})"
        )
    # metadata plane (ISSUE 15): the answering node's effective meta
    # quorums; per-node disagreement is flagged META-RF! in the rows
    self_meta = next(
        (
            (n.get("digest") or {}).get("meta")
            for n in r.get("nodes", [])
            if n.get("isSelf") and (n.get("digest") or {}).get("meta")
        ),
        None,
    )
    if self_meta:
        head.append(
            f"meta quorums\trf {self_meta.get('rf')} "
            f"(read {self_meta.get('rq')} / write {self_meta.get('wq')})"
        )
    # codec X-ray (ISSUE 17): cluster dispatch economics at a glance —
    # worst-node pad waste and the cluster compile burden
    if agg.get("codecDispatches"):
        cpw = agg.get("codecPadWasteWorst")
        head.append(
            f"codec\t{agg.get('codecDispatches', 0):g} dispatches, "
            f"pad waste {'-' if cpw is None else f'{cpw * 100:.1f}%'} worst, "
            f"{agg.get('codecCompileEvents', 0):g} compiles "
            f"({agg.get('codecCompileSeconds', 0):g}s)"
        )
    # rebalance observatory (rpc/transition.py): version spread + how
    # many nodes see an open transition, from the gossiped lt.* keys
    if agg.get("layoutVersionSpread") or agg.get("layoutNodesInTransition"):
        skw = agg.get("clockSkewWorstMs")
        head.append(
            f"layout\tversion spread {agg.get('layoutVersionSpread', 0):g}, "
            f"{agg.get('layoutNodesInTransition', 0):g} node(s) in "
            "transition, worst skew "
            f"{'-' if skw is None else f'{skw:.0f}ms'}"
        )
    # tenant observatory (rpc/tenant.py): cluster-wide worst tenant
    # share vs the fair-share-multiple knob — the `cluster tenants`
    # one-liner (the per-tenant table lives behind `cluster tenants`)
    hog_share = agg.get("tenantHogShare")
    hog_warn = agg.get("tenantHogShareWarn") or 3.0
    if hog_share is not None:
        n_ten = agg.get("tenantsSeen") or 0
        fair = 1.0 / n_ten if n_ten else 0.0
        line = (
            f"tenants\t{n_ten:g} seen, worst cluster share "
            f"{hog_share * 100:.1f}%"
        )
        if n_ten >= 2 and fair and hog_share > hog_warn * fair:
            line += (
                f" HOG! (> {hog_warn:g}x fair share {fair * 100:.1f}%)"
            )
        head.append(line)
    # TPU probe verdict (bench.py phased_probe, ISSUE 11): the answering
    # box's newest banked wedge profile — structured evidence, not
    # "wedged at devices" folklore
    probe = r.get("tpuProbe")
    if probe:
        head.append(
            f"tpu probe\t{probe.get('result')} at "
            f"{probe.get('wedgedAt') or '-'} (rc {probe.get('rc')}"
            + (", timeout" if probe.get("timedOut") else "")
            + f", banked {probe.get('utc')})"
        )
    out = format_table(head) + "\n\n"
    skew_warn = agg.get("clockSkewWarnMs") or 250.0
    rows = [
        "id\thost\tup\tage\treq/s\t5xx/s\tp99\tlag99\tresyncq\tbrk\tcnry"
        "\thot\thog\tlayv\tflags"
    ]
    for n in r.get("nodes", []):
        d = n.get("digest") or {}
        s3 = d.get("s3") or {}
        cn = d.get("canary") or {}
        flags = []
        if n.get("isSelf"):
            flags.append("self")
        if n["id"] in outliers:
            flags.append("OUTLIER")
        if not d:
            flags.append("no-digest")
        # overload-control plane: a node above ladder level 0 is
        # degrading background planes / shedding admission tiers
        lvl = (d.get("ovl") or {}).get("lvl") or 0
        if lvl:
            flags.append(f"SHED-L{lvl}")
        # recency, not history: flag the LAST cycle's verdict — a single
        # transient failed leg must not mark a recovered node forever
        if cn.get("ok") == 0:
            flags.append("CANARY-FAIL")
        # a node whose effective meta RF disagrees with this node's is
        # misconfigured (or mid-rollout): its table quorums won't match
        nm = d.get("meta")
        if self_meta and nm and nm.get("rf") != self_meta.get("rf"):
            flags.append(f"META-RF={nm.get('rf')}!")
        # rebalance observatory: the node's acked layout version ("*"
        # while it still sees 2+ active versions); SKEW! when its clock
        # offset exceeds the threshold — past that, the merged event
        # timeline's ordering is not trustworthy
        lt = d.get("lt") or {}
        sk = lt.get("sk")
        if sk is not None and abs(sk) > skew_warn:
            flags.append("SKEW!")
        layv = (
            f"v{lt.get('ack')}" + ("*" if (lt.get("act") or 0) >= 2 else "")
            if lt.get("ack") is not None
            else "-"
        )
        # canary column: probe p99 + cumulative failures, "-" when the
        # node runs no prober (or hasn't probed yet)
        cnry = (
            f"{_ms(cn.get('p99'))}/{cn.get('err', 0):g}"
            if cn.get("ops")
            else "-"
        )
        # traffic observatory: the node's hottest bucket by (decayed)
        # ops from the gossiped trf digest — skew is visible without
        # touching the admin API
        trf = d.get("trf") or {}
        hot = str(trf.get("hb") or "-")[:14]
        # tenant observatory: the node's busiest-tenant ops share, with
        # a HOG! flag when it exceeds the fair-share multiple of the
        # node's own tracked-tenant count (the cluster-wide verdict is
        # the head line / `cluster tenants`)
        tn = d.get("tn") or {}
        top1 = tn.get("top1") or 0.0
        trk = tn.get("trk") or 0
        hog_col = f"{float(top1) * 100:.0f}%" if top1 else "-"
        if trk >= 2 and top1 and float(top1) > hog_warn * (1.0 / trk):
            flags.append("HOG!")
        rows.append(
            f"{n['id'][:16]}\t{n.get('hostname', '?')}\t"
            f"{'y' if n.get('isUp') else 'n'}\t{n.get('ageSecs', 0):.0f}s\t"
            f"{s3.get('rps', 0):.1f}\t{s3.get('eps', 0):.1f}\t"
            f"{_ms(s3.get('p99'))}\t{_ms((d.get('loop') or {}).get('p99'))}\t"
            f"{(d.get('resync') or {}).get('q', 0)}\t"
            f"{(d.get('rpc') or {}).get('open', 0)}\t"
            f"{cnry}\t{hot}\t{hog_col}\t{layv}\t"
            f"{','.join(flags) or '-'}"
        )
    out += format_table(rows)
    for nid, reasons in sorted(outliers.items()):
        out += f"\n  outlier {nid[:16]}: " + "; ".join(reasons)
    return out


def _render_cluster_hot(r: dict, top: int = 10) -> str:
    """`cluster hot`: the traffic observatory as an operator table —
    hot objects, hot buckets, op mix, slow-peer piece-fetch ranking,
    and the cluster-wide hottest bucket from the gossiped digests."""
    local = r.get("local") or {}
    head = [
        f"observatory\t{'enabled' if r.get('enabled') else 'DISABLED'}",
        f"ops seen\t{local.get('totalOps', 0)} "
        f"(read fraction {local.get('readFraction')})",
        f"keyspace skew\tzipf s = {local.get('zipfS')}",
    ]
    cluster = r.get("cluster") or {}
    hb = cluster.get("hotBucket")
    if hb:
        head.append(
            f"cluster hot bucket\t{hb['bucket']} "
            f"(~{hb.get('ops', 0):g} decayed ops on {hb['node'][:16]})"
        )
    out = format_table(head) + "\n"
    objs = (local.get("hotObjects") or [])[:top]
    if objs:
        rows = ["bucket/key\test ops\t±err\tshare"]
        for o in objs:
            rows.append(
                f"{o['bucket']}/{o['key']}\t{o['count']:g}\t"
                f"{o['errorBound']:g}\t{o['share'] * 100:.1f}%"
            )
        out += "\n== hot objects ==\n" + format_table(rows)
    bkts = (local.get("hotBuckets") or [])[:top]
    if bkts:
        rows = ["bucket\test ops\tops/s\tshare"]
        for b in bkts:
            rows.append(
                f"{b['bucket']}\t{b['count']:g}\t{b['opsPerSec']:g}\t"
                f"{b['share'] * 100:.1f}%"
            )
        out += "\n\n== hot buckets ==\n" + format_table(rows)
    mix = local.get("opMix") or {}
    if any(mix.values()):
        out += "\n\n== op mix ==\n" + format_table(
            [
                f"{op}\t{n}"
                for op, n in sorted(mix.items(), key=lambda kv: -kv[1])
                if n
            ]
        )
    peers = r.get("slowPeers") or []
    if peers:
        rows = ["peer\tstate\tpiece lat\tfetches\tbytes ewma"]
        for p in peers[:top]:
            rows.append(
                f"{p['peer'][:16]}\t"
                f"{p['state']}{' SICK' if p.get('sick') else ''}\t"
                f"{p['latMsecEwma'] if p['latMsecEwma'] is not None else '-'}"
                f"ms\t{p['pieceFetches']}\t{p.get('bytesEwma') or '-'}"
            )
        out += "\n\n== slow peers (piece fetch) ==\n" + format_table(rows)
    return out


def _render_cluster_durability(r: dict) -> str:
    """`cluster durability`: the redundancy ledger as an operator table
    — cluster health fraction, per-node classes, zone-loss exposure,
    repair ETA, layout-transition progress (model: `cluster hot`)."""
    agg = (r.get("cluster") or {}).get("aggregate") or {}
    local = r.get("local") or {}
    hf = agg.get("healthyFraction")
    eta = agg.get("repairEtaSeconds")
    head = [
        f"observatory\t{'enabled' if r.get('enabled') else 'DISABLED'}",
        f"blocks\t{agg.get('blocksTotal', 0):g} classified "
        f"({'-' if hf is None else f'{hf * 100:.1f}%'} healthy)",
        f"classes\thealthy {agg.get('healthy', 0):g}, "
        f"degraded {agg.get('degraded', 0):g}, "
        f"at_risk {agg.get('atRisk', 0):g}, "
        f"unreadable {agg.get('unreadable', 0):g}",
        f"min redundancy\t{agg.get('minRedundancy')} "
        "(live pieces minus k, worst block cluster-wide)",
        f"repair eta\t{'-' if eta is None else f'{eta:.0f}s'} "
        f"(backlog ~{agg.get('backlogBytes', 0):g} B, "
        f"{agg.get('missingPieces', 0):g} pieces"
        + (
            f", {agg.get('repairEtaUnknownNodes'):g} node(s) STALLED"
            if agg.get("repairEtaUnknownNodes")
            else ""
        )
        + ")",
    ]
    snap = local.get("snapshot") or {}
    lay = snap.get("layout") or {}
    if lay:
        head.append(
            f"layout\tv{lay.get('version')} "
            f"{lay.get('partitionsSynced', 0)}/{lay.get('partitions', 0)} "
            f"partitions synced ({(lay.get('progress') or 0) * 100:.0f}%)"
        )
    re_ = snap.get("resyncErrors") or {}
    if re_.get("transient") or re_.get("stuck"):
        oldest = re_.get("oldestAgeSecs")
        head.append(
            f"resync errors\t{re_.get('transient', 0)} transient, "
            f"{re_.get('stuck', 0)} stuck "
            + (
                f"(oldest {oldest}s)"
                if oldest is not None
                else "(ages unknown: pre-upgrade entries)"
            )
        )
    out = format_table(head) + "\n"
    zones = agg.get("zoneExposure") or {}
    if zones:
        rows = ["zone\tblocks below k if lost"]
        for z, n in sorted(zones.items(), key=lambda kv: -kv[1]):
            rows.append(f"{z}\t{n:g}")
        out += "\n== zone-loss exposure ==\n" + format_table(rows) + "\n"
    nodes = (r.get("cluster") or {}).get("nodes") or []
    rows = ["id\tup\towned\thealthy\tdegr\tat-risk\tunread\tminr\teta\tage"]
    for n in nodes:
        d = n.get("durability")
        if not isinstance(d, dict) or d.get("tot") is None:
            rows.append(
                f"{n['id'][:16]}\t{'y' if n.get('isUp') else 'n'}\t"
                "-\t-\t-\t-\t-\t-\t-\tno-ledger"
            )
            continue
        eta_n = d.get("eta")
        rows.append(
            f"{n['id'][:16]}\t{'y' if n.get('isUp') else 'n'}\t"
            f"{d.get('tot', 0)}\t{d.get('h', 0)}\t{d.get('dg', 0)}\t"
            f"{d.get('ar', 0)}\t{d.get('ur', 0)}\t{d.get('minr')}\t"
            f"{'-' if eta_n is None else f'{eta_n:g}s'}\t"
            f"{d.get('age')}s"
        )
    out += "\n== nodes ==\n" + format_table(rows)
    return out


def _render_cluster_codec(r: dict) -> str:
    """`cluster codec`: the codec X-ray as an operator table — cluster
    aggregate, then one row per node from the gossiped codec.* digest
    keys (model: `cluster durability`)."""
    agg = (r.get("cluster") or {}).get("aggregate") or {}
    local = r.get("local") or {}
    pw = agg.get("padWasteWorst")
    ovl = agg.get("overlapEfficiencyWorst")
    ll = agg.get("laneLingerP99SecondsWorst")
    head = [
        f"dispatches\t{agg.get('dispatches', 0):g} cluster-wide",
        f"pad waste\t{'-' if pw is None else f'{pw * 100:.1f}%'} (worst node)",
        f"compiles\t{agg.get('compileEvents', 0):g} events, "
        f"{agg.get('compileSeconds', 0):g}s total",
        f"overlap\t{'-' if ovl is None else f'{ovl:.2f}'} "
        "(wall / transfer+compute; 1.0 = fully sequential)",
        f"lane linger p99\t{'-' if ll is None else _ms(ll)} (worst node)",
        f"platforms\t{', '.join(local.get('platforms') or []) or '-'}",
    ]
    out = format_table(head) + "\n"
    nodes = (r.get("cluster") or {}).get("nodes") or []
    rows = ["id\tup\tdisp\tpad-waste\tcompiles\tcompile-s\tovl\tlinger99"]
    for n in nodes:
        c = n.get("codec")
        if not isinstance(c, dict):
            rows.append(
                f"{n['id'][:16]}\t{'y' if n.get('isUp') else 'n'}\t"
                "-\t-\t-\t-\t-\tno-digest"
            )
            continue
        rows.append(
            f"{n['id'][:16]}\t{'y' if n.get('isUp') else 'n'}\t"
            f"{c.get('dsp', 0):g}\t{(c.get('pw') or 0) * 100:.1f}%\t"
            f"{c.get('ce', 0):g}\t{c.get('cs', 0):g}\t"
            f"{c.get('ovl', 0):.2f}\t{_ms(c.get('ll99'))}"
        )
    out += "\n== nodes ==\n" + format_table(rows)
    return out


def _render_cluster_tenants(r: dict, sort: str = "ops", top: int = 10) -> str:
    """`cluster tenants`: the tenant observatory as an operator table —
    fairness header, cluster-summed per-tenant consumption, then one
    row per node from the gossiped tn.* digest keys (model: `cluster
    durability` / `cluster codec`)."""
    cluster = r.get("cluster") or {}
    agg = cluster.get("aggregate") or {}
    fair = cluster.get("fairness") or {}
    hog = cluster.get("hog")
    head = [
        f"observatory\t{'enabled' if r.get('enabled') else 'DISABLED'}",
        f"nodes\t{cluster.get('nodesReporting', 0)}/"
        f"{len(cluster.get('nodes') or [])} reporting tenant digests",
        f"ops\t{agg.get('ops', 0):g} cluster-wide "
        f"({agg.get('opsPerSec', 0):g}/s), {agg.get('sheds', 0):g} shed",
        f"identity\t{agg.get('claimedMismatches', 0):g} claimed/"
        "authenticated key-id mismatches",
        f"fairness\t{fair.get('tenants', 0)} tenants, top-1 share "
        f"{(fair.get('top1Share') or 0) * 100:.1f}% "
        f"(fair {(fair.get('fairShare') or 0) * 100:.1f}%), "
        f"max/median {fair.get('maxMedianRatio') or '-'}, "
        f"worst burn {fair.get('worstBurn', 0):g}",
    ]
    if hog:
        head.append(
            f"HOG!\ttenant {hog.get('id')} holds "
            f"{(hog.get('share') or 0) * 100:.1f}% of cluster ops — "
            f"{hog.get('multiple')}x its fair share "
            f"(warn multiple {hog.get('warnMultiple'):g})"
        )
    out = format_table(head) + "\n"
    sort_key = {
        "ops": lambda t: t.get("ops") or 0,
        "rps": lambda t: t.get("opsPerSec") or 0,
        "bytes": lambda t: t.get("bytes") or 0,
        "shed": lambda t: t.get("shed") or 0,
        "burn": lambda t: (t.get("burn") or {}).get("worst") or 0,
    }.get(sort) or (lambda t: t.get("ops") or 0)
    tenants = sorted(
        cluster.get("tenants") or [], key=sort_key, reverse=True
    )[: max(1, top)]
    rows = ["tenant\tclass\tops\tshare\treq/s\tbytes\tshed\tburn\tnodes"]
    for t in tenants:
        b = t.get("burn") or {}
        rows.append(
            f"{str(t.get('id'))[:20]}\t{t.get('class') or '-'}\t"
            f"{t.get('ops', 0):g}\t{(t.get('share') or 0) * 100:.1f}%\t"
            f"{t.get('opsPerSec', 0):g}\t{t.get('bytes', 0):g}\t"
            f"{t.get('shed', 0):g}\t{b.get('worst', 0):g}\t"
            f"{t.get('nodesReporting', 0)}"
        )
    out += "\n== tenants (cluster-summed) ==\n" + format_table(rows)
    nrows = ["id\tup\ttracked\tops\treq/s\tshed\ttop1\twburn\tmm"]
    for n in cluster.get("nodes") or []:
        d = n.get("tenant")
        if not isinstance(d, dict):
            nrows.append(
                f"{n['id'][:16]}\t{'y' if n.get('isUp') else 'n'}\t"
                "-\t-\t-\t-\t-\t-\tno-digest"
            )
            continue
        nrows.append(
            f"{n['id'][:16]}\t{'y' if n.get('isUp') else 'n'}\t"
            f"{d.get('trk', 0):g}\t{d.get('ops', 0):g}\t"
            f"{d.get('rps', 0):g}\t{d.get('shed', 0):g}\t"
            f"{(d.get('top1') or 0) * 100:.0f}%\t{d.get('wburn', 0):g}\t"
            f"{d.get('mm', 0):g}"
        )
    out += "\n\n== nodes ==\n" + format_table(nrows)
    return out


def _render_cluster_transition(r: dict) -> str:
    """`cluster transition`: the rebalance observatory as an operator
    table — local flight deck (partition states, per-pair bytes,
    throughput, ETA), then one row per node from the gossiped lt.*
    digest keys (model: `cluster durability`)."""
    agg = (r.get("cluster") or {}).get("aggregate") or {}
    local = r.get("local") or {}
    parts = local.get("partitions") or {}
    skw = agg.get("clockSkewWorstMs")
    thr = local.get("throughputBytesPerSec")
    eta = local.get("etaSecs")
    head = [
        f"observatory\t{'enabled' if r.get('enabled') else 'DISABLED'}",
        f"transition\t"
        + (
            f"OPEN (v{local.get('fromVersion')} -> v{local.get('version')}, "
            f"{local.get('elapsedSecs', 0):g}s elapsed)"
            if local.get("inTransition")
            else f"idle at v{local.get('version')}"
        ),
        f"sync\t{(local.get('syncFraction') or 0) * 100:.1f}% "
        f"({parts.get('synced', 0)}/{parts.get('total', 0)} synced, "
        f"{parts.get('moving', 0)} moving, {parts.get('pending', 0)} pending)",
        f"moved\t{local.get('bytesMoved', 0):g} B"
        + (f" @ {thr:g} B/s" if thr else "")
        + (f", eta {eta:g}s" if eta is not None else ""),
        f"version spread\t{agg.get('versionSpread', 0):g} "
        f"(newest v{agg.get('newestVersion')}, "
        f"{agg.get('nodesReporting', 0)} reporting)",
        f"stale nodes\t"
        f"{', '.join(s[:16] for s in agg.get('staleNodes') or []) or '(none)'}",
        f"clock skew\tworst {'-' if skw is None else f'{skw:g}ms'} "
        f"(warn above {agg.get('clockSkewWarnMs'):g}ms)",
    ]
    rep = local.get("lastReport")
    if rep:
        head.append(
            f"last report\tv{rep.get('version')} in "
            f"{rep.get('durationSecs'):g}s, {rep.get('bytesMoved', 0):g} B "
            f"over {len(rep.get('pairs') or [])} pair(s), "
            f"slo burn max {rep.get('sloBurnMax')}, "
            f"canary {'ok' if rep.get('canaryOk') else 'FAILED'}"
        )
    out = format_table(head) + "\n"
    pairs = local.get("pairs") or []
    if pairs:
        rows = ["src\tdst\tbytes"]
        for p in pairs[:16]:
            rows.append(f"{p['src']}\t{p['dst']}\t{p['bytes']:g}")
        out += "\n== bytes moved by pair ==\n" + format_table(rows) + "\n"
    nodes = (r.get("cluster") or {}).get("nodes") or []
    rows = ["id\tup\tver\tack\tsync\tactive\tfrac\tmoved\tskew"]
    for n in nodes:
        lt = n.get("lt")
        if not isinstance(lt, dict):
            rows.append(
                f"{n['id'][:16]}\t{'y' if n.get('isUp') else 'n'}\t"
                "-\t-\t-\t-\t-\t-\tno-digest"
            )
            continue
        sk = lt.get("sk")
        frac = lt.get("frac")
        rows.append(
            f"{n['id'][:16]}\t{'y' if n.get('isUp') else 'n'}\t"
            f"{lt.get('v')}\t{lt.get('ack')}\t{lt.get('sync')}\t"
            f"{lt.get('act')}\t"
            f"{'-' if frac is None else f'{frac * 100:.0f}%'}\t"
            f"{lt.get('mvb', 0):g}\t"
            f"{'-' if sk is None else f'{sk:g}ms'}"
        )
    out += "\n== nodes ==\n" + format_table(rows)
    return out


def _render_event_lines(events: list) -> list[str]:
    """One line per timeline event: corrected time, node, severity,
    name, then the attrs (truncated — the JSON surface has them all)."""
    lines = []
    for e in events:
        attrs = " ".join(
            f"{k}={v}" for k, v in sorted((e.get("attrs") or {}).items())
        )
        if len(attrs) > 120:
            attrs = attrs[:117] + "..."
        t = time.strftime(
            "%H:%M:%S", time.localtime(e.get("time") or 0)
        ) + f".{int(((e.get('time') or 0) % 1) * 1000):03d}"
        lines.append(
            f"{t}  {e.get('node', '?')[:16]}  "
            f"{(e.get('severity') or 'info').upper():8s} "
            f"{e.get('name')}  {attrs}"
        )
    return lines


def _render_cluster_events(r: dict) -> str:
    """`cluster events`: the federated timeline as text — header with
    fan-out coverage, then the skew-corrected, causally-ordered lines."""
    head = [
        f"nodes\t{len(r.get('nodesResponding') or [])} responding"
        + (
            f", {len(r.get('nodesFailed') or [])} FAILED "
            f"({', '.join(r.get('nodesFailed') or [])})"
            if r.get("nodesFailed")
            else ""
        ),
        f"filter\tsince {r.get('since', 0):g}, "
        f"min severity {r.get('minSeverity', 'info')}",
        f"events\t{len(r.get('events') or [])}",
    ]
    out = format_table(head)
    lines = _render_event_lines(r.get("events") or [])
    if lines:
        out += "\n\n" + "\n".join(lines)
    return out


def _render_codec_top(r: dict) -> str:
    """`codec top`: this node's per-kernel dispatch economics — where
    the accelerator's batches pad, compile and linger (the `local` leg
    of the shared codec_response serialization)."""
    local = r.get("local") or {}
    head = [
        f"dispatches\t{local.get('dispatches', 0):g} (this node)",
        f"pad waste\t{(local.get('padWaste') or 0) * 100:.1f}% "
        "of dispatched rows",
        f"compiles\t{local.get('compileEvents', 0):g} events, "
        f"{local.get('compileSecs', 0):g}s",
        f"platforms\t{', '.join(local.get('platforms') or []) or '-'}",
    ]
    out = format_table(head) + "\n"
    kernels = local.get("kernels") or {}
    if kernels:
        rows = ["kernel\trows\tpadded-to\tpad-waste\toverlap"]
        for name, k in sorted(
            kernels.items(), key=lambda kv: -kv[1].get("padded", 0)
        ):
            kovl = k.get("overlapEfficiency")
            rows.append(
                f"{name}\t{k.get('requested', 0):g}\t{k.get('padded', 0):g}\t"
                f"{(k.get('padWaste') or 0) * 100:.1f}%\t"
                f"{'-' if kovl is None else f'{kovl:.2f}'}"
            )
        out += "\n== kernels ==\n" + format_table(rows) + "\n"
    comp = local.get("compile") or {}
    if comp:
        rows = ["cache\tcompile events\tsecs"]
        for name, c in sorted(
            comp.items(), key=lambda kv: -kv[1].get("secs", 0)
        ):
            rows.append(f"{name}\t{c.get('events', 0)}\t{c.get('secs', 0):g}")
        out += "\n== compile ==\n" + format_table(rows) + "\n"
    lanes = local.get("lanes") or {}
    if lanes:
        rows = ["lane\tflush\tblocks\tlinger-total\tlinger-p99"]
        for lname, lane in sorted(lanes.items()):
            for fname, fl in sorted((lane.get("flush") or {}).items()):
                p99 = fl.get("lingerP99")
                rows.append(
                    f"{lname}\t{fname}\t{fl.get('blocks', 0)}\t"
                    f"{fl.get('lingerSecsTotal', 0):g}s\t"
                    f"{'-' if p99 is None else _ms(p99)}"
                )
        out += "\n== batcher lanes ==\n" + format_table(rows)
    return out


async def dispatch(args, call, config) -> str | None:
    from ..utils.config import _parse_capacity

    jd = (lambda x: json.dumps(x, indent=2, default=repr)) if args.json else None

    if args.cmd == "status":
        st = await call("status")
        if jd:
            return jd(st)
        rows = ["==== NODE ====", f"node id\t{st['node_id']}"]
        h = st["health"]
        rows += [
            f"cluster health\t{h['status']}",
            f"nodes\t{h['connected_nodes']}/{h['known_nodes']} connected",
            f"partitions ok\t{h['partitions_quorum']}/{h['partitions']}",
            f"layout version\t{st['layout_version']}",
        ]
        out = format_table(rows) + "\n\n==== PEERS ====\n"
        prow = ["id\tstate\thostname"]
        for p in st["peers"]:
            prow.append(f"{p['id'][:16]}\t{p['state']}\t{p['hostname']}")
        out += format_table(prow)
        if st["roles"]:
            out += "\n\n==== ROLES ====\n"
            rrow = ["id\tzone\tcapacity"]
            for nid, r in st["roles"].items():
                cap = "gateway" if r["capacity"] is None else str(r["capacity"])
                rrow.append(f"{nid[:16]}\t{r['zone']}\t{cap}")
            out += format_table(rrow)
        return out

    if args.cmd == "stats":
        st = await call("stats")
        if jd:
            return jd(st)
        rows = ["==== NODE ====", f"db engine\t{st['db_engine']}"]
        tm = st.get("telemetry") or {}
        if tm:
            rows.append(f"uptime\t{tm.get('up', 0):.0f}s")
        out = format_table(rows) + "\n\n==== TABLES ====\n"
        trow = ["table\tentries\tmerkle todo\tgc todo"]
        for name, t in st["tables"].items():
            trow.append(
                f"{name}\t{t['entries']}\t{t['merkle_todo']}\t{t['gc_todo']}"
            )
        out += format_table(trow) + "\n\n==== BLOCKS ====\n"
        b = st["blocks"]
        out += format_table(
            [
                f"rc entries\t{b['rc_entries']}",
                f"resync queue\t{b['resync_queue']}",
                f"resync errors\t{b['resync_errors']}",
            ]
        )
        if tm:
            out += "\n\n==== TELEMETRY (local digest) ====\n"
            s3, loop_, rpc = (
                tm.get("s3") or {}, tm.get("loop") or {}, tm.get("rpc") or {}
            )
            drow = [
                f"s3 req/s\t{s3.get('rps', 0):.2f}",
                f"s3 5xx/s\t{s3.get('eps', 0):.2f}",
                f"s3 p50/p99\t{_ms(s3.get('p50'))} / {_ms(s3.get('p99'))}",
                f"loop lag p99\t{_ms(loop_.get('p99'))}",
                f"worker errors\t{(tm.get('work') or {}).get('errs', 0):g}",
                f"breakers open\t{rpc.get('open', 0)}",
                f"repair backlog\t{(tm.get('repair') or {}).get('backlog', 0)}",
                f"tpu dispatch/s\t{(tm.get('tpu') or {}).get('dps', 0):.2f}",
                "codec pad waste / compiles\t"
                f"{(tm.get('codec') or {}).get('pw', 0):.1%} / "
                f"{(tm.get('codec') or {}).get('ce', 0):g}",
            ]
            slo = tm.get("slo")
            if slo:
                drow.append(
                    "slo budget (avail/lat)\t"
                    f"{slo['avail']['rem'] * 100:.1f}% / "
                    f"{slo['lat']['rem'] * 100:.1f}%"
                )
            out += format_table(drow)
        probe = st.get("tpuProbe")
        if probe:
            # newest banked TPU probe wedge (bench.py phased_probe): the
            # structured failure_reason, not "wedged at devices" folklore
            out += "\n\n==== TPU PROBE (last banked failure) ====\n"
            out += format_table(
                [
                    f"result\t{probe.get('result')}",
                    f"wedged at\t{probe.get('wedgedAt') or '-'}",
                    f"phase rc\t{probe.get('rc')}"
                    + (" (timeout)" if probe.get("timedOut") else ""),
                    f"phase secs\t{probe.get('dt')}",
                    f"banked\t{probe.get('utc')} ({probe.get('profile')})",
                ]
            )
        return out

    if args.cmd == "cluster":
        if args.cluster_cmd == "hot":
            if args.profile:
                return json.dumps(
                    await call("traffic-profile"), indent=2, default=repr
                )
            r = await call("traffic")
            if args.json:
                return json.dumps(r, indent=2, default=repr)
            return _render_cluster_hot(r, top=args.top)
        if args.cluster_cmd == "durability":
            r = await call("durability")
            if args.json:
                return json.dumps(r, indent=2, default=repr)
            return _render_cluster_durability(r)
        if args.cluster_cmd == "codec":
            r = await call("codec")
            if args.json:
                return json.dumps(r, indent=2, default=repr)
            return _render_cluster_codec(r)
        if args.cluster_cmd == "telemetry":
            return json.dumps(
                await call("cluster-telemetry"), indent=2, default=repr
            )
        if args.cluster_cmd == "transition":
            r = await call("transition")
            if args.json:
                return json.dumps(r, indent=2, default=repr)
            return _render_cluster_transition(r)
        if args.cluster_cmd == "tenants":
            r = await call("tenants")
            if args.json:
                return json.dumps(r, indent=2, default=repr)
            return _render_cluster_tenants(r, sort=args.sort, top=args.top)
        if args.cluster_cmd == "events":
            a = {"since": args.since, "min_severity": args.min_severity}
            if not args.follow:
                r = await call("cluster-events", a)
                if args.json:
                    return json.dumps(r, indent=2, default=repr)
                return _render_cluster_events(r)
            # --follow: poll and stream only unseen events.  The server
            # filters on each node's OWN clock, so the watermark lags
            # one second behind the newest corrected time and a seen-set
            # dedups the overlap (skew must not drop or repeat events).
            seen: set = set()
            try:
                while True:
                    r = await call("cluster-events", a)
                    fresh = []
                    for e in r.get("events") or []:
                        k = (e.get("node"), e.get("rawTime"), e.get("name"))
                        if k in seen:
                            continue
                        seen.add(k)
                        fresh.append(e)
                    for line in _render_event_lines(fresh):
                        print(line, flush=True)
                    if fresh:
                        a["since"] = max(
                            e.get("rawTime") or 0.0 for e in fresh
                        ) - 1.0
                        seen = {
                            k for k in seen if k[1] >= a["since"]
                        }
                    await asyncio.sleep(max(0.2, args.interval))
            # graft-lint: allow-cancel(interactive follow loop: ctrl-C is the exit gesture, the CLI returns to the shell)
            except (KeyboardInterrupt, asyncio.CancelledError):
                return None
        # cluster top: live table; --once (or --json) renders one frame
        if args.json:
            return json.dumps(
                await call("cluster-telemetry"), indent=2, default=repr
            )
        if args.once:
            return _render_cluster_top(await call("cluster-telemetry"))
        try:
            while True:
                frame = _render_cluster_top(await call("cluster-telemetry"))
                # clear screen + home, like top(1)
                print("\x1b[2J\x1b[H" + frame, flush=True)
                await asyncio.sleep(max(0.2, args.interval))
        # graft-lint: allow-cancel(interactive top loop: ctrl-C is the exit gesture, the CLI returns to the shell)
        except (KeyboardInterrupt, asyncio.CancelledError):
            return None

    if args.cmd == "node" and args.node_cmd == "connect":
        nid, _, hostport = args.arg.partition("@")
        host, _, port = hostport.rpartition(":")
        return await call("connect", {"node": nid, "host": host, "port": int(port)})

    if args.cmd == "layout":
        lc = args.layout_cmd
        if lc == "assign":
            a = {
                "node": args.node,
                "zone": args.zone,
                "tags": args.tags,
                "gateway": args.gateway,
            }
            if not args.gateway:
                if not args.capacity:
                    return "error: -s/--capacity required (or -g for gateway)"
                a["capacity"] = _parse_capacity(args.capacity)
            return str(await call("layout-assign", a))
        if lc == "remove":
            return str(await call("layout-remove", {"node": args.node}))
        if lc == "apply":
            r = await call("layout-apply", {"version": args.version})
            return f"layout version {r['version']} applied:\n" + "\n".join(r["report"])
        if lc == "revert":
            return str(await call("layout-revert"))
        if lc == "config":
            return str(
                await call("layout-config", {"zone_redundancy": args.zone_redundancy})
            )
        if lc == "history":
            r = await call("layout-history")
            if jd:
                return jd(r)
            rows = [
                f"current version\t{r['current_version']}",
                f"oldest active\t{r['min_stored']}",
            ]
            for v in r["versions"]:
                rows.append(
                    f"v{v['version']}\t{v['status']}\t"
                    f"{v['storage_nodes']} storage / {v['gateway_nodes']} gateway"
                )
            rows.append("-- update trackers --")
            rows.append("node\tack\tsync\tsync_ack")
            for nid, t in r["trackers"].items():
                rows.append(f"{nid[:16]}\t{t['ack']}\t{t['sync']}\t{t['sync_ack']}")
            return format_table(rows)
        if lc == "skip-dead-nodes":
            r = await call(
                "layout-skip-dead-nodes",
                {
                    "version": args.version,
                    "allow_missing_data": args.allow_missing_data,
                },
            )
            return (
                f"trackers forced to v{r['version']} for: "
                + (", ".join(n[:16] for n in r["skipped_nodes"]) or "(none)")
            )
        if lc == "show":
            r = await call("layout-show")
            if jd:
                return jd(r)
            rows = [f"version\t{r['version']}", f"partition size\t{r['partition_size']}"]
            for nid, (zone, cap, tags) in r["roles"].items():
                rows.append(
                    f"{nid[:16]}\t{zone}\t{'gateway' if cap is None else cap}\t{','.join(tags)}"
                )
            if r["staged"]:
                rows.append("-- staged changes --")
                for nid, role in r["staged"]:
                    rows.append(f"{nid[:16]}\t{role}")
            return format_table(rows)

    if args.cmd == "bucket":
        bc = args.bucket_cmd
        if bc == "list":
            bs = await call("bucket-list")
            return format_table(
                ["id\taliases"]
                + [f"{b['id'][:16]}\t{','.join(b['aliases'])}" for b in bs]
            )
        if bc == "create":
            return str(await call("bucket-create", {"name": args.name}))
        if bc == "delete":
            return str(await call("bucket-delete", {"name": args.name}))
        if bc == "info":
            return json.dumps(
                await call("bucket-info", {"name": args.name}), indent=2, default=repr
            )
        if bc == "allow":
            return str(
                await call(
                    "bucket-allow",
                    {
                        "bucket": args.bucket,
                        "key": args.key,
                        "read": args.read,
                        "write": args.write,
                        "owner": args.owner,
                    },
                )
            )
        if bc == "deny":
            return str(await call("bucket-deny", {"bucket": args.bucket, "key": args.key}))
        if bc == "website":
            return str(
                await call(
                    "bucket-website",
                    {
                        "bucket": args.bucket,
                        "allow": args.allow,
                        "index_document": args.index_document,
                        "error_document": args.error_document,
                    },
                )
            )
        if bc == "quota":
            # only send the quotas the operator named; absent = unchanged
            a = {"bucket": args.bucket}
            if args.max_size is not None:
                a["max_size"] = (
                    None if args.max_size == "none" else _parse_capacity(args.max_size)
                )
            if args.max_objects is not None:
                a["max_objects"] = (
                    None if args.max_objects == "none" else int(args.max_objects)
                )
            return str(await call("bucket-quota", a))
        if bc in ("alias", "unalias"):
            return str(
                await call(
                    f"bucket-{bc}",
                    {
                        "bucket": args.bucket,
                        "alias": args.alias,
                        "local_key": args.local,
                    },
                )
            )

    if args.cmd == "key":
        kc = args.key_cmd
        if kc == "new":
            r = await call(
                "key-new",
                {"name": args.name, "allow_create_bucket": args.allow_create_bucket},
            )
            return f"Key ID: {r['key_id']}\nSecret key: {r['secret_key']}"
        if kc == "list":
            ks = await call("key-list")
            return format_table(
                ["key id\tname"] + [f"{k['key_id']}\t{k['name']}" for k in ks]
            )
        if kc == "info":
            return json.dumps(
                await call("key-info", {"key": args.key, "show_secret": args.show_secret}),
                indent=2,
                default=repr,
            )
        if kc == "delete":
            return str(await call("key-delete", {"key": args.key}))
        if kc == "import":
            r = await call(
                "key-import",
                {"key_id": args.key_id, "secret": args.secret, "name": args.name},
            )
            return f"imported {r['key_id']}"
        if kc == "set":
            acb = None
            if args.allow_create_bucket:
                acb = True
            elif args.deny_create_bucket:
                acb = False
            return json.dumps(
                await call(
                    "key-set",
                    {"key": args.key, "name": args.name,
                     "allow_create_bucket": acb},
                )
            )

    if args.cmd == "codec" and args.codec_cmd == "top":
        r = await call("codec")
        if jd:
            return jd(r)
        return _render_codec_top(r)

    if args.cmd == "overload" and args.overload_cmd == "status":
        r = await call("overload-status")
        if jd:
            return jd(r)
        adm = r.get("admission") or {}
        rows = [
            f"in flight\t{adm.get('inFlight')}/{adm.get('maxInFlight')}"
            f" (queued {adm.get('queued')})",
            f"shedding tiers\t{adm.get('shedFromTier') or '(none)'}",
        ]
        rows.append("tier\tadmitted\tqueued\tshed")
        for tname, t in (adm.get("tiers") or {}).items():
            rows.append(
                f"{tname}\t{t['admitted']}\t{t['queued']}\t{t['shed']}"
            )
        lad = r.get("ladder")
        if lad:
            rows.append(
                f"ladder level\t{lad['level']}/{lad['maxLevel']} "
                f"(burn {lad['burnRate']:.2f}, "
                f"lag p99 {lad['loopLagP99Ms']:.0f}ms)"
            )
            applied = [s["name"] for s in lad["ladder"] if s["applied"]]
            rows.append(f"applied steps\t{', '.join(applied) or '(none)'}")
            rows.append(
                f"steps up/down\t{lad['stepsUp']}/{lad['stepsDown']}"
            )
            if lad.get("lastReason"):
                rows.append(f"last change\t{lad['lastReason']}")
        if adm.get("keyTokens"):
            rows.append("key\ttokens left")
            for k, v in adm["keyTokens"].items():
                rows.append(f"{k}\t{v:g}")
        return format_table(rows)

    if args.cmd == "worker" and args.worker_cmd == "get":
        return json.dumps(await call("worker-get", {"var": args.var}))
    if args.cmd == "worker" and args.worker_cmd == "set":
        return json.dumps(
            await call("worker-set", {"var": args.var, "value": args.value})
        )
    if args.cmd == "worker":
        import time as _time

        ws = await call("worker-list")
        if jd:
            return jd(ws)
        rows = ["id\tname\tstate\terrors\ttranq\trate\tlast\tinfo"]
        now = _time.time()
        for w in ws:
            tq = w.get("tranquility")
            rate = w.get("throughput")
            done = w.get("last_completed")
            rows.append(
                f"{w['id']}\t{w['name']}\t{w['state']}\t{w['errors']}\t"
                f"{'-' if tq is None else tq}\t"
                f"{'-' if rate is None else f'{rate:.2f}/s'}\t"
                f"{'-' if done is None else f'{max(0, now - done):.0f}s ago'}\t"
                f"{w['info']}"
            )
        return format_table(rows)

    if args.cmd == "debug":
        if args.debug_cmd == "profile":
            a = {"seconds": args.seconds, "hz": args.hz}
            if args.speedscope:
                a["format"] = "speedscope"
            r = await call("debug-profile", a)
            body = (
                json.dumps(r["speedscope"]) if args.speedscope else r["folded"]
            )
            if args.output:
                # graft-lint: allow-blocking(one-shot CLI command, loop not shared)
                with open(args.output, "w") as f:
                    f.write(body)
                return (
                    f"wrote {len(body)} bytes "
                    f"({r['samples']} sampling rounds) to {args.output}"
                )
            return body
        if args.debug_cmd == "latency":
            r = await call("debug-latency")
            if jd:
                return jd(r)
            if not r["enabled"]:
                return (
                    "latency X-ray disabled ([admin] latency_xray = false)"
                )
            if not r["ops"]:
                return "no attributed requests recorded yet"
            out_parts = []
            for op, st in sorted(r["ops"].items()):
                w = st["wallMs"]
                rows = [
                    f"== {op} ==\t({st['count']} reqs)",
                    f"wall ms p50/p95/p99\t"
                    f"{w['p50']:.1f} / {w['p95']:.1f} / {w['p99']:.1f}",
                    f"coverage\t{st['coverage'] * 100:.0f}%",
                    f"overlap efficiency\t{st['overlapEfficiency']:.2f} "
                    "(1.0 = fully sequential)",
                    "phase\tp50ms\tp95ms\tp99ms\tshare",
                ]
                for ph, ps in st["phases"].items():
                    rows.append(
                        f"{ph}\t{ps['p50']:.1f}\t{ps['p95']:.1f}\t"
                        f"{ps['p99']:.1f}\t"
                        f"{ps['criticalPathShare'] * 100:.0f}%"
                    )
                out_parts.append(format_table(rows))
            return "\n\n".join(out_parts)
        if args.debug_cmd == "slow":
            r = await call("debug-slow")
            if jd:
                return jd(r)
            if not r["enabled"]:
                return (
                    "flight recorder disabled "
                    "([admin] flight_recorder = false)"
                )
            if not r["requests"]:
                return (
                    f"no requests above {r['thresholdMs']:g} ms recorded"
                )
            rows = ["trace\tname\tms\tspans\tok\ttop phases\tattrs"]
            for q in r["requests"]:
                attrs = ",".join(f"{k}={v}" for k, v in q["attrs"].items())
                wf = q.get("phases") or {}
                top = ", ".join(
                    f"{ph} {st['ms']:.0f}ms"
                    for ph, st in list((wf.get("phases") or {}).items())[:3]
                )
                rows.append(
                    f"{q['traceId'][:16]}\t{q['name']}\t"
                    f"{q['durationMs']:.1f}\t{len(q['spans'])}\t"
                    f"{'y' if q['ok'] else 'n'}\t{top or '-'}\t{attrs}"
                )
            return format_table(rows)

    if args.cmd == "block":
        bc = args.block_cmd
        if bc == "list-errors":
            errs = await call("block-list-errors")
            if jd:
                return jd(errs)
            rows = ["hash\tfailures\tage\tnext try in"]
            for e in errs:
                age = e.get("age_secs")
                rows.append(
                    f"{e['hash'][:16]}\t{e['failures']}\t"
                    f"{'-' if age is None else f'{age}s'}\t"
                    f"{e['next_try_in_secs']}s"
                )
            return format_table(rows)
        if bc == "info":
            return json.dumps(
                await call("block-info", {"hash": args.hash}), indent=2, default=repr
            )
        if bc == "retry-now":
            if not args.all and not args.hash:
                return "error: give a hash or --all"
            return str(
                await call(
                    "block-retry-now", {"hash": args.hash, "all": args.all}
                )
            )
        if bc == "purge":
            return json.dumps(
                await call("block-purge", {"hash": args.hash, "yes": args.yes}),
                indent=2,
            )

    if args.cmd == "repair":
        a = {"what": args.what}
        if args.what == "scrub":
            a["cmd"] = args.sub_cmd or "start"
            if args.sub_value is not None:
                a["value"] = args.sub_value
        if args.what == "plan":
            a["cmd"] = args.sub_cmd or "status"
            if args.fresh:
                a["fresh"] = True
            r = await call("repair", a)
            if isinstance(r, dict):
                if jd:
                    return jd(r)
                rows = [
                    f"running\t{r.get('running')}",
                    f"state\t{r.get('state', '-')}",
                    f"backlog\t{r.get('backlog', 0)}",
                    f"repaired\t{r.get('repaired', 0)}",
                    f"rounds\t{r.get('rounds', 0)}",
                    f"nudged\t{r.get('nudged', 0)}",
                    f"lost\t{r.get('lost', 0)}",
                ]
                for u, n in (r.get("backlogByUrgency") or {}).items():
                    rows.append(f"backlog[{u}]\t{n}")
                return format_table(rows)
            return str(r)
        return str(await call("repair", a))

    if args.cmd == "meta" and args.meta_cmd == "snapshot":
        return json.dumps(await call("meta-snapshot"))

    return None


if __name__ == "__main__":
    sys.exit(main())
