"""CodecBatcher: cross-request coalescing of foreground EC encodes.

ROADMAP item 1: the EC PUT path used to call `codec.encode(data)`
synchronously per block, so N concurrent PUT requests serialized N
single-block codec dispatches on the event loop — the batched offload
the BASELINE.json north star is about never reached the foreground
write path (only the PR 4 repair plane batched).  This module closes
that gap with a dynamic batcher in front of the codec:

  - concurrent `encode()` calls queue their blocks and share ONE
    coalesced dispatch (`EcCodec.encode_batch_hashed`: fused
    encode+BLAKE3 on device backends with power-of-two batch buckets
    and donated inputs, native C codec + batched native BLAKE3 on the
    host backend);

  - a lone request flushes after a bounded linger (`linger_msec`,
    default 2 ms — noise against the EC PUT's quorum round-trips, so
    single-client latency never regresses), while a full batch
    (`max_blocks` / `max_bytes`) flushes immediately;

  - the dispatch itself runs in a worker thread (`asyncio.to_thread`),
    so the codec math never blocks the event loop between any two
    requests — the pre-batcher pipeline's real serialization point.
    This and the power-of-two batch bucketing below it are LINT-ENFORCED
    (ISSUE 11): graft-lint's `host-sync` family flags device round-trips
    reachable from coroutines, and `recompile-hazard` flags compiled
    dispatches whose batch never flowed through `ops/bucketing.py` —
    see doc/static-analysis.md;

  - a dispatch error fails only that batch's waiters; a cancelled PUT
    abandons its entry without poisoning the other requests coalesced
    into the same dispatch.

Phase attribution (utils/latency.py): the submitting request records
`codec_batch_wait` (queue time until its dispatch starts) separately
from `encode` (the dispatch itself), so the X-ray waterfall shows
whether latency went to coalescing or to the codec.

Metric families (doc/monitoring.md):

  block_codec_batch_size          blocks per coalesced dispatch (H)
  block_codec_batch_dispatch_total{flush}  dispatches by flush reason
                                  (full | linger | drain)
  block_codec_batch_coalesced_total  blocks that shared a dispatch
                                  with at least one other block
  block_codec_batch_queue_depth{id}  blocks waiting in the batcher (G)
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import time

from ..utils.aio import spawn_supervised
from ..utils.error import Error
from ..utils.latency import phase_span
from ..utils.metrics import SIZE_BUCKETS, registry

logger = logging.getLogger("garage.block.codec_batch")

registry.set_buckets("block_codec_batch_size", SIZE_BUCKETS)

# gauge `id` source: process-wide (several in-process nodes share the
# registry; per-node ids would collide — utils/background.py pattern)
_gauge_ids = itertools.count(1)


class _Entry:
    __slots__ = ("data", "arrived", "started", "fut")

    def __init__(self, data: bytes):
        self.data = data
        self.arrived = time.monotonic()
        # set when this entry's dispatch begins (ends codec_batch_wait)
        self.started = asyncio.Event()
        self.fut: asyncio.Future = asyncio.get_running_loop().create_future()


class CodecBatcher:
    """Short-linger queue coalescing concurrent block encodes into
    mesh-sized codec dispatches.  One instance per BlockManager (per
    node); the flusher task spawns lazily on first use and is reaped by
    `close()`."""

    def __init__(
        self,
        codec,
        *,
        linger_msec: float = 2.0,
        max_blocks: int = 64,
        max_bytes: int = 64 * 1024 * 1024,
        impl: str = "auto",
    ):
        self.codec = codec
        # live-tunable (BgVars `codec-batch-*`): read on every flush
        self.linger_msec = float(linger_msec)
        self.max_blocks = int(max_blocks)
        self.max_bytes = int(max_bytes)
        self.impl = impl
        self._pending: list[_Entry] = []
        self._pending_bytes = 0
        self._wake = asyncio.Event()
        self._task: asyncio.Task | None = None
        self._closed = False
        self._gauge_key = (
            "block_codec_batch_queue_depth",
            (("id", str(next(_gauge_ids))),),
        )
        registry.register_gauge(
            *self._gauge_key, lambda: float(len(self._pending))
        )

    # --- submit side ----------------------------------------------------------

    async def encode(self, data: bytes) -> tuple[list[bytes], list[bytes] | None]:
        """Queue one block; returns (pieces, piece_hashes | None) once
        its coalesced dispatch completes.  Runs in the caller's task, so
        the phase spans land on the caller's trace."""
        if self._closed:
            raise Error("codec batcher is closed")
        entry = _Entry(data)
        self._pending.append(entry)
        self._pending_bytes += len(data)
        self._wake.set()
        if self._task is None:
            self._task = spawn_supervised(self._run(), name="codec-batcher")
        try:
            with phase_span("codec_batch_wait"):
                await entry.started.wait()
            with phase_span("encode"):
                return await entry.fut
        except asyncio.CancelledError:
            # a PUT cancelled mid-batch abandons its slot; the dispatch
            # (if already in flight) completes for the OTHER waiters,
            # and `_take`/`_dispatch` skip the cancelled future
            entry.fut.cancel()
            raise

    # --- flusher --------------------------------------------------------------

    def _batch_full(self) -> bool:
        return (
            len(self._pending) >= self.max_blocks
            or self._pending_bytes >= self.max_bytes
        )

    async def _run(self) -> None:
        while not self._closed:
            if not self._pending:
                self._wake.clear()
                # re-check: an encode() may have queued between the
                # pending check and the clear
                if not self._pending:
                    await self._wake.wait()
                continue
            flush = "full"
            if not self._batch_full():
                # linger anchored at the HEAD entry's arrival: entries
                # that queued while a previous dispatch was running have
                # already waited their window and flush immediately
                deadline = self._pending[0].arrived + self.linger_msec / 1e3
                flush = "linger"
                while True:
                    self._wake.clear()
                    if self._batch_full():  # re-check after the clear
                        flush = "full"
                        break
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    try:
                        await asyncio.wait_for(self._wake.wait(), remaining)
                    except asyncio.TimeoutError:
                        break
            await self._dispatch(self._take(), flush)

    def _take(self) -> list[_Entry]:
        """Drain up to max_blocks/max_bytes of live entries (cancelled
        waiters are dropped here, before they cost a dispatch slot)."""
        batch: list[_Entry] = []
        size = 0
        while self._pending and len(batch) < self.max_blocks:
            if batch and size + len(self._pending[0].data) > self.max_bytes:
                break
            e = self._pending.pop(0)
            self._pending_bytes -= len(e.data)
            if e.fut.cancelled():
                e.started.set()
                continue
            batch.append(e)
            size += len(e.data)
        return batch

    async def _dispatch(self, batch: list[_Entry], flush: str) -> None:
        if not batch:
            return
        for e in batch:
            e.started.set()
        registry.observe("block_codec_batch_size", (), float(len(batch)))
        registry.incr("block_codec_batch_dispatch_total", (("flush", flush),))
        if len(batch) > 1:
            registry.incr("block_codec_batch_coalesced_total", by=len(batch))
        try:
            # the sync batch encode is handed to a worker thread — the
            # loop keeps serving other requests' fan-outs while the
            # codec math runs (graft-lint passed-not-called remedy)
            results = await asyncio.to_thread(
                self.codec.encode_batch_hashed,
                [e.data for e in batch],
                self.impl,
            )
        except Exception as e:  # noqa: BLE001 — fails THIS batch's waiters
            for ent in batch:
                if not ent.fut.done():
                    ent.fut.set_exception(
                        Error(f"batched codec dispatch failed: {e!r}")
                    )
            return
        except BaseException:
            # flusher cancelled mid-dispatch (close() during node stop):
            # this batch was already drained out of _pending, so close()
            # can't fail its futures — do it here or every waiter of the
            # in-flight batch hangs forever on `await entry.fut`
            for ent in batch:
                if not ent.fut.done():
                    ent.fut.set_exception(
                        Error("codec batcher closed mid-dispatch")
                    )
            raise
        for ent, res in zip(batch, results):
            if not ent.fut.done():  # a waiter may have been cancelled
                ent.fut.set_result(res)

    async def close(self) -> None:
        """Fail pending waiters, reap the flusher, drop the gauge (the
        PR 8 resource rule: registered at creation, unregistered at
        close)."""
        self._closed = True
        self._wake.set()
        for e in self._pending:
            e.started.set()
            if not e.fut.done():
                e.fut.set_exception(Error("codec batcher is closed"))
        self._pending.clear()
        self._pending_bytes = 0
        if self._task is not None:
            from ..utils.aio import reap

            await reap([self._task], log=logger, what="codec-batcher flusher")
            self._task = None
        registry.unregister_gauge(*self._gauge_key)
