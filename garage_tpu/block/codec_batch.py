"""CodecBatcher: cross-request coalescing of foreground EC codec work.

ROADMAP item 1: the EC PUT path used to call `codec.encode(data)`
synchronously per block, so N concurrent PUT requests serialized N
single-block codec dispatches on the event loop — the batched offload
the BASELINE.json north star is about never reached the foreground
write path (only the PR 4 repair plane batched).  This module closes
that gap with a dynamic batcher in front of the codec, organized as two
LANES sharing one set of knobs:

  - the **encode lane** (PR 9): concurrent `encode()` calls queue their
    blocks and share ONE coalesced dispatch (`EcCodec.encode_batch_hashed`:
    fused encode+BLAKE3 on device backends with power-of-two batch
    buckets and donated inputs, native C codec + batched native BLAKE3
    on the host backend);

  - the **decode lane** (ISSUE 13): degraded-mode GETs — a data shard
    missing, a real reconstruction needed — queue their gathered pieces
    and share one grouped reconstruction dispatch
    (`EcCodec.decode_batch`), so a burst of reads against a degraded
    stripe set coalesces instead of serializing N single-block matrix
    solves.  Healthy-cluster GETs never come here: the systematic
    streaming fast path (block/manager.py) needs zero decode.

Shared behavior per lane:

  - a lone request flushes after a bounded linger (`linger_msec`,
    default 2 ms — noise against the EC quorum round-trips, so
    single-client latency never regresses), while a full batch
    (`max_blocks` / `max_bytes`) flushes immediately;

  - the dispatch itself runs in a worker thread (`asyncio.to_thread`),
    so the codec math never blocks the event loop between any two
    requests — the pre-batcher pipeline's real serialization point.
    This and the power-of-two batch bucketing below it are LINT-ENFORCED
    (ISSUE 11): graft-lint's `host-sync` family flags device round-trips
    reachable from coroutines, and `recompile-hazard` flags compiled
    dispatches whose batch never flowed through `ops/bucketing.py` —
    see doc/static-analysis.md;

  - a dispatch error fails only that batch's waiters; a cancelled
    request abandons its entry without poisoning the other requests
    coalesced into the same dispatch.

Phase attribution (utils/latency.py): the submitting request records
`codec_batch_wait` (queue time until its dispatch starts) separately
from `encode`/`decode` (the dispatch itself), so the X-ray waterfall
shows whether latency went to coalescing or to the codec.

Metric families (doc/monitoring.md):

  block_codec_batch_size          blocks per coalesced encode dispatch (H)
  block_codec_batch_dispatch_total{flush}  encode dispatches by flush
                                  reason (full | linger)
  block_codec_batch_decode_dispatch_total{flush}  decode-lane dispatches
  block_codec_batch_coalesced_total  blocks that shared a dispatch
                                  with at least one other block
  block_codec_batch_queue_depth{id}  blocks waiting in a lane (G; one
                                  instance per lane)
  block_codec_batch_lane_linger{lane,flush}  seconds each block sat in
                                  its lane from submit to dispatch start
                                  (H) — joined with the flush-reason
                                  label, this answers "is latency going
                                  to coalescing?" per lane instead of
                                  per guess (Codec X-ray, ISSUE 17; the
                                  digest's `codec.ll99` is this family's
                                  merged p99)
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import time

from ..utils.aio import spawn_supervised
from ..utils.error import Error
from ..utils.latency import phase_span
from ..utils.metrics import SIZE_BUCKETS, registry

logger = logging.getLogger("garage.block.codec_batch")

registry.set_buckets("block_codec_batch_size", SIZE_BUCKETS)

# gauge `id` source: process-wide (several in-process nodes share the
# registry; per-node ids would collide — utils/background.py pattern)
_gauge_ids = itertools.count(1)


class _Entry:
    __slots__ = ("payload", "nbytes", "arrived", "started", "fut")

    def __init__(self, payload, nbytes: int):
        self.payload = payload
        self.nbytes = nbytes
        self.arrived = time.monotonic()
        # set when this entry's dispatch begins (ends codec_batch_wait)
        self.started = asyncio.Event()
        self.fut: asyncio.Future = asyncio.get_running_loop().create_future()


class _Lane:
    """One coalescing queue (encode or decode) reading the batcher's
    live knobs on every flush.  `dispatch_fn(payloads, impl)` is the
    SYNC codec entry point, run via asyncio.to_thread; `phase` is the
    latency-X-ray phase the post-wait dispatch time lands in."""

    def __init__(self, batcher: "CodecBatcher", name: str, phase: str,
                 dispatch_fn, size_metrics: bool):
        self.batcher = batcher
        self.name = name
        self.phase = phase
        self.dispatch_fn = dispatch_fn
        # encode keeps the PR 9 family names; decode gets its own
        # dispatch counter so coalescing tests/panels can tell the lanes
        # apart.  Size/coalesced histograms stay encode-only (the doc'd
        # families) — the decode volume split already lives in
        # `block_codec_blocks_total{op="decode",...}`.
        self.size_metrics = size_metrics
        self.dispatch_counter = (
            "block_codec_batch_dispatch_total"
            if name == "encode"
            else f"block_codec_batch_{name}_dispatch_total"
        )
        self.pending: list[_Entry] = []
        self.pending_bytes = 0
        self.wake = asyncio.Event()
        self.task: asyncio.Task | None = None
        self.gauge_key = (
            "block_codec_batch_queue_depth",
            (("id", str(next(_gauge_ids))),),
        )
        registry.register_gauge(
            *self.gauge_key, lambda: float(len(self.pending))
        )

    # --- submit side ----------------------------------------------------------

    async def submit(self, payload, nbytes: int):
        if self.batcher._closed:
            raise Error("codec batcher is closed")
        entry = _Entry(payload, nbytes)
        self.pending.append(entry)
        self.pending_bytes += nbytes
        self.wake.set()
        if self.task is None:
            self.task = spawn_supervised(
                self._run(), name=f"codec-batcher-{self.name}"
            )
        try:
            with phase_span("codec_batch_wait"):
                await entry.started.wait()
            with phase_span(self.phase):
                return await entry.fut
        except asyncio.CancelledError:
            # a request cancelled mid-batch abandons its slot; the
            # dispatch (if already in flight) completes for the OTHER
            # waiters, and `_take`/`_dispatch` skip the cancelled future
            entry.fut.cancel()
            raise

    # --- flusher --------------------------------------------------------------

    def _batch_full(self) -> bool:
        return (
            len(self.pending) >= self.batcher.max_blocks
            or self.pending_bytes >= self.batcher.max_bytes
        )

    async def _run(self) -> None:
        while not self.batcher._closed:
            if not self.pending:
                self.wake.clear()
                # re-check: a submit() may have queued between the
                # pending check and the clear
                if not self.pending:
                    await self.wake.wait()
                continue
            flush = "full"
            if not self._batch_full():
                # linger anchored at the HEAD entry's arrival: entries
                # that queued while a previous dispatch was running have
                # already waited their window and flush immediately
                deadline = (
                    self.pending[0].arrived + self.batcher.linger_msec / 1e3
                )
                flush = "linger"
                while True:
                    self.wake.clear()
                    if self._batch_full():  # re-check after the clear
                        flush = "full"
                        break
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    try:
                        await asyncio.wait_for(self.wake.wait(), remaining)
                    except asyncio.TimeoutError:
                        break
            await self._dispatch(self._take(), flush)

    def _take(self) -> list[_Entry]:
        """Drain up to max_blocks/max_bytes of live entries (cancelled
        waiters are dropped here, before they cost a dispatch slot)."""
        batch: list[_Entry] = []
        size = 0
        while self.pending and len(batch) < self.batcher.max_blocks:
            if batch and size + self.pending[0].nbytes > self.batcher.max_bytes:
                break
            e = self.pending.pop(0)
            self.pending_bytes -= e.nbytes
            if e.fut.cancelled():
                e.started.set()
                continue
            batch.append(e)
            size += e.nbytes
        return batch

    async def _dispatch(self, batch: list[_Entry], flush: str) -> None:
        if not batch:
            return
        now = time.monotonic()
        linger_lbl = (("lane", self.name), ("flush", flush))
        for e in batch:
            e.started.set()
            registry.observe(
                "block_codec_batch_lane_linger", linger_lbl, now - e.arrived
            )
        if self.size_metrics:
            registry.observe(
                "block_codec_batch_size", (), float(len(batch))
            )
        registry.incr(self.dispatch_counter, (("flush", flush),))
        if len(batch) > 1 and self.size_metrics:
            registry.incr("block_codec_batch_coalesced_total", by=len(batch))
        try:
            # the sync batch dispatch is handed to a worker thread — the
            # loop keeps serving other requests' fan-outs while the
            # codec math runs (graft-lint passed-not-called remedy)
            results = await asyncio.to_thread(
                self.dispatch_fn,
                [e.payload for e in batch],
                self.batcher.impl,
            )
        except Exception as e:  # noqa: BLE001 — fails THIS batch's waiters
            for ent in batch:
                if not ent.fut.done():
                    ent.fut.set_exception(
                        Error(f"batched codec dispatch failed: {e!r}")
                    )
            return
        except BaseException:
            # flusher cancelled mid-dispatch (close() during node stop):
            # this batch was already drained out of `pending`, so close()
            # can't fail its futures — do it here or every waiter of the
            # in-flight batch hangs forever on `await entry.fut`
            for ent in batch:
                if not ent.fut.done():
                    ent.fut.set_exception(
                        Error("codec batcher closed mid-dispatch")
                    )
            raise
        for ent, res in zip(batch, results):
            if not ent.fut.done():  # a waiter may have been cancelled
                ent.fut.set_result(res)

    async def close(self) -> None:
        for e in self.pending:
            e.started.set()
            if not e.fut.done():
                e.fut.set_exception(Error("codec batcher is closed"))
        self.pending.clear()
        self.pending_bytes = 0
        if self.task is not None:
            from ..utils.aio import reap

            await reap(
                [self.task], log=logger,
                what=f"codec-batcher {self.name} flusher",
            )
            self.task = None
        registry.unregister_gauge(*self.gauge_key)


class CodecBatcher:
    """Short-linger queues coalescing concurrent block encodes (and
    degraded-read decodes) into mesh-sized codec dispatches.  One
    instance per BlockManager (per node); each lane's flusher task
    spawns lazily on first use and is reaped by `close()`."""

    def __init__(
        self,
        codec,
        *,
        linger_msec: float = 2.0,
        max_blocks: int = 64,
        max_bytes: int = 64 * 1024 * 1024,
        impl: str = "auto",
    ):
        self.codec = codec
        # live-tunable (BgVars `codec-batch-*`): read on every flush,
        # shared by both lanes
        self.linger_msec = float(linger_msec)
        self.max_blocks = int(max_blocks)
        self.max_bytes = int(max_bytes)
        self.impl = impl
        self._closed = False
        self._encode = _Lane(
            self, "encode", "encode", codec.encode_batch_hashed,
            size_metrics=True,
        )
        # late-bound so a codec without decode_batch (stub codecs in
        # tests) still constructs; a decode() against one fails only
        # that call's batch
        self._decode = _Lane(
            self, "decode", "decode",
            lambda items, impl: self.codec.decode_batch(items, impl),
            size_metrics=False,
        )

    async def encode(self, data: bytes) -> tuple[list[bytes], list[bytes] | None]:
        """Queue one block; returns (pieces, piece_hashes | None) once
        its coalesced dispatch completes.  Runs in the caller's task, so
        the phase spans land on the caller's trace."""
        return await self._encode.submit(data, len(data))

    async def decode(self, pieces: dict[int, bytes], block_len: int) -> bytes:
        """Queue one degraded-read reconstruction; returns the plaintext
        block once its coalesced `decode_batch` dispatch completes."""
        return await self._decode.submit(
            (pieces, block_len), sum(len(p) for p in pieces.values())
        )

    async def close(self) -> None:
        """Fail pending waiters, reap the flushers, drop the gauges (the
        PR 8 resource rule: registered at creation, unregistered at
        close)."""
        self._closed = True
        self._encode.wake.set()
        self._decode.wake.set()
        await self._encode.close()
        await self._decode.close()
