"""Scrub / repair / rebalance workers (reference src/block/repair.rs).

RepairWorker  — walk the whole rc table and queue every block for resync;
                one-shot, spawned by the CLI `repair blocks` command (M5).
ScrubWorker   — continuously read + verify every block file on disk
                (tranquilized pacing; corrupted files are quarantined and
                queued for re-fetch).  Progress (cursor) is persisted so
                restarts resume.  The EC scrub fast path batches shard
                hashing through the TPU pipeline (M8).
RebalanceWorker — move block files to their new primary directory after a
                multi-drive layout change; one-shot, spawned by the CLI
                `repair rebalance` command (M5).
"""

from __future__ import annotations

import asyncio
import logging
import os

from ..utils.background import Worker, WorkerState
from ..utils.migrate import Migratable
from ..utils.persister import Persister
from ..utils.tranquilizer import Tranquilizer

logger = logging.getLogger("garage.block.repair")

SCRUB_BATCH = 16


class RepairWorker(Worker):
    """Re-examine every known block (one-shot).

    Replica mode queues everything through the resync loop.  EC mode takes
    the batched path: blocks whose local piece is missing are repaired in
    groups of EC_REPAIR_BATCH through BlockCodec.reconstruct_batch — one
    grouped device dispatch per erasure pattern (the BASELINE 10k-block
    single-dispatch resync target)."""

    EC_REPAIR_BATCH = 256

    def __init__(self, manager):
        self.manager = manager
        self.cursor: bytes | None = b""
        self.queued = 0
        self.rebuilt = 0

    def name(self) -> str:
        return "block_repair"

    def status(self):
        return {
            "queued": self.queued,
            "rebuilt": self.rebuilt,
            "done": self.cursor is None,
        }

    async def work(self):
        if self.cursor is None:
            return WorkerState.DONE
        ec = self.manager.codec.n_pieces > 1
        n = 0
        batch: list[bytes] = []
        for key, _v in self.manager.rc.tree.iter_range(start=self.cursor):
            if ec:
                batch.append(key)
            else:
                self.manager.resync.queue_block(key)
            self.cursor = key + b"\x00"
            self.queued += 1
            n += 1
            if n >= (self.EC_REPAIR_BATCH if ec else 100):
                break
        if not n:
            self.cursor = None
            return WorkerState.BUSY
        if ec and batch:
            # same driver + metric families as the repair planner
            # (block/repair_plan.py), so `repair blocks` rounds land in
            # repair_plan_batch_size / repair_plan_blocks_total too
            from .repair_plan import drive_bulk

            self.rebuilt += await drive_bulk(self.manager, batch)
        return WorkerState.BUSY


class ScrubPersisted(Migratable):
    VERSION_MARKER = b"GT0scrub"

    def __init__(self, cursor: bytes = b"", tranquility: int = 4, corruptions: int = 0):
        self.cursor = cursor
        self.tranquility = tranquility
        self.corruptions = corruptions

    def to_obj(self):
        return [self.cursor, self.tranquility, self.corruptions]

    @classmethod
    def from_obj(cls, obj):
        return cls(bytes(obj[0]), int(obj[1]), int(obj[2]))


class ScrubWorker(Worker):
    """Verify every stored block against its hash, slowly and forever."""

    def __init__(self, manager, metadata_dir: str | None = None):
        self.manager = manager
        self.tranquilizer = Tranquilizer()
        self.persister = (
            Persister(metadata_dir, "scrub_info", ScrubPersisted)
            if metadata_dir
            else None
        )
        self.state = (self.persister.load() if self.persister else None) or ScrubPersisted()
        self.paused = False

    def name(self) -> str:
        return "scrub"

    def status(self):
        return {
            "cursor": self.state.cursor.hex()[:16],
            "corruptions": self.state.corruptions,
            "paused": self.paused,
        }

    def tranquility(self) -> int | None:
        return self.state.tranquility

    # --- operator controls (reference `garage repair scrub {…}`) -------------

    def cmd_start(self) -> None:
        """Begin a fresh pass immediately."""
        self.state.cursor = b""
        self.paused = False
        self._save()

    def cmd_pause(self) -> None:
        self.paused = True

    def cmd_resume(self) -> None:
        self.paused = False

    def cmd_cancel(self) -> None:
        """Abort the in-progress pass (the next one starts from zero)."""
        self.state.cursor = b""
        self.paused = True
        self._save()

    def cmd_set_tranquility(self, t: int) -> None:
        self.state.tranquility = max(0, int(t))
        self._save()

    async def work(self):
        if self.paused:
            return (WorkerState.THROTTLED, 5.0)
        self.tranquilizer.reset()
        n = 0
        for key, _v in self.manager.rc.tree.iter_range(start=self.state.cursor):
            hash32 = key
            await self._scrub_one(hash32)
            self.state.cursor = key + b"\x00"
            n += 1
            if n >= SCRUB_BATCH:
                break
        if n == 0:
            # cycle complete: restart from the beginning after a long rest
            self.state.cursor = b""
            await self._save_async()
            return (WorkerState.THROTTLED, 3600.0)
        await self._save_async()
        delay = self.tranquilizer.tranquilize_delay(self.state.tranquility)
        return (WorkerState.THROTTLED, max(delay, 0.05))

    async def _scrub_one(self, hash32: bytes) -> None:
        mgr = self.manager
        if mgr.codec.n_pieces > 1:
            await self._scrub_pieces([hash32])
            return
        found = mgr.find_block_file(hash32)
        if found is None:
            return
        data = await mgr.read_block_local(hash32)  # verifies + quarantines
        if data is None and mgr.rc.is_needed(hash32):
            self.state.corruptions += 1
            logger.warning("scrub: corrupted block %s queued for refetch", hash32.hex()[:16])

    async def _scrub_pieces(self, hashes: list[bytes]) -> None:
        """Verify every local EC piece of `hashes` against its header
        BLAKE3.  Equal-length pieces are hashed in ONE batch — through the
        jax kernel (TPU offload) when available, else the native batch —
        so a scrub pass over thousands of shards is a few dispatches."""
        import numpy as np

        from .manager import _read_file_sync, piece_hash, stored_piece_parts

        mgr = self.manager
        groups: dict[int, list[tuple[bytes, int, str, bytes, bytes]]] = {}
        for h in hashes:
            for pi, (path, compressed) in mgr.local_pieces(h).items():
                try:
                    stored = await asyncio.to_thread(_read_file_sync, path)
                except OSError:
                    continue
                parts = stored_piece_parts(stored)
                if parts is None:
                    continue  # v1 piece: no integrity hash to check
                blen, want, piece = parts
                groups.setdefault(len(piece), []).append(
                    (h, pi, path, want, piece)
                )
        for plen, items in groups.items():
            got = None
            if plen % 64 == 0:
                # worker-thread hops for the WHOLE group path: the
                # np.stack is a megacopy of the group, blake3_batch's
                # np.asarray is a device round-trip (host-sync), and the
                # native fallback is a long CPU hash run — any of them
                # dispatched inline stalls the event loop for the whole
                # scrub batch, worst exactly on nodes already degraded
                # to the host path
                batch = await asyncio.to_thread(
                    np.stack,
                    [np.frombuffer(p, dtype=np.uint8) for *_x, p in items],
                )
                try:
                    from ..ops.hash_tpu import blake3_batch as jax_batch

                    got = await asyncio.to_thread(jax_batch, batch)
                except Exception as e:  # noqa: BLE001 — unsupported shape/backend
                    logger.debug("scrub: jax batch hash fell back: %r", e)
                    got = None
                if got is None:
                    from .. import _native

                    got = await asyncio.to_thread(_native.blake3_batch, batch)
            for idx, (h, pi, path, want, piece) in enumerate(items):
                digest = bytes(got[idx]) if got is not None else piece_hash(piece)
                if digest != want:
                    self.state.corruptions += 1
                    logger.warning(
                        "scrub: corrupted piece %d of %s quarantined",
                        pi, h.hex()[:16],
                    )
                    await mgr._quarantine(path)
                    mgr.resync.queue_block(h)

    def _save(self):
        if self.persister:
            self.persister.save(self.state)

    async def _save_async(self):
        # work()-path checkpoints fsync off the event loop (loop-blocker);
        # the sync _save stays for the operator cmd_* one-shots
        if self.persister:
            await self.persister.save_in_thread(self.state)


class RebalanceWorker(Worker):
    """Move block files onto their current primary directory (one-shot)."""

    def __init__(self, manager):
        self.manager = manager
        self.cursor: bytes | None = b""
        self.moved = 0

    def name(self) -> str:
        return "rebalance"

    def status(self):
        return {"moved": self.moved, "done": self.cursor is None}

    async def work(self):
        if self.cursor is None:
            return WorkerState.DONE
        mgr = self.manager
        n = 0
        for key, _v in mgr.rc.tree.iter_range(start=self.cursor):
            self.cursor = key + b"\x00"
            n += 1
            primary = mgr.data_layout.primary_dir(key)
            want_dir = mgr.data_layout.block_dir(primary, key)
            for piece, (path, compressed) in mgr.local_pieces(key).items():
                want = os.path.join(want_dir, mgr._file_name(key, piece, compressed))
                if path != want:
                    await asyncio.to_thread(os.makedirs, want_dir, exist_ok=True)
                    await asyncio.to_thread(os.replace, path, want)
                    self.moved += 1
            if n >= 100:
                return WorkerState.BUSY
        self.cursor = None
        return WorkerState.BUSY
