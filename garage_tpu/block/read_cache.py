"""Hot-block read cache: a bounded-bytes LRU of assembled plaintext
blocks, keyed by content hash.

ROADMAP item 1c / ISSUE 13: zipfian traffic means a small fraction of
blocks serves most GETs — with the block store content-addressed, a
cached block can never go stale (a different payload IS a different
hash), so there is no invalidation protocol at all.  A repeat GET of a
hot object becomes a memory read instead of k piece fetches + a join
(EC) or a disk read + hash verify (replica remote fetch).

The cache lives ON the BlockManager instance — one per node, NOT a
process-wide singleton.  In-process test clusters share the process,
and a shared cache would let node A "read" a block it never fetched
(the PhaseAggregator/flight-recorder singleton hazard from PRs 6/9,
this time corrupting read-path semantics rather than metrics).

Entries are inserted only for blocks whose assembly cost something
remote (EC piece gathers, replica fetches from peers) — a replica-mode
local disk read is already served from the page cache and caching it
again would just duplicate RAM.

Metric families (doc/monitoring.md): `block_cache_{hits,misses,
evictions}_total` counters (process-wide aggregates) and a
`block_cache_bytes{id}` gauge per instance (`id` is process-unique,
the codec-batcher gauge pattern); the gauge is registered at
construction and unregistered at `close()` (the PR 8 resource rule).
Sized by `[block] read_cache_bytes` (0 disables), live-tunable via
`worker set read-cache-bytes`.
"""

from __future__ import annotations

import itertools
from collections import OrderedDict

from ..utils.metrics import registry

# gauge `id` source: process-wide (several in-process nodes share the
# registry; per-node ids would collide — utils/background.py pattern)
_cache_ids = itertools.count(1)


class BlockCache:
    """Bounded-bytes LRU of verified plaintext blocks (one per node)."""

    def __init__(self, max_bytes: int):
        self.max_bytes = max(0, int(max_bytes))
        self._map: OrderedDict[bytes, bytes] = OrderedDict()
        self._bytes = 0
        self._gauge_key = (
            "block_cache_bytes",
            (("id", str(next(_cache_ids))),),
        )
        registry.register_gauge(*self._gauge_key, lambda: float(self._bytes))

    def __len__(self) -> int:
        return len(self._map)

    @property
    def bytes_used(self) -> int:
        return self._bytes

    def get(self, hash32: bytes) -> bytes | None:
        """Cached plaintext for `hash32`, refreshing recency; None on a
        miss.  A disabled cache (max_bytes == 0) returns None without
        counting — it would poison every hit-ratio panel with misses it
        was configured never to convert."""
        if self.max_bytes <= 0:
            return None
        data = self._map.get(hash32)
        if data is None:
            registry.incr("block_cache_misses_total")
            return None
        self._map.move_to_end(hash32)
        registry.incr("block_cache_hits_total")
        return data

    def put(self, hash32: bytes, data: bytes) -> None:
        """Insert a VERIFIED plaintext block (callers hash-check before
        inserting — the cache must never launder a corrupt assembly into
        future reads).  Oversized blocks are skipped, not force-fitted."""
        if self.max_bytes <= 0 or len(data) > self.max_bytes:
            return
        if hash32 in self._map:
            self._map.move_to_end(hash32)  # same hash = same bytes
            return
        self._map[hash32] = data
        self._bytes += len(data)
        self._evict()

    def _evict(self) -> None:
        while self._bytes > self.max_bytes and self._map:
            _h, old = self._map.popitem(last=False)
            self._bytes -= len(old)
            registry.incr("block_cache_evictions_total")

    def set_max_bytes(self, n: int) -> None:
        """Live resize (`worker set read-cache-bytes`): shrinking evicts
        down immediately; 0 disables and empties."""
        self.max_bytes = max(0, int(n))
        if self.max_bytes == 0:
            self._map.clear()
            self._bytes = 0
        else:
            self._evict()

    def close(self) -> None:
        """Drop the per-instance gauge (registered at construction,
        unregistered here — the resource rule for transient owners)."""
        registry.unregister_gauge(*self._gauge_key)
        self._map.clear()
        self._bytes = 0
