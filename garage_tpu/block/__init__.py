"""Content-addressed block store (reference src/block/).

Objects are chunked into blocks (1 MiB default) identified by the BLAKE2
hash of their plaintext.  Blocks live as files under the data directories,
optionally zstd-compressed, replicated (or erasure-coded — the rebuild's
TPU north star) to the nodes the layout assigns to the block hash.

  codec/    BlockCodec seam: ReplicaCodec (whole copies) and EcCodec
            (GF(2^8) Reed-Solomon shards, batched on TPU)
  layout    multi-drive data layout (1024 sub-partitions ∝ capacity)
  rc        transactional per-block reference counts
  manager   the BlockManager: local files + Get/Put/Need RPCs + quorum
  resync    persistent retry queue: fetch missing / offload unneeded
  repair    scrub (verify all blocks), full repair, drive rebalance
"""

from .manager import BlockManager

__all__ = ["BlockManager"]
