"""BlockManager: local block files + replication RPCs.

Reference src/block/manager.rs.  Blocks are stored as files named by their
hash under `<dir>/<hh>/<hh>/`, zstd-compressed when beneficial
(`<hash>.zst`), plain otherwise.  Writes verify the hash, optionally
fsync, and are serialized by a 256-way mutex shard.  Reads verify before
returning.  Remote ops on endpoint `block/data`:

  ["Put", hash, {"c": compressed}]  + data in body   store one block/piece
  ["Get", hash]                     -> {"c":..}, data   read stored form
  ["Need", hash]                    -> bool   does this node still need it?

Block payloads ride ATTACHED BYTE STREAMS (reference src/net/stream.rs +
manager.rs:366 rpc_put_block streaming): the body carries only the small
msgpack header, the payload flows as stream chunks through the frame
scheduler's priority QoS, and the serving side reads files in chunks
instead of one big buffer.  Aggregate payload RAM is bounded by a
`block_ram_buffer_max` byte-budget semaphore (reference manager.rs:96) —
a resync burst queues behind the budget instead of ballooning RSS.

With an erasure codec (`replication_mode = ec:k:m`), each node in the
block's assignment stores the piece whose index equals the node's rank in
the assignment; `rpc_get_block` then gathers `k` pieces and decodes
(codec-driven, see codec/ec.py).
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from typing import Any

import zstandard

from ..db import Db
from ..net.message import PRIO_BACKGROUND, PRIO_NORMAL, Req, Resp
from ..rpc.layout.types import partition_of
from ..rpc.rpc_helper import RpcHelper
from ..rpc.system import System
from ..utils.background import BackgroundRunner
from ..utils.config import DataDir
from ..utils.data import blake2sum
from ..utils.error import Error, Quorum
from ..utils.persister import Persister
from .codec import BlockCodec, ReplicaCodec
from .layout import DataLayout
from .rc import BlockRc

logger = logging.getLogger("garage.block")

INLINE_THRESHOLD = 3072  # smaller objects inline in the object table

# EC piece files carry the original block length (to strip the codec's
# stripe padding at decode time) and the BLAKE3 of the piece (per-piece
# integrity for scrub — the block hash only covers the decoded plaintext):
#   b"GTP2" + u64 block_len + 32B blake3(piece) + piece
# (v1 "GTP1" files without the hash are still readable.)
PIECE_MAGIC_V1 = b"GTP1"
PIECE_MAGIC = b"GTP2"


def _read_file_sync(path: str) -> bytes:
    """Whole-file read — always call through asyncio.to_thread from
    coroutines (graft-lint loop-blocker): a disk read on the event loop
    stalls EVERY concurrent request on the node."""
    with open(path, "rb") as f:
        return f.read()


def _file_stream(path: str, chunk: int = 256 * 1024):
    """Async generator reading a block file in chunks (serving side of
    streamed Get: no whole-file buffer).  Each read runs in a worker
    thread so a slow/contended disk never blocks the event loop between
    chunks."""

    async def gen():
        f = await asyncio.to_thread(open, path, "rb")
        try:
            while True:
                b = await asyncio.to_thread(f.read, chunk)
                if not b:
                    return
                yield b
        finally:
            # close in a thread too: after a cancelled read, close()
            # blocks on the BufferedReader lock until the in-flight disk
            # read finishes — on the loop that would be exactly the stall
            # this function exists to avoid.  Shielded so a cancel
            # delivered mid-close can't abandon the fd (cancel-safety).
            await asyncio.shield(asyncio.to_thread(f.close))

    return gen()


async def _resp_payload(resp, budget=None) -> tuple[dict, bytes]:
    """(meta, stored_bytes) from a Get response — streamed or legacy
    inline.  With `budget`, RAM is reserved (from the declared size)
    BEFORE the stream is buffered."""
    body = resp.body
    if len(body) > 2 and body[2] is not None:
        return body[1], bytes(body[2])
    from ..net.stream import read_stream_to_end

    if budget is not None:
        async with budget.reserve(int(body[1].get("s", 4 * 1024 * 1024))):
            return body[1], await read_stream_to_end(resp.stream)
    return body[1], await read_stream_to_end(resp.stream)


def piece_hash(piece: bytes) -> bytes:
    from .. import _native

    h = _native.blake3(piece)
    if h is not None:
        return h
    from ..ops.blake3_ref import blake3 as _py_blake3

    return _py_blake3(piece)


def wrap_piece(block_len: int, piece: bytes, phash: bytes | None = None) -> bytes:
    """Build the stored piece header.  `phash` is the sender-provided
    BLAKE3 of the piece (computed inside the batched encode dispatch,
    `block/codec_batch.py`): when present the receiving node skips its
    own per-piece hash.  Trust is unchanged — the sender is already the
    authority for the piece bytes themselves, and a wrong hash surfaces
    at scrub exactly like a corrupted piece would (quarantine + resync
    rebuild)."""
    if phash is None or len(phash) != 32:
        phash = piece_hash(piece)
    return PIECE_MAGIC + block_len.to_bytes(8, "big") + phash + piece


def unwrap_piece(stored: bytes, verify: bool = True) -> tuple[int, bytes]:
    if stored[:4] == PIECE_MAGIC:
        blen = int.from_bytes(stored[4:12], "big")
        want = stored[12:44]
        piece = stored[44:]
        if verify and piece_hash(piece) != want:
            raise Error("EC piece integrity hash mismatch")
        return blen, piece
    if stored[:4] == PIECE_MAGIC_V1:
        return int.from_bytes(stored[4:12], "big"), stored[12:]
    raise Error("not an EC piece file")


def stored_piece_parts(stored: bytes) -> tuple[int, bytes, bytes] | None:
    """(block_len, expected_hash, piece) for v2 files; None for v1."""
    if stored[:4] != PIECE_MAGIC:
        return None
    return (
        int.from_bytes(stored[4:12], "big"),
        stored[12:44],
        stored[44:],
    )


import contextvars

# re-entrancy marker: a task that already holds a ByteBudget reservation
# must not block on a nested one — the local-shortcut RPC path dispatches
# the Put handler IN the caller's task, and caller + handler reserving
# from the same budget would deadlock once the budget is contended
_budget_held: contextvars.ContextVar[bool] = contextvars.ContextVar(
    "block_budget_held", default=False
)


class ByteBudget:
    """Async RAM budget: holders of block payload buffers `reserve(n)`
    bytes; when the budget is exhausted new work waits instead of
    allocating (reference manager.rs block_ram_buffer_max semaphore).
    Re-entrant per task: a nested reserve inside a held one is free."""

    def __init__(self, limit: int):
        self.limit = max(1, limit)
        self.used = 0
        self._cond = asyncio.Condition()

    def reserve(self, n: int):
        from contextlib import asynccontextmanager

        # one oversized item may exceed the budget alone (never deadlock)
        n = min(n, self.limit)

        @asynccontextmanager
        async def ctx():
            if _budget_held.get():
                yield  # caller's reservation already covers this task
                return
            async with self._cond:
                while self.used + n > self.limit:
                    await self._cond.wait()
                self.used += n
            token = _budget_held.set(True)
            try:
                yield
            finally:
                _budget_held.reset(token)
                async with self._cond:
                    self.used -= n
                    self._cond.notify_all()

        return ctx()


class BlockManager:
    def __init__(
        self,
        system: System,
        helper: RpcHelper,
        db: Db,
        data_dirs: list[DataDir],
        metadata_dir: str,
        compression_level: int | None = 1,
        codec: BlockCodec | None = None,
        data_fsync: bool = False,
        ram_buffer_max: int = 256 * 1024 * 1024,
        disable_scrub: bool = False,
        block_config=None,
    ):
        from ..utils.config import BlockConfig

        self.system = system
        self.helper = helper
        self.db = db
        self.metadata_dir = metadata_dir
        self.codec = codec or ReplicaCodec()
        self.compression_level = compression_level
        self.data_fsync = data_fsync
        self.disable_scrub = disable_scrub
        self.buffers = ByteBudget(ram_buffer_max)
        self.rc = BlockRc(db)
        # foreground codec batcher ([block] knobs, utils/config.py):
        # coalesces concurrent PUT encodes into one dispatch.  EC only —
        # the replica codec has no encode step to batch.
        self.block_config = block_config or BlockConfig()
        self.batcher = None
        if (
            self.codec.n_pieces > 1
            and self.block_config.batch_enabled
            and hasattr(self.codec, "encode_batch_hashed")
        ):
            from .codec_batch import CodecBatcher

            self.batcher = CodecBatcher(
                self.codec,
                linger_msec=self.block_config.batch_linger_msec,
                max_blocks=self.block_config.batch_max_blocks,
                max_bytes=self.block_config.batch_max_bytes,
                impl=self.block_config.batch_impl,
            )
        # hot-block read cache (ISSUE 13): per-NODE on purpose — a
        # process-wide singleton would let in-process test-cluster node A
        # "read" a block it never fetched (the PR 6/9 singleton hazard)
        from .read_cache import BlockCache

        self.read_cache = BlockCache(self.block_config.read_cache_bytes)
        # seedable disk-fault seam (net/fault.py FaultPlan): when set,
        # local block reads/writes may fail per the plan's probabilities
        self.fault_plan = None

        self._layout_persister: Persister[DataLayout] = Persister(
            metadata_dir, "data_layout", DataLayout
        )
        existing = self._layout_persister.load()
        if existing is None:
            self.data_layout = DataLayout.initial(data_dirs)
        else:
            existing.check_markers()
            self.data_layout = existing.update(data_dirs)
        self.data_layout.ensure_markers()
        self._layout_persister.save(self.data_layout)

        self._locks = [asyncio.Lock() for _ in range(256)]
        self.endpoint = system.netapp.endpoint("block/data")
        self.endpoint.set_handler(self._handle)

        from .resync import BlockResyncManager

        self.resync = BlockResyncManager(self)

    def spawn_workers(self, bg: BackgroundRunner) -> None:
        from .repair import ScrubWorker

        self.resync.spawn_workers(bg)
        # kept as an attribute so the admin scrub controls (pause/resume/
        # cancel/tranquility) can reach the running worker
        self.scrub_worker = None
        if not self.disable_scrub:  # config.rs disable_scrub / manager.rs:202
            self.scrub_worker = ScrubWorker(self, metadata_dir=self.metadata_dir)
            bg.spawn(self.scrub_worker)

    # --- placement -----------------------------------------------------------

    def storage_nodes_of(self, hash32: bytes) -> list[bytes]:
        layout = self.system.layout_manager.history
        nodes: list[bytes] = []
        for s in layout.write_sets_of(hash32):
            for n in s:
                if n not in nodes:
                    nodes.append(n)
        return nodes

    def read_nodes_of(self, hash32: bytes) -> list[bytes]:
        return self.system.layout_manager.history.read_nodes_of(hash32)

    # --- local file store -----------------------------------------------------

    def _file_name(self, hash32: bytes, piece: int, compressed: bool) -> str:
        # EC pieces carry their index in the name ("<hash>.p<i>"): node
        # rank changes across layout versions, so piece identity must live
        # with the file, not be inferred from placement
        name = hash32.hex()
        if piece != 0 or self.codec.n_pieces > 1:
            name += f".p{piece}"
        return name + (".zst" if compressed else "")

    def find_block_file(self, hash32: bytes, piece: int = 0) -> tuple[str, bool] | None:
        for base in self.data_layout.all_dirs(hash32):
            d = self.data_layout.block_dir(base, hash32)
            for compressed in (True, False):
                p = os.path.join(d, self._file_name(hash32, piece, compressed))
                if os.path.exists(p):
                    return (p, compressed)
            if piece == 0 and self.codec.n_pieces > 1:
                # legacy replica-format file (codec switched to EC)
                p = os.path.join(d, hash32.hex())
                for cand in (p + ".zst", p):
                    if os.path.exists(cand):
                        return (cand, cand.endswith(".zst"))
        return None

    def local_pieces(self, hash32: bytes) -> dict[int, tuple[str, bool]]:
        """All locally stored pieces of a block (EC scrub/read path)."""
        out: dict[int, tuple[str, bool]] = {}
        for i in range(self.codec.n_pieces):
            f = self.find_block_file(hash32, piece=i)
            if f:
                out[i] = f
        return out

    def has_block(self, hash32: bytes) -> bool:
        return self.find_block_file(hash32) is not None

    async def write_block_local(
        self, hash32: bytes, stored: bytes, compressed: bool, piece: int = 0
    ) -> None:
        """Store already-encoded bytes (compressed or plain) for hash."""
        if self.fault_plan is not None and self.fault_plan.should_fail_disk(
            "write"
        ):
            from ..net.fault import InjectedDiskFault

            raise InjectedDiskFault("injected block write fault")
        async with self._locks[hash32[0]]:  # graft-lint: allow-lock-await(per-prefix write lock intentionally spans the threaded write: shard serialization is the contract (ISSUE 10 known-intended case))
            existing = self.find_block_file(hash32, piece=piece)
            if existing is not None:
                ex_path, ex_comp = existing
                if ex_comp or not compressed:
                    return  # already have an equal-or-better copy
            base = self.data_layout.primary_dir(hash32)
            d = self.data_layout.block_dir(base, hash32)
            path = os.path.join(d, self._file_name(hash32, piece, compressed))
            # the mkdir/write/fsync/rename sequence runs in a worker
            # thread: with data_fsync on, an fsync on the loop thread
            # used to stall every concurrent request for the duration of
            # a disk flush (the single biggest per-request event-loop
            # blocker on the EC PUT path).  The per-prefix lock is held
            # across the await, so write serialization per hash shard is
            # unchanged.
            await asyncio.to_thread(
                self._write_block_file_sync, d, path, stored
            )
            if existing is not None and existing[0] != path:
                try:
                    await asyncio.to_thread(os.remove, existing[0])
                except OSError:
                    pass

    def _write_block_file_sync(self, d: str, path: str, stored: bytes) -> None:
        """Blocking half of write_block_local — runs via
        asyncio.to_thread, never call from a coroutine directly."""
        os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(stored)
            if self.data_fsync:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, path)

    async def read_block_local(self, hash32: bytes) -> bytes | None:
        """Read + verify + decompress the locally stored piece/block."""
        found = self.find_block_file(hash32)
        if found is None:
            return None
        if self.fault_plan is not None and self.fault_plan.should_fail_disk(
            "read"
        ):
            # an unreadable sector behaves like a local miss: the caller
            # falls back to peers, resync re-examines the block
            logger.warning(
                "injected block read fault for %s", hash32.hex()[:16]
            )
            self.resync.queue_block(hash32)
            return None
        path, compressed = found
        stored = await asyncio.to_thread(_read_file_sync, path)
        try:
            data = zstandard.decompress(stored) if compressed else stored
        except zstandard.ZstdError as e:
            logger.error("local block %s undecodable: %r", hash32.hex()[:16], e)
            await self._quarantine(path)
            self.resync.queue_block(hash32)
            return None
        if not self._verify(hash32, data):
            logger.error("local block %s is corrupted", hash32.hex()[:16])
            await self._quarantine(path)
            self.resync.queue_block(hash32)
            return None
        return data

    def _verify(self, hash32: bytes, piece: bytes) -> bool:
        """For replication, the piece IS the block: hash must match.  For
        EC, pieces are not the block; integrity uses stored piece hashes
        (shard headers, M8) — here we accept and rely on codec checks."""
        if self.codec.n_pieces == 1:
            return blake2sum(piece) == hash32
        return True

    async def _quarantine(self, path: str) -> None:
        from ..utils.metrics import registry

        registry.incr("block_corrupted_count")
        try:
            await asyncio.to_thread(os.replace, path, path + ".corrupted")
        except OSError:
            pass

    def _maybe_compress(self, data: bytes) -> tuple[bytes, bool]:
        if self.compression_level is None:
            return data, False
        comp = zstandard.compress(data, self.compression_level)
        if len(comp) < len(data):
            return comp, True
        return data, False

    # --- rpc handlers ---------------------------------------------------------

    async def _handle(self, from_id: bytes, req: Req) -> Resp:
        op = req.body
        if op[0] == "Put":
            hash32, meta = bytes(op[1]), op[2]
            # reserve BEFORE buffering the payload (the sender declares the
            # size in meta["s"]) — this is what actually bounds receiver RSS
            async with self.buffers.reserve(int(meta.get("s", 4 * 1024 * 1024))):
                if len(op) > 3 and op[3] is not None:
                    payload = bytes(op[3])  # legacy inline-body form
                else:
                    from ..net.stream import read_stream_to_end

                    payload = await read_stream_to_end(req.stream)
                piece = int(meta.get("p", 0))
                if self.codec.n_pieces == 1 and not bool(meta.get("c")):
                    # replica mode stores the block itself: verify first
                    # (hashing a whole block is CPU-bound — off the loop
                    # above the same threshold the sender uses)
                    if len(payload) >= self.block_config.cpu_offload_min_bytes:
                        digest = await asyncio.to_thread(blake2sum, payload)
                    else:
                        digest = blake2sum(payload)
                    if digest != hash32:
                        raise Error("put payload does not match block hash")
                if "l" in meta:  # fresh EC piece: wrap with its block length
                    ph = meta.get("ph")
                    payload = wrap_piece(
                        int(meta["l"]), payload,
                        phash=bytes(ph) if ph is not None else None,
                    )
                await self.write_block_local(
                    hash32, payload, bool(meta.get("c")), piece=piece
                )
            return Resp(None)
        if op[0] == "Get":
            hash32 = bytes(op[1])
            piece = int(op[2]) if len(op) > 2 and op[2] is not None else 0
            found = self.find_block_file(hash32, piece=piece)
            if found is None:
                raise Error(f"block {hash32.hex()[:16]} piece {piece} not found")
            path, compressed = found
            # stream the file in chunks: the whole block never sits in one
            # send buffer, and the QoS scheduler interleaves other traffic;
            # "s" lets the receiver reserve RAM before buffering
            size = os.path.getsize(path)
            return Resp(
                ["ok", {"c": compressed, "s": size}], stream=_file_stream(path)
            )
        if op[0] == "Need":
            hash32 = bytes(op[1])
            return Resp(self.rc.is_needed(hash32) and not self.has_block(hash32))
        if op[0] == "Pieces":
            hash32 = bytes(op[1])
            return Resp(sorted(self.local_pieces(hash32).keys()))
        if op[0] == "Inv":
            # bulk piece inventory (repair-plane survey, block/repair_plan.py):
            # one RPC answers for hundreds of hashes what "Pieces" answers
            # for one — [[piece_indices], piece_payload_len] per hash
            out = []
            for h in op[1]:
                h = bytes(h)
                pieces = self.local_pieces(h)
                plen = 0
                for _pi, (path, compressed) in sorted(pieces.items()):
                    if compressed:
                        continue  # legacy .zst replica file: size lies
                    from .repair_plan import _stored_piece_len

                    plen = await asyncio.to_thread(_stored_piece_len, path)
                    break
                out.append([sorted(pieces.keys()), plen])
            return Resp(out)
        if op[0] == "Queue":
            # bulk resync nudge: a remote planner found stripes whose
            # missing ranks live HERE; this node's resync heals them
            hashes = [bytes(h) for h in op[1]]
            self.resync.queue_blocks(hashes)
            return Resp(len(hashes))
        raise Error(f"unknown block op {op[0]!r}")

    async def close(self) -> None:
        """Tear down foreground resources (Garage.stop): the codec
        batcher's flusher tasks + queue-depth gauges and the read
        cache's bytes gauge."""
        if self.batcher is not None:
            await self.batcher.close()
        self.read_cache.close()

    async def _encode_ec(
        self, data: bytes
    ) -> tuple[list[bytes], list[bytes] | None]:
        """EC piece encode for the foreground write path: coalesced with
        concurrent requests through the batcher when enabled (which also
        yields the per-piece BLAKE3 hashes from the fused dispatch);
        otherwise a single-block dispatch in a worker thread.  Either
        way the codec math stays OFF the event loop — the pre-batcher
        pipeline's real serialization point under concurrency."""
        if self.batcher is not None:
            return await self.batcher.encode(data)
        from ..utils.latency import phase_span

        with phase_span("encode"):
            pieces = await asyncio.to_thread(self.codec.encode, data)
        return pieces, None

    # --- cluster ops ----------------------------------------------------------

    async def rpc_put_block(self, hash32: bytes, data: bytes) -> None:
        """Store a block on its replica set (quorum in every active layout
        version).  With an EC codec, each node receives only its piece.
        Payloads ride attached streams; aggregate buffer RAM is budgeted."""
        from ..utils.metrics import registry
        from ..utils.tracing import span

        with span("block:put", size=len(data)):
            await self._rpc_put_block(hash32, data)
        registry.incr("block_bytes_written", by=len(data))  # successes only

    async def _rpc_put_block(self, hash32: bytes, data: bytes) -> None:
        from ..net.stream import bytes_stream
        from ..utils.latency import phase_span

        layout = self.system.layout_manager.history
        write_sets = layout.write_sets_of(hash32)
        quorum = self.system.replication_mode.write_quorum()
        if self.codec.n_pieces == 1:
            with phase_span("encode"):
                # zstd is CPU-bound: at block sizes a thread hop is noise
                # against the compression itself, so large blocks leave
                # the event loop (graft-lint can't see this blocker —
                # it's compute, not I/O — but it stalled every concurrent
                # request for the duration of a block compression)
                if (
                    self.compression_level is not None
                    and len(data) >= self.block_config.cpu_offload_min_bytes
                ):
                    stored, compressed = await asyncio.to_thread(
                        self._maybe_compress, data
                    )
                else:
                    stored, compressed = self._maybe_compress(data)
            async with self.buffers.reserve(len(stored)):
                # replica sends + their quorum wait are one awaited call;
                # the whole window is attributed to the fan-out phase.
                # prio audit (overload plane): foreground S3 PUT fan-out
                # — PRIO_NORMAL by design, below interactive GET piece
                # fetches (PRIO_HIGH, api/s3/objects.py) and above every
                # background plane (PRIO_BACKGROUND: resync, repair,
                # table sync)
                with phase_span("fanout"):
                    await self.helper.try_write_many_sets(
                        self.endpoint,
                        write_sets,
                        ["Put", hash32, {"c": compressed, "s": len(stored)}],
                        quorum=quorum,
                        prio=PRIO_NORMAL,
                        stream_factory=lambda: bytes_stream(stored),
                    )
            return
        # EC: one distinct piece per node rank, placed in EVERY active
        # layout version (the EC analog of try_write_many_sets, reference
        # rpc_helper.rs:432-533): a block written mid-migration must be
        # decodable even if either version's node set dies afterwards.
        # Pieces are not compressed (parity shards don't compress; data
        # shards rarely worth it).
        #
        # Like the replica path, the PUT returns as soon as every active
        # version holds its piece quorum; leftover sends finish in the
        # background (slow nodes still get their piece — they'd otherwise
        # heal via resync anyway).  Waiting for ALL k+m sends made the EC
        # PUT p99 the max over k+m nodes vs the replica path's
        # quorum-of-RF, measurably fattening the tail (bench_s3.py).
        pieces, piece_hashes = await self._encode_ec(data)
        send_targets, per_version = self._ec_piece_targets(hash32, layout)
        # quorum counts DISTINCT pieces stored per layout version; tolerate
        # up to half the parity pieces missing (resync rebuilds them) — but
        # EVERY active version's node set must independently reach quorum
        m = self.codec.n_pieces - self.codec.min_pieces
        quorum_pieces = self.codec.n_pieces - m // 2

        ok: set[tuple[bytes, int]] = set()
        failed: set[tuple[bytes, int]] = set()
        errors: list[str] = []
        done_ev = asyncio.Event()

        def distinct_ok(vt) -> int:
            return len({i for (n, i) in vt if (n, i) in ok})

        def satisfied() -> bool:
            return all(distinct_ok(vt) >= quorum_pieces for vt in per_version)

        def hopeless() -> bool:
            return any(
                len({i for (n, i) in vt if (n, i) not in failed})
                < quorum_pieces
                for vt in per_version
            )

        async def one(n: bytes, i: int) -> None:
            try:
                # per-send phase spans run in the sender task but share
                # the caller's trace (context captured at spawn); the
                # analyzer merges the parallel windows into one wall-
                # clock fan-out interval
                with phase_span("fanout"):
                    # prio audit: EC PUT piece fan-out is foreground
                    # S3-path work — PRIO_NORMAL, same class as the
                    # replica fan-out above (interactive reads outrank
                    # it at PRIO_HIGH; background planes sit below)
                    meta = {"c": False, "p": i, "l": len(data),
                            "s": len(pieces[i])}
                    if piece_hashes is not None:
                        # hash computed inside the batched encode
                        # dispatch: the receiver stores it instead of
                        # re-hashing the piece on its event loop
                        meta["ph"] = piece_hashes[i]
                    await self.helper.call(
                        self.endpoint, n,
                        ["Put", hash32, meta],
                        prio=PRIO_NORMAL,
                        # same deadline as the caller's quorum wait below
                        # — a longer per-send default would abort slow-
                        # but-alive sends as "quorum failure" with an
                        # empty error list
                        timeout=self.helper.default_timeout,
                        stream_factory=lambda i=i: bytes_stream(pieces[i]),
                    )
                ok.add((n, i))
            except Exception as e:  # noqa: BLE001 — tallied for Quorum
                failed.add((n, i))
                errors.append(f"{n.hex()[:8]}/p{i}: {e!r}")
            if satisfied() or hopeless():
                done_ev.set()

        async def send_all() -> None:
            # the reservation lives here so background-draining sends keep
            # their piece buffers budgeted until the last one finishes
            async with self.buffers.reserve(
                sum(len(pieces[i]) for _n, i in send_targets)
            ):
                await asyncio.gather(
                    *[one(n, i) for n, i in send_targets],
                    return_exceptions=True,
                )
            done_ev.set()

        from ..utils.background import spawn

        sender = spawn(send_all(), name=f"ec-put-{hash32.hex()[:8]}")
        try:
            # quorum_wait's EXCLUSIVE time subtracts the fan-out window
            # (utils/latency.py RESIDUAL_OF): what's left is the tail
            # where sends finished but a quorum still hadn't
            with phase_span("quorum_wait"):
                await asyncio.wait_for(
                    done_ev.wait(), self.helper.default_timeout + 5.0
                )
        except asyncio.TimeoutError:
            pass
        if not satisfied():
            from ..utils.aio import reap

            # cancel AND drain: a bare cancel() returns while the sender
            # still holds stream buffers and its in-flight RPCs race the
            # resync queueing below (graft-lint cancel-safety)
            await reap([sender], log=logger, what="ec-put sender")
            got = min((distinct_ok(vt) for vt in per_version), default=0)
            raise Quorum(quorum_pieces, got, errors)
        # pieces not yet confirmed on their primary node heal via resync.
        # Queued EAGERLY (before returning success, while stragglers drain
        # in background): a crash after this return must not leave the
        # quorum-only block unrecorded for repair.  Queueing a block whose
        # stragglers then succeed is a no-op for resync.
        if len(ok) < len(send_targets):
            self.resync.queue_block(hash32)

    def _ec_piece_targets(
        self, hash32: bytes, layout
    ) -> tuple[list[tuple[bytes, int]], list[list[tuple[bytes, int]]]]:
        """Piece placement spanning all active layout versions.

        Returns (send_targets, per_version): `send_targets` is the deduped
        list of (node, piece_rank) sends — a node keeps the same piece if
        its rank agrees across versions, and receives several pieces when
        it doesn't; `per_version` holds each version's (node, piece) list
        for independent quorum accounting (reference
        src/rpc/rpc_helper.rs:432-533 multi-set write guarantee)."""
        versions = [v for v in layout.versions if v.ring_assignment]
        if not versions:
            # zero versions would mean zero sends below — a silent
            # durability lie; fail like the replica path does
            raise Error("no layout version with a ring assignment yet")
        seen: dict[tuple[bytes, int], None] = {}
        per_version: list[list[tuple[bytes, int]]] = []
        for v in versions:
            nodes = v.nodes_of(hash32)
            if len(nodes) < self.codec.n_pieces:
                raise Error(
                    f"EC({self.codec.min_pieces},"
                    f"{self.codec.n_pieces - self.codec.min_pieces}) needs "
                    f"{self.codec.n_pieces} nodes per block, layout v"
                    f"{v.version} assigns {len(nodes)}"
                )
            vt = [(nodes[i], i) for i in range(self.codec.n_pieces)]
            per_version.append(vt)
            for t in vt:
                seen.setdefault(t)
        return list(seen), per_version

    async def rpc_get_block(
        self, hash32: bytes, prio: int = PRIO_NORMAL, order_tag=None
    ) -> bytes:
        """Fetch a block — hedged and (EC) systematic-streamed
        internally (reference manager.rs:243-344 local-then-peers, plus
        the ISSUE 13 read pipeline); replica mode reads local disk, then
        the cache, then peers; EC reads the cache, then gathers pieces.
        This form assembles the whole block.  `order_tag` serializes the fetch
        within a multi-block GET pipeline so responses stream
        back-to-back (reference net/message.rs:62-89)."""
        from ..utils.metrics import registry
        from ..utils.tracing import span

        with span("block:get"):
            parts = [
                c
                async for c in self._get_block_chunks(hash32, prio, order_tag)
            ]
        data = parts[0] if len(parts) == 1 else b"".join(parts)
        registry.incr("block_bytes_read", by=len(data))
        return data

    def start_block_read(
        self, hash32: bytes, prio: int = PRIO_NORMAL, order_tag=None
    ) -> "BlockRead":
        """Begin fetching NOW (a pump task drives the piece machinery)
        and hand back a streamable handle — the S3 GET pipeline
        (api/s3/objects.py) prefetches a window of these so block N's
        systematic pieces stream out while blocks N+1.. are in flight."""
        return BlockRead(self, hash32, prio, order_tag)

    async def _get_block_chunks(self, hash32: bytes, prio, order_tag=None):
        """Plaintext chunks of one block, in order (the shared backend of
        rpc_get_block / BlockRead)."""
        from ..utils.latency import phase_span

        if self.codec.n_pieces == 1:
            with phase_span("piece_fetch"):
                data = await self._replica_get(hash32, prio, order_tag)
            yield data
            return
        async for chunk in self._ec_get_stream(hash32, prio, order_tag):
            yield chunk

    # --- replica read path ----------------------------------------------------

    async def _replica_get(self, hash32: bytes, prio, order_tag=None) -> bytes:
        """Replica-mode block read: local disk, then the hot-block cache,
        then peers raced through the hedge helper — a slow first replica
        costs one hedge delay, not a full adaptive timeout (ISSUE 13
        satellite; the old loop walked peers strictly sequentially)."""
        local = await self.read_block_local(hash32)
        if local is not None:
            return local
        cached = self.read_cache.get(hash32)
        if cached is not None:
            return cached
        nodes = [
            n
            for n in self.helper.request_order(self.read_nodes_of(hash32))
            if n != self.system.id
        ]
        if not nodes:
            raise Error(f"block {hash32.hex()[:16]} unavailable: no peers")
        foreground = prio != PRIO_BACKGROUND
        data = await self._hedged_race(
            [
                (n, lambda n=n: self._fetch_replica(n, hash32, prio, order_tag))
                for n in nodes
            ],
            self._hedge_delay(nodes),
            what=f"block {hash32.hex()[:16]}",
            hedge=foreground,
        )
        # FOREGROUND remote fetches cache (repeat GETs become memory
        # reads); local disk reads don't — the page cache already holds
        # those bytes — and neither do background-priority reads: a
        # resync/rebalance sweep inserting thousands of cold blocks
        # would evict the hot set exactly while foreground latency
        # matters (background reads may still HIT the cache above)
        if foreground:
            self.read_cache.put(hash32, data)
        return data

    async def _fetch_replica(
        self, node: bytes, hash32: bytes, prio, order_tag=None
    ) -> bytes:
        # health-tracked + retried: a sick peer fast-fails (circuit
        # breaker) instead of stalling the GET, and transient transport
        # blips retry with jittered backoff
        resp = await self.helper.call(
            self.endpoint, node, ["Get", hash32], prio=prio,
            order_tag=order_tag, idempotent=True,
        )
        declared = int(resp.body[1].get("s", 4 * 1024 * 1024))
        # reserve before buffering; held through decompress+verify
        async with self.buffers.reserve(declared):
            meta, stored = await _resp_payload(resp)
            data = zstandard.decompress(stored) if meta.get("c") else stored
            if blake2sum(data) != hash32:
                raise Error("hash mismatch from peer")
            return data

    # --- hedging (ISSUE 13) ---------------------------------------------------

    def _count_hedge(self, outcome: str) -> None:
        from ..utils.metrics import registry

        registry.incr("block_read_hedges_total", (("outcome", outcome),))

    def _hedge_delay(self, nodes: list[bytes]) -> float:
        """Seconds a fetch may stay unanswered before a hedge launches:
        RTT-derived from the slowest HEALTHY candidate's piece-fetch /
        rtt EWMA (sick peers are hedged immediately, never waited on),
        floored at `[block] read_hedge_min_msec`."""
        health = self.helper.health
        est = 0.0
        for n in nodes:
            if n == self.system.id or health.is_sick(n):
                continue
            e = health.fetch_latency_estimate(n)
            if e is not None:
                est = max(est, e)
        cfg = self.block_config
        return max(
            cfg.read_hedge_min_msec / 1e3, est * cfg.read_hedge_rtt_mult
        )

    def _victim_order(self, ranks: list[int], nodes: list[bytes]) -> list[int]:
        """Hedge-victim priority among outstanding ranks: sick/breaker-
        open peers first, then slowest by the per-peer piece-fetch
        ranking (rpc/peer_health.py — the PR 12 slow-rank feed)."""
        pos = {
            row["peer"]: i
            for i, row in enumerate(self.helper.health.piece_fetch_ranking())
        }
        return sorted(ranks, key=lambda r: pos.get(nodes[r].hex(), len(pos)))

    async def _hedged_race(
        self, attempts, delay: float, what: str, hedge: bool = True
    ):
        """Race a candidate list with hedging (replica GET path): start
        the first attempt; when nothing has answered within `delay` of
        the last event, start the next candidate as a hedge; a FAILED
        attempt is replaced immediately (failover, not counted).  First
        success wins; losers are cancelled and drained.  `attempts` is
        [(node, coro_factory)] in preference order.  `hedge=False`
        (background-priority reads) keeps the sequential failover but
        never races extra fetches — resync must not amplify load."""
        tasks: dict[asyncio.Task, tuple[bytes, bool]] = {}
        counted: set[asyncio.Task] = set()
        errors: list[str] = []
        idx = 0
        hedge_on = hedge and self.block_config.read_hedge_enabled

        def launch(is_hedge: bool) -> None:
            nonlocal idx
            node, factory = attempts[idx]
            idx += 1
            t = asyncio.create_task(factory())
            tasks[t] = (node, is_hedge)

        try:
            launch(False)
            while True:
                pending = [t for t in tasks if not t.done()]
                if not pending:
                    if idx < len(attempts):
                        launch(False)  # every prior attempt failed
                        continue
                    raise Error(f"{what} unavailable: {errors}")
                timeout = (
                    delay if (hedge_on and idx < len(attempts)) else None
                )
                done, _ = await asyncio.wait(
                    pending, timeout=timeout,
                    return_when=asyncio.FIRST_COMPLETED,
                )
                if not done:
                    # hedge window expired: race the next candidate
                    # against the slow in-flight one
                    launch(True)
                    continue
                winner = None
                for t in done:
                    node, is_hedge = tasks[t]
                    exc = t.exception()
                    if exc is None:
                        winner = t
                        break
                    errors.append(f"{node.hex()[:8]}: {exc!r}")
                    if is_hedge and t not in counted:
                        counted.add(t)
                        self._count_hedge("failed")
                    # replace the failure NOW even while another attempt
                    # is still pending — waiting out a second hedge
                    # window for the next candidate is exactly the stall
                    # this helper exists to avoid
                    if idx < len(attempts):
                        launch(False)
                if winner is None:
                    continue
                for t, (_n, is_hedge) in tasks.items():
                    if is_hedge and t not in counted:
                        counted.add(t)
                        self._count_hedge(
                            "won" if t is winner else "lost"
                        )
                return winner.result()
        finally:
            leftovers = [t for t in tasks if not t.done()]
            if leftovers:
                from ..utils.aio import reap

                await reap(
                    leftovers, log=logger, what=f"{what} read attempt"
                )

    async def _fetch_piece(
        self, node: bytes, hash32: bytes, piece: int, prio, order_tag=None
    ) -> tuple[int, bytes]:
        """-> (block_len, piece_bytes)"""
        if node == self.system.id:
            found = self.find_block_file(hash32, piece=piece)
            if found is None:
                raise Error("piece not local")
            stored = await asyncio.to_thread(_read_file_sync, found[0])
            if found[1]:
                stored = zstandard.decompress(stored)
            return unwrap_piece(stored)
        t0 = time.perf_counter()
        resp = await self.helper.call(
            self.endpoint, node, ["Get", hash32, piece], prio=prio,
            order_tag=order_tag, idempotent=True,
        )
        meta, stored = await _resp_payload(resp, budget=self.buffers)
        if meta.get("c"):
            stored = zstandard.decompress(stored)
        blen, data = unwrap_piece(stored)
        self._note_piece_fetch(
            node, time.perf_counter() - t0, len(data), hash32=hash32
        )
        return blen, data

    def _note_piece_fetch(
        self, node: bytes, secs: float, nbytes: int, hash32: bytes | None = None
    ) -> None:
        """Per-peer EC read attribution (rpc/traffic.py): the peer-health
        EWMAs feed the /v1/traffic slow-rank ranking, the histogram feeds
        the per-peer piece-fetch p99 Grafana panel.  The `peer` label is
        bounded by cluster membership (same space the breaker families
        use) — never a key or bucket."""
        from ..utils.metrics import registry

        self.helper.health.record_piece_fetch(node, secs, nbytes)
        lbl = (("peer", node.hex()[:16]),)
        registry.observe("block_piece_fetch_duration", lbl, secs)
        registry.incr("block_piece_fetch_bytes_total", lbl, by=nbytes)
        # rebalance observatory (rpc/transition.py): while a layout
        # transition is open, inbound fetches are attributed to the
        # (src -> dst) pair ledger — the tracker no-ops when idle
        tt = getattr(self.system, "transition_tracker", None)
        if tt is not None:
            tt.note_transfer(
                node, self.system.id, nbytes,
                partition=partition_of(hash32) if hash32 else None,
            )

    async def gather_pieces(
        self, hash32: bytes, want_k: int, prio=PRIO_NORMAL, exclude_self=False,
        order_tag=None,
    ) -> tuple[int, dict[int, bytes]]:
        """Collect at least want_k distinct pieces -> (block_len, pieces).

        Fast path assumes rank-i placement in the current layout version;
        the slow path asks every node of EVERY active version what it
        holds, so blocks written mid-migration (pieces spanning versions)
        stay readable whichever node set survives."""
        layout = self.system.layout_manager.history
        nodes = layout.current().nodes_of(hash32)
        pieces: dict[int, bytes] = {}
        block_len = -1
        errors: list[str] = []
        # first want_k ranks, widened past rank k-1 when exclude_self
        # knocks our own rank out — otherwise every repair gather (self is
        # a holder by definition) fell to the ask-every-node slow path,
        # one extra RPC round per block in a 10k-block repair plan
        cand = [
            (i, nodes[i])
            for i in range(min(self.codec.n_pieces, len(nodes)))
            if not (exclude_self and nodes[i] == self.system.id)
        ]
        fetches = cand[:want_k]
        results = await asyncio.gather(
            *[
                self._fetch_piece(n, hash32, i, prio, order_tag=order_tag)
                for i, n in fetches
            ],
            return_exceptions=True,
        )
        for (i, n), r in zip(fetches, results):
            if isinstance(r, Exception):
                errors.append(f"piece {i}@{n.hex()[:8]}: {r!r}")
            else:
                block_len, pieces[i] = r
        if len(pieces) < want_k:
            blen2 = await self._gather_more(
                hash32, want_k, pieces, errors, prio,
                order_tag=order_tag, exclude_self=exclude_self,
            )
            if blen2 != -1:
                block_len = blen2
        if len(pieces) < want_k:
            raise Error(
                f"block {hash32.hex()[:16]}: only {len(pieces)}/{want_k} "
                f"pieces reachable: {errors}"
            )
        return block_len, pieces

    async def _gather_more(
        self, hash32: bytes, want_k: int, pieces: dict[int, bytes],
        errors: list[str], prio, order_tag=None, exclude_self=False,
    ) -> int:
        """Slow-path gather: ask every node of EVERY active version what
        it holds and fetch missing pieces until `want_k` — blocks written
        mid-migration span versions, so rank-placement assumptions don't
        hold.  Mutates `pieces`/`errors` in place; returns the last
        learned block_len (-1 when nothing new was fetched).  `order_tag`
        is threaded through every fetch (ISSUE 13 satellite: it used to
        be dropped here, losing multi-block GET response pipelining
        exactly when the cluster was degraded)."""
        block_len = -1
        for n in self.helper.request_order(self.storage_nodes_of(hash32)):
            if len(pieces) >= want_k:
                break
            if exclude_self and n == self.system.id:
                continue
            try:
                resp = await self.helper.call(
                    self.endpoint, n, ["Pieces", hash32], prio=prio,
                    idempotent=True,
                )
                for pi in resp.body or []:
                    pi = int(pi)
                    if pi not in pieces:
                        try:
                            block_len, pieces[pi] = await self._fetch_piece(
                                n, hash32, pi, prio, order_tag=order_tag
                            )
                        except Exception as e:  # noqa: BLE001
                            errors.append(f"piece {pi}@{n.hex()[:8]}: {e!r}")
                    if len(pieces) >= want_k:
                        break
            except Exception as e:  # noqa: BLE001
                errors.append(f"pieces@{n.hex()[:8]}: {e!r}")
        return block_len

    async def _decode_pieces(
        self, pieces: dict[int, bytes], blen: int
    ) -> bytes:
        """Degraded-read decode: coalesced through the batcher's decode
        lane (concurrent degraded GETs share one grouped reconstruction
        dispatch), else a single worker-thread dispatch — either way the
        codec math stays off the event loop."""
        if self.batcher is not None:
            return await self.batcher.decode(pieces, blen)
        return await asyncio.to_thread(self.codec.decode, pieces, blen)

    async def _ec_get_stream(self, hash32: bytes, prio, order_tag=None):
        """The EC GET pipeline (ISSUE 13): an async generator of
        plaintext chunks.

        Fast path: for ec:k:m the k systematic pieces ARE the plaintext,
        so all k are fetched concurrently and piece i streams to the
        caller while piece i+1 is still in flight — zero decode, counted
        `path="systematic"` via the codec's read hook.  Systematic ranks
        on sick/breaker-open peers are hedged to parity ranks
        IMMEDIATELY (never waited on); the rest get one hedge round when
        nothing lands within the RTT-derived hedge delay, victims
        ordered by the per-peer slow-rank ranking.  The moment any k
        pieces are on hand while the next systematic piece is not, the
        stream falls back to reconstruction with whichever k landed
        first (`path="reconstruct"`, coalesced through the batcher's
        decode lane).  If even that cannot reach k, the ask-every-node
        slow path covers mid-migration blocks.

        Integrity: every remote piece carries its own BLAKE3 (GTP2
        header, verified in unwrap_piece), so streamed chunks are
        piece-level-verified; the end-to-end plaintext hash check still
        runs before the generator finishes, so an inconsistent assembly
        surfaces as a mid-stream error (the consumer aborts the
        connection) and is never cached."""
        from ..utils.latency import phase_span

        cached = self.read_cache.get(hash32)
        if cached is not None:
            yield cached
            return

        k = self.codec.min_pieces
        layout = self.system.layout_manager.history
        nodes = layout.current().nodes_of(hash32)
        health = self.helper.health
        n_av = min(self.codec.n_pieces, len(nodes))
        sys_ranks = list(range(min(k, n_av)))

        results: dict[int, bytes] = {}  # rank -> piece payload
        order: list[int] = []  # rank completion order
        failed: dict[int, str] = {}
        errors: list[str] = []
        tasks: dict[asyncio.Task, int] = {}
        by_rank: dict[int, asyncio.Task] = {}
        counted_hedges: set[int] = set()  # parity ranks launched as hedges
        used: set[int] = set()  # ranks whose bytes served the read
        blen: int | None = None

        # healthy parity ranks are better hedge targets than sick ones
        parity_pool = sorted(
            range(k, n_av),
            key=lambda r: 1 if health.is_sick(nodes[r]) else 0,
        )

        def launch(rank: int) -> None:
            t = asyncio.create_task(
                self._fetch_piece(
                    nodes[rank], hash32, rank, prio, order_tag=order_tag
                )
            )
            tasks[t] = rank
            by_rank[rank] = t

        def inflight() -> int:
            return sum(1 for t in tasks if not t.done())

        def launch_parity(as_hedge: bool) -> bool:
            while parity_pool:
                r = parity_pool.pop(0)
                if r in by_rank:
                    continue
                launch(r)
                if as_hedge:
                    counted_hedges.add(r)
                return True
            return False

        # background-priority reads (resync handoffs) neither hedge nor
        # cache: a cold-block sweep must not amplify cluster load or
        # evict the hot set (they may still HIT the cache above)
        foreground = prio != PRIO_BACKGROUND
        hedge_on = (
            foreground and self.block_config.read_hedge_enabled and n_av > k
        )
        for r in sys_ranks:
            launch(r)
        if hedge_on:
            # sick/breaker-open systematic ranks are hedged up front —
            # their own fetch may still win (a breaker fast-fail costs
            # nothing), but the read never WAITS on them
            sick = [
                r for r in sys_ranks
                if nodes[r] != self.system.id and health.is_sick(nodes[r])
            ]
            for r in self._victim_order(sick, nodes):
                if not launch_parity(as_hedge=True):
                    break
        deadline = (
            time.monotonic()
            + self._hedge_delay([nodes[r] for r in sys_ranks])
            if hedge_on
            else None
        )

        emitted = 0  # next systematic rank to stream
        emitted_bytes = 0
        out_parts: list[bytes] = []
        data: bytes | None = None  # set on the reconstruction paths

        try:
            while True:
                # stream the ready systematic prefix
                while emitted < k and emitted in results and blen is not None:
                    piece = results[emitted]
                    used.add(emitted)
                    chunk = piece[: max(0, blen - emitted * len(piece))]
                    emitted += 1
                    if chunk:
                        out_parts.append(chunk)
                        emitted_bytes += len(chunk)
                        yield chunk
                if emitted >= k:
                    break  # fully systematic: everything streamed
                if len(results) >= k:
                    # the next systematic piece is missing but k pieces
                    # are on hand: reconstruct with whichever k landed
                    # first (landed data ranks preferred — no matrix
                    # work for shards already in memory)
                    use_ranks = [r for r in order if r < k][:k]
                    for r in order:
                        if len(use_ranks) >= k:
                            break
                        if r >= k:
                            use_ranks.append(r)
                    used.update(use_ranks)
                    with phase_span("decode"):
                        data = await self._decode_pieces(
                            {r: results[r] for r in use_ranks}, blen
                        )
                    break
                live = [t for t in tasks if not t.done()]
                if not live:
                    # fast path exhausted below k: mid-migration blocks
                    # keep their pieces under older layout versions
                    pieces = dict(results)
                    with phase_span("piece_fetch"):
                        blen2 = await self._gather_more(
                            hash32, k, pieces, errors, prio,
                            order_tag=order_tag,
                        )
                    if len(pieces) < k:
                        raise Error(
                            f"block {hash32.hex()[:16]}: only "
                            f"{len(pieces)}/{k} pieces reachable: {errors}"
                        )
                    if blen is None:
                        blen = blen2
                    used.update(pieces)
                    with phase_span("decode"):
                        data = await self._decode_pieces(pieces, blen)
                    break
                timeout = None
                if deadline is not None:
                    timeout = max(0.0, deadline - time.monotonic())
                with phase_span("piece_fetch"):
                    done, _ = await asyncio.wait(
                        live, timeout=timeout,
                        return_when=asyncio.FIRST_COMPLETED,
                    )
                if not done:
                    # hedge window expired: hedge every outstanding
                    # systematic rank, sickest/slowest victims first
                    deadline = None
                    outstanding = [
                        r for r in sys_ranks
                        if r not in results and r not in failed
                    ]
                    for r in self._victim_order(outstanding, nodes):
                        if not launch_parity(as_hedge=True):
                            break
                    continue
                for t in done:
                    rank = tasks[t]
                    if rank in results or rank in failed:
                        continue
                    exc = t.exception()
                    if exc is not None:
                        failed[rank] = repr(exc)
                        errors.append(
                            f"piece {rank}@{nodes[rank].hex()[:8]}: {exc!r}"
                        )
                        # replace a FAILED fetch immediately while a
                        # deficit remains (failover, not a timed hedge)
                        if len(results) + inflight() < k:
                            launch_parity(as_hedge=False)
                    else:
                        blen_r, piece = t.result()
                        if blen is None:
                            blen = blen_r
                        results[rank] = piece
                        order.append(rank)

            if data is not None:
                # reconstruction path: verify BEFORE streaming the
                # remainder (the already-streamed prefix is exactly the
                # landed data shards the decode reused, and each carried
                # its own piece hash)
                if blake2sum(data) != hash32:
                    raise Error("EC decode does not match block hash")
                rest = data[emitted_bytes:]
                if rest:
                    yield rest
                if foreground:
                    self.read_cache.put(hash32, data)
            else:
                plain = (
                    out_parts[0] if len(out_parts) == 1 else b"".join(out_parts)
                )
                if blake2sum(plain) != hash32:
                    raise Error(
                        "EC systematic read does not match block hash"
                    )
                # the join happened HERE (piece-by-piece, streamed), so
                # the codec never saw a decode() — report it so the
                # op="decode" systematic/reconstruct split stays honest
                note = getattr(self.codec, "note_systematic_read", None)
                if note is not None:
                    note(len(plain))
                if foreground:
                    self.read_cache.put(hash32, plain)
        finally:
            # hedge accounting + straggler cleanup (a systematic
            # completion leaves its hedges in flight by design)
            for r in counted_hedges:
                if r in used:
                    self._count_hedge("won")
                elif r in failed:
                    self._count_hedge("failed")
                else:
                    self._count_hedge("lost")
            leftovers = [t for t in tasks if not t.done()]
            if leftovers:
                from ..utils.aio import reap

                await reap(leftovers, log=logger, what="ec-get piece fetch")

    def _verify_gathered(self, hash32: bytes, pieces: dict[int, bytes], blen: int):
        """Reject reconstruction inputs whose decoded block doesn't match
        the content hash — otherwise one corrupt surviving piece would be
        laundered into freshly rebuilt pieces."""
        if blake2sum(self.codec.decode(dict(pieces), blen)) != hash32:
            raise Error(
                f"block {hash32.hex()[:16]}: gathered pieces are corrupt"
            )

    def ec_ranks_of(self, hash32: bytes) -> list[int]:
        """THIS node's piece ranks across ALL active layout versions,
        newest version first.  A node whose rank differs between versions
        holds SEVERAL pieces while a migration is open (the write path
        places them; resync must track and heal every one, or the
        per-version decode guarantee silently erodes)."""
        layout = self.system.layout_manager.history
        ranks: list[int] = []
        for v in reversed([v for v in layout.versions if v.ring_assignment]):
            nodes = v.nodes_of(hash32)
            if self.system.id in nodes[: self.codec.n_pieces]:
                r = nodes.index(self.system.id)
                if r not in ranks:
                    ranks.append(r)
        return ranks

    async def reconstruct_local_piece(self, hash32: bytes) -> bool:
        """Rebuild THIS node's missing piece(s) from surviving peers (EC
        resync path).  Returns True if any piece was stored."""
        missing = [
            r for r in self.ec_ranks_of(hash32)
            if not self.find_block_file(hash32, piece=r)
        ]
        if not missing:
            return False
        blen, pieces = await self.gather_pieces(
            hash32, self.codec.min_pieces, prio=PRIO_BACKGROUND, exclude_self=True
        )
        self._verify_gathered(hash32, pieces, blen)
        rec = self.codec.reconstruct_pieces(pieces, missing, blen)
        for r in missing:
            await self.write_block_local(
                hash32, wrap_piece(blen, rec[r]), False, piece=r
            )
        return True

    async def bulk_reconstruct(self, hashes: list[bytes]) -> int:
        """Batched EC repair: gather surviving pieces for MANY blocks
        concurrently, run ONE grouped reconstruction through the codec
        (TPU dispatch for large batches, BASELINE 10k-block resync
        target), store the results.  Blocks that cannot be gathered are
        queued for resync's retry/backoff loop.  Returns pieces rebuilt."""
        todo: list[tuple[bytes, int]] = []
        for h in hashes:
            if not self.rc.is_needed(h):
                continue  # never resurrect deleted blocks
            for r in self.ec_ranks_of(h):
                if not self.find_block_file(h, piece=r):
                    todo.append((h, r))
        if not todo:
            return 0

        sem = asyncio.Semaphore(16)

        async def gather_one(h, rank):
            async with sem:
                try:
                    blen, pieces = await self.gather_pieces(
                        h, self.codec.min_pieces, prio=PRIO_BACKGROUND,
                        exclude_self=True,
                    )
                    self._verify_gathered(h, pieces, blen)
                    return (h, rank, pieces, blen)
                except Error as e:
                    logger.warning(
                        "bulk repair: cannot gather %s (%r); queued for resync",
                        h.hex()[:16], e,
                    )
                    self.resync.queue_block(h)
                    return None

        gathered = await asyncio.gather(*[gather_one(h, r) for h, r in todo])
        batch = [g for g in gathered if g is not None]
        if not batch:
            return 0
        # worker-thread hop: the grouped reconstruction is a device
        # dispatch + host fetch (or a long native-codec run) — inline it
        # would stall the event loop for the whole repair batch, exactly
        # what the codec batcher already avoids on the encode side
        recs = await asyncio.to_thread(
            self.codec.reconstruct_batch,
            [(pieces, [rank], blen) for _h, rank, pieces, blen in batch],
        )
        n = 0
        for (h, rank, _p, blen), rec in zip(batch, recs):
            await self.write_block_local(
                h, wrap_piece(blen, rec[rank]), False, piece=rank
            )
            n += 1
        return n


_READ_EOF = object()


class BlockRead:
    """One in-flight block read (the S3 GET pipeline's unit of
    prefetch, api/s3/objects.py): fetching starts at CONSTRUCTION in a
    supervised pump task — context captured at spawn keeps its phase
    spans on the requesting trace, the EC-PUT-sender pattern — so a
    window of BlockReads overlaps across blocks while `chunks()`
    streams each block's systematic pieces in arrival order within it.

    The queue holds at most one block's worth of chunks (the pump
    produces one block), so per-read RAM is bounded by block_size just
    like the assembled form was."""

    def __init__(self, mgr: BlockManager, hash32: bytes, prio, order_tag):
        from ..utils.background import spawn

        self._q: asyncio.Queue = asyncio.Queue()
        self._task = spawn(
            self._pump(mgr, hash32, prio, order_tag),
            name=f"block-read-{hash32.hex()[:8]}",
        )

    async def _pump(self, mgr, hash32, prio, order_tag) -> None:
        from ..utils.metrics import registry
        from ..utils.tracing import span

        try:
            total = 0
            with span("block:get"):
                async for chunk in mgr._get_block_chunks(
                    hash32, prio, order_tag
                ):
                    total += len(chunk)
                    self._q.put_nowait(chunk)
            registry.incr("block_bytes_read", by=total)
            self._q.put_nowait(_READ_EOF)
        except asyncio.CancelledError:
            # unblock a consumer racing the abort, then end CANCELLED
            self._q.put_nowait(Error("block read aborted"))
            raise
        except Exception as e:  # noqa: BLE001 — delivered to the consumer
            self._q.put_nowait(e)

    async def chunks(self):
        """Plaintext chunks in block order; raises what the fetch
        raised."""
        while True:
            item = await self._q.get()
            if item is _READ_EOF:
                return
            if isinstance(item, BaseException):
                raise item
            yield item

    async def bytes(self) -> bytes:
        parts = [c async for c in self.chunks()]
        return parts[0] if len(parts) == 1 else b"".join(parts)

    async def abort(self) -> None:
        """Cancel + drain the pump (consumer-gone teardown)."""
        from ..utils.aio import reap

        await reap([self._task], log=logger, what="block read")
