"""Block resynchronization: the self-healing loop.

Reference src/block/resync.rs.  A persistent, time-ordered queue of block
hashes to (re)examine.  For each due item:

  - node needs the block (rc > 0) but doesn't have it  -> fetch from peers
  - node has it but rc == 0 past the GC delay          -> make sure no
    storage node still needs it (Need RPC), push to any that do, then
    delete the local file
  - errors retry with exponential backoff 1 min -> 64 min (errors tree)

Workers (1..MAX_RESYNC_WORKERS) drain the queue with a Tranquilizer.
Resync traffic runs at PRIO_BACKGROUND: the frame scheduler guarantees it
never starves interactive transfers.
"""

from __future__ import annotations

import asyncio
import logging
import os

from ..net.message import PRIO_BACKGROUND
from ..rpc.layout.types import partition_of
from ..utils.backoff import expo
from ..utils.background import BackgroundRunner, Worker, WorkerState
from ..utils.time_util import now_msec
from ..utils.tranquilizer import Tranquilizer

logger = logging.getLogger("garage.block.resync")

BACKOFF_MIN_MS = 60 * 1000
BACKOFF_MAX_MS = 64 * 60 * 1000
MAX_RESYNC_WORKERS = 8


def unpack_error(raw: bytes) -> tuple[int, int, int | None]:
    """(failure count, next retry msec, first-failure msec).  Error
    entries written before error-age tracking are 2-element lists —
    their first-failure time is unknown (None), never fabricated."""
    import msgpack

    obj = msgpack.unpackb(raw)
    first = int(obj[2]) if len(obj) > 2 else None
    return int(obj[0]), int(obj[1]), first


class BlockResyncManager:
    def __init__(self, manager):
        self.manager = manager
        db = manager.db
        self.queue = db.open_tree("block_resync_queue")  # [when|hash] -> b""
        self.errors = db.open_tree("block_resync_errors")  # hash -> [count, when]
        self.n_workers = 1
        self.tranquility = 2
        self._kick = asyncio.Event()
        # oldest-error-age cache: status() runs after every worker
        # iteration and the durability digest reads it per collection —
        # neither should pay an O(errors) tree walk each time
        self._age_cache: tuple[float, float | None] | None = None

    # --- queueing -------------------------------------------------------------

    def queue_block(self, hash32: bytes, delay_ms: int = 0, tx=None) -> None:
        """Pass `tx` when queueing from inside a table updated() hook."""
        when = now_msec() + delay_ms
        key = when.to_bytes(8, "big") + hash32
        if tx is not None:
            tx.insert(self.queue, key, b"")
        else:
            self.queue.insert(key, b"")
        self._kick.set()

    def queue_blocks(self, hashes: list[bytes], delay_ms: int = 0) -> None:
        """Bulk enqueue (repair-plane `Queue` nudges, gather failures):
        one kick instead of one per hash."""
        when = (now_msec() + delay_ms).to_bytes(8, "big")
        for h in hashes:
            self.queue.insert(when + h, b"")
        if hashes:
            self._kick.set()

    def queue_len(self) -> int:
        return len(self.queue)

    def due_empty(self) -> bool:
        """True if no queue entry is due yet.  The queue is time-ordered
        (`when|hash` keys), so this is O(1).  Future-dated entries
        (GC-delay deletes, error backoffs) must not gate layout-sync
        completion — under steady delete traffic the queue is never
        LITERALLY empty and a migration would never close."""
        f = self.queue.first()
        return f is None or f[0][:8] > now_msec().to_bytes(8, "big")

    def errors_len(self) -> int:
        return len(self.errors)

    def oldest_error_age_secs(self) -> float | None:
        """Age of the OLDEST entry in the error set (None when empty, or
        when every entry predates error-age tracking).  Cached ~1 s —
        callers poll this per worker iteration / digest collection."""
        import time

        now = time.monotonic()
        if self._age_cache is not None and now - self._age_cache[0] < 1.0:
            return self._age_cache[1]
        oldest: int | None = None
        for _h, raw in self.errors.iter_range():
            _count, _next_try, first = unpack_error(raw)
            if first is not None and (oldest is None or first < oldest):
                oldest = first
        age = (
            max(0.0, (now_msec() - oldest) / 1000.0)
            if oldest is not None
            else None
        )
        self._age_cache = (now, age)
        return age

    def error_age_counts(self, stuck_after_secs: float) -> tuple[int, int]:
        """(transiently-failing, stuck) block counts: an errored block
        older than `stuck_after_secs` is stuck — retries have been
        failing long past the first backoff rungs.  Unknown-age entries
        (pre-upgrade format) count transient."""
        cutoff = now_msec() - int(stuck_after_secs * 1000)
        transient = stuck = 0
        for _h, raw in self.errors.iter_range():
            _count, _next_try, first = unpack_error(raw)
            if first is not None and first <= cutoff:
                stuck += 1
            else:
                transient += 1
        return transient, stuck

    # --- one unit of work -----------------------------------------------------

    async def resync_iter(self) -> bool:
        """Process one due queue item; returns True if work was done."""
        now = now_msec()
        for key, _ in self.queue.iter_range():
            when = int.from_bytes(key[:8], "big")
            if when > now:
                return False
            hash32 = key[8:]
            # error backoff: skip if a retry is scheduled later
            err = self.errors.get(hash32)
            if err is not None:
                count, next_try, _first = unpack_error(err)
                if next_try > now:
                    self.queue.remove(key)
                    self.queue.insert(next_try.to_bytes(8, "big") + hash32, b"")
                    return True
            try:
                await self._resync_block(hash32)
                self.errors.remove(hash32)
                self.queue.remove(key)
            except Exception as e:  # noqa: BLE001
                import msgpack

                count = 0
                first = now_msec()  # error AGE: first-failure timestamp
                # survives retries so the ledger can tell a fresh blip
                # from a block that has been failing for an hour
                if err is not None:
                    count, _next, prev_first = unpack_error(err)
                    if prev_first is not None:
                        first = prev_first
                backoff = int(expo(count, BACKOFF_MIN_MS, BACKOFF_MAX_MS))
                self.errors.insert(
                    hash32,
                    msgpack.packb([count + 1, now_msec() + backoff, first]),
                )
                self.queue.remove(key)
                self.queue.insert(
                    (now_msec() + backoff).to_bytes(8, "big") + hash32, b""
                )
                logger.info(
                    "resync of %s failed (try %d): %r",
                    hash32.hex()[:16],
                    count + 1,
                    e,
                )
            return True
        return False

    async def _resync_block(self, hash32: bytes) -> None:
        mgr = self.manager
        needed = mgr.rc.is_needed(hash32)
        have = mgr.has_block(hash32)
        i_store = mgr.system.id in mgr.storage_nodes_of(hash32)

        if mgr.codec.n_pieces > 1:
            # EC mode: this node's unit of storage is its piece(s).  A
            # node is a holder if it ranks < n_pieces in ANY active
            # layout version (possibly with different ranks -> several
            # pieces) — an old-version holder must NOT drop pieces while
            # a migration is open (the multi-set write guarantee says
            # either version's set alone can decode); it hands off only
            # after trim retires the old version.
            nodes = mgr.system.layout_manager.history.current().nodes_of(hash32)
            my_ranks = mgr.ec_ranks_of(hash32)
            is_holder = bool(my_ranks)
            local = mgr.local_pieces(hash32)
            if needed and is_holder and any(r not in local for r in my_ranks):
                await mgr.reconstruct_local_piece(hash32)
                logger.debug("resync: reconstructed piece for %s", hash32.hex()[:16])
                return
            if local and not needed and mgr.rc.is_deletable(hash32):
                # block deleted: reclaim every local piece
                for _pi, (path, _c) in local.items():
                    try:
                        await asyncio.to_thread(os.remove, path)
                    except OSError:
                        pass
                mgr.rc.clear_deleted(hash32)
                logger.debug("resync: deleted pieces of %s", hash32.hex()[:16])
                return
            if local and needed and not is_holder:
                # no longer a holder (layout change): delete only once the
                # current holders can serve >= k distinct pieces without us
                distinct: set[int] = set()
                for n in nodes[: mgr.codec.n_pieces]:
                    try:
                        resp = await mgr.helper.call(
                            mgr.endpoint, n, ["Pieces", hash32],
                            prio=PRIO_BACKGROUND, idempotent=True,
                        )
                        distinct.update(int(p) for p in resp.body or [])
                    except Exception as e:
                        raise RuntimeError(f"cannot check holders: {e!r}") from e
                if len(distinct) >= mgr.codec.min_pieces:
                    for _pi, (path, _c) in local.items():
                        try:
                            await asyncio.to_thread(os.remove, path)
                        except OSError:
                            pass
                else:
                    raise RuntimeError(
                        f"holders have only {len(distinct)} distinct pieces; "
                        "keeping ours until they heal"
                    )
            return

        if needed and i_store and not have:
            data = await mgr.rpc_get_block(hash32, prio=PRIO_BACKGROUND)
            stored, compressed = mgr._maybe_compress(data)
            await mgr.write_block_local(hash32, stored, compressed)
            logger.debug("resync: fetched %s", hash32.hex()[:16])
            return

        if have and (not needed or not i_store):
            if not mgr.rc.is_deletable(hash32) and not i_store:
                # rc still counting somewhere else; we just don't store it
                pass
            elif not mgr.rc.is_deletable(hash32):
                return  # deletion delay not yet passed
            # before deleting, push to any storage node that needs it
            for n in mgr.storage_nodes_of(hash32):
                if n == mgr.system.id:
                    continue
                try:
                    resp = await mgr.helper.call(
                        mgr.endpoint, n, ["Need", hash32],
                        prio=PRIO_BACKGROUND, idempotent=True,
                    )
                    if resp.body:
                        found = mgr.find_block_file(hash32)
                        if found:
                            from ..net.stream import bytes_stream

                            from .manager import _read_file_sync

                            path, compressed = found
                            stored = await asyncio.to_thread(
                                _read_file_sync, path
                            )
                            async with mgr.buffers.reserve(len(stored)):
                                # content-addressed Put: safe to retry
                                await mgr.helper.call(
                                    mgr.endpoint, n,
                                    ["Put", hash32,
                                     {"c": compressed, "s": len(stored)}],
                                    prio=PRIO_BACKGROUND,
                                    timeout=120.0,
                                    stream_factory=lambda: bytes_stream(stored),
                                    idempotent=True,
                                )
                            # rebalance observatory (rpc/transition.py):
                            # attribute the outbound handoff to the
                            # (self -> n) pair — no-op outside a transition
                            tt = getattr(
                                mgr.system, "transition_tracker", None
                            )
                            if tt is not None:
                                tt.note_transfer(
                                    mgr.system.id, n, len(stored),
                                    partition=partition_of(hash32),
                                )
                except Exception as e:
                    raise RuntimeError(
                        f"cannot verify/hand off to {n.hex()[:8]}: {e!r}"
                    ) from e
            found = mgr.find_block_file(hash32)
            if found:
                try:
                    await asyncio.to_thread(os.remove, found[0])
                    logger.debug("resync: deleted %s", hash32.hex()[:16])
                except OSError:
                    pass
            mgr.rc.clear_deleted(hash32)

    # --- workers --------------------------------------------------------------

    def spawn_workers(self, bg: BackgroundRunner) -> None:
        for i in range(MAX_RESYNC_WORKERS):
            bg.spawn(_ResyncWorker(self, i))
        bg.spawn(_LayoutSyncWorker(self))


class _LayoutSyncWorker(Worker):
    """The block plane's role in a layout transition.

    On every new layout version, re-queue every locally-referenced block
    so the resync logic migrates / hands off / reconstructs pieces for
    the new assignment; once the scan is done AND the resync queue has
    drained with no errored blocks, report the "block" sync component to
    the layout manager.  Version retirement (LayoutHistory.trim) is
    gated on this report exactly like on the table syncers' — without
    it, old versions could be retired while blocks still live only on
    the outgoing node set, stranding acked data (see
    doc/ec-placement.md "When does a transition complete?")."""

    SCAN_BATCH = 200

    def __init__(self, resync: BlockResyncManager):
        self.resync = resync
        self.lm = resync.manager.system.layout_manager
        self.lm.register_sync_component("block")
        self._changed = asyncio.Event()
        self._changed.set()  # initial pass reports the boot version
        self._last_seen = self.lm.history.current().version
        self.lm.subscribe(self._on_layout_change)
        self._version: int | None = None  # version currently being driven
        self._cursor: bytes | None = None  # rc-table scan position
        self._queued = 0

    def _on_layout_change(self) -> None:
        # trigger only on NEW versions — tracker gossip also notifies,
        # and re-scanning on every tracker advance would loop forever
        v = self.lm.history.current().version
        if v != self._last_seen:
            self._last_seen = v
            self._changed.set()

    def name(self) -> str:
        return "block layout sync"

    def status(self):
        return {
            "version": self._version,
            "queued": self._queued,
            "scanning": self._cursor is not None,
        }

    async def work(self):
        mgr = self.resync.manager
        if self._changed.is_set():
            self._changed.clear()
            h = self.lm.history
            self._version = h.current().version
            self._queued = 0
            active = [v for v in h.versions if v.ring_assignment]
            if (
                len(active) <= 1
                and h.sync.get(mgr.system.id) >= self._version
            ):
                # plain restart of an already-synced node: report without
                # sweeping the whole rc table
                self._cursor = None
            else:
                self._cursor = b""
        if self._version is None:
            return WorkerState.IDLE
        if self._cursor is not None:
            n = 0
            for key, _v in mgr.rc.tree.iter_range(start=self._cursor):
                self.resync.queue_block(key)
                self._cursor = key + b"\x00"
                self._queued += 1
                n += 1
                if n >= self.SCAN_BATCH:
                    await asyncio.sleep(0)  # yield: the scan is sync code
                    return WorkerState.BUSY
            self._cursor = None
            return WorkerState.BUSY
        if self.resync.due_empty() and self.resync.errors_len() == 0:
            self.lm.component_synced("block", self._version)
            self._version = None
        return WorkerState.IDLE

    async def wait_for_work(self) -> None:
        try:
            await asyncio.wait_for(self._changed.wait(), timeout=2.0)
        except asyncio.TimeoutError:
            pass


class _ResyncWorker(Worker):
    def __init__(self, resync: BlockResyncManager, index: int):
        self.resync = resync
        self.index = index
        self.tranquilizer = Tranquilizer()

    def name(self) -> str:
        return f"resync:{self.index}"

    def status(self):
        age = self.resync.oldest_error_age_secs()
        return {
            "queue": self.resync.queue_len(),
            "errors": self.resync.errors_len(),
            "oldest_error_secs": round(age, 1) if age is not None else None,
        }

    def tranquility(self) -> int | None:
        return self.resync.tranquility

    def queue_length(self) -> int | None:
        # the resync queue is shared by all resync workers: only index 0
        # exports it, or aggregations over the family would overcount
        # the backlog n_workers times
        return self.resync.queue_len() if self.index == 0 else None

    async def work(self):
        if self.index >= self.resync.n_workers:
            return (WorkerState.THROTTLED, 10.0)  # worker disabled by config
        self.tranquilizer.reset()
        did = await self.resync.resync_iter()
        if not did:
            return WorkerState.IDLE
        delay = self.tranquilizer.tranquilize_delay(self.resync.tranquility)
        return (WorkerState.THROTTLED, delay) if delay else WorkerState.BUSY

    async def wait_for_work(self) -> None:
        self.resync._kick.clear()
        try:
            await asyncio.wait_for(self.resync._kick.wait(), timeout=10.0)
        except asyncio.TimeoutError:
            pass
