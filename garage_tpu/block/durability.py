"""Durability observatory: the cluster-wide redundancy ledger.

Garage's durability story is redundancy without consensus — EC/replica
placement plus Merkle anti-entropy and the repair plane — yet nothing
could answer the operator's FIRST question: *how many blocks are one
failure away from loss, and when will repair catch up?*  The scrub and
repair planes each see their own backlog; the telemetry plane (PR 5)
gossips those backlogs; but no surface joins the block refs against the
layout and liveness state into redundancy CLASSES.  This module is that
join — the observability prerequisite for the layout-change-under-fire
campaign (ROADMAP item 4: "the telemetry plane narrating recovery").

A `DurabilityScanner` worker incrementally walks the local rc tree
(every block this node still references) in tranquilized batches and
classifies each OWNED block by how many of its stripe's pieces are
believed reachable:

  healthy     all k+m pieces on live ranks
  degraded    k < live < k+m    (urgency-bucketed high/low via
                                 repair_plan.classify)
  at_risk     live == k         (one more failure loses data)
  unreadable  live < k

Liveness is LOCAL evidence, not a survey: this node's own ranks are
checked on disk; a remote rank counts live iff its node is connected
and not behind an OPEN circuit breaker (rpc/peer_health.py).  The
resync error set adds the orthogonal "stuck" dimension (blocks that
keep failing to heal, by error age).  A connected peer that silently
lost its disk is NOT detected here — that is the scrub/repair-survey
planes' job (block/repair_plan.py `Inv` RPCs); the ledger is the
always-on cheap view, the survey is the expensive exact one.

OWNERSHIP makes cluster sums exact: a block is counted by the first
LIVE node of its stripe assignment (rank 0 at steady state; the next
live rank takes over when earlier holders die), so summing per-node
ledgers over the digest gossip yields cluster totals without
double-counting.  Min-redundancy federates as min-over-nodes.

From the same pass the scanner derives:

  zone-loss exposure   per layout zone Z: how many owned blocks would
                       drop below k pieces if zone Z vanished
  repair ETA           EWMA of observed backlog drain (missing pieces
                       per second, across passes) + the live
                       RepairPlanner's own throughput, vs the backlog
  layout transition    fraction of partitions whose current-version
                       replicas have all reported sync (the progress
                       bar for a migration in flight)

Surfaces: digest `dur.*` keys federated through the PR 5 gossip
(`rpc/telemetry_digest.py`), admin `GET /v1/cluster/durability`,
admin-RPC `durability` -> `cli cluster durability`, registry gauges
`durability_*` (id-labelled, registered by model/garage.py), and a
flight-recorder slow-ring EVENT whenever a block transitions into
`at_risk`/`unreadable` (utils/flight.py record_event).
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import os
import time

from ..utils.background import Worker, WorkerState
from ..utils.time_util import now_msec
from ..utils.tranquilizer import Tranquilizer
from .repair_plan import (
    DEFAULT_PIECE_EST,
    URGENCY_HIGH,
    URGENCY_LOW,
    classify,
)

logger = logging.getLogger("garage.block.durability")

DUR_HEALTHY = "healthy"
DUR_DEGRADED = "degraded"
DUR_AT_RISK = "at_risk"
DUR_UNREADABLE = "unreadable"
DUR_CLASSES = (DUR_HEALTHY, DUR_DEGRADED, DUR_AT_RISK, DUR_UNREADABLE)

# EWMA smoothing for the drain-rate / piece-size estimates
RATE_ALPHA = 0.3
# cap on the at_risk/unreadable hash set kept for transition detection;
# past it, new transitions still alert (conservatively: every at-risk
# block looks "new") but memory stays bounded
ALERT_SET_MAX = 262_144
# at most this many local piece files are size-sampled per batch (the
# byte-backlog estimate needs a piece-size EWMA, not a census)
SIZE_SAMPLES_PER_BATCH = 8

# gauge `id` label source: process-unique (several in-process nodes
# share the global registry — utils/background.py _gauge_ids pattern)
_gauge_ids = itertools.count(1)


def classify_block(live: int, k: int, width: int) -> str:
    """Redundancy class of a stripe with `live` of `width` pieces
    reachable, k needed to read."""
    if live >= width:
        return DUR_HEALTHY
    if live < k:
        return DUR_UNREADABLE
    if live == k:
        return DUR_AT_RISK
    return DUR_DEGRADED


def zone_exposed(live_by_zone: dict, live: int, k: int) -> list:
    """Zones whose loss would drop this stripe below k live pieces.
    Pure function: `live_by_zone` maps zone -> live pieces it holds."""
    return [z for z, c in live_by_zone.items() if c and live - c < k]


def layout_transition(history) -> dict:
    """Progress of the block plane toward the CURRENT layout version:
    a partition counts synced when every node of its current assignment
    has reported sync >= that version (the same trackers that gate
    version retirement, rpc/layout/history.py)."""
    cur = history.current()
    active = [v for v in history.versions if v.ring_assignment]
    if not cur.ring_assignment:
        return {
            "version": cur.version,
            "minStored": history.min_stored(),
            "activeVersions": len(active),
            "partitions": 0,
            "partitionsSynced": 0,
            "progress": 1.0,
        }
    total = len(cur.ring_assignment)
    synced = 0
    for p in range(total):
        nodes = cur.nodes_of_partition(p)
        if nodes and all(
            history.sync.get(n) >= cur.version for n in nodes
        ):
            synced += 1
    return {
        "version": cur.version,
        "minStored": history.min_stored(),
        "activeVersions": len(active),
        "partitions": total,
        "partitionsSynced": synced,
        # a transition is IN FLIGHT only while an older version is still
        # retained (trim retires it once every component reports sync);
        # a settled cluster reads 1.0 even before its trackers tick
        "progress": (
            1.0 if len(active) <= 1 else round(synced / total, 4)
        ),
    }


class ScanParams:
    """Mutable knobs shared between the composition root (config +
    BgVars setters) and the running scanner — `worker set
    durability-tranquility 4` applies on the next batch."""

    def __init__(
        self,
        tranquility: int = 2,
        scan_batch: int = 256,
        interval_secs: float = 60.0,
        stuck_error_secs: float = 900.0,
    ):
        self.tranquility = tranquility
        self.scan_batch = scan_batch
        self.interval_secs = interval_secs
        self.stuck_error_secs = stuck_error_secs


class DurabilityScanner(Worker):
    """The redundancy-ledger worker (see module docstring).  One per
    node, always constructed (the digest reads it), spawned by
    `Garage.spawn_workers` when `[durability] enabled`.  Tests and
    bench_repair drive `scan_pass()` directly for determinism."""

    def __init__(
        self,
        manager,
        params: ScanParams | None = None,
        planner_fn=None,
        clock=time.monotonic,
    ):
        self.manager = manager
        self.params = params or ScanParams()
        # the live RepairPlanner, if any (its throughput seeds the ETA
        # before two ledger passes have observed a drain)
        self.planner_fn = planner_fn or (lambda: None)
        self.clock = clock
        self.tranquilizer = Tranquilizer()
        self.gauge_id = str(next(_gauge_ids))
        self.passes = 0
        self._cursor: bytes | None = None  # None = no pass in progress
        self._cur: dict | None = None  # accumulating pass state
        self._published: dict | None = None  # last completed pass
        self._published_at: float | None = None
        self._drain_ewma: float | None = None  # missing pieces/sec
        self._piece_est: float | None = None  # bytes, sampled EWMA
        # hash -> class for blocks currently at_risk/unreadable: a block
        # WORSENING (at_risk -> unreadable) re-alerts, a block merely
        # staying bad does not
        self._alerted: dict[bytes, str] = {}
        self._kick = asyncio.Event()
        # a layout change restripes ownership and liveness: rescan now,
        # not at the next interval tick
        manager.system.layout_manager.subscribe(self._kick.set)

    # --- worker interface -----------------------------------------------------

    def name(self) -> str:
        return "durability_scan"

    def status(self):
        out = {
            "passes": self.passes,
            "scanning": self._cursor is not None,
        }
        p = self._published
        if p is not None:
            out.update(
                {
                    "total": p["total"],
                    "healthy": p["healthy"],
                    "degraded": p["degraded"],
                    "atRisk": p["atRisk"],
                    "unreadable": p["unreadable"],
                    "missingPieces": p["missingPieces"],
                    "etaSecs": self.repair_eta_secs(),
                }
            )
        return out

    def tranquility(self) -> int | None:
        return self.params.tranquility

    async def work(self):
        if self._cursor is None:
            due = (
                self._published_at is None
                or self.clock() - self._published_at
                >= self.params.interval_secs
                or self._kick.is_set()
            )
            if not due:
                return WorkerState.IDLE
            self._kick.clear()
            self._begin_pass()
        self.tranquilizer.reset()
        more = await self._scan_step()
        if not more:
            self._finish_pass()
            return WorkerState.IDLE
        delay = self.tranquilizer.tranquilize_delay(self.params.tranquility)
        return (WorkerState.THROTTLED, delay) if delay else WorkerState.BUSY

    async def wait_for_work(self) -> None:
        try:
            await asyncio.wait_for(
                self._kick.wait(),
                timeout=max(0.05, min(self.params.interval_secs / 4, 5.0)),
            )
        except asyncio.TimeoutError:
            pass

    async def scan_pass(self) -> dict:
        """Run ONE full ledger pass to completion (no pacing) and return
        the published snapshot — the deterministic driver tests and
        bench_repair use instead of the worker loop."""
        if self._cursor is None:
            self._begin_pass()
        while await self._scan_step():
            pass
        self._finish_pass()
        assert self._published is not None
        return self._published

    # --- the pass -------------------------------------------------------------

    def _begin_pass(self) -> None:
        self._cursor = b""
        self._cur = {
            "total": 0,
            "healthy": 0,
            "degraded": 0,
            "at_risk": 0,
            "unreadable": 0,
            "urgency": {URGENCY_HIGH: 0, URGENCY_LOW: 0},
            "missing_pieces": 0,
            "local_missing": 0,
            "unplaceable": 0,
            "zone_exposed": {},
            "min_margin": None,
            "alert_hashes": {},
            "new_alerts": [],
            "t0": self.clock(),
        }

    def _geometry(self) -> tuple[int, int]:
        """(stripe width, pieces needed to read).  EC: (k+m, k); replica:
        (rf, 1) — any single live copy serves a read."""
        codec = self.manager.codec
        if codec.n_pieces > 1:
            return codec.n_pieces, codec.min_pieces
        lm = self.manager.system.layout_manager
        return lm.history.current().replication_factor, 1

    async def _scan_step(self) -> bool:
        """Classify one batch of rc-tree keys; returns False when the
        pass is complete."""
        from ..rpc.peer_health import OPEN

        mgr = self.manager
        cur = self._cur
        assert cur is not None
        layout = mgr.system.layout_manager.history.current()
        if not layout.ring_assignment:
            self._cursor = None
            return False
        width, k = self._geometry()
        ec = mgr.codec.n_pieces > 1
        self_id = mgr.system.id
        health = mgr.helper.health
        netapp = mgr.system.netapp

        hashes: list[bytes] = []
        cursor = self._cursor or b""
        for key, val in mgr.rc.tree.iter_range(start=cursor):
            cursor = key + b"\x00"
            if val and not val.startswith(b"del") and int.from_bytes(
                val[:8], "big"
            ) > 0:
                hashes.append(key)
            if len(hashes) >= max(1, int(self.params.scan_batch)):
                break
        else:
            cursor = None  # type: ignore[assignment]
        self._cursor = cursor
        if not hashes:
            return self._cursor is not None

        # placement + liveness snapshot (loop-side, pure memory reads)
        zone_of = {
            n: r.zone for n, r in layout.roles.items() if r.capacity is not None
        }
        # two liveness signals, deliberately distinct: a piece counts
        # reachable only if its node is connected AND not behind an open
        # breaker (fetchability from HERE); ownership keys on
        # connectivity alone — the breaker is a local verdict, and using
        # it for ownership would let this node claim blocks whose
        # connected owner still counts them (double-count)
        reach: dict[bytes, bool] = {}
        conn: dict[bytes, bool] = {}

        def is_reachable(n: bytes) -> bool:
            got = reach.get(n)
            if got is None:
                got = n == self_id or (
                    netapp.is_connected(n) and health.state_of(n) != OPEN
                )
                reach[n] = got
            return got

        def is_connected(n: bytes) -> bool:
            got = conn.get(n)
            if got is None:
                got = n == self_id or netapp.is_connected(n)
                conn[n] = got
            return got

        assign: dict[bytes, list[bytes]] = {}
        my_ranks: dict[bytes, list[int]] = {}
        for h in hashes:
            nodes = layout.nodes_of(h)[:width]
            if len(nodes) < width:
                cur["unplaceable"] += 1
                continue
            assign[h] = nodes
            my_ranks[h] = [i for i, n in enumerate(nodes) if n == self_id]

        # local piece presence: file checks leave the event loop
        to_check = [
            (h, ranks) for h, ranks in my_ranks.items() if ranks
        ]
        present, samples = await asyncio.to_thread(
            self._inspect_files, to_check, ec
        )
        for size in samples:
            self._piece_est = (
                float(size)
                if self._piece_est is None
                else RATE_ALPHA * size + (1 - RATE_ALPHA) * self._piece_est
            )

        for h, nodes in assign.items():
            have = present.get(h, set())
            cur["local_missing"] += sum(
                1 for r in my_ranks[h] if r not in have
            )
            # ownership: the first CONNECTED node of the stripe counts
            # this block, so per-node ledgers sum to exact cluster totals
            owner = next((n for n in nodes if is_connected(n)), None)
            if owner != self_id:
                continue
            live = 0
            by_zone: dict[str, int] = {}
            for r, n in enumerate(nodes):
                ok = (r in have) if n == self_id else is_reachable(n)
                if ok:
                    live += 1
                    z = zone_of.get(n)
                    if z is not None:
                        by_zone[z] = by_zone.get(z, 0) + 1
            cur["total"] += 1
            cls = classify_block(live, k, width)
            cur[cls] += 1
            missing = width - live
            cur["missing_pieces"] += missing
            margin = live - k
            if cur["min_margin"] is None or margin < cur["min_margin"]:
                cur["min_margin"] = margin
            if cls == DUR_DEGRADED:
                u = classify(missing, width - k)
                if u in cur["urgency"]:
                    cur["urgency"][u] += 1
            for z in zone_exposed(by_zone, live, k):
                cur["zone_exposed"][z] = cur["zone_exposed"].get(z, 0) + 1
            if cls in (DUR_AT_RISK, DUR_UNREADABLE):
                if len(cur["alert_hashes"]) < ALERT_SET_MAX:
                    cur["alert_hashes"][h] = cls
                if self._alerted.get(h) != cls:
                    cur["new_alerts"].append((h, cls))
        return self._cursor is not None

    def _inspect_files(
        self, to_check: list[tuple[bytes, list[int]]], ec: bool
    ) -> tuple[dict[bytes, set[int]], list[int]]:
        """Thread-side: which of OUR ranks' pieces exist on disk, plus a
        few piece-size samples for the byte-backlog estimate."""
        mgr = self.manager
        present: dict[bytes, set[int]] = {}
        samples: list[int] = []
        for h, ranks in to_check:
            have: set[int] = set()
            for r in ranks:
                found = mgr.find_block_file(h, piece=r if ec else 0)
                if found:
                    have.add(r)
                    if len(samples) < SIZE_SAMPLES_PER_BATCH:
                        try:
                            samples.append(os.path.getsize(found[0]))
                        except OSError:
                            pass
            present[h] = have
        return present, samples

    def _finish_pass(self) -> None:
        cur = self._cur
        assert cur is not None
        self._cursor = None
        self._cur = None
        now = self.clock()
        mgr = self.manager
        transient, stuck = mgr.resync.error_age_counts(
            self.params.stuck_error_secs
        )
        oldest = mgr.resync.oldest_error_age_secs()
        worst = (
            max(cur["zone_exposed"].items(), key=lambda kv: kv[1])
            if cur["zone_exposed"]
            else None
        )
        snap = {
            "total": cur["total"],
            "healthy": cur["healthy"],
            "degraded": cur["degraded"],
            "atRisk": cur["at_risk"],
            "unreadable": cur["unreadable"],
            "degradedByUrgency": dict(cur["urgency"]),
            "missingPieces": cur["missing_pieces"],
            "localMissingPieces": cur["local_missing"],
            "unplaceable": cur["unplaceable"],
            "minMargin": cur["min_margin"],
            "zoneExposed": dict(cur["zone_exposed"]),
            "worstZone": (
                {"zone": worst[0], "blocks": worst[1]} if worst else None
            ),
            "resyncErrors": {
                "transient": transient,
                "stuck": stuck,
                "oldestAgeSecs": (
                    round(oldest, 1) if oldest is not None else None
                ),
            },
            "layout": layout_transition(
                mgr.system.layout_manager.history
            ),
            "passSecs": round(now - cur["t0"], 3),
            "scannedAtMs": now_msec(),
        }
        prev, prev_at = self._published, self._published_at
        if prev is not None and prev_at is not None and now > prev_at:
            drained = prev["missingPieces"] - snap["missingPieces"]
            if drained > 0:
                sample = drained / (now - prev_at)
                self._drain_ewma = (
                    sample
                    if self._drain_ewma is None
                    else RATE_ALPHA * sample
                    + (1 - RATE_ALPHA) * self._drain_ewma
                )
        self._published = snap
        self._published_at = now
        self.passes += 1
        if cur["new_alerts"]:
            self._emit_alert(cur["new_alerts"], snap)
        self._alerted = cur["alert_hashes"]

    def _emit_alert(self, new_alerts: list, snap: dict) -> None:
        """Blocks TRANSITIONED into at_risk/unreadable this pass: one
        slow-ring event + one log line per pass, not per block."""
        from ..utils import flight

        examples = ",".join(h.hex()[:16] for h, _c in new_alerts[:3])
        worst = (
            DUR_UNREADABLE
            if any(c == DUR_UNREADABLE for _h, c in new_alerts)
            else DUR_AT_RISK
        )
        attrs = {
            "node": self.manager.system.id.hex()[:16],
            "newBlocks": len(new_alerts),
            "atRiskTotal": snap["atRisk"],
            "unreadableTotal": snap["unreadable"],
            "examples": examples,
        }
        try:
            flight.record_event(
                f"durability-alert:{worst}",
                attrs,
                severity=(
                    "critical" if worst == DUR_UNREADABLE else "warn"
                ),
            )
        except Exception as e:  # noqa: BLE001 — the ledger must not die on diagnostics
            logger.debug("durability alert event failed: %r", e)
        logger.warning(
            "durability: %d block(s) newly %s (at_risk=%d unreadable=%d, "
            "e.g. %s)", len(new_alerts), worst, snap["atRisk"],
            snap["unreadable"], examples,
        )

    # --- derived numbers ------------------------------------------------------

    def repair_eta_secs(self) -> float | None:
        """Seconds until the missing-piece backlog drains at the current
        repair throughput: observed cross-pass drain EWMA, or the live
        RepairPlanner's own rate before two passes have seen a drain.
        None = backlog with no observed progress (stalled/unknown)."""
        p = self._published
        if p is None:
            return None
        missing = p["missingPieces"]
        if missing <= 0:
            return 0.0
        rates = []
        if self._drain_ewma:
            rates.append(self._drain_ewma)
        planner = self.planner_fn()
        if planner is not None and not getattr(planner, "finished", True):
            plan = planner.plan
            elapsed = (now_msec() - plan.started_ms) / 1000.0
            if plan.repaired > 0 and elapsed > 0:
                rates.append(plan.repaired / elapsed)
        if not rates:
            return None
        return round(missing / max(rates), 1)

    def backlog_bytes(self) -> float:
        """Raises before the first completed pass (gauge contract: a
        dropped sample, never a fabricated zero backlog)."""
        p = self._published
        if p is None:
            raise ValueError("no completed durability pass yet")
        est = self._piece_est or float(DEFAULT_PIECE_EST)
        return float(p["missingPieces"]) * est

    def published_value(self, key: str) -> float:
        """Scrape-time gauge feed; raises before the first pass so the
        sample is dropped, never fabricated as 0."""
        p = self._published
        if p is None:
            raise ValueError("no completed durability pass yet")
        return float(p[key])

    def published_class(self, cls: str) -> float:
        key = {
            DUR_HEALTHY: "healthy",
            DUR_DEGRADED: "degraded",
            DUR_AT_RISK: "atRisk",
            DUR_UNREADABLE: "unreadable",
        }[cls]
        return self.published_value(key)

    def worst_zone_exposed(self) -> float:
        """Blocks the WORST single-zone loss would drop below k (0 when
        no zone is exposed); raises before the first pass."""
        p = self._published
        if p is None:
            raise ValueError("no completed durability pass yet")
        return float(p["worstZone"]["blocks"]) if p["worstZone"] else 0.0

    def layout_sync_fraction(self) -> float:
        p = self._published
        if p is None:
            raise ValueError("no completed durability pass yet")
        return float(p["layout"]["progress"])

    def scan_age_secs(self) -> float:
        if self._published_at is None:
            raise ValueError("no completed durability pass yet")
        return max(0.0, self.clock() - self._published_at)

    def ledger(self) -> dict:
        """The local half of `GET /v1/cluster/durability` (full detail,
        zone names included — JSON only, never metric labels)."""
        p = self._published
        return {
            "passes": self.passes,
            "scanning": self._cursor is not None,
            "snapshot": p,
            "repairEtaSecs": self.repair_eta_secs(),
            "backlogBytes": (
                round(self.backlog_bytes(), 1) if p is not None else None
            ),
            "drainPiecesPerSec": (
                round(self._drain_ewma, 3) if self._drain_ewma else None
            ),
            "ageSecs": (
                round(self.clock() - self._published_at, 1)
                if self._published_at is not None
                else None
            ),
        }

    def digest_fields(self) -> dict:
        """Compact `dur.*` block for the gossiped node digest
        (rpc/telemetry_digest.py; additive keys, DIGEST_VERSION stays
        1).  Counts are OWNED blocks -> cluster totals are sums; `minr`
        federates as min-over-nodes; `zl` is a small zone->count map
        (zones are operator-bounded; names stay out of metric labels)."""
        p = self._published
        if p is None:
            return {"age": None}
        return {
            "tot": p["total"],
            "h": p["healthy"],
            "dg": p["degraded"],
            "ar": p["atRisk"],
            "ur": p["unreadable"],
            "mp": p["missingPieces"],
            "lmp": p["localMissingPieces"],
            "minr": p["minMargin"],
            "eta": self.repair_eta_secs(),
            "bkb": round(self.backlog_bytes(), 1),
            "zx": (
                p["worstZone"]["blocks"] if p["worstZone"] else 0
            ),
            "zl": p["zoneExposed"],
            "lt": p["layout"]["progress"],
            "age": (
                round(self.clock() - self._published_at, 1)
                if self._published_at is not None
                else None
            ),
        }


# --- cluster rollup + the one serialization per endpoint ----------------------


def _num(v, default=None):
    try:
        return float(v)
    except (TypeError, ValueError):
        return default


def durability_response(garage) -> dict:
    """The one serialization of the durability observatory, shared by
    admin `GET /v1/cluster/durability` and the admin-RPC `durability`
    op (key casing cannot drift between transports).  Cluster rows come
    from the gossiped `dur.*` digest keys — any node answers for all;
    a digest-less old peer renders `durability: null`, never an error."""
    from ..rpc.telemetry_digest import _valid_digest

    system = garage.system
    system.expire_node_status()
    sc = getattr(garage, "durability_scanner", None)
    local = _valid_digest(garage.telemetry.collect()) or {}
    rows = [
        {
            "id": system.id.hex(),
            "isSelf": True,
            "isUp": True,
            "durability": local.get("dur"),
        }
    ]
    for pid, (pst, _ts) in sorted(system.node_status.items()):
        d = _valid_digest(pst.telemetry) or {}
        rows.append(
            {
                "id": pid.hex(),
                "isSelf": False,
                "isUp": system.netapp.is_connected(pid),
                "durability": d.get("dur"),
            }
        )
    # aggregate only CONNECTED nodes: a dead peer's last-gossiped row
    # (still shown in `nodes` until status expiry) claims the health it
    # had while alive, and its blocks are re-owned by the surviving
    # first-live ranks — summing both would double-count every stripe
    # the cluster just lost a rank of
    with_dur = [
        r
        for r in rows
        if r.get("isUp")
        and isinstance(r.get("durability"), dict)
        and r["durability"].get("tot") is not None
    ]

    def nsum(key: str) -> float:
        return sum(
            _num(r["durability"].get(key), 0.0) for r in with_dur
        )

    minrs = [
        v
        for r in with_dur
        if (v := _num(r["durability"].get("minr"))) is not None
    ]
    etas = [
        v
        for r in with_dur
        if (v := _num(r["durability"].get("eta"))) is not None
    ]
    zones: dict[str, float] = {}
    for r in with_dur:
        zl = r["durability"].get("zl")
        if isinstance(zl, dict):
            for z, c in zl.items():
                c = _num(c, 0.0)
                if c:
                    zones[str(z)] = zones.get(str(z), 0.0) + c
    total = nsum("tot")
    healthy = nsum("h")
    # the scanner object always exists (the digest reads it); "enabled"
    # must reflect whether the WORKER runs, or a disabled observatory
    # reads as a stuck one
    enabled = sc is not None and bool(
        getattr(garage.config.durability, "enabled", True)
    )
    return {
        "node": garage.node_id.hex(),
        "enabled": enabled,
        "local": sc.ledger() if sc is not None else None,
        "cluster": {
            "nodes": rows,
            "nodesReporting": len(with_dur),
            "aggregate": {
                "blocksTotal": total,
                "healthy": healthy,
                "degraded": nsum("dg"),
                "atRisk": nsum("ar"),
                "unreadable": nsum("ur"),
                "missingPieces": nsum("mp"),
                "backlogBytes": nsum("bkb"),
                "healthyFraction": (
                    round(healthy / total, 4) if total else None
                ),
                # the slowest node gates full redundancy; min margin is
                # the cluster's distance from data loss
                "minRedundancy": min(minrs) if minrs else None,
                "repairEtaSeconds": max(etas) if etas else None,
                # nodes with a backlog but NO eta (no observed drain, no
                # planner): "repair stalled" — a healthy node's 0.0 must
                # not mask these in the max above
                "repairEtaUnknownNodes": sum(
                    1
                    for r in with_dur
                    if _num(r["durability"].get("mp"), 0.0) > 0
                    and _num(r["durability"].get("eta")) is None
                ),
                "zoneExposure": zones,
            },
        },
    }
