"""Multi-drive data layout (reference src/block/layout.rs:13-120).

Blocks are mapped to drives by 1024 sub-partitions (top 10 bits of the
block hash) allocated to data directories proportionally to their
configured capacity.  The layout is persisted; after a drive change, a
new layout is computed that minimizes moved sub-partitions, keeping the
old location as `secondary` so reads keep working while the rebalance
worker moves files.  A marker file per drive detects unmounted drives.
"""

from __future__ import annotations

import logging
import os
from typing import Any

from ..utils.config import DataDir
from ..utils.migrate import Migratable

logger = logging.getLogger("garage.block.layout")

DRIVE_NPART = 1024  # 2^10 sub-partitions
MARKER = ".garage-marker"


class DataLayout(Migratable):
    VERSION_MARKER = b"GT0datalayout"

    def __init__(
        self,
        dirs: list[str],
        primary: list[int],
        secondary: list[list[int]],
    ):
        self.dirs = dirs  # directory paths
        self.primary = primary  # sub-partition -> dir index
        self.secondary = secondary  # sub-partition -> old dir indexes

    # --- queries -------------------------------------------------------------

    @staticmethod
    def subpart_of(hash32: bytes) -> int:
        return ((hash32[0] << 8) | hash32[1]) >> 6  # top 10 bits

    def primary_dir(self, hash32: bytes) -> str:
        return self.dirs[self.primary[self.subpart_of(hash32)]]

    def all_dirs(self, hash32: bytes) -> list[str]:
        sp = self.subpart_of(hash32)
        idxs = [self.primary[sp]] + list(self.secondary[sp])
        return [self.dirs[i] for i in idxs if 0 <= i < len(self.dirs)]

    def block_dir(self, base: str, hash32: bytes) -> str:
        """Two-level fan-out dir for a hash (reference block.rs)."""
        h = hash32.hex()
        return os.path.join(base, h[:2], h[2:4])

    # --- construction --------------------------------------------------------

    @classmethod
    def initial(cls, data_dirs: list[DataDir]) -> "DataLayout":
        usable = [d for d in data_dirs if not d.read_only]
        if not usable:
            raise ValueError("no writable data directories")
        dirs = [d.path for d in data_dirs]
        caps = [
            (d.capacity if d.capacity is not None else 1) if not d.read_only else 0
            for d in data_dirs
        ]
        primary = _allocate(caps, DRIVE_NPART)
        return cls(dirs, primary, [[] for _ in range(DRIVE_NPART)])

    def update(self, data_dirs: list[DataDir]) -> "DataLayout":
        """Recompute for a changed drive set, minimizing moves; previous
        primaries become secondaries of moved sub-partitions."""
        new_dirs = [d.path for d in data_dirs]
        caps = [
            (d.capacity if d.capacity is not None else 1) if not d.read_only else 0
            for d in data_dirs
        ]
        old_index = {p: i for i, p in enumerate(self.dirs)}
        # start from current placement translated to new dir indexes
        target_counts = _allocate_counts(caps, DRIVE_NPART)
        counts = [0] * len(new_dirs)
        primary = [-1] * DRIVE_NPART
        # keep sub-partitions where they are if the drive still exists and
        # has remaining quota
        for sp in range(DRIVE_NPART):
            old_path = self.dirs[self.primary[sp]]
            ni = new_dirs.index(old_path) if old_path in new_dirs else -1
            if ni >= 0 and counts[ni] < target_counts[ni]:
                primary[sp] = ni
                counts[ni] += 1
        for sp in range(DRIVE_NPART):
            if primary[sp] < 0:
                ni = max(
                    range(len(new_dirs)),
                    key=lambda i: target_counts[i] - counts[i],
                )
                primary[sp] = ni
                counts[ni] += 1
        secondary: list[list[int]] = []
        for sp in range(DRIVE_NPART):
            old_path = self.dirs[self.primary[sp]]
            secs = []
            if old_path in new_dirs and new_dirs.index(old_path) != primary[sp]:
                secs.append(new_dirs.index(old_path))
            # carry over still-valid old secondaries
            for osi in self.secondary[sp]:
                if 0 <= osi < len(self.dirs) and self.dirs[osi] in new_dirs:
                    nsi = new_dirs.index(self.dirs[osi])
                    if nsi != primary[sp] and nsi not in secs:
                        secs.append(nsi)
            secondary.append(secs)
        return DataLayout(new_dirs, primary, secondary)

    def ensure_markers(self) -> None:
        """Write marker files; a missing marker on an existing dir means
        the drive is not mounted -> refuse to run (reference layout.rs)."""
        for p in self.dirs:
            os.makedirs(p, exist_ok=True)
            marker = os.path.join(p, MARKER)
            if not os.path.exists(marker):
                with open(marker, "w") as f:
                    f.write("garage-tpu data dir\n")

    def check_markers(self) -> None:
        for p in self.dirs:
            if os.path.isdir(p) and not os.path.exists(os.path.join(p, MARKER)):
                raise RuntimeError(
                    f"data dir {p} exists but has no marker file; is the "
                    "drive mounted?"
                )

    # --- serde ---------------------------------------------------------------

    def to_obj(self) -> Any:
        return {
            "dirs": self.dirs,
            "primary": self.primary,
            "secondary": self.secondary,
        }

    @classmethod
    def from_obj(cls, obj: Any) -> "DataLayout":
        return cls(list(obj["dirs"]), list(obj["primary"]), [list(s) for s in obj["secondary"]])


def _allocate_counts(caps: list[int], total: int) -> list[int]:
    capsum = sum(caps)
    if capsum == 0:
        raise ValueError("no usable drive capacity")
    counts = [c * total // capsum for c in caps]
    rem = total - sum(counts)
    order = sorted(
        range(len(caps)),
        key=lambda i: -(caps[i] * total % capsum),
    )
    for i in order[:rem]:
        counts[i] += 1
    return counts


def _allocate(caps: list[int], total: int) -> list[int]:
    counts = _allocate_counts(caps, total)
    out = []
    for i, c in enumerate(counts):
        out.extend([i] * c)
    return out
