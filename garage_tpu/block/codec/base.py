"""BlockCodec: how a logical block maps onto stored pieces.

The seam between the block store and the (TPU) math.  A codec decides how
many pieces a block becomes, which subset suffices to reconstruct it, and
how reconstruction happens — the block manager and resync/scrub workers
are codec-agnostic (BASELINE.json north star: `replication_mode = ec:k:m`
plugs in here without touching the storage protocol).

Piece indices: 0..n_pieces-1.  For ReplicaCodec n_pieces == 1 (the single
piece IS the block, each replica node stores it).  For EcCodec(k, m)
n_pieces == k+m and any k pieces reconstruct.
"""

from __future__ import annotations

import numpy as np


class BlockCodec:
    n_pieces: int = 1
    min_pieces: int = 1  # how many distinct pieces reconstruct a block

    def encode(self, block: bytes) -> list[bytes]:
        """block -> n_pieces stored pieces."""
        raise NotImplementedError

    def decode(self, pieces: dict[int, bytes], block_len: int) -> bytes:
        """>= min_pieces pieces -> original block (exact length)."""
        raise NotImplementedError

    def reconstruct_pieces(
        self, pieces: dict[int, bytes], want: list[int], block_len: int
    ) -> dict[int, bytes]:
        """Rebuild specific missing pieces from surviving ones."""
        raise NotImplementedError

    # --- batched (the TPU path; default falls back to the scalar API) -------

    def encode_batch(self, blocks: list[bytes]) -> list[list[bytes]]:
        return [self.encode(b) for b in blocks]

    def reconstruct_batch(
        self,
        batches: list[tuple[dict[int, bytes], list[int], int]],
    ) -> list[dict[int, bytes]]:
        """[(pieces, want, block_len)] -> [reconstructed pieces]."""
        return [self.reconstruct_pieces(p, w, n) for p, w, n in batches]

    def decode_batch(
        self, items: list[tuple[dict[int, bytes], int]], impl: str = "auto"
    ) -> list[bytes]:
        """[(pieces, block_len)] -> [plaintext blocks] — the codec
        batcher's decode-lane backend (block/codec_batch.py); default
        falls back to the scalar decode."""
        return [self.decode(p, n) for p, n in items]

    def piece_len(self, block_len: int) -> int:
        raise NotImplementedError


def pad_to(data: bytes, n: int) -> bytes:
    return data if len(data) >= n else data + b"\x00" * (n - len(data))


def as_u8(data: bytes) -> np.ndarray:
    return np.frombuffer(data, dtype=np.uint8)
