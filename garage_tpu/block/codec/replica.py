"""Whole-copy codec: the reference's replication model (n copies of the
block, one per node in the hash's replica set)."""

from __future__ import annotations

from .base import BlockCodec


class ReplicaCodec(BlockCodec):
    n_pieces = 1
    min_pieces = 1

    def encode(self, block: bytes) -> list[bytes]:
        return [block]

    def decode(self, pieces, block_len: int) -> bytes:
        return pieces[0][:block_len]

    def reconstruct_pieces(self, pieces, want, block_len: int):
        return {i: pieces[0] for i in want}

    def piece_len(self, block_len: int) -> int:
        return block_len
