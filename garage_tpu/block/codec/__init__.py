from .base import BlockCodec
from .replica import ReplicaCodec

__all__ = ["BlockCodec", "ReplicaCodec", "get_codec"]


def get_codec(ec_params=None, tpu_enable=True, platform=None) -> BlockCodec:
    if ec_params is None:
        return ReplicaCodec()
    from .ec import EcCodec

    k, m = ec_params
    return EcCodec(k, m, tpu_enable=tpu_enable, platform=platform)
