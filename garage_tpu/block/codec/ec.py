"""Erasure codec: GF(2^8) Cauchy Reed-Solomon, batched on TPU.

A block becomes k data shards + m parity shards; any k of the k+m pieces
reconstruct it.  Shard size is padded to a multiple of 64 bytes so the
fused scrub pipeline can BLAKE3-hash shards on-device
(garage_tpu/models/pipeline.py).

Single blocks go through the numpy LUT reference codec (dispatch latency
dominates for one block); batches go to the XLA bit-plane kernel
(ops/ec_tpu.py) when enabled, which groups reconstructions by erasure
pattern so thousands of blocks repair in a handful of device dispatches.
"""

from __future__ import annotations

import logging

import numpy as np

from ...ops import gf
from ...utils.metrics import registry
from .base import BlockCodec

logger = logging.getLogger("garage.block.codec")

SHARD_ALIGN = 64  # blake3 batch hashing wants multiples of 64 bytes
TPU_BATCH_MIN = 8  # below this, the numpy path wins


def _count(op: str, path: str, blocks: int, nbytes: int) -> None:
    """Codec-layer view of the offload decision: which path (tpu batch vs
    numpy scalar) actually served how many blocks/bytes.  A production
    node silently degraded to the scalar path shows up as a rising
    `path="numpy"` share instead of staying invisible."""
    lbl = (("op", op), ("path", path))
    registry.incr("block_codec_blocks_total", lbl, blocks)
    registry.incr("block_codec_bytes_total", lbl, nbytes)


class EcCodec(BlockCodec):
    def __init__(self, k: int, m: int, tpu_enable: bool = True, platform=None):
        self.k, self.m = k, m
        self.n_pieces = k + m
        self.min_pieces = k
        self._parity_mat = gf.cauchy_parity_matrix(k, m)
        self._tpu = None
        if tpu_enable:
            try:
                from ...ops.ec_tpu import EcTpu

                self._tpu = EcTpu(k, m, platform=platform)
            except Exception as e:  # noqa: BLE001 — fall back to numpy
                logger.warning("TPU codec unavailable, using numpy: %r", e)

    def piece_len(self, block_len: int) -> int:
        s = (block_len + self.k - 1) // self.k
        return (s + SHARD_ALIGN - 1) // SHARD_ALIGN * SHARD_ALIGN

    def _split(self, block: bytes) -> np.ndarray:
        s = self.piece_len(len(block))
        if len(block) == self.k * s:
            # aligned block (the common case: block_size is a multiple of
            # k * 64): a zero-copy read-only view — the foreground encode
            # loop must not memcpy every block while holding the GIL
            return np.frombuffer(block, dtype=np.uint8).reshape(self.k, s)
        buf = np.zeros(self.k * s, dtype=np.uint8)
        buf[: len(block)] = np.frombuffer(block, dtype=np.uint8)
        return buf.reshape(self.k, s)

    # --- scalar API ----------------------------------------------------------

    def encode(self, block: bytes) -> list[bytes]:
        # padded split bytes (k*s), same unit the tpu path and both
        # reconstruct paths count — the tpu-vs-numpy byte shares compare
        _count("encode", "numpy", 1, self.k * self.piece_len(len(block)))
        data = self._split(block)  # (k, s)
        parity = gf.apply_matrix(self._parity_mat, data)
        return [bytes(data[i]) for i in range(self.k)] + [
            bytes(parity[i]) for i in range(self.m)
        ]

    def decode(self, pieces: dict[int, bytes], block_len: int) -> bytes:
        data_idx = [i for i in range(self.k) if i in pieces]
        if len(data_idx) == self.k:
            # systematic fast path: the k data shards ARE the plaintext —
            # counted under its own path label so the GET pipeline's
            # systematic share (systematic / (systematic+reconstruct)
            # within op="decode") is computable (ROADMAP item 1a feeds
            # on exactly this number)
            _count(
                "decode", "systematic", 1, self.k * self.piece_len(block_len)
            )
            return b"".join(pieces[i] for i in range(self.k))[:block_len]
        # degraded GET: some data shard is missing, a real decode runs.
        # Counted as op="decode" (the GET-path view) IN ADDITION to the
        # op="reconstruct" count inside reconstruct_pieces — that label
        # is shared with the background repair plane, so without this
        # one the GET decode share would be unrecoverable
        _count("decode", "reconstruct", 1, self.k * self.piece_len(block_len))
        missing = [i for i in range(self.k) if i not in pieces]
        rec = self.reconstruct_pieces(pieces, missing, block_len)
        full = {**pieces, **rec}
        return b"".join(full[i] for i in range(self.k))[:block_len]

    def reconstruct_pieces(
        self, pieces: dict[int, bytes], want: list[int], block_len: int
    ) -> dict[int, bytes]:
        present = sorted(pieces.keys())
        if len(present) < self.k:
            raise ValueError(
                f"need {self.k} pieces to reconstruct, have {len(present)}"
            )
        use = present[: self.k]
        s = self.piece_len(block_len)
        _count("reconstruct", "numpy", 1, self.k * s)
        shards = np.stack(
            [np.frombuffer(pieces[i], dtype=np.uint8) for i in use]
        )  # (k, s)
        assert shards.shape[-1] == s, (shards.shape, s)
        rmat = gf.reconstruction_matrix(self.k, self.m, use, want)
        rec = gf.apply_matrix(rmat, shards)
        return {w: bytes(rec[j]) for j, w in enumerate(want)}

    # --- batched API (TPU) ----------------------------------------------------

    def encode_batch(self, blocks: list[bytes]) -> list[list[bytes]]:
        if self._tpu is None or len(blocks) < TPU_BATCH_MIN:
            return [self.encode(b) for b in blocks]
        # group by shard size so each group is one rectangular dispatch
        out: list[list[bytes] | None] = [None] * len(blocks)
        groups: dict[int, list[int]] = {}
        for idx, b in enumerate(blocks):
            groups.setdefault(self.piece_len(len(b)), []).append(idx)
        for s, idxs in groups.items():
            data = np.stack([self._split(blocks[i]) for i in idxs])  # (B,k,s)
            _count("encode", "tpu", len(idxs), data.nbytes)
            parity = self._tpu.encode(data)  # (B,m,s)
            for j, i in enumerate(idxs):
                out[i] = [bytes(data[j, x]) for x in range(self.k)] + [
                    bytes(parity[j, x]) for x in range(self.m)
                ]
        return out  # type: ignore[return-value]

    # --- coalesced foreground dispatch (the codec batcher backend) ------------

    def _prefer_xla(self) -> bool:
        """auto-impl policy for the foreground batcher: the XLA path only
        wins on a real device backend — on CPU the einsum body software-
        emulates the bit-plane matmul at ~1% of the native LUT codec's
        throughput, so `auto` keeps foreground encodes on the host
        backend there (measured: 54 ms vs 0.5 ms per 1 MiB block)."""
        if self._tpu is None:
            return False
        from ...ops.telemetry import is_host_platform, resolved_platform

        # the ONE shared definition of "host backend" (lint rule
        # backend-gate): scattered string compares are how silent
        # fallbacks breed
        return not is_host_platform(resolved_platform(self._tpu.platform))

    def encode_batch_hashed(
        self, blocks: list[bytes], impl: str = "auto"
    ) -> list[tuple[list[bytes], list[bytes] | None]]:
        """ONE coalesced encode dispatch per shard-size group:
        `[(pieces, piece_hashes | None)] ` aligned with `blocks`.

        This is the codec batcher's backend (block/codec_batch.py).
        `impl`: "xla" routes to the device kernel (fused encode+BLAKE3,
        batch axis padded to its power-of-two bucket), "host" to the
        native C codec + batched native BLAKE3, "auto" picks per
        `_prefer_xla()`.  Piece hashes cover all k+m pieces in piece
        order; None when no batched hasher is available (callers fall
        back to per-piece host hashing on the receiving node)."""
        use_xla = self._tpu is not None and (
            impl == "xla" or (impl == "auto" and self._prefer_xla())
        )
        if not use_xla:
            return self._encode_hashed_host(blocks)
        out: list[tuple[list[bytes], list[bytes] | None] | None] = [None] * len(blocks)
        groups: dict[int, list[int]] = {}
        for idx, b in enumerate(blocks):
            groups.setdefault(self.piece_len(len(b)), []).append(idx)
        for s, idxs in groups.items():
            data = np.stack([self._split(blocks[i]) for i in idxs])  # (B,k,s)
            _count("encode", "tpu", len(idxs), data.nbytes)
            parity, hashes = self._tpu.encode_and_hash(data)
            for j, i in enumerate(idxs):
                pieces = [bytes(data[j, x]) for x in range(self.k)] + [
                    bytes(parity[j, x]) for x in range(self.m)
                ]
                hs = (
                    None
                    if hashes is None
                    else [bytes(hashes[j, x]) for x in range(self.n_pieces)]
                )
                out[i] = (pieces, hs)
        return out  # type: ignore[return-value]

    def _encode_hashed_host(
        self, blocks: list[bytes]
    ) -> list[tuple[list[bytes], list[bytes] | None]]:
        """Host backend of the coalesced dispatch: a straight per-block
        loop over the native C codec + native BLAKE3.  Deliberately NO
        batch stacking here — every heavy step (GF matmul, hashing) is a
        ctypes call that RELEASES the GIL, while numpy stack/transpose
        megacopies would hold it and stall the event loop from inside
        the "off-loop" worker thread (measured: a 64-block stacked
        dispatch held the GIL for tens of ms and made the batcher a
        pessimization on CPU).  The coalescing win on the host backend
        is one thread hop + one telemetry record per BATCH, with the
        loop left free the whole time."""
        from ... import _native
        from ...ops import telemetry

        nbytes = sum(self.k * self.piece_len(len(b)) for b in blocks)
        _count("encode", "numpy", len(blocks), nbytes)
        out: list[tuple[list[bytes], list[bytes] | None]] = []
        with telemetry.dispatch(
            "ec_encode_host", "host", len(blocks), nbytes
        ) as rec:
            # the host path never pads (no fixed-shape executable), so
            # its pad-waste is an honest 0 — keeping the kernel in the
            # X-ray's pad table instead of absent
            rec.pad(len(blocks), len(blocks))
            with rec.compute():
                for block in blocks:
                    data = self._split(block)  # zero-copy view when aligned
                    parity = gf.apply_matrix(self._parity_mat, data)
                    pieces = [bytes(data[i]) for i in range(self.k)] + [
                        bytes(parity[i]) for i in range(self.m)
                    ]
                    hashes: list[bytes] | None = []
                    for p in pieces:
                        h = _native.blake3(p)
                        if h is None:  # native lib absent: receiver hashes
                            hashes = None
                            break
                        hashes.append(h)
                    out.append((pieces, hashes))
        return out

    def note_systematic_read(self, block_len: int) -> None:
        """The streamed systematic GET (block/manager.py) joins the k
        data shards OUTSIDE the codec — piece i goes to the caller while
        piece i+1 is still in flight, so `decode()` never runs.  It
        reports here instead, keeping the `op="decode"` systematic/
        reconstruct split honest (the ROADMAP 1a share)."""
        _count("decode", "systematic", 1, self.k * self.piece_len(block_len))

    def decode_batch(
        self, items: list[tuple[dict[int, bytes], int]], impl: str = "auto"
    ) -> list[bytes]:
        """ONE coalesced reconstruction dispatch per erasure-pattern/
        shard-size group: `[plaintext]` aligned with `items` — the codec
        batcher's decode-lane backend (degraded-mode GETs under load
        share a device dispatch instead of N single-block ones).

        `impl` mirrors `encode_batch_hashed`: the XLA path only wins on
        a real device backend; on the host backend this stays a per-block
        loop over the native LUT codec (NO batch stacking — the numpy
        megacopies would hold the GIL inside the worker thread, the PR 9
        trap).  Items whose k data shards all arrived are systematic
        joins either way and never touch the device."""
        use_xla = self._tpu is not None and (
            impl == "xla" or (impl == "auto" and self._prefer_xla())
        )
        if not use_xla or len(items) < TPU_BATCH_MIN:
            return self._decode_batch_host(items)
        out: list[bytes | None] = [None] * len(items)
        # systematic items: zero decode, plain host join
        groups: dict[tuple, list[int]] = {}
        for idx, (pieces, block_len) in enumerate(items):
            if all(i in pieces for i in range(self.k)):
                out[idx] = self.decode(pieces, block_len)
                continue
            present = tuple(sorted(pieces.keys())[: self.k])
            want = tuple(i for i in range(self.k) if i not in pieces)
            groups.setdefault(
                (present, want, self.piece_len(block_len)), []
            ).append(idx)
        for (present, want, s), idxs in groups.items():
            shards = np.stack(
                [
                    np.stack(
                        [
                            np.frombuffer(items[i][0][p], dtype=np.uint8)
                            for p in present
                        ]
                    )
                    for i in idxs
                ]
            )  # (B, k, s)
            _count("decode", "reconstruct", len(idxs), shards.nbytes)
            _count("reconstruct", "tpu", len(idxs), shards.nbytes)
            rec = self._tpu.reconstruct(shards, list(present), list(want))
            for j, i in enumerate(idxs):
                pieces, block_len = items[i]
                full = {**pieces}
                for x, w in enumerate(want):
                    full[w] = bytes(rec[j, x])
                out[i] = b"".join(full[r] for r in range(self.k))[:block_len]
        return out  # type: ignore[return-value]

    def _decode_batch_host(
        self, items: list[tuple[dict[int, bytes], int]]
    ) -> list[bytes]:
        """Host backend of the coalesced decode: a per-block loop over
        the scalar decode (native LUT reconstruction inside), ONE thread
        hop + one telemetry record per batch — the `_encode_hashed_host`
        pattern."""
        from ...ops import telemetry

        nbytes = sum(self.k * self.piece_len(n) for _p, n in items)
        with telemetry.dispatch(
            "ec_decode_host", "host", len(items), nbytes
        ) as rec:
            rec.pad(len(items), len(items))
            with rec.compute():
                return [self.decode(p, n) for p, n in items]

    def reconstruct_batch(self, batches):
        for idx, (pieces, _w, _n) in enumerate(batches):
            if len(pieces) < self.k:
                raise ValueError(
                    f"batch entry {idx}: need {self.k} pieces to "
                    f"reconstruct, have {len(pieces)}"
                )
        if self._tpu is None or len(batches) < TPU_BATCH_MIN:
            return [self.reconstruct_pieces(p, w, n) for p, w, n in batches]
        out: list[dict[int, bytes] | None] = [None] * len(batches)
        # group by (erasure pattern, want, shard size): one kernel call per
        # group, one compiled kernel per shard shape overall
        groups: dict[tuple, list[int]] = {}
        for idx, (pieces, want, block_len) in enumerate(batches):
            present = tuple(sorted(pieces.keys())[: self.k])
            key = (present, tuple(sorted(want)), self.piece_len(block_len))
            groups.setdefault(key, []).append(idx)
        for (present, want, s), idxs in groups.items():
            shards = np.stack(
                [
                    np.stack(
                        [
                            np.frombuffer(batches[i][0][p], dtype=np.uint8)
                            for p in present
                        ]
                    )
                    for i in idxs
                ]
            )  # (B, k, s)
            _count("reconstruct", "tpu", len(idxs), shards.nbytes)
            rec = self._tpu.reconstruct(shards, list(present), list(want))
            for j, i in enumerate(idxs):
                out[i] = {w: bytes(rec[j, x]) for x, w in enumerate(want)}
        return out  # type: ignore[return-value]
