"""Erasure codec: GF(2^8) Cauchy Reed-Solomon, batched on TPU.

A block becomes k data shards + m parity shards; any k of the k+m pieces
reconstruct it.  Shard size is padded to a multiple of 64 bytes so the
fused scrub pipeline can BLAKE3-hash shards on-device
(garage_tpu/models/pipeline.py).

Single blocks go through the numpy LUT reference codec (dispatch latency
dominates for one block); batches go to the XLA bit-plane kernel
(ops/ec_tpu.py) when enabled, which groups reconstructions by erasure
pattern so thousands of blocks repair in a handful of device dispatches.
"""

from __future__ import annotations

import logging

import numpy as np

from ...ops import gf
from ...utils.metrics import registry
from .base import BlockCodec

logger = logging.getLogger("garage.block.codec")

SHARD_ALIGN = 64  # blake3 batch hashing wants multiples of 64 bytes
TPU_BATCH_MIN = 8  # below this, the numpy path wins


def _count(op: str, path: str, blocks: int, nbytes: int) -> None:
    """Codec-layer view of the offload decision: which path (tpu batch vs
    numpy scalar) actually served how many blocks/bytes.  A production
    node silently degraded to the scalar path shows up as a rising
    `path="numpy"` share instead of staying invisible."""
    lbl = (("op", op), ("path", path))
    registry.incr("block_codec_blocks_total", lbl, blocks)
    registry.incr("block_codec_bytes_total", lbl, nbytes)


class EcCodec(BlockCodec):
    def __init__(self, k: int, m: int, tpu_enable: bool = True, platform=None):
        self.k, self.m = k, m
        self.n_pieces = k + m
        self.min_pieces = k
        self._tpu = None
        if tpu_enable:
            try:
                from ...ops.ec_tpu import EcTpu

                self._tpu = EcTpu(k, m, platform=platform)
            except Exception as e:  # noqa: BLE001 — fall back to numpy
                logger.warning("TPU codec unavailable, using numpy: %r", e)

    def piece_len(self, block_len: int) -> int:
        s = (block_len + self.k - 1) // self.k
        return (s + SHARD_ALIGN - 1) // SHARD_ALIGN * SHARD_ALIGN

    def _split(self, block: bytes) -> np.ndarray:
        s = self.piece_len(len(block))
        buf = np.zeros(self.k * s, dtype=np.uint8)
        buf[: len(block)] = np.frombuffer(block, dtype=np.uint8)
        return buf.reshape(self.k, s)

    # --- scalar API ----------------------------------------------------------

    def encode(self, block: bytes) -> list[bytes]:
        # padded split bytes (k*s), same unit the tpu path and both
        # reconstruct paths count — the tpu-vs-numpy byte shares compare
        _count("encode", "numpy", 1, self.k * self.piece_len(len(block)))
        data = self._split(block)  # (k, s)
        parity = gf.apply_matrix(
            gf.cauchy_parity_matrix(self.k, self.m), data
        )
        return [bytes(data[i]) for i in range(self.k)] + [
            bytes(parity[i]) for i in range(self.m)
        ]

    def decode(self, pieces: dict[int, bytes], block_len: int) -> bytes:
        data_idx = [i for i in range(self.k) if i in pieces]
        if len(data_idx) == self.k:
            return b"".join(pieces[i] for i in range(self.k))[:block_len]
        missing = [i for i in range(self.k) if i not in pieces]
        rec = self.reconstruct_pieces(pieces, missing, block_len)
        full = {**pieces, **rec}
        return b"".join(full[i] for i in range(self.k))[:block_len]

    def reconstruct_pieces(
        self, pieces: dict[int, bytes], want: list[int], block_len: int
    ) -> dict[int, bytes]:
        present = sorted(pieces.keys())
        if len(present) < self.k:
            raise ValueError(
                f"need {self.k} pieces to reconstruct, have {len(present)}"
            )
        use = present[: self.k]
        s = self.piece_len(block_len)
        _count("reconstruct", "numpy", 1, self.k * s)
        shards = np.stack(
            [np.frombuffer(pieces[i], dtype=np.uint8) for i in use]
        )  # (k, s)
        assert shards.shape[-1] == s, (shards.shape, s)
        rmat = gf.reconstruction_matrix(self.k, self.m, use, want)
        rec = gf.apply_matrix(rmat, shards)
        return {w: bytes(rec[j]) for j, w in enumerate(want)}

    # --- batched API (TPU) ----------------------------------------------------

    def encode_batch(self, blocks: list[bytes]) -> list[list[bytes]]:
        if self._tpu is None or len(blocks) < TPU_BATCH_MIN:
            return [self.encode(b) for b in blocks]
        # group by shard size so each group is one rectangular dispatch
        out: list[list[bytes] | None] = [None] * len(blocks)
        groups: dict[int, list[int]] = {}
        for idx, b in enumerate(blocks):
            groups.setdefault(self.piece_len(len(b)), []).append(idx)
        for s, idxs in groups.items():
            data = np.stack([self._split(blocks[i]) for i in idxs])  # (B,k,s)
            _count("encode", "tpu", len(idxs), data.nbytes)
            parity = self._tpu.encode(data)  # (B,m,s)
            for j, i in enumerate(idxs):
                out[i] = [bytes(data[j, x]) for x in range(self.k)] + [
                    bytes(parity[j, x]) for x in range(self.m)
                ]
        return out  # type: ignore[return-value]

    def reconstruct_batch(self, batches):
        for idx, (pieces, _w, _n) in enumerate(batches):
            if len(pieces) < self.k:
                raise ValueError(
                    f"batch entry {idx}: need {self.k} pieces to "
                    f"reconstruct, have {len(pieces)}"
                )
        if self._tpu is None or len(batches) < TPU_BATCH_MIN:
            return [self.reconstruct_pieces(p, w, n) for p, w, n in batches]
        out: list[dict[int, bytes] | None] = [None] * len(batches)
        # group by (erasure pattern, want, shard size): one kernel call per
        # group, one compiled kernel per shard shape overall
        groups: dict[tuple, list[int]] = {}
        for idx, (pieces, want, block_len) in enumerate(batches):
            present = tuple(sorted(pieces.keys())[: self.k])
            key = (present, tuple(sorted(want)), self.piece_len(block_len))
            groups.setdefault(key, []).append(idx)
        for (present, want, s), idxs in groups.items():
            shards = np.stack(
                [
                    np.stack(
                        [
                            np.frombuffer(batches[i][0][p], dtype=np.uint8)
                            for p in present
                        ]
                    )
                    for i in idxs
                ]
            )  # (B, k, s)
            _count("reconstruct", "tpu", len(idxs), shards.nbytes)
            rec = self._tpu.reconstruct(shards, list(present), list(want))
            for j, i in enumerate(idxs):
                out[i] = {w: bytes(rec[j, x]) for x, w in enumerate(want)}
        return out  # type: ignore[return-value]
