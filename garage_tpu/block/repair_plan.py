"""Repair plane: cluster-wide batched-reconstruction planner.

The reactive repair paths fix blocks ONE AT A TIME: resync pops queue
entries, scrub re-queues what it finds corrupt.  `bulk_reconstruct`
(block/manager.py) can rebuild thousands of pieces in a handful of
device dispatches — but until now nothing PLANNED at that scale, so the
TPU codec's mesh fan-out threshold (2x devices, ops/ec_tpu.py) was
cleared only by accident.  This module is the batched-inference
scheduler of the storage plane: aggregate many small independent repairs
into hardware-sized dispatches under admission control.

A `RepairPlanner` worker runs in three phases:

  scan     — walk the local rc tree (every block this cluster still
             references) in batches; for each batch, survey piece
             inventories: local files plus one bulk `Inv` RPC per peer
             (breaker-aware: open-breaker peers are skipped and their
             pieces conservatively counted missing).  Each stripe with
             missing shards becomes a ledger entry classified by
             URGENCY = how many shards are gone (closest to data loss
             first).  Stripes whose missing ranks live on OTHER nodes
             are nudged there (bulk `Queue` RPC -> their resync queue);
             stripes with fewer than k shards anywhere are recorded as
             `lost` (operator surface, nothing to dispatch).
  repair   — repeatedly coalesce compatible ledger entries (same k/m by
             construction; sorted so equal-urgency stripes of the same
             shard length are adjacent -> rectangular dispatches) into
             batches sized to clear the mesh threshold, capped by the
             bytes-in-flight budget, and drive them through
             `bulk_reconstruct`.  Stripes whose surviving shards sit
             behind open circuit breakers are deferred — the batch
             keeps filling with later stripes instead of stalling.
             Gather failures fall to resync's retry/backoff ladder
             (bulk_reconstruct queues them); the planner moves on.
  done     — final checkpoint, gauges unregistered.

Progress is CHECKPOINTED (`repair_plan` persister file) after every scan
step and repair round: a restarted daemon resumes the plan — ledger,
cursor and stats intact — instead of rescanning the cluster
(`Garage.spawn_workers` auto-resumes an in-progress plan).

Admission control is runtime-tunable via BgVars (`worker set`):
`repair-tranquility` (Tranquilizer pacing, same contract as resync) and
`repair-bytes-in-flight` (bytes of surviving shards gathered per round).

Metric families (catalogued in doc/monitoring.md, rendered by the admin
/metrics endpoint):

  repair_plan_backlog{urgency,id}      G  ledger depth by urgency class
  repair_plan_blocks_total             C  pieces rebuilt by the plane
  repair_plan_rounds_total             C  bulk_reconstruct rounds driven
  repair_plan_batch_size               H  blocks per round (pow2, _sum)
  repair_plan_dispatch_duration        H  seconds per round
  repair_plan_remote_nudges_total      C  hashes queued on remote nodes
  repair_plan_deferred_total           C  breaker-deferred stripe picks
  tpu_mesh_engaged_total{kernel,platform,devices}
                                       C  dispatches actually served by
                                          the multi-device mesh path
                                          (ops/telemetry.py)
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import os

from ..utils.background import Worker, WorkerState
from ..utils.metrics import SIZE_BUCKETS, registry
from ..utils.migrate import Migratable
from ..utils.persister import Persister
from ..utils.time_util import now_msec
from ..utils.tranquilizer import Tranquilizer

logger = logging.getLogger("garage.block.repair_plan")

# value-histogram family: blocks per bulk_reconstruct round
registry.set_buckets("repair_plan_batch_size", SIZE_BUCKETS)

SCAN_BATCH = 512  # rc-tree keys surveyed per work() iteration
SCAN_CHECKPOINT_EVERY = 8  # scan steps between checkpoints: the save
# rewrites the WHOLE growing ledger, so per-step saves would be
# O(ledger^2) on a heavily degraded cluster; a crash merely re-surveys
# the unpersisted steps (cursor and ledger snapshot together, so resume
# cannot duplicate entries)
INV_RPC_HASHES = 256  # hashes per bulk Inv/Queue RPC
DEFAULT_BATCH_TARGET = 256  # floor for the mesh-sized coalescing target
DEFAULT_PIECE_EST = 256 * 1024  # bytes budget estimate when plen unknown
DEFER_ROUNDS_MAX = 60  # all-deferred rounds before handing off to resync
DEFER_RETRY_SECS = 2.0  # pause between all-deferred rounds

# urgency classes, most severe first (repair order within the ledger)
URGENCY_LOST = "lost"  # < k shards reachable: nothing to dispatch
URGENCY_CRITICAL = "critical"  # one more loss means data loss
URGENCY_HIGH = "high"  # over half the parity budget consumed
URGENCY_LOW = "low"
URGENCIES = (URGENCY_CRITICAL, URGENCY_HIGH, URGENCY_LOW, URGENCY_LOST)

# gauge `id` label: process-unique (several in-process nodes share the
# global registry — see utils/background.py _gauge_ids for the pattern)
_gauge_ids = itertools.count(1)


def classify(n_missing: int, m: int) -> str:
    """Urgency of a stripe with `n_missing` shards gone, parity width m."""
    if n_missing > m:
        return URGENCY_LOST
    if n_missing == m:
        return URGENCY_CRITICAL
    if n_missing >= (m + 1) // 2:
        return URGENCY_HIGH
    return URGENCY_LOW


class PlanParams:
    """Mutable admission-control knobs, shared between the composition
    root (config + BgVars setters) and the running planner — `worker set
    repair-tranquility 4` takes effect on the NEXT round, no restart."""

    def __init__(
        self,
        tranquility: int = 2,
        bytes_in_flight: int = 128 * 1024 * 1024,
        batch_blocks: int | None = None,
    ):
        self.tranquility = tranquility
        self.bytes_in_flight = bytes_in_flight
        self.batch_blocks = batch_blocks  # None: mesh-derived target


class PlanPersisted(Migratable):
    """Checkpointed plan state.  Ledger entries are
    [hash32, local_missing_ranks, n_missing_total, piece_len]."""

    VERSION_MARKER = b"GT0rplan"

    def __init__(
        self,
        state: str = "scanning",
        cursor: bytes | None = b"",
        ledger: list | None = None,
        lost: list | None = None,
        scanned: int = 0,
        repaired: int = 0,
        rounds: int = 0,
        nudged: int = 0,
        deferred: int = 0,
        started_ms: int = 0,
    ):
        self.state = state
        self.cursor = cursor  # rc-tree scan position; None = scan done
        self.ledger = ledger if ledger is not None else []
        self.lost = lost if lost is not None else []
        self.scanned = scanned
        self.repaired = repaired
        self.rounds = rounds
        self.nudged = nudged
        self.deferred = deferred
        self.started_ms = started_ms

    def to_obj(self):
        return [
            self.state,
            self.cursor,
            [[bytes(h), list(lr), nm, pl] for h, lr, nm, pl in self.ledger],
            [bytes(h) for h in self.lost],
            self.scanned,
            self.repaired,
            self.rounds,
            self.nudged,
            self.deferred,
            self.started_ms,
        ]

    @classmethod
    def from_obj(cls, obj):
        return cls(
            state=str(obj[0]),
            cursor=bytes(obj[1]) if obj[1] is not None else None,
            ledger=[
                (bytes(h), [int(r) for r in lr], int(nm), int(pl))
                for h, lr, nm, pl in obj[2]
            ],
            lost=[bytes(h) for h in obj[3]],
            scanned=int(obj[4]),
            repaired=int(obj[5]),
            rounds=int(obj[6]),
            nudged=int(obj[7]),
            deferred=int(obj[8]),
            started_ms=int(obj[9]),
        )


def _mesh_width(manager) -> int:
    """Devices the codec would fan a batch over (1 when the TPU codec is
    unavailable) — the 2x threshold the coalescer must clear."""
    tpu = getattr(manager.codec, "_tpu", None)
    if tpu is None:
        return 1
    try:
        return max(1, tpu._mesh_width())
    except Exception as e:  # noqa: BLE001 — planner must not die on telemetry
        logger.debug("mesh width probe failed, assuming 1: %r", e)
        return 1


async def drive_bulk(manager, hashes: list[bytes]) -> int:
    """One repair-plane round: `bulk_reconstruct` wrapped in the
    repair_plan metric families.  Shared by the planner and the one-shot
    `repair blocks` worker (block/repair.py) so dispatch accounting
    cannot drift between the two drivers."""
    registry.observe("repair_plan_batch_size", (), float(len(hashes)))
    with registry.timer("repair_plan_dispatch_duration", ()):
        n = await manager.bulk_reconstruct(hashes)
    registry.incr("repair_plan_blocks_total", (), n)
    registry.incr("repair_plan_rounds_total")
    return n


class RepairPlanner(Worker):
    """Cluster-degradation planner (see module docstring).

    One planner per node; launched from the admin API/CLI (`repair plan
    launch`) or auto-resumed from a checkpoint at daemon start.  Drives
    only THIS node's missing pieces through the TPU path — remote-only
    degradation is delegated to the owning nodes via `Queue` nudges, so
    pod-level repair remains every node draining its own rank at mesh
    batch sizes (BASELINE row 5)."""

    def __init__(
        self,
        manager,
        metadata_dir: str | None = None,
        params: PlanParams | None = None,
        fresh: bool = False,
    ):
        if manager.codec.n_pieces <= 1:
            raise ValueError(
                "repair planner requires an erasure-coded block codec "
                "(replication_mode = ec:k:m)"
            )
        self.manager = manager
        self.params = params or PlanParams()
        self.tranquilizer = Tranquilizer()
        self.persister = (
            Persister(metadata_dir, "repair_plan", PlanPersisted)
            if metadata_dir
            else None
        )
        self.plan = None if fresh else self._load_resumable()
        self.resumed = self.plan is not None
        if self.plan is None:
            self.plan = PlanPersisted(started_ms=now_msec())
        self.finished = False
        self._cancel = False
        self._defer_rounds = 0
        self._scan_steps = 0
        self._gauge_keys: list[tuple] = []
        self._register_gauges()
        if self.resumed:
            logger.info(
                "repair plan resumed from checkpoint: state=%s backlog=%d "
                "repaired=%d", self.plan.state, len(self.plan.ledger),
                self.plan.repaired,
            )

    def _load_resumable(self) -> PlanPersisted | None:
        if self.persister is None:
            return None
        try:
            plan = self.persister.load()
        except Exception as e:  # noqa: BLE001 — a corrupt/foreign-version
            # checkpoint must cost a rescan, never a crashed planner
            logger.warning(
                "repair plan checkpoint unreadable (%r); starting fresh", e
            )
            return None
        if plan is not None and plan.state in ("scanning", "repairing"):
            return plan
        return None

    @classmethod
    def resumable(cls, metadata_dir: str | None) -> bool:
        """Is there an in-progress checkpoint to resume on this node?
        Unreadable checkpoints (corruption, a newer build's format after
        a downgrade) answer False — auto-resume runs inside daemon boot
        and one bad auxiliary file must not brick startup."""
        if not metadata_dir:
            return False
        try:
            plan = Persister(
                metadata_dir, "repair_plan", PlanPersisted
            ).load()
        except Exception as e:  # noqa: BLE001
            logger.warning("unreadable repair_plan checkpoint ignored: %r", e)
            return False
        return plan is not None and plan.state in ("scanning", "repairing")

    # --- worker interface -----------------------------------------------------

    def name(self) -> str:
        return "repair_plan"

    def status(self):
        return {
            "state": self.plan.state,
            "backlog": len(self.plan.ledger),
            "scanned": self.plan.scanned,
            "repaired": self.plan.repaired,
            "rounds": self.plan.rounds,
            "nudged": self.plan.nudged,
            "deferred": self.plan.deferred,
            "lost": len(self.plan.lost),
            "scanning": self.plan.cursor is not None,
        }

    def tranquility(self) -> int | None:
        return self.params.tranquility

    def queue_length(self) -> int | None:
        return len(self.plan.ledger)

    def cmd_cancel(self) -> None:
        """Stop after the in-flight round; the checkpoint keeps state
        "cancelled" so a later launch starts a fresh plan."""
        self._cancel = True

    def backlog_by_urgency(self) -> dict[str, int]:
        m = self.manager.codec.n_pieces - self.manager.codec.min_pieces
        out = {u: 0 for u in URGENCIES}
        for _h, _lr, n_missing, _pl in self.plan.ledger:
            # ledger entries are repairable by construction; a partial
            # survey can overstate n_missing past m (unanswered peers
            # count missing conservatively), which must read as
            # "critical", never as the lost data-loss alarm
            out[classify(min(n_missing, m), m)] += 1
        out[URGENCY_LOST] += len(self.plan.lost)
        return out

    def status_full(self) -> dict:
        """Admin-API view: worker status + urgency breakdown + knobs."""
        st = self.status()
        st["backlogByUrgency"] = self.backlog_by_urgency()
        st["startedMs"] = self.plan.started_ms
        st["meshWidth"] = _mesh_width(self.manager)
        st["batchTarget"] = self._batch_target()
        return st

    async def work(self):
        if self._cancel and not self.finished:
            return await self._finish("cancelled")
        if self.finished:
            return WorkerState.DONE
        self.tranquilizer.reset()
        if self.plan.state == "scanning":
            more = await self._scan_step()
            self._scan_steps += 1
            if not more and self.plan.state == "scanning":
                self.plan.state = "repairing" if self.plan.ledger else "done"
            if not more or self._scan_steps % SCAN_CHECKPOINT_EVERY == 0:
                await self._save_async()
            if self.plan.state == "done":
                return await self._finish("done")
            return self._throttle()
        if self.plan.state == "repairing":
            if not self.plan.ledger:
                return await self._finish("done")
            picked = await self._repair_round()
            await self._save_async()
            if not self.plan.ledger:
                return await self._finish("done")
            if picked == 0:
                # everything pickable sits behind open breakers: wait for
                # half-open probes rather than spinning; after too long,
                # hand the tail to resync's error ladder and finish
                self._defer_rounds += 1
                if self._defer_rounds >= DEFER_ROUNDS_MAX:
                    for h, _lr, _nm, _pl in self.plan.ledger:
                        self.manager.resync.queue_block(h)
                    logger.warning(
                        "repair plan: %d stripes stuck behind open "
                        "breakers for %d rounds; handed to resync",
                        len(self.plan.ledger), self._defer_rounds,
                    )
                    self.plan.ledger = []
                    return await self._finish("done")
                return (WorkerState.THROTTLED, DEFER_RETRY_SECS)
            self._defer_rounds = 0
            return self._throttle()
        return await self._finish(self.plan.state or "done")

    def _throttle(self):
        delay = self.tranquilizer.tranquilize_delay(self.params.tranquility)
        return (WorkerState.THROTTLED, delay) if delay else WorkerState.BUSY

    # --- scan phase -----------------------------------------------------------

    async def _scan_step(self) -> bool:
        """Survey one SCAN_BATCH of the rc tree; returns False when the
        scan is complete."""
        mgr = self.manager
        hashes: list[bytes] = []
        cursor = self.plan.cursor or b""
        for key, val in mgr.rc.tree.iter_range(start=cursor):
            cursor = key + b"\x00"
            if val and not val.startswith(b"del") and int.from_bytes(
                val[:8], "big"
            ) > 0:
                hashes.append(key)
            if len(hashes) >= SCAN_BATCH:
                break
        else:
            self.plan.cursor = None
        if self.plan.cursor is not None:
            self.plan.cursor = cursor
        if hashes:
            await self._survey(hashes)
            self.plan.scanned += len(hashes)
        return self.plan.cursor is not None

    async def _survey(self, hashes: list[bytes]) -> None:
        """Inventory `hashes` across their assignment, append degraded
        stripes to the ledger, nudge remote-only holders."""
        from ..net.message import PRIO_BACKGROUND

        mgr = self.manager
        layout = mgr.system.layout_manager.history.current()
        npieces = mgr.codec.n_pieces
        k = mgr.codec.min_pieces
        self_id = mgr.system.id
        health = mgr.helper.health

        assign: dict[bytes, list[bytes]] = {}
        present: dict[bytes, set[int]] = {}
        plen: dict[bytes, int] = {}
        per_node: dict[bytes, list[bytes]] = {}
        for h in hashes:
            nodes = layout.nodes_of(h)[:npieces]
            if len(nodes) < npieces:
                continue  # layout narrower than the stripe: nothing to plan
            assign[h] = nodes
            local = mgr.local_pieces(h)
            present[h] = set(local.keys())
            for _pi, (path, compressed) in sorted(local.items()):
                if compressed:
                    continue  # legacy .zst replica file: size lies
                plen[h] = await asyncio.to_thread(_stored_piece_len, path)
                break
            # survey EVERY node that may hold pieces — the union of all
            # active layout versions (storage_nodes_of), not just the
            # current assignment: mid-migration, pieces still sit on
            # previous-version holders, and asking only current holders
            # would misreport fully recoverable stripes as lost
            for n in set(mgr.storage_nodes_of(h)) | set(nodes):
                if n != self_id:
                    per_node.setdefault(n, []).append(h)

        # hashes with at least one unanswered holder: their shards count
        # missing CONSERVATIVELY, so they must never be classified lost,
        # and their remote holders must not be nudged on guesswork
        unsurveyed: set[bytes] = set()
        for n, hs in per_node.items():
            from ..rpc.peer_health import OPEN

            if health.state_of(n) == OPEN:
                # skip the sick peer; its pieces count as missing
                # (conservative: worst case we rebuild a piece that still
                # exists there — content-addressed, so harmless)
                registry.incr("repair_plan_deferred_total", (), len(hs))
                self.plan.deferred += len(hs)
                unsurveyed.update(hs)
                continue
            for i in range(0, len(hs), INV_RPC_HASHES):
                chunk = hs[i : i + INV_RPC_HASHES]
                try:
                    resp = await mgr.helper.call(
                        mgr.endpoint, n, ["Inv", chunk],
                        prio=PRIO_BACKGROUND, idempotent=True,
                    )
                except Exception as e:  # noqa: BLE001 — peer counts missing
                    logger.debug("repair plan: Inv to %s failed: %r",
                                 n.hex()[:8], e)
                    unsurveyed.update(chunk)
                    continue
                for h, (idxs, pl) in zip(chunk, resp.body):
                    if h in present:
                        present[h].update(int(x) for x in idxs)
                        if pl and h not in plen:
                            plen[h] = int(pl)

        nudges: dict[bytes, set[bytes]] = {}
        for h, nodes in assign.items():
            missing = [r for r in range(npieces) if r not in present[h]]
            if not missing:
                continue
            my_ranks = set(mgr.ec_ranks_of(h))
            local_missing = [r for r in missing if r in my_ranks]
            if len(present[h]) < k and h not in unsurveyed:
                # every holder answered and fewer than k shards exist
                # anywhere: genuinely unrepairable (operator surface)
                self.plan.lost.append(h)
                continue
            if local_missing:
                self.plan.ledger.append(
                    (h, local_missing, len(missing), plen.get(h, 0))
                )
            if h in unsurveyed:
                continue  # don't nudge holders based on a partial survey
            for r in missing:
                if r not in my_ranks:
                    nudges.setdefault(nodes[r], set()).add(h)

        for n, hs in nudges.items():
            from ..net.message import PRIO_BACKGROUND
            from ..rpc.peer_health import OPEN

            if health.state_of(n) == OPEN:
                continue  # sick holder: its own resync finds the gap later
            hl = sorted(hs)
            for i in range(0, len(hl), INV_RPC_HASHES):
                chunk = hl[i : i + INV_RPC_HASHES]
                try:
                    await mgr.helper.call(
                        mgr.endpoint, n, ["Queue", chunk],
                        prio=PRIO_BACKGROUND, idempotent=True,
                    )
                    self.plan.nudged += len(chunk)
                    registry.incr(
                        "repair_plan_remote_nudges_total", (), len(chunk)
                    )
                except Exception as e:  # noqa: BLE001
                    logger.debug("repair plan: Queue to %s failed: %r",
                                 n.hex()[:8], e)

    # --- repair phase ---------------------------------------------------------

    def _batch_target(self) -> int:
        """Blocks to coalesce per round: explicit config, else large
        enough to clear the mesh fan-out threshold with headroom."""
        if self.params.batch_blocks:
            return max(1, int(self.params.batch_blocks))
        return max(2 * _mesh_width(self.manager), DEFAULT_BATCH_TARGET)

    def _pick_batch(self) -> list[int]:
        """Ledger indices for the next round: urgency-first (most missing
        shards first), same-shard-length stripes adjacent so grouped
        dispatches stay rectangular, capped by the bytes-in-flight
        budget, open-breaker stripes skipped (the batch widens past them
        instead of stalling)."""
        from ..rpc.peer_health import OPEN

        mgr = self.manager
        layout = mgr.system.layout_manager.history.current()
        health = mgr.helper.health
        npieces = mgr.codec.n_pieces
        k = mgr.codec.min_pieces
        self_id = mgr.system.id

        target = self._batch_target()
        budget = max(1, int(self.params.bytes_in_flight))
        order = sorted(
            range(len(self.plan.ledger)),
            key=lambda i: (-self.plan.ledger[i][2], self.plan.ledger[i][3]),
        )
        picked: list[int] = []
        used = 0
        for i in order:
            if len(picked) >= target:
                break
            h, local_missing, _nm, pl = self.plan.ledger[i]
            est = k * (pl or DEFAULT_PIECE_EST)
            if picked and used + est > budget:
                break  # ledger is urgency-ordered; later entries can wait
            nodes = layout.nodes_of(h)[:npieces]
            open_peers = sum(
                1
                for n in set(nodes)
                if n != self_id and health.state_of(n) == OPEN
            )
            if npieces - open_peers - len(local_missing) < k:
                # not enough reachable survivors right now: defer, keep
                # filling the batch with stripes that CAN repair
                registry.incr("repair_plan_deferred_total")
                self.plan.deferred += 1
                continue
            picked.append(i)
            used += est
        return picked

    async def _repair_round(self) -> int:
        """Drive one coalesced batch through bulk_reconstruct; returns
        how many stripes were picked (0 = everything deferred)."""
        picked = self._pick_batch()
        if not picked:
            return 0
        hashes = [self.plan.ledger[i][0] for i in picked]
        rebuilt = await drive_bulk(self.manager, hashes)
        self.plan.repaired += rebuilt
        self.plan.rounds += 1
        # picked entries leave the ledger whatever happened: repaired ones
        # are done, gather failures were queued to resync (which owns the
        # retry/backoff ladder) by bulk_reconstruct itself
        dead = set(picked)
        self.plan.ledger = [
            e for i, e in enumerate(self.plan.ledger) if i not in dead
        ]
        logger.info(
            "repair plan: round %d rebuilt %d pieces (%d stripes, "
            "%d left)", self.plan.rounds, rebuilt, len(picked),
            len(self.plan.ledger),
        )
        return len(picked)

    # --- persistence / lifecycle ----------------------------------------------

    async def _save_async(self) -> None:
        # work()-path checkpoints go off-loop: a plan ledger fsync on the
        # event loop stalls every concurrent request (loop-blocker)
        if self.persister is not None:
            await self.persister.save_in_thread(self.plan)

    async def _finish(self, state: str):
        self.plan.state = state
        await self._save_async()
        self._unregister_gauges()
        self.finished = True
        logger.info(
            "repair plan %s: scanned=%d repaired=%d rounds=%d lost=%d",
            state, self.plan.scanned, self.plan.repaired, self.plan.rounds,
            len(self.plan.lost),
        )
        return WorkerState.DONE

    def _register_gauges(self) -> None:
        gid = str(next(_gauge_ids))
        for u in URGENCIES:
            lbl = (("urgency", u), ("id", gid))
            registry.register_gauge(
                "repair_plan_backlog", lbl,
                lambda u=u: float(self.backlog_by_urgency()[u]),
            )
            self._gauge_keys.append(("repair_plan_backlog", lbl))

    def _unregister_gauges(self) -> None:
        for name, lbl in self._gauge_keys:
            registry.unregister_gauge(name, lbl)
        self._gauge_keys = []


def _stored_piece_len(path: str) -> int:
    """Payload length of a stored EC piece file (0 when unknown) — used
    only for batch byte-budget estimates and shard-length coalescing."""
    from .manager import PIECE_MAGIC, PIECE_MAGIC_V1

    try:
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            magic = f.read(4)
    except OSError:
        return 0
    if magic == PIECE_MAGIC:
        return max(0, size - 44)
    if magic == PIECE_MAGIC_V1:
        return max(0, size - 12)
    return 0
