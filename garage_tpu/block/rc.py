"""Per-block reference counts (reference src/block/rc.rs).

The block_ref table's `updated()` hook increments/decrements these
transactionally with the metadata write.  When a count reaches zero the
block is not deleted immediately: a deletion marker with a deadline
(BLOCK_GC_DELAY, 10 min) is stored, and resync offloads/deletes after the
delay — protecting against the reordering where a concurrent PutObject
re-references the block.

Tree values: 8-byte big-endian count, or b"del" + 8-byte deadline msec.
"""

from __future__ import annotations

from ..db import Db, Tx
from ..utils.time_util import now_msec

BLOCK_GC_DELAY_MS = 10 * 60 * 1000


class BlockRc:
    def __init__(self, db: Db):
        self.db = db
        self.tree = db.open_tree("block_rc")

    # --- transactional ops (called from table updated() hooks) ---------------

    def incr(self, tx: Tx, hash32: bytes) -> bool:
        """Returns True if the block became referenced (0 -> 1)."""
        cur = self._get_tx(tx, hash32)
        newly = cur == 0
        tx.insert(self.tree, hash32, (cur + 1).to_bytes(8, "big"))
        return newly

    def decr(self, tx: Tx, hash32: bytes) -> bool:
        """Returns True if the block became unreferenced (rc -> 0)."""
        cur = self._get_tx(tx, hash32)
        if cur <= 1:
            deadline = now_msec() + BLOCK_GC_DELAY_MS
            tx.insert(self.tree, hash32, b"del" + deadline.to_bytes(8, "big"))
            return True
        tx.insert(self.tree, hash32, (cur - 1).to_bytes(8, "big"))
        return False

    def _get_tx(self, tx: Tx, hash32: bytes) -> int:
        raw = tx.get(self.tree, hash32)
        return _count(raw)

    # --- queries -------------------------------------------------------------

    def get(self, hash32: bytes) -> int:
        return _count(self.tree.get(hash32))

    def is_deletable(self, hash32: bytes) -> bool:
        """rc is zero and the deletion delay has passed."""
        raw = self.tree.get(hash32)
        if raw is None:
            return True
        if raw.startswith(b"del"):
            return int.from_bytes(raw[3:11], "big") <= now_msec()
        return False

    def is_needed(self, hash32: bytes) -> bool:
        return _count(self.tree.get(hash32)) > 0

    def clear_deleted(self, hash32: bytes) -> None:
        """Drop an EXPIRED deletion marker (housekeeping after the file is
        gone).  Markers still inside their delay window are kept — they are
        the race protection against concurrent re-uploads (reference
        src/block/rc.rs clear_deleted_block_rc)."""
        raw = self.tree.get(hash32)
        if (
            raw is not None
            and raw.startswith(b"del")
            and int.from_bytes(raw[3:11], "big") <= now_msec()
        ):
            self.tree.remove(hash32)


def _count(raw: bytes | None) -> int:
    if raw is None or raw.startswith(b"del"):
        return 0
    return int.from_bytes(raw[:8], "big")
