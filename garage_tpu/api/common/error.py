"""S3-style API errors -> XML error responses
(reference src/api/common/ error plumbing + s3 error codes)."""

from __future__ import annotations


class ApiError(Exception):
    code = "InternalError"
    status = 500

    def __init__(self, message: str = "", code: str | None = None, status: int | None = None):
        super().__init__(message or self.code)
        self.message = message or self.code
        if code:
            self.code = code
        if status:
            self.status = status


class BadRequest(ApiError):
    code = "InvalidRequest"
    status = 400


class Forbidden(ApiError):
    code = "AccessDenied"
    status = 403


class AuthError(ApiError):
    code = "SignatureDoesNotMatch"
    status = 403


class NoSuchBucket(ApiError):
    code = "NoSuchBucket"
    status = 404


class NoSuchKey(ApiError):
    code = "NoSuchKey"
    status = 404


class NoSuchUpload(ApiError):
    code = "NoSuchUpload"
    status = 404


class BucketNotEmpty(ApiError):
    code = "BucketNotEmpty"
    status = 409


class BucketAlreadyExists(ApiError):
    code = "BucketAlreadyExists"
    status = 409


class EntityTooLarge(ApiError):
    code = "EntityTooLarge"
    status = 400


class InvalidRange(ApiError):
    code = "InvalidRange"
    status = 416


class PreconditionFailed(ApiError):
    code = "PreconditionFailed"
    status = 412


class NotImplementedError_(ApiError):
    code = "NotImplemented"
    status = 501


class SlowDown(ApiError):
    """S3-semantic overload rejection (admission control,
    api/overload.py): AWS SDKs back off and retry on this code."""

    code = "SlowDown"
    status = 503


def error_xml(err: ApiError, resource: str = "", request_id: str = "") -> str:
    from xml.sax.saxutils import escape

    return (
        '<?xml version="1.0" encoding="UTF-8"?>\n'
        f"<Error><Code>{escape(err.code)}</Code>"
        f"<Message>{escape(err.message)}</Message>"
        f"<Resource>{escape(resource)}</Resource>"
        f"<RequestId>{escape(request_id)}</RequestId></Error>"
    )
