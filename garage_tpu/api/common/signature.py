"""AWS Signature Version 4 verification + signing.

Reference src/api/common/signature/payload.rs (canonical request, scope,
key derivation) — implemented from the SigV4 spec, both header-based
`Authorization` and presigned query (`X-Amz-Signature`) forms.  Payload
policy: `x-amz-content-sha256` of UNSIGNED-PAYLOAD, the hex sha256 of the
body (checked), or the aws-chunked streaming forms (see streaming.py).

The same functions sign outgoing requests for the in-repo client
(no boto3 in this environment) and the integration tests.
"""

from __future__ import annotations

import hashlib
import hmac
import urllib.parse
from datetime import datetime, timezone

from .error import AuthError, BadRequest, Forbidden

ALGORITHM = "AWS4-HMAC-SHA256"
UNSIGNED = "UNSIGNED-PAYLOAD"


def _uri_encode(s: str, encode_slash: bool = True) -> str:
    safe = "-_.~" if encode_slash else "-_.~/"
    return urllib.parse.quote(s, safe=safe)


def canonical_query(query_items: list[tuple[str, str]], skip: set[str] = frozenset()) -> str:
    items = sorted(
        (_uri_encode(k), _uri_encode(v)) for k, v in query_items if k not in skip
    )
    return "&".join(f"{k}={v}" for k, v in items)


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def signing_key(secret: str, date: str, region: str, service: str = "s3") -> bytes:
    k = _hmac(("AWS4" + secret).encode(), date)
    k = _hmac(k, region)
    k = _hmac(k, service)
    return _hmac(k, "aws4_request")


def canonical_request(
    method: str,
    path: str,
    query_items: list[tuple[str, str]],
    headers: dict[str, str],
    signed_headers: list[str],
    payload_hash: str,
    skip_query: set[str] = frozenset(),
) -> str:
    canon_headers = "".join(
        f"{h}:{' '.join(headers.get(h, '').split())}\n" for h in signed_headers
    )
    return "\n".join(
        [
            method.upper(),
            _uri_encode(path, encode_slash=False),
            canonical_query(query_items, skip_query),
            canon_headers,
            ";".join(signed_headers),
            payload_hash,
        ]
    )


def string_to_sign(timestamp: str, scope: str, canon_req: str) -> str:
    return "\n".join(
        [ALGORITHM, timestamp, scope, hashlib.sha256(canon_req.encode()).hexdigest()]
    )


def compute_signature(
    secret: str,
    method: str,
    path: str,
    query_items: list[tuple[str, str]],
    headers: dict[str, str],
    signed_headers: list[str],
    payload_hash: str,
    timestamp: str,
    date: str,
    region: str,
    service: str = "s3",
    skip_query: set[str] = frozenset(),
) -> str:
    scope = f"{date}/{region}/{service}/aws4_request"
    creq = canonical_request(
        method, path, query_items, headers, signed_headers, payload_hash, skip_query
    )
    sts = string_to_sign(timestamp, scope, creq)
    key = signing_key(secret, date, region, service)
    return hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()


class AuthContext:
    """Parsed+verified request authentication."""

    def __init__(self, key_id: str, payload_hash: str | None, streaming=None):
        self.key_id = key_id
        self.content_sha256 = payload_hash  # None = unsigned
        # "signed" | "unsigned-trailer" framing context (StreamingContext
        # or the string "unsigned"); None = plain body
        self.streaming = streaming


def parse_authorization(auth: str) -> tuple[str, str, str, str, list[str], str]:
    """-> (key_id, date, region, service, signed_headers, signature)"""
    if not auth.startswith(ALGORITHM):
        raise AuthError("unsupported authorization algorithm")
    parts = {}
    for item in auth[len(ALGORITHM):].strip().split(","):
        k, _, v = item.strip().partition("=")
        parts[k] = v
    try:
        cred = parts["Credential"].split("/")
        key_id, date, region, service = cred[0], cred[1], cred[2], cred[3]
        signed_headers = parts["SignedHeaders"].split(";")
        signature = parts["Signature"]
    except (KeyError, IndexError) as e:
        raise AuthError(f"malformed Authorization header: {e}") from e
    return key_id, date, region, service, signed_headers, signature


async def verify_request(request, get_secret, region: str) -> AuthContext:
    """Verify an aiohttp request.  `get_secret(key_id) -> secret | None`
    (async).  Returns the auth context; raises AuthError/Forbidden."""
    headers = {k.lower(): v for k, v in request.headers.items()}
    query_items = [(k, v) for k, v in request.query.items()]
    path = request.path

    if "x-amz-signature" in {k.lower() for k, _ in query_items}:
        return await _verify_presigned(
            request, headers, query_items, path, get_secret, region
        )

    auth = headers.get("authorization")
    if not auth:
        raise Forbidden("missing Authorization header")
    key_id, date, req_region, service, signed_headers, signature = (
        parse_authorization(auth)
    )
    if req_region != region:
        raise AuthError(f"wrong region {req_region!r}, expected {region!r}")
    timestamp = headers.get("x-amz-date") or headers.get("date", "")
    if not timestamp:
        raise AuthError("missing x-amz-date")
    # clock-skew window + scope-date consistency (replay resistance)
    try:
        t0 = datetime.strptime(timestamp, "%Y%m%dT%H%M%SZ").replace(
            tzinfo=timezone.utc
        )
    except ValueError as e:
        raise AuthError(f"bad x-amz-date: {e}") from e
    if abs((datetime.now(timezone.utc) - t0).total_seconds()) > 15 * 60:
        raise AuthError("request timestamp outside the allowed window")
    if timestamp[:8] != date:
        raise AuthError("x-amz-date does not match credential scope date")
    payload_hash = headers.get("x-amz-content-sha256", UNSIGNED)
    secret = await get_secret(key_id)
    if secret is None:
        raise Forbidden(f"unknown access key {key_id}")
    expected = compute_signature(
        secret, request.method, path, query_items, headers, signed_headers,
        payload_hash, timestamp, date, req_region, service,
    )
    if not hmac.compare_digest(expected, signature):
        raise AuthError("request signature does not match")
    from .streaming import (
        STREAMING_SIGNED,
        STREAMING_UNSIGNED_TRAILER,
        StreamingContext,
    )

    if payload_hash == STREAMING_SIGNED:
        scope = f"{date}/{req_region}/{service}/aws4_request"
        sctx = StreamingContext(
            signing_key(secret, date, req_region, service),
            timestamp, scope, expected,
        )
        return AuthContext(key_id, None, streaming=sctx)
    if payload_hash == STREAMING_UNSIGNED_TRAILER:
        return AuthContext(key_id, None, streaming="unsigned")
    return AuthContext(key_id, None if payload_hash == UNSIGNED else payload_hash)


async def _verify_presigned(request, headers, query_items, path, get_secret, region):
    q = {k.lower(): v for k, v in query_items}
    try:
        cred = q["x-amz-credential"].split("/")
        key_id, date, req_region, service = cred[0], cred[1], cred[2], cred[3]
        timestamp = q["x-amz-date"]
        signature = q["x-amz-signature"]
        signed_headers = q["x-amz-signedheaders"].split(";")
        expires = int(q.get("x-amz-expires", "86400"))
    except (KeyError, IndexError) as e:
        raise AuthError(f"malformed presigned query: {e}") from e
    if req_region != region:
        raise AuthError(f"wrong region {req_region!r}")
    # mirror the header path's checks: expires bounds (AWS max 7 days),
    # scope-date consistency, and no far-future timestamps
    if not 1 <= expires <= 604800:
        raise AuthError("X-Amz-Expires must be between 1 and 604800 seconds")
    if timestamp[:8] != date:
        raise AuthError("X-Amz-Date does not match credential scope date")
    try:
        t0 = datetime.strptime(timestamp, "%Y%m%dT%H%M%SZ").replace(
            tzinfo=timezone.utc
        )
        age = (datetime.now(timezone.utc) - t0).total_seconds()
        if age > expires:
            raise AuthError("presigned URL expired")
        if age < -15 * 60:
            raise AuthError("X-Amz-Date is in the future")
    except ValueError as e:
        raise AuthError(f"bad X-Amz-Date: {e}") from e
    secret = await get_secret(key_id)
    if secret is None:
        raise Forbidden(f"unknown access key {key_id}")
    expected = compute_signature(
        secret, request.method, path,
        [(k, v) for k, v in query_items if k.lower() != "x-amz-signature"],
        headers, signed_headers, UNSIGNED, timestamp, date, req_region, service,
    )
    if not hmac.compare_digest(expected, signature):
        raise AuthError("presigned signature does not match")
    return AuthContext(key_id, None)


async def check_payload(body: bytes, ctx: AuthContext) -> None:
    if ctx.content_sha256 is not None:
        if hashlib.sha256(body).hexdigest() != ctx.content_sha256:
            raise BadRequest(
                "payload sha256 does not match x-amz-content-sha256",
                code="XAmzContentSHA256Mismatch",
            )


# --- client-side signing (in-repo client + tests) ----------------------------


def sign_request_headers(
    method: str,
    url_path: str,
    query_items: list[tuple[str, str]],
    headers: dict[str, str],
    body: bytes,
    key_id: str,
    secret: str,
    region: str,
    service: str = "s3",
) -> dict[str, str]:
    """Returns headers with Authorization added (lowercased names kept)."""
    now = datetime.now(timezone.utc)
    timestamp = now.strftime("%Y%m%dT%H%M%SZ")
    date = now.strftime("%Y%m%d")
    h = {k.lower(): v for k, v in headers.items()}
    h["x-amz-date"] = timestamp
    payload_hash = h.get("x-amz-content-sha256") or hashlib.sha256(body).hexdigest()
    h["x-amz-content-sha256"] = payload_hash
    signed_headers = sorted(set(list(h.keys()) + ["host"]))
    sig = compute_signature(
        secret, method, url_path, query_items, h, signed_headers,
        payload_hash, timestamp, date, region, service,
    )
    scope = f"{date}/{region}/{service}/aws4_request"
    h["authorization"] = (
        f"{ALGORITHM} Credential={key_id}/{scope}, "
        f"SignedHeaders={';'.join(signed_headers)}, Signature={sig}"
    )
    return h
