"""Object checksums: x-amz-checksum-{crc32,crc32c,sha1,sha256}
(reference src/api/common/signature/checksum.rs).

The client declares a checksum (base64) on upload; we compute it over the
plaintext stream, reject mismatches, persist it in the object metadata and
return it on GET/HEAD.
crc32c (Castagnoli) is table-driven Python — fine at block granularity;
the native extension can take it over later.
"""

from __future__ import annotations

import base64
import hashlib
import zlib

from .error import BadRequest

ALGOS = ("crc32", "crc32c", "sha1", "sha256")
HEADER_PREFIX = "x-amz-checksum-"

# --- crc32c (Castagnoli, reflected, poly 0x1EDC6F41) -------------------------

_CRC32C_TABLE = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ (0x82F63B78 if _c & 1 else 0)
    _CRC32C_TABLE.append(_c)


class Crc32c:
    def __init__(self):
        self._crc = 0xFFFFFFFF

    def update(self, data: bytes) -> None:
        crc = self._crc
        table = _CRC32C_TABLE
        for b in data:
            crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
        self._crc = crc

    def digest(self) -> bytes:
        return ((self._crc ^ 0xFFFFFFFF) & 0xFFFFFFFF).to_bytes(4, "big")


class Crc32:
    def __init__(self):
        self._crc = 0

    def update(self, data: bytes) -> None:
        self._crc = zlib.crc32(data, self._crc)

    def digest(self) -> bytes:
        return (self._crc & 0xFFFFFFFF).to_bytes(4, "big")


def _hasher(algo: str):
    if algo == "crc32":
        return Crc32()
    if algo == "crc32c":
        return Crc32c()
    return hashlib.new(algo)


class ChecksumRequest:
    """One declared upload checksum: algorithm + expected base64 value."""

    def __init__(self, algo: str, expected_b64: str | None):
        self.algo = algo
        self.expected_b64 = expected_b64  # None until the trailer arrives
        self.hasher = _hasher(algo)

    def resolve_trailer(self, trailers: dict[str, str]) -> None:
        if self.expected_b64 is None:
            self.expected_b64 = trailers.get(HEADER_PREFIX + self.algo, "").strip()
            if not self.expected_b64:
                raise BadRequest(
                    f"declared trailer checksum {self.algo} missing from trailers"
                )

    @classmethod
    def from_headers(cls, headers) -> "ChecksumRequest | None":
        h = {k.lower(): v for k, v in headers.items()}
        found = [a for a in ALGOS if HEADER_PREFIX + a in h]
        if not found:
            # a trailer declaration means the value arrives AFTER the body
            trailer = h.get("x-amz-trailer", "").strip().lower()
            if trailer.startswith(HEADER_PREFIX):
                algo = trailer[len(HEADER_PREFIX):]
                if algo in ALGOS:
                    return cls(algo, None)
            return None
        if len(found) > 1:
            raise BadRequest("multiple checksum headers supplied")
        algo = found[0]
        return cls(algo, h[HEADER_PREFIX + algo].strip())

    def update(self, data: bytes) -> None:
        self.hasher.update(data)

    def verify(self) -> dict:
        """-> {"algo": .., "b64": ..} for the object meta; raises on
        mismatch."""
        got = base64.b64encode(self.hasher.digest()).decode()
        if got != self.expected_b64:
            raise BadRequest(
                f"checksum mismatch: computed {got}, header said "
                f"{self.expected_b64}",
                code="BadDigest",
            )
        return {"algo": self.algo, "b64": got}


def response_headers(meta: dict) -> dict[str, str]:
    cks = meta.get("cks")
    if not cks:
        return {}
    return {HEADER_PREFIX + cks["algo"]: cks["b64"]}
