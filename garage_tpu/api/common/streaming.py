"""aws-chunked streaming signatures
(reference src/api/common/signature/streaming.rs, 618 LoC).

For `x-amz-content-sha256: STREAMING-AWS4-HMAC-SHA256-PAYLOAD` the body is
a sequence of framed chunks, each carrying its own signature chained from
the request's seed (Authorization) signature:

    <hex size>;chunk-signature=<sig>\r\n <bytes> \r\n ...
    0;chunk-signature=<final sig>\r\n\r\n

    sig_i = HMAC(signing_key, "AWS4-HMAC-SHA256-PAYLOAD\n" + timestamp +
                 "\n" + scope + "\n" + sig_{i-1} + "\n" + sha256("") +
                 "\n" + sha256(chunk_i))

so a long upload is authenticated incrementally without buffering it.
`STREAMING-UNSIGNED-PAYLOAD-TRAILER` uses the same framing without
per-chunk signatures; trailers (e.g. `x-amz-checksum-*`) are captured by
the decoder and verified by the put path over the decoded stream.
"""

from __future__ import annotations

import hashlib
import hmac

from .error import AuthError, BadRequest

STREAMING_SIGNED = "STREAMING-AWS4-HMAC-SHA256-PAYLOAD"
STREAMING_UNSIGNED_TRAILER = "STREAMING-UNSIGNED-PAYLOAD-TRAILER"
EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()
MAX_CHUNK_HEADER = 8 * 1024
MAX_CHUNK_SIZE = 16 * 1024 * 1024  # declared chunk cap: bound buffering


class StreamingContext:
    """Per-request signing context carried in the AuthContext."""

    def __init__(self, signing_key: bytes, timestamp: str, scope: str, seed_sig: str):
        self.signing_key = signing_key
        self.timestamp = timestamp
        self.scope = scope
        self.seed_sig = seed_sig

    def chunk_signature(self, prev_sig: str, chunk: bytes) -> str:
        sts = "\n".join(
            [
                "AWS4-HMAC-SHA256-PAYLOAD",
                self.timestamp,
                self.scope,
                prev_sig,
                EMPTY_SHA256,
                hashlib.sha256(chunk).hexdigest(),
            ]
        )
        return hmac.new(self.signing_key, sts.encode(), hashlib.sha256).hexdigest()


class ChunkedDecoder:
    """Wraps the raw body stream; `.read(n)` yields decoded payload bytes,
    verifying each chunk signature as it completes."""

    def __init__(self, raw, ctx: StreamingContext | None):
        self.raw = raw  # aiohttp StreamReader (.read(n))
        self.ctx = ctx  # None = unsigned-trailer framing
        self.prev_sig = ctx.seed_sig if ctx else ""
        self.buf = b""
        self.pending = b""  # decoded-but-undelivered payload
        self.eof = False
        self.trailers: dict[str, str] = {}  # e.g. trailing checksums

    async def _fill(self, n: int) -> None:
        while len(self.buf) < n:
            chunk = await self.raw.read(64 * 1024)
            if not chunk:
                raise BadRequest("truncated aws-chunked body")
            self.buf += chunk

    async def _read_line(self) -> bytes:
        while b"\r\n" not in self.buf:
            if len(self.buf) > MAX_CHUNK_HEADER:
                raise BadRequest("oversized chunk header")
            chunk = await self.raw.read(64 * 1024)
            if not chunk:
                raise BadRequest("truncated aws-chunked body")
            self.buf += chunk
        line, self.buf = self.buf.split(b"\r\n", 1)
        return line

    async def _next_chunk(self) -> bytes | None:
        header = await self._read_line()
        size_hex, _, ext = header.partition(b";")
        try:
            size = int(size_hex, 16)
        except ValueError as e:
            raise BadRequest(f"bad chunk size {size_hex!r}") from e
        if size > MAX_CHUNK_SIZE:
            raise BadRequest(
                f"chunk of {size} bytes exceeds the {MAX_CHUNK_SIZE} limit"
            )
        sig = None
        if ext.startswith(b"chunk-signature="):
            sig = ext[len(b"chunk-signature="):].decode()
        if self.ctx is not None and sig is None:
            raise AuthError("chunk without signature in signed streaming body")
        await self._fill(size)
        data, self.buf = self.buf[:size], self.buf[size:]
        if self.ctx is not None:
            expected = self.ctx.chunk_signature(self.prev_sig, data)
            if not hmac.compare_digest(expected, sig or ""):
                raise AuthError("chunk signature does not match")
            self.prev_sig = expected
        if size == 0:
            # capture trailers (e.g. x-amz-checksum-*) until blank line/EOF
            while True:
                try:
                    line = await self._read_line()
                except BadRequest:
                    break
                if line == b"":
                    break
                name, sep, value = line.decode(errors="replace").partition(":")
                if sep:
                    self.trailers[name.strip().lower()] = value.strip()
            return None
        # trailing CRLF after the data
        await self._fill(2)
        if self.buf[:2] != b"\r\n":
            raise BadRequest("missing CRLF after chunk data")
        self.buf = self.buf[2:]
        return data

    async def read(self, n: int) -> bytes:
        while not self.eof and len(self.pending) < n:
            chunk = await self._next_chunk()
            if chunk is None:
                self.eof = True
                break
            self.pending += chunk
        out, self.pending = self.pending[:n], self.pending[n:]
        return out


# --- client-side encoding (in-repo client + tests) ----------------------------


def encode_chunked(
    data: bytes, ctx: StreamingContext, chunk_size: int = 64 * 1024
) -> bytes:
    out = []
    prev = ctx.seed_sig
    for i in range(0, max(len(data), 1), chunk_size):
        chunk = data[i : i + chunk_size]
        sig = ctx.chunk_signature(prev, chunk)
        out.append(f"{len(chunk):x};chunk-signature={sig}\r\n".encode())
        out.append(chunk)
        out.append(b"\r\n")
        prev = sig
    final = ctx.chunk_signature(prev, b"")
    out.append(f"0;chunk-signature={final}\r\n\r\n".encode())
    return b"".join(out)
