"""K2V REST API (reference src/api/k2v/ router.rs:15-51, item.rs,
batch.rs, index.rs).

  GET    /bucket                         ReadIndex (partition keys + counts)
  POST   /bucket                         InsertBatch (JSON)
  POST   /bucket?search                  ReadBatch (JSON)
  POST   /bucket?delete                  DeleteBatch (JSON)
  GET    /bucket/pk/sk                   ReadItem (raw or JSON per Accept)
  GET    /bucket/pk/sk?poll&causality_token=..&timeout=..  PollItem
  PUT    /bucket/pk/sk                   InsertItem (X-Garage-Causality-Token)
  DELETE /bucket/pk/sk                   DeleteItem (token required)

Values travel base64 in JSON bodies, raw in single-value responses.
SigV4 auth + bucket permissions, same as S3.
"""

from __future__ import annotations

import base64
import json
import logging
import urllib.parse

from aiohttp import web

from ...model.k2v.item_table import CausalContext
from ...utils.error import Error
from ..common.error import ApiError, BadRequest, Forbidden, NoSuchKey, error_xml
from ..common.signature import verify_request

logger = logging.getLogger("garage.api.k2v")

TOKEN_HEADER = "X-Garage-Causality-Token"


class K2VApiServer:
    def __init__(self, garage):
        self.garage = garage
        self.region = garage.config.s3_api.s3_region
        self.app = web.Application(client_max_size=64 * 1024 * 1024)
        self.app.router.add_route("*", "/{tail:.*}", self._entry)
        self.runner: web.AppRunner | None = None

    async def start(self, host: str, port: int) -> None:
        self.runner = web.AppRunner(self.app, access_log=None)
        await self.runner.setup()
        site = web.TCPSite(self.runner, host, port)
        await site.start()
        logger.info("k2v api listening on %s:%d", host, port)

    async def stop(self) -> None:
        if self.runner:
            await self.runner.cleanup()

    async def _get_secret(self, key_id: str):
        k = await self.garage.key_table.get(key_id.encode(), b"")
        if k is None or k.is_deleted():
            return None
        return k.secret()

    async def _entry(self, request: web.Request) -> web.StreamResponse:
        from ...utils.metrics import request_metrics

        try:
            with request_metrics(
                "api_k2v", request.method, "api:k2v", path=request.path
            ):
                return await self._handle(request)
        except ApiError as e:
            return web.Response(
                status=e.status,
                text=error_xml(e, request.path),
                content_type="application/xml",
            )
        except Error as e:
            status = 404 if "not found" in str(e) else 500
            return web.Response(status=status, text=str(e))
        except (ValueError, KeyError, TypeError) as e:
            # malformed tokens / numbers / JSON bodies are caller errors
            return web.Response(status=400, text=f"bad request: {e!r}")
        except Exception as e:  # noqa: BLE001
            logger.exception("k2v api error")
            return web.Response(status=500, text=repr(e))

    async def _handle(self, request: web.Request) -> web.StreamResponse:
        ctx = await verify_request(request, self._get_secret, self.region)
        api_key = await self.garage.helper.get_key(ctx.key_id)
        # split the RAW path first so %2F inside keys survives, then
        # unquote each segment
        raw = request.raw_path.split("?")[0].lstrip("/")
        parts = [urllib.parse.unquote(p) for p in raw.split("/", 2)]
        bucket_name = parts[0]
        if not bucket_name:
            raise BadRequest("no bucket")
        bucket_id = await self.garage.helper.resolve_bucket(bucket_name, api_key)
        perm = api_key.bucket_permissions(bucket_id)
        pk = parts[1] if len(parts) > 1 else None
        sk = parts[2] if len(parts) > 2 else None
        q = request.query
        m = request.method

        if pk is None or pk == "":
            if m == "GET":
                _req(perm.allow_read)
                return await self._read_index(bucket_id, request)
            if m == "POST":
                _req(perm.allow_write)
                if "delete" in q:
                    return await self._delete_batch(bucket_id, request)
                if "search" in q:
                    _req(perm.allow_read)
                    return await self._read_batch(bucket_id, request)
                return await self._insert_batch(bucket_id, request)
            raise BadRequest(f"unsupported {m} on bucket")

        if sk is None:
            if m == "POST" and "poll_range" in q:
                _req(perm.allow_read)
                return await self._poll_range(bucket_id, pk, request)
            raise BadRequest("missing sort key")

        if m == "GET":
            _req(perm.allow_read)
            if "poll" in q:
                return await self._poll_item(bucket_id, pk, sk, request)
            return await self._read_item(bucket_id, pk, sk, request)
        if m == "PUT":
            _req(perm.allow_write)
            body = await request.read()
            causal = _token_of(request)
            await self.garage.k2v_rpc.insert(bucket_id, pk, sk, causal, body)
            return web.Response(status=204)
        if m == "DELETE":
            _req(perm.allow_write)
            causal = _token_of(request)
            if causal is None:
                raise BadRequest("DeleteItem requires X-Garage-Causality-Token")
            await self.garage.k2v_rpc.insert(bucket_id, pk, sk, causal, None)
            return web.Response(status=204)
        raise BadRequest(f"unsupported method {m}")

    # --- item ops -------------------------------------------------------------

    async def _read_item(self, bucket_id, pk, sk, request) -> web.Response:
        item = await self.garage.k2v_item_table.get(
            bucket_id + pk.encode(), sk.encode()
        )
        if item is None or item.is_tombstone():
            raise NoSuchKey("item not found")
        token = item.causal_context().serialize()
        values = item.live_values()
        accept = request.headers.get("Accept", "*/*")
        if len(values) == 1 and "application/json" not in accept:
            return web.Response(
                body=values[0],
                headers={TOKEN_HEADER: token},
                content_type="application/octet-stream",
            )
        return web.json_response(
            [base64.b64encode(v).decode() for v in values],
            headers={TOKEN_HEADER: token},
        )

    async def _poll_item(self, bucket_id, pk, sk, request) -> web.Response:
        token = request.query.get("causality_token", "")
        timeout = min(float(request.query.get("timeout", "300")), 600.0)
        causal = CausalContext.parse(token) if token else CausalContext()
        item = await self.garage.k2v_rpc.poll_item(bucket_id, pk, sk, causal, timeout)
        if item is None:
            return web.Response(status=304)
        values = item.live_values()
        return web.json_response(
            [base64.b64encode(v).decode() for v in values],
            headers={TOKEN_HEADER: item.causal_context().serialize()},
        )

    async def _poll_range(self, bucket_id, pk, request) -> web.Response:
        """PollRange (reference src/api/k2v/batch.rs:255): long-poll a
        whole sort-key range for changes the seenMarker hasn't covered."""
        body = json.loads(await request.read() or b"{}")
        timeout = min(max(float(body.get("timeout", 300)), 1.0), 600.0)
        res = await self.garage.k2v_rpc.poll_range(
            bucket_id,
            pk,
            body.get("start"),
            body.get("end"),
            body.get("prefix"),
            body.get("seenMarker"),
            timeout,
        )
        if res is None:
            return web.Response(status=304)
        items, seen_marker = res
        return web.json_response(
            {
                "items": [
                    {
                        "sk": sk,
                        "ct": item.causal_context().serialize(),
                        "v": [
                            base64.b64encode(v).decode() if v is not None else None
                            for v in item.values()
                        ],
                    }
                    for sk, item in items.items()
                ],
                "seenMarker": seen_marker,
            }
        )

    # --- index + batches ------------------------------------------------------

    async def _read_index(self, bucket_id, request) -> web.Response:
        q = request.query
        prefix = q.get("prefix", "")
        limit = min(int(q.get("limit", "1000")), 1000)
        start = q.get("start", "")
        # full ReadIndexQuery surface (reference index.rs): prefix, start,
        # end, limit, reverse.  Partition keys live in the counter table,
        # keyed (bucket, pk): an ordered distributed range read, streamed
        # so filtered-out rows never eat the page budget.
        end = q.get("end")
        reverse = q.get("reverse") == "true"
        begin = self._range_begin(prefix or None, start or None, reverse)
        nodes = self.garage.system.layout_manager.history.current().storage_nodes()
        seen = []
        async for ent in self._iter_range(
            self.garage.k2v_counter_table, bucket_id, begin, None, reverse,
            lambda e: e.sk.decode(errors="replace"),
        ):
            pk = ent.sk.decode(errors="replace")
            if prefix and not pk.startswith(prefix):
                if (not reverse and pk > prefix) or (reverse and pk < prefix):
                    break  # sorted: past the prefix range
                continue
            if end is not None and (
                (not reverse and pk >= end) or (reverse and pk <= end)
            ):
                break
            vals = ent.aggregate(nodes)
            if vals.get("items", 0) <= 0:
                continue
            if len(seen) > limit:
                break
            seen.append((pk, vals))
        truncated = len(seen) > limit
        seen = seen[:limit]
        return web.json_response(
            {
                "prefix": prefix or None,
                "partitionKeys": [
                    {
                        "pk": pk,
                        "entries": v.get("items", 0),
                        "conflicts": v.get("conflicts", 0),
                        "values": v.get("values", 0),
                        "bytes": v.get("bytes", 0),
                    }
                    for pk, v in seen
                ],
                "more": truncated,
            }
        )

    async def _insert_batch(self, bucket_id, request) -> web.Response:
        body = json.loads(await request.read())
        items = []
        for it in body:
            v = it.get("v")
            items.append(
                (
                    it["pk"],
                    it["sk"],
                    CausalContext.parse(it["ct"]) if it.get("ct") else None,
                    base64.b64decode(v) if v is not None else None,
                )
            )
        await self.garage.k2v_rpc.insert_batch(bucket_id, items)
        return web.Response(status=204)

    async def _read_batch(self, bucket_id, request) -> web.Response:
        """ReadBatch with the full reference query surface
        (src/api/k2v/batch.rs ReadBatchQuery): prefix, start, end, limit,
        reverse, singleItem, conflictsOnly, tombstones."""
        body = json.loads(await request.read())
        out = []
        for search in body:
            pk = search["partitionKey"]
            prefix = search.get("prefix")
            start = search.get("start")
            end = search.get("end")
            limit = min(int(search.get("limit") or 1000), 1000)
            reverse = bool(search.get("reverse"))
            single = bool(search.get("singleItem"))
            conflicts_only = bool(search.get("conflictsOnly"))
            tombstones = bool(search.get("tombstones"))
            filt = None if tombstones else "present"

            if single:
                if start is None:
                    raise ValueError("singleItem requires start")
                item = await self.garage.k2v_item_table.get(
                    bucket_id + pk.encode(), start.encode()
                )

                async def _single(_item=item):
                    if _item is not None:
                        yield _item

                items = _single()
            else:
                items = self._iter_partition(
                    bucket_id + pk.encode(),
                    self._range_begin(prefix, start, reverse),
                    filt,
                    reverse,
                )
            rows = []
            more = False
            next_start = None
            async for item in items:
                sk = item.sort_key
                if prefix is not None and not sk.startswith(prefix):
                    if (not reverse and sk > prefix) or (reverse and sk < prefix):
                        break
                    continue
                if end is not None and (
                    (not reverse and sk >= end) or (reverse and sk <= end)
                ):
                    break
                if not tombstones and item.is_tombstone():
                    continue
                if conflicts_only and len(item.live_values()) <= 1:
                    continue
                if len(rows) >= limit:
                    more = True
                    next_start = sk
                    break
                rows.append(
                    {
                        "sk": sk,
                        "ct": item.causal_context().serialize(),
                        "v": [
                            base64.b64encode(v).decode() if v is not None else None
                            for v in (
                                item.values() if tombstones else item.live_values()
                            )
                        ],
                    }
                )
            out.append(
                {
                    "partitionKey": pk,
                    "prefix": prefix,
                    "start": start,
                    "end": end,
                    "limit": limit,
                    "reverse": reverse,
                    "singleItem": single,
                    "conflictsOnly": conflicts_only,
                    "tombstones": tombstones,
                    "items": rows,
                    "more": more,
                    "nextStart": next_start,
                }
            )
        return web.json_response(out)

    @staticmethod
    def _range_begin(prefix: str | None, start: str | None, reverse: bool):
        """Start bound for a (possibly reverse) range enumeration, shared
        by ReadBatch and ReadIndex.  Reverse scans start AT the bound and
        walk DOWN, so `start` is an upper bound there; with only a prefix
        the reverse scan starts just past the prefix range."""
        if reverse:
            if start is not None:
                return start.encode()
            if prefix is not None:
                from ...db import _prefix_end

                return _prefix_end(prefix.encode())
            return None
        begin = start if start is not None else prefix
        return begin.encode() if begin else None

    async def _iter_range(self, table, part_pk: bytes, begin_bytes, filt,
                          reverse, sk_of):
        """Page through a partition range without a silent row cap —
        filters may discard arbitrarily many rows before filling a page,
        so enumeration must continue until the range is exhausted.
        `sk_of(entry) -> str` extracts the sort key."""
        cursor = begin_bytes
        skip_past: str | None = None  # reverse resume is inclusive: skip it
        while True:
            batch = await table.get_range(
                part_pk, cursor, filt, 1000, reverse=reverse
            )
            if not batch:
                return
            for item in batch:
                if skip_past is not None and sk_of(item) >= skip_past:
                    continue
                yield item
            last = sk_of(batch[-1])
            if len(batch) < 1000:
                return
            if reverse:
                cursor, skip_past = last.encode(), last
            else:
                cursor, skip_past = last.encode() + b"\x00", None

    def _iter_partition(self, part_pk: bytes, begin_bytes, filt, reverse):
        return self._iter_range(
            self.garage.k2v_item_table, part_pk, begin_bytes, filt, reverse,
            lambda item: item.sort_key,
        )

    async def _delete_batch(self, bucket_id, request) -> web.Response:
        """DeleteBatch with the reference query shape (batch.rs
        DeleteBatchQuery): prefix, start, end, singleItem — streamed over
        the full range via the shared enumeration."""
        body = json.loads(await request.read())
        # validate EVERY query item before mutating anything — a malformed
        # later entry must not leave earlier deletions half-applied
        for d in body:
            d["partitionKey"]  # KeyError -> 400 before any delete
            if d.get("singleItem") and d.get("start") is None:
                raise ValueError("singleItem requires start")
        deleted = []
        for d in body:
            pk = d["partitionKey"]
            prefix = d.get("prefix")
            start = d.get("start")
            end = d.get("end")
            single = d.get("singleItem", False)
            n = 0
            if single:
                item = await self.garage.k2v_item_table.get(
                    bucket_id + pk.encode(), start.encode()
                )
                if item is not None and not item.is_tombstone():
                    await self.garage.k2v_rpc.insert(
                        bucket_id, pk, start, item.causal_context(), None
                    )
                    n = 1
            else:
                # collect tombstones and flush in bounded-concurrency
                # batches — one sequential quorum RPC per item would make
                # big range deletes N x RTT
                pending: list = []
                async for item in self._iter_partition(
                    bucket_id + pk.encode(),
                    self._range_begin(prefix, start, False),
                    "present",
                    False,
                ):
                    sk = item.sort_key
                    if prefix is not None and not sk.startswith(prefix):
                        if sk > prefix:
                            break
                        continue
                    if end is not None and sk >= end:
                        break
                    pending.append((pk, sk, item.causal_context(), None))
                    n += 1
                    if len(pending) >= 256:
                        await self.garage.k2v_rpc.insert_batch(bucket_id, pending)
                        pending = []
                if pending:
                    await self.garage.k2v_rpc.insert_batch(bucket_id, pending)
            deleted.append({"partitionKey": pk, "deletedItems": n})
        return web.json_response(deleted)


def _token_of(request) -> CausalContext | None:
    tok = request.headers.get(TOKEN_HEADER)
    return CausalContext.parse(tok) if tok else None


def _req(cond: bool) -> None:
    if not cond:
        raise Forbidden("access denied")