"""Bucket configuration subresources: website, CORS, lifecycle
(reference src/api/s3/{website,cors,lifecycle}.rs).

Configs are stored as LWW registers in the bucket params and consumed by
the web server (website/CORS) and the lifecycle worker.
"""

from __future__ import annotations

import xml.etree.ElementTree as ET

from aiohttp import web

from ..common.error import ApiError, BadRequest
from .xml_util import xml_doc


def _tag(e):  # strip xmlns
    return e.tag.rsplit("}", 1)[-1]


async def _read_checked(request, ctx) -> bytes:
    body = await request.read()
    if ctx is not None:
        from ..common.signature import check_payload

        await check_payload(body, ctx)
    return body


def _parse(body: bytes):
    try:
        return ET.fromstring(body.decode())
    except ET.ParseError as e:
        raise BadRequest(f"malformed XML: {e}") from e


# --- website ------------------------------------------------------------------

async def handle_put_website(garage, bucket, request, ctx=None):
    root = _parse(await _read_checked(request, ctx))
    index = error = None
    for e in root.iter():
        if _tag(e) == "IndexDocument":
            for c in e:
                if _tag(c) == "Suffix":
                    index = c.text
        if _tag(e) == "ErrorDocument":
            for c in e:
                if _tag(c) == "Key":
                    error = c.text
    if not index:
        raise BadRequest("IndexDocument.Suffix is required")
    bucket.params().website.update({"index_document": index, "error_document": error})
    await garage.bucket_table.insert(bucket)
    return web.Response(status=200)


async def handle_get_website(garage, bucket, request):
    w = bucket.params().website.get()
    if not w:
        raise ApiError(
            "no website configuration", code="NoSuchWebsiteConfiguration", status=404
        )
    children = [("IndexDocument", [("Suffix", w["index_document"])])]
    if w.get("error_document"):
        children.append(("ErrorDocument", [("Key", w["error_document"])]))
    return web.Response(
        text=xml_doc("WebsiteConfiguration", children), content_type="application/xml"
    )


async def handle_delete_website(garage, bucket, request):
    bucket.params().website.update(None)
    await garage.bucket_table.insert(bucket)
    return web.Response(status=204)


# --- CORS ---------------------------------------------------------------------

async def handle_put_cors(garage, bucket, request, ctx=None):
    root = _parse(await _read_checked(request, ctx))
    rules = []
    for e in root:
        if _tag(e) != "CORSRule":
            continue
        rule = {"origins": [], "methods": [], "headers": [], "expose": [], "max_age": None}
        for c in e:
            t = _tag(c)
            if t == "AllowedOrigin":
                rule["origins"].append(c.text)
            elif t == "AllowedMethod":
                rule["methods"].append(c.text)
            elif t == "AllowedHeader":
                rule["headers"].append(c.text)
            elif t == "ExposeHeader":
                rule["expose"].append(c.text)
            elif t == "MaxAgeSeconds":
                rule["max_age"] = int(c.text)
        rules.append(rule)
    bucket.params().cors.update(rules)
    await garage.bucket_table.insert(bucket)
    return web.Response(status=200)


async def handle_get_cors(garage, bucket, request):
    rules = bucket.params().cors.get()
    if not rules:
        raise ApiError("no CORS configuration", code="NoSuchCORSConfiguration", status=404)
    children = []
    for r in rules:
        rc = (
            [("AllowedOrigin", o) for o in r["origins"]]
            + [("AllowedMethod", m) for m in r["methods"]]
            + [("AllowedHeader", h) for h in r["headers"]]
            + [("ExposeHeader", h) for h in r["expose"]]
        )
        if r.get("max_age") is not None:
            rc.append(("MaxAgeSeconds", r["max_age"]))
        children.append(("CORSRule", rc))
    return web.Response(
        text=xml_doc("CORSConfiguration", children), content_type="application/xml"
    )


async def handle_delete_cors(garage, bucket, request):
    bucket.params().cors.update(None)
    await garage.bucket_table.insert(bucket)
    return web.Response(status=204)


def find_matching_cors_rule(params, origin: str, method: str) -> dict | None:
    rules = params.cors.get() or []
    for r in rules:
        if method not in r["methods"] and "*" not in r["methods"]:
            continue
        for o in r["origins"]:
            if o == "*" or o == origin:
                return r
            if "*" in o:
                pre, _, suf = o.partition("*")
                if origin.startswith(pre) and origin.endswith(suf):
                    return r
    return None


def add_cors_headers(resp, rule: dict, origin: str) -> None:
    resp.headers["Access-Control-Allow-Origin"] = (
        "*" if "*" in rule["origins"] else origin
    )
    resp.headers["Access-Control-Allow-Methods"] = ", ".join(rule["methods"])
    if rule["headers"]:
        resp.headers["Access-Control-Allow-Headers"] = ", ".join(rule["headers"])
    if rule["expose"]:
        resp.headers["Access-Control-Expose-Headers"] = ", ".join(rule["expose"])
    if rule.get("max_age") is not None:
        resp.headers["Access-Control-Max-Age"] = str(rule["max_age"])


# --- lifecycle ----------------------------------------------------------------

async def handle_put_lifecycle(garage, bucket, request, ctx=None):
    root = _parse(await _read_checked(request, ctx))
    rules = []
    for e in root:
        if _tag(e) != "Rule":
            continue
        rule = {
            "id": None, "enabled": True, "prefix": "",
            "expiration_days": None, "expiration_date": None,
            "abort_mpu_days": None,
        }
        for c in e:
            t = _tag(c)
            if t == "ID":
                rule["id"] = c.text
            elif t == "Status":
                rule["enabled"] = c.text == "Enabled"
            elif t == "Prefix":
                rule["prefix"] = c.text or ""
            elif t == "Filter":
                for f in c.iter():
                    if _tag(f) == "Prefix":
                        rule["prefix"] = f.text or ""
            elif t == "Expiration":
                for f in c:
                    if _tag(f) == "Days":
                        rule["expiration_days"] = int(f.text)
                    elif _tag(f) == "Date":
                        rule["expiration_date"] = f.text
            elif t == "AbortIncompleteMultipartUpload":
                for f in c:
                    if _tag(f) == "DaysAfterInitiation":
                        rule["abort_mpu_days"] = int(f.text)
        if rule["expiration_days"] is not None and rule["expiration_days"] <= 0:
            raise BadRequest("Expiration.Days must be positive")
        rules.append(rule)
    bucket.params().lifecycle.update(rules)
    await garage.bucket_table.insert(bucket)
    return web.Response(status=200)


async def handle_get_lifecycle(garage, bucket, request):
    rules = bucket.params().lifecycle.get()
    if not rules:
        raise ApiError(
            "no lifecycle configuration",
            code="NoSuchLifecycleConfiguration",
            status=404,
        )
    children = []
    for r in rules:
        rc = [
            ("ID", r["id"] or ""),
            ("Status", "Enabled" if r["enabled"] else "Disabled"),
            ("Filter", [("Prefix", r["prefix"])]),
        ]
        if r["expiration_days"] is not None:
            rc.append(("Expiration", [("Days", r["expiration_days"])]))
        if r["expiration_date"]:
            rc.append(("Expiration", [("Date", r["expiration_date"])]))
        if r["abort_mpu_days"] is not None:
            rc.append(
                (
                    "AbortIncompleteMultipartUpload",
                    [("DaysAfterInitiation", r["abort_mpu_days"])],
                )
            )
        children.append(("Rule", rc))
    return web.Response(
        text=xml_doc("LifecycleConfiguration", children),
        content_type="application/xml",
    )


async def handle_delete_lifecycle(garage, bucket, request):
    bucket.params().lifecycle.update(None)
    await garage.bucket_table.insert(bucket)
    return web.Response(status=204)