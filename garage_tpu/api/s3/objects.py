"""Object read/write handlers: the S3 hot paths.

PutObject (reference src/api/s3/put.rs): chunk the body at block_size;
objects <= INLINE_THRESHOLD live inline in the object entry; larger
objects get an Uploading version, blocks stored with bounded parallelism
(PUT_BLOCKS_MAX_PARALLEL in flight), block refs + version entries written
as we go, then the version flips to Complete.  A failure marks the
version Aborted (cleanup cascade deletes blocks).

GetObject (reference src/api/s3/get.rs): resolve the newest complete
version; inline data answers immediately; block lists stream with
prefetch of the next block while the current one is sent; Range requests
slice the block list.
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import os

from aiohttp import web

from ...block.manager import INLINE_THRESHOLD
from ...net.message import PRIO_HIGH
from ...model.s3.block_ref_table import BlockRef
from ...model.s3.object_table import Object, ObjectVersion
from ...model.s3.version_table import Version
from ...utils.aio import reap
from ...utils.crdt import CrdtMap
from ...utils.data import blake2sum, gen_uuid
from ...utils.latency import mark_op, phase_span
from ...utils.time_util import now_msec
from ..common.error import (
    ApiError,
    InvalidRange,
    NoSuchKey,
    PreconditionFailed,
)

logger = logging.getLogger("garage.api.s3")

PUT_BLOCKS_MAX_PARALLEL = 3  # reference put.rs:42

SAVED_HEADERS = [
    "content-type",
    "content-encoding",
    "content-language",
    "content-disposition",
    "cache-control",
    "expires",
]


def extract_meta_headers(request) -> list[list[str]]:
    """Object metadata persisted with a version: the standard
    SAVED_HEADERS plus every x-amz-meta-* user-metadata header
    (reference put.rs:668-677).  aws-chunked is transport framing, not
    object metadata — the stored body is the decoded plaintext."""
    headers = [
        [h, request.headers[h_orig]]
        for h in SAVED_HEADERS
        for h_orig in [next((k for k in request.headers if k.lower() == h), None)]
        if h_orig
    ]
    headers = [
        [h, ",".join(t for t in v.split(",") if t.strip() != "aws-chunked")]
        for h, v in headers
        if not (h == "content-encoding" and v.strip() == "aws-chunked")
    ]
    for k, v in request.headers.items():
        kl = k.lower()
        if kl.startswith("x-amz-meta-"):
            headers.append([kl, v])
    return headers


async def _read_at_least(body, n: int) -> bytes:
    """Read until >= n bytes or EOF (StreamReader.read(n) may return any
    currently-buffered amount — trusting one read truncates uploads)."""
    buf = b""
    while len(buf) < n:
        chunk = await body.read(n - len(buf))
        if not chunk:
            break
        buf += chunk
    return buf


def _check_sha256(ctx, digest: "hashlib._Hash") -> None:
    if ctx is not None and ctx.content_sha256 is not None:
        if digest.hexdigest() != ctx.content_sha256:
            from ..common.error import BadRequest

            raise BadRequest(
                "payload sha256 does not match x-amz-content-sha256",
                code="XAmzContentSHA256Mismatch",
            )


# canonical implementation lives with the CRDT it protects
from ...model.s3.object_table import next_timestamp  # noqa: E402,F401


async def check_quotas(
    garage, bucket_id: bytes, key: str, new_size: int, existing=None
) -> None:
    """Enforce bucket quotas against the distributed counters, crediting
    the object being overwritten (reference put.rs:315 check_quotas).
    `existing` skips a second quorum read when the caller already has the
    object row."""
    bucket = await garage.helper.get_bucket(bucket_id)
    q = bucket.params().quotas.get() or {}
    if not q.get("max_size") and not q.get("max_objects"):
        return
    counts = await garage.object_counter.get_values(bucket_id)
    prev_objects = prev_bytes = 0
    if existing is None:
        existing = await garage.object_table.get(bucket_id, key.encode())
    if existing is not None:
        vis = existing.last_visible()
        if vis is not None:
            prev_objects = 1
            prev_bytes = vis.data.get("meta", {}).get("size", 0)
    if q.get("max_objects") is not None:
        if counts.get("objects", 0) - prev_objects + 1 > q["max_objects"]:
            raise ApiError("object count quota exceeded", code="QuotaExceeded", status=403)
    if q.get("max_size") is not None:
        if counts.get("bytes", 0) - prev_bytes + new_size > q["max_size"]:
            raise ApiError("size quota exceeded", code="QuotaExceeded", status=403)



def _absorb_hashes_sync(block: bytes, md5, sha, extra_hash) -> None:
    """Chain the request-level digests over one block — CPU-bound, so
    large blocks run it via asyncio.to_thread (the digest objects are
    only ever advanced from the sequential read loop; hashlib releases
    the GIL on large buffers)."""
    md5.update(block)
    sha.update(block)
    if extra_hash is not None:
        extra_hash.update(block)


def _prep_block_sync(block: bytes, transform) -> tuple[bytes, bytes]:
    """(stored_bytes, block_hash) — SSE transform + content hash, the
    CPU-bound head of put_one (to_thread above the offload threshold)."""
    stored = transform(block) if transform else block
    return stored, blake2sum(stored)


async def stream_blocks(
    garage, vid: bytes, bucket_id: bytes, key: str, part_number: int,
    body, block_size: int, first: bytes = b"", transform=None, extra_hash=None,
):
    """THE block-write pipeline shared by PutObject and UploadPart:
    chunk the body, store blocks with bounded parallelism
    (PUT_BLOCKS_MAX_PARALLEL), record version block entries + block refs
    as we go.  Returns (md5_hex, sha_obj, total_bytes); on failure the
    caller is responsible for tombstoning `vid`.

    Pipelining: block N's CPU work (hash, SSE, codec encode — all off
    the event loop) overlaps block N-1's fan-out, because up to
    PUT_BLOCKS_MAX_PARALLEL put_one tasks run concurrently and none of
    their stages blocks the loop anymore.  The
    `api_s3_overlap_efficiency{op="put"}` gauge (utils/latency.py) is
    the direct measure: 1.0 = the old strictly-sequential pipeline,
    below 1.0 = the stages genuinely overlap."""
    md5 = hashlib.md5()
    sha = hashlib.sha256()
    total = 0
    offset = 0
    offload_min = garage.config.block.cpu_offload_min_bytes
    inflight: set[asyncio.Task] = set()
    # every committed block entry, for the caller's version-cache warm
    # (the union of these IS the quorum-committed version row)
    committed_blocks: list[tuple[int, int, bytes, int]] = []

    async def put_meta(h: bytes, stored_len: int, block_offset: int):
        with phase_span("meta_commit"):
            v = Version(vid, bucket_id, key)
            v.blocks.put(
                [part_number, block_offset], {"h": h, "s": stored_len}
            )
            # independent tables: commit both rows in one round-trip
            # window instead of two sequential quorum waits
            await asyncio.gather(
                garage.version_table.insert(v),
                garage.block_ref_table.insert(BlockRef(h, vid)),
            )
            committed_blocks.append(
                (part_number, block_offset, h, stored_len)
            )

    async def put_one(block: bytes, block_offset: int):
        with phase_span("hash"):
            if len(block) >= offload_min:
                stored, h = await asyncio.to_thread(
                    _prep_block_sync, block, transform
                )
            else:
                stored, h = _prep_block_sync(block, transform)
        # block fan-out and meta rows commit CONCURRENTLY (reference
        # put.rs put_block_and_meta's try_join!): the meta quorum wait
        # used to serialize after the piece quorum wait, ~doubling the
        # per-block critical path.  Failure of either leg raises out of
        # stream_blocks and the caller's tombstone (version aborted /
        # deleted marker) cascades the cleanup of whichever half landed.
        await asyncio.gather(
            garage.block_manager.rpc_put_block(h, stored),
            put_meta(h, len(stored), block_offset),
        )

    async def launch(block: bytes, block_offset: int):
        # backpressure: at most PUT_BLOCKS_MAX_PARALLEL blocks buffered in
        # flight — the read loop (and the client) stall while storage
        # catches up (reference put.rs:42)
        while len(inflight) >= PUT_BLOCKS_MAX_PARALLEL:
            done, _ = await asyncio.wait(inflight, return_when=asyncio.FIRST_COMPLETED)
            for t in done:
                inflight.discard(t)
                # result() re-raises with the task's own traceback —
                # `raise t.exception()` raised a bare instance whose
                # context started HERE, losing the put_one frames
                t.result()
        inflight.add(asyncio.create_task(put_one(block, block_offset)))

    async def absorb(block: bytes) -> None:
        with phase_span("hash"):
            if len(block) >= offload_min:
                await asyncio.to_thread(
                    _absorb_hashes_sync, block, md5, sha, extra_hash
                )
            else:
                _absorb_hashes_sync(block, md5, sha, extra_hash)

    try:
        buf = first
        while True:
            while len(buf) >= block_size:
                block, buf = buf[:block_size], buf[block_size:]
                await absorb(block)
                await launch(block, offset)
                offset += len(block)
                total += len(block)
            with phase_span("chunk"):
                chunk = await body.read(block_size)
            if not chunk:
                break
            buf += chunk
        if buf:
            await absorb(buf)
            await launch(buf, offset)
            total += len(buf)
        if inflight:
            await asyncio.gather(*inflight)
    except BaseException:
        # cancel + DRAIN: a bare t.cancel() abandoned the in-flight
        # tasks mid-write — their exceptions surfaced as never-retrieved
        # warnings and a cancelled put could still be touching the
        # version table while the caller tombstoned it
        await reap(inflight, log=logger, what="put-block task")
        raise
    return md5.hexdigest(), sha, total, committed_blocks


async def handle_put_object(
    garage, bucket_id: bytes, key: str, request, ctx=None
) -> web.Response:
    from ..common.checksum import ChecksumRequest
    from .encryption import EncryptionParams

    mark_op("put")
    enc = EncryptionParams.from_headers(request.headers)
    cks = ChecksumRequest.from_headers(request.headers)
    headers = extract_meta_headers(request)
    body = request.content
    block_size = garage.config.block_size
    with phase_span("index_read"):
        existing = await garage.object_table.get(bucket_id, key.encode())
    ts = next_timestamp(existing)

    with phase_span("chunk"):
        first = await _read_at_least(body, INLINE_THRESHOLD + 1)
    if len(first) <= INLINE_THRESHOLD:
        # inline object
        with phase_span("hash"):
            sha = hashlib.sha256(first)
        _check_sha256(ctx, sha)
        with phase_span("index_read"):
            await check_quotas(
                garage, bucket_id, key, len(first), existing=existing
            )
        etag = hashlib.md5(first).hexdigest()
        meta = {"size": len(first), "etag": etag, "headers": headers}
        if cks is not None:
            cks.update(first)
            if cks.expected_b64 is None:
                cks.resolve_trailer(getattr(body, "trailers", {}) or {})
            meta["cks"] = cks.verify()
        stored = first
        if enc is not None:
            stored = enc.encrypt_block(first)
            meta["enc"] = enc.meta()
        version = ObjectVersion(
            gen_uuid(),
            ts,
            "complete",
            {"t": "inline", "bytes": stored, "meta": meta},
        )
        with phase_span("meta_commit"):
            await garage.object_table.insert(
                Object(bucket_id, key, [version])
            )
        resp_headers = {"ETag": f'"{etag}"'}
        if enc is not None:
            resp_headers.update(enc.response_headers())
        return web.Response(status=200, headers=resp_headers)

    # multi-block object
    vid = gen_uuid()
    version0 = ObjectVersion(vid, ts, "uploading", {"t": "first_block", "vid": vid})
    with phase_span("meta_commit"):
        # independent tables: one quorum round-trip window, not two
        await asyncio.gather(
            garage.object_table.insert(Object(bucket_id, key, [version0])),
            garage.version_table.insert(Version(vid, bucket_id, key)),
        )
    buf_first = first

    in_indeterminate_zone = False
    try:
        md5_hex, sha, total, committed_blocks = await stream_blocks(
            garage, vid, bucket_id, key, 0, body, block_size, first=buf_first,
            transform=enc.encrypt_block if enc else None, extra_hash=cks,
        )
        _check_sha256(ctx, sha)
        if cks is not None and cks.expected_b64 is None:
            cks.resolve_trailer(getattr(body, "trailers", {}) or {})
        with phase_span("index_read"):
            await check_quotas(
                garage, bucket_id, key, total, existing=existing
            )

        etag = md5_hex
        meta = {"size": total, "etag": etag, "headers": headers}
        if cks is not None:
            meta["cks"] = cks.verify()
        if enc is not None:
            meta["enc"] = enc.meta()
        final = ObjectVersion(
            vid, ts, "complete",
            {"t": "first_block", "vid": vid, "meta": meta},
        )
        # INDETERMINATE ZONE — do not abort past this point.  A quorum
        # timeout on the final insert can leave the "complete" row on a
        # MINORITY of nodes: their CRDT prune then drops the previous
        # version and cascades its version-table deletion.  If we then
        # inserted "aborted" (which beats "complete" in the state
        # order), the new version un-completes everywhere while the old
        # one's data is already tombstoned — the last ACKED write 404s
        # ("version data missing") with nothing left to heal it.  The
        # jepsen combined-nemeses flake under CPU load was exactly this
        # (pinned repro: tests/test_model.py
        # test_put_overwrite_indeterminate_complete_not_aborted).  At
        # this point every block and version row is quorum-committed, so
        # the safe failure mode is to LEAVE the uploading row (pruned by
        # the next successful overwrite) and return 500 — at-least-once,
        # never un-complete.  See doc/metadata-replication.md.
        in_indeterminate_zone = True
        with phase_span("meta_commit"):
            await garage.object_table.insert(Object(bucket_id, key, [final]))
        # warm the metadata fast path: the union of the per-block rows
        # this request quorum-committed IS the version row a GET would
        # read — the next GET of this key skips the version quorum read.
        # One-shot CrdtMap construction (single sort): per-block put()
        # re-merges the whole map each time, O(n^2 log n) on a
        # many-thousand-block PUT, synchronously on the event loop.
        full_v = Version(vid, bucket_id, key)
        full_v.blocks = CrdtMap(
            [([pn, off], {"h": h, "s": sz})
             for pn, off, h, sz in committed_blocks]
        )
        garage.version_cache.put(vid, full_v)
        resp_headers = {"ETag": f'"{etag}"'}
        if enc is not None:
            resp_headers.update(enc.response_headers())
        return web.Response(status=200, headers=resp_headers)
    except BaseException:
        if in_indeterminate_zone:
            raise
        # InterruptedCleanup (reference put.rs:217-223): mark aborted so
        # the cascade reclaims stored blocks
        aborted = ObjectVersion(vid, ts, "aborted", {"t": "first_block", "vid": vid})
        try:
            await garage.object_table.insert(Object(bucket_id, key, [aborted]))
        except Exception:  # noqa: BLE001
            logger.exception("failed to mark aborted upload")
        raise


def _pick_version(obj: Object | None) -> ObjectVersion:
    if obj is None:
        raise NoSuchKey("object not found")
    v = obj.last_visible()
    if v is None:
        raise NoSuchKey("object not found")
    return v


def _meta_headers(version: ObjectVersion) -> dict[str, str]:
    from ..common.checksum import response_headers as _cks_headers

    meta = version.data.get("meta", {})
    out = {
        "ETag": f'"{meta.get("etag", "")}"',
        "Content-Length": str(meta.get("size", 0)),
        "Last-Modified": _http_date(version.timestamp),
        "x-amz-version-id": version.uuid.hex(),
        "Accept-Ranges": "bytes",
    }
    for name, value in meta.get("headers", []):
        out[name.title()] = value
    out.update(_cks_headers(meta))
    return out


def _http_date(ts_ms: int) -> str:
    from datetime import datetime, timezone

    dt = datetime.fromtimestamp(ts_ms / 1000, tz=timezone.utc)
    return dt.strftime("%a, %d %b %Y %H:%M:%S GMT")


def _parse_http_date(s: str) -> float:
    from email.utils import parsedate_to_datetime

    from ..common.error import BadRequest

    try:
        return parsedate_to_datetime(s).timestamp()
    except (TypeError, ValueError) as e:
        raise BadRequest(f"invalid HTTP date {s!r}") from e


class Preconditions:
    """RFC 7232 §6 conditional evaluation (reference get.rs:783-885),
    shared by GET/HEAD and the x-amz-copy-source-if-* variants."""

    __slots__ = ("if_match", "if_none_match", "if_modified_since",
                 "if_unmodified_since")

    _HDRS = ("If-Match", "If-None-Match", "If-Modified-Since",
             "If-Unmodified-Since")
    _COPY_HDRS = tuple(f"x-amz-copy-source-{h.lower()}" for h in _HDRS)

    def __init__(self, headers, names):
        im, inm, ims, ius = (headers.get(n) for n in names)
        etags = lambda v: [e.strip().strip('"') for e in v.split(",")]  # noqa: E731
        self.if_match = etags(im) if im is not None else None
        self.if_none_match = etags(inm) if inm is not None else None
        self.if_modified_since = _parse_http_date(ims) if ims else None
        self.if_unmodified_since = _parse_http_date(ius) if ius else None

    @classmethod
    def parse(cls, request) -> "Preconditions":
        return cls(request.headers, cls._HDRS)

    @classmethod
    def parse_copy_source(cls, request) -> "Preconditions":
        return cls(request.headers, cls._COPY_HDRS)

    def check(self, version: ObjectVersion) -> int | None:
        """Returns 304/412 when a precondition short-circuits, else None."""
        etag = version.data.get("meta", {}).get("etag", "")
        v_date = version.timestamp / 1000.0
        if self.if_match is not None:
            if not any(x == etag or x == "*" for x in self.if_match):
                return 412
        elif self.if_unmodified_since is not None:
            if v_date > self.if_unmodified_since:
                return 412
        if self.if_none_match is not None:
            if any(x == etag or x == "*" for x in self.if_none_match):
                return 304
        elif self.if_modified_since is not None:
            if v_date <= self.if_modified_since:
                return 304
        return None

    def check_copy_source(self, version: ObjectVersion) -> None:
        if self.check(version) is not None:
            raise PreconditionFailed("copy source precondition failed")


def _check_conditionals(request, version: ObjectVersion) -> None:
    status = Preconditions.parse(request).check(version)
    if status == 304:
        raise ApiError("not modified", code="NotModified", status=304)
    if status == 412:
        raise PreconditionFailed("precondition failed")


def _parse_range(request, size: int) -> tuple[int, int] | None:
    rng = request.headers.get("Range")
    if not rng or not rng.startswith("bytes="):
        return None
    spec = rng[len("bytes="):].split(",")[0].strip()
    start_s, _, end_s = spec.partition("-")
    try:
        if start_s == "":  # suffix range: last N bytes
            n = int(end_s)
            if n <= 0:
                raise InvalidRange("empty suffix range")
            return (max(0, size - n), size)
        start = int(start_s)
        end = int(end_s) + 1 if end_s else size
    except ValueError as e:
        raise InvalidRange(f"bad Range: {rng!r}") from e
    if start >= size or start >= end:
        raise InvalidRange(f"range {rng!r} outside object of size {size}")
    return (start, min(end, size))


def _plain_len(blk: dict, enc_params) -> int:
    from .encryption import OVERHEAD

    return blk["s"] - (OVERHEAD if enc_params is not None else 0)


def part_bounds(blocks, part_number: int, enc_params) -> tuple[int, int] | None:
    """Plaintext [begin, end) extent of a stored part (reference
    get.rs:620-633 calculate_part_bounds), or None if no such part."""
    offset = 0
    begin = None
    for (pn, _off), blk in blocks:
        if pn == part_number and begin is None:
            begin = offset
        elif pn != part_number and begin is not None:
            return (begin, offset)
        offset += _plain_len(blk, enc_params)
    return (begin, offset) if begin is not None else None


# depth 8 fully hides a 2ms inter-node RTT at 64 KiB blocks (bench_s3
# --bigget sweep: depth 1 = 3.7s, 4 = 1.9s, 8 = 1.15s = local floor for
# a 100 MiB object).  Per-GET RAM is bounded by depth x block_size
# (fetched-but-unconsumed window); transfer-time RAM is additionally
# under the shared ByteBudget inside rpc_get_block.  The window blocks
# must NOT hold shared-budget reservations while parked: consumption
# order differs from acquisition order across concurrent GETs, which
# deadlocks a contended budget.
GET_PREFETCH_DEPTH = max(1, int(os.environ.get("GARAGE_GET_PREFETCH", "8")))


async def plain_block_stream(garage, blocks, start: int, end: int, enc_params):
    """Async generator of plaintext chunks covering [start, end) of a
    version's block list (the GET hot loop, reference get.rs:650-760) —
    shared by GetObject and UploadPartCopy.

    Prefetches GET_PREFETCH_DEPTH blocks ahead so a multi-block read
    streams back-to-back instead of paying one RPC round-trip per block;
    the fetches ride one OrderTag sub-stream, so the storage side
    transmits them in order (reference net/message.rs:62-89 +
    get.rs:650-760 pipeline)."""
    wanted: list[tuple[int, int, bytes]] = []
    pos = 0
    for (_part, _off), blk in blocks:
        b_start, b_end = pos, pos + _plain_len(blk, enc_params)
        pos = b_end
        if b_end <= start or b_start >= end:
            continue
        wanted.append((b_start, b_end, blk["h"]))

    from ...net.message import new_order_stream

    bm = garage.block_manager
    tag_stream = new_order_stream()
    reads: list = []
    nxt = 0
    try:
        for i, (b_start, b_end, _h) in enumerate(wanted):
            while nxt < len(wanted) and nxt < i + GET_PREFETCH_DEPTH:
                # tags allocate in spawn order == block order.
                # PRIO_HIGH: interactive GET is the top admission tier
                # (api/overload.py), and its piece fetches must outrank
                # PUT fan-out (PRIO_NORMAL) and background resync
                # (PRIO_BACKGROUND) at the connection scheduler too —
                # the RPC-level mirror of the HTTP priority classes.
                # start_block_read begins fetching NOW: block i's
                # systematic pieces stream out below while blocks
                # i+1..i+depth gather theirs (ISSUE 13).
                reads.append(
                    bm.start_block_read(
                        wanted[nxt][2], prio=PRIO_HIGH,
                        order_tag=tag_stream.order(),
                    )
                )
                nxt += 1
            br = reads[i]
            lo = max(start - b_start, 0)
            hi = min(end, b_end) - b_start
            if enc_params is not None:
                # SSE blocks only decrypt whole: assemble, then slice
                data = enc_params.decrypt_block(await br.bytes())
                yield data[lo:hi]
                del data
            else:
                # stream chunks as the block's pieces land, clipped to
                # the requested [lo, hi) plaintext window
                pos = 0
                async for chunk in br.chunks():
                    c = chunk[max(lo - pos, 0): max(hi - pos, 0)]
                    pos += len(chunk)
                    if c:
                        yield c  # consumer records stream_out
                    del chunk
            reads[i] = None  # drop the handle: window RAM stays bounded
    finally:
        # consumer gone (disconnect) or error: abort every in-flight
        # prefetch, including the one currently consumed
        live = [r for r in reads if r is not None]

        async def _abort_reads(rs):
            # concurrent: teardown costs the slowest cancel, not the sum
            await asyncio.gather(*[r.abort() for r in rs])

        # ONE shielded coroutine for the aborts: a cancel landing
        # mid-drain re-raises at this await but every pump is still
        # reaped in the shielded task (graft-lint cancel-safety)
        if live:
            await asyncio.shield(_abort_reads(live))


def _parse_part_number(request) -> int | None:
    pn_s = request.query.get("partNumber")
    if pn_s is None:
        return None
    from ..common.error import BadRequest

    try:
        pn = int(pn_s)
    except ValueError as e:
        raise BadRequest(f"bad partNumber {pn_s!r}") from e
    if not 1 <= pn <= 10000:
        raise BadRequest("partNumber must be in 1..10000")
    if "Range" in request.headers:
        raise BadRequest("cannot specify both partNumber and Range")
    return pn


async def _escalate_version_missing(garage, bucket_id, key, stale):
    """The object row resolved a version whose version-table row is
    tombstoned or absent.  The legitimate cause (pinned by
    tests/test_put_abort_race.py, the jepsen `404 version data missing`
    lead): an indeterminate overwrite landed its "complete" row on a
    minority of object replicas, and that minority's CRDT prune cascade
    tombstoned OUR version's row at quorum speed — so quorum reads that
    skip the minority replica keep resolving a version with no data.
    Recovery: merge the object row from EVERY reachable replica
    (read-repairing the merge back), and serve the newer version it
    surfaces.  If the full merge still resolves the same version, the
    data is genuinely gone — 404."""
    with phase_span("index_read"):
        obj = await garage.object_table.get_merged_all(
            bucket_id, key.encode()
        )
    version = _pick_version(obj)
    if version.data.get("t") == "inline":
        return version, None
    if bytes(version.data.get("vid", b"")) == bytes(
        stale.data.get("vid", b"")
    ):
        raise NoSuchKey("version data missing")
    with phase_span("index_read"):
        ver = await garage.version_table.get(version.data["vid"], b"")
    if ver is None or ver.deleted.get():
        raise NoSuchKey("version data missing")
    return version, ver


async def handle_get_object(
    garage,
    bucket_id: bytes,
    key: str,
    request,
    head_only: bool = False,
    allow_overrides: bool = True,
) -> web.StreamResponse:
    from .encryption import EncryptionParams, check_match

    mark_op("head" if head_only else "get")
    part_number = _parse_part_number(request)
    with phase_span("index_read"):
        obj = await garage.object_table.get(bucket_id, key.encode())
    version = _pick_version(obj)
    blocks = None
    # plain HEAD never needs the block list — don't pay a version-table
    # quorum read on that hot path
    if version.data.get("t") != "inline" and (
        part_number is not None or not head_only
    ):
        # metadata fast path: a visible complete version's row is
        # immutable (VersionRowCache safety argument), so repeat GETs
        # skip the second quorum read entirely
        vid = bytes(version.data["vid"])
        ver = garage.version_cache.get(vid)
        if ver is None:
            with phase_span("index_read"):
                ver = await garage.version_table.get(vid, b"")
            if ver is not None and not ver.deleted.get():
                garage.version_cache.put(vid, ver)
        if ver is None or ver.deleted.get():
            # escalate before 404ing (tests/test_put_abort_race.py): a
            # newer complete overwrite may exist on a MINORITY of object
            # replicas, its prune cascade having tombstoned OUR version
            # at quorum speed while the staggered quorum read above
            # never consulted that replica
            version, ver = await _escalate_version_missing(
                garage, bucket_id, key, version
            )
        if ver is not None:
            blocks = ver.sorted_blocks()
    _check_conditionals(request, version)
    meta = version.data.get("meta", {})
    enc_params = EncryptionParams.from_headers(request.headers)
    check_match(meta.get("enc"), enc_params)
    size = meta.get("size", 0)
    headers = _meta_headers(version)
    if enc_params is not None:
        headers.update(enc_params.response_headers())

    # response-* query overrides (reference get.rs:100-117): SIGNED
    # requests only — on the anonymous website path a visitor-controlled
    # ?response-content-type would turn uploaded blobs into stored XSS
    if allow_overrides:
        for qname, hname in (
            ("response-cache-control", "Cache-Control"),
            ("response-content-disposition", "Content-Disposition"),
            ("response-content-encoding", "Content-Encoding"),
            ("response-content-language", "Content-Language"),
            ("response-content-type", "Content-Type"),
            ("response-expires", "Expires"),
        ):
            if qname in request.query:
                headers[hname] = request.query[qname]

    is_inline = version.data.get("t") == "inline"

    status = 200
    if part_number is not None:
        # part-number read (reference get.rs:144-190, 534-592): a ranged
        # read over the part's stored extent, with the parts count exposed
        if is_inline:
            if part_number != 1:
                raise ApiError("no such part", code="InvalidPart", status=400)
            rng = (0, size)
            n_parts = 1
        else:
            b = part_bounds(blocks, part_number, enc_params)
            if b is None:
                raise ApiError("no such part", code="InvalidPart", status=400)
            rng = b
            n_parts = len({pn for (pn, _off), _blk in blocks})
        headers["x-amz-mp-parts-count"] = str(n_parts)
        status = 206
    else:
        rng = _parse_range(request, size)
        if rng is not None:
            status = 206
    if rng is not None and status == 206:
        start, end = rng
        headers["Content-Range"] = f"bytes {start}-{end - 1}/{size}"
        headers["Content-Length"] = str(end - start)

    if head_only:
        return web.Response(status=status, headers=headers)

    if is_inline:
        data = version.data["bytes"]
        if enc_params is not None:
            data = enc_params.decrypt_block(data)
        if rng is not None:
            data = data[rng[0] : rng[1]]
        return web.Response(status=status, body=data, headers=headers)

    start, end = rng if rng is not None else (0, size)
    resp = web.StreamResponse(status=status, headers=headers)
    await resp.prepare(request)
    try:
        async for chunk in plain_block_stream(
            garage, blocks, start, end, enc_params
        ):
            with phase_span("stream_out"):
                await resp.write(chunk)
    except Exception as e:  # noqa: BLE001
        # 200 + Content-Length are already on the wire, so an error
        # document can no longer be sent — abort the connection so the
        # client sees a truncated transfer NOW instead of waiting out its
        # own timeout on a body that will never complete (the error
        # middleware would otherwise try to send a second response on
        # this same connection)
        logger.warning("aborting GET mid-stream: %r", e)
        resp.force_close()
        if request.transport is not None:
            request.transport.close()
        return resp
    await resp.write_eof()
    return resp


async def handle_delete_object(garage, bucket_id: bytes, key: str) -> web.Response:
    mark_op("delete")
    with phase_span("index_read"):
        obj = await garage.object_table.get(bucket_id, key.encode())
    if obj is None or obj.last_visible() is None:
        # deleting a non-existent object is a success in S3
        return web.Response(status=204)
    dm = ObjectVersion(
        gen_uuid(), next_timestamp(obj), "complete", {"t": "delete_marker"}
    )
    with phase_span("meta_commit"):
        await garage.object_table.insert(Object(bucket_id, key, [dm]))
    return web.Response(status=204)
