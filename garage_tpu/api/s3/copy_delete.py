"""CopyObject + DeleteObjects batch (reference src/api/s3/copy.rs,
delete.rs).

CopyObject is metadata-only for block objects: the new version references
the same content-addressed blocks (fresh block refs, no data movement) —
dedup makes server-side copy O(metadata).
"""

from __future__ import annotations

import urllib.parse
import xml.etree.ElementTree as ET

from aiohttp import web

from ...model.s3.block_ref_table import BlockRef
from ...model.s3.object_table import Object, ObjectVersion
from ...model.s3.version_table import Version
from ...utils.data import gen_uuid
from ..common.error import BadRequest, NoSuchKey
from .objects import handle_delete_object
from .xml_util import http_iso as _http_iso, xml_doc


async def resolve_copy_source(garage, helper, api_key, request):
    """Resolve x-amz-copy-source to its newest visible version, enforcing
    read permission and the x-amz-copy-source-if-* preconditions
    (reference copy.rs source resolution, shared with UploadPartCopy)."""
    from .objects import Preconditions

    src = urllib.parse.unquote(request.headers["x-amz-copy-source"])
    src = src.lstrip("/")
    if "/" not in src:
        raise BadRequest("x-amz-copy-source must be bucket/key")
    src_bucket_name, src_key = src.split("/", 1)
    src_bucket_id = await helper.resolve_bucket(src_bucket_name, api_key)
    perm = api_key.bucket_permissions(src_bucket_id)
    if not perm.allow_read:
        from ..common.error import Forbidden

        raise Forbidden("no read permission on copy source")

    obj = await garage.object_table.get(src_bucket_id, src_key.encode())
    sv = obj.last_visible() if obj else None
    if sv is None:
        raise NoSuchKey("copy source not found")
    Preconditions.parse_copy_source(request).check_copy_source(sv)
    return sv


async def handle_copy_object(garage, helper, api_key, dest_bucket_id, dest_key, request):
    from .objects import next_timestamp

    sv = await resolve_copy_source(garage, helper, api_key, request)
    meta = dict(sv.data.get("meta", {}))
    # x-amz-metadata-directive: REPLACE takes the new metadata from this
    # request instead of copying the source's (reference copy.rs:84-89);
    # etag/size stay with the (unchanged) content.  Unknown directive
    # values are rejected, not silently treated as COPY.
    directive = request.headers.get("x-amz-metadata-directive", "COPY").upper()
    if directive not in ("COPY", "REPLACE"):
        raise BadRequest(
            f"invalid x-amz-metadata-directive {directive!r}",
            code="InvalidArgument",
        )
    if directive == "REPLACE":
        from .objects import extract_meta_headers

        meta["headers"] = extract_meta_headers(request)
    dest_existing = await garage.object_table.get(dest_bucket_id, dest_key.encode())
    ts = next_timestamp(dest_existing)
    new_uuid = gen_uuid()

    if sv.data.get("t") == "inline":
        nv = ObjectVersion(
            new_uuid, ts, "complete",
            {"t": "inline", "bytes": sv.data["bytes"], "meta": meta},
        )
        await garage.object_table.insert(Object(dest_bucket_id, dest_key, [nv]))
    else:
        src_ver = await garage.version_table.get(bytes(sv.data["vid"]), b"")
        if src_ver is None or src_ver.deleted.get():
            raise NoSuchKey("copy source data missing")
        dst_ver = Version(new_uuid, dest_bucket_id, dest_key)
        for (pn, off), blk in src_ver.sorted_blocks():
            dst_ver.blocks.put([pn, off], {"h": blk["h"], "s": blk["s"]})
        await garage.version_table.insert(dst_ver)
        for _k, blk in dst_ver.sorted_blocks():
            await garage.block_ref_table.insert(BlockRef(blk["h"], new_uuid))
        nv = ObjectVersion(
            new_uuid, ts, "complete",
            {"t": "first_block", "vid": new_uuid, "meta": meta},
        )
        await garage.object_table.insert(Object(dest_bucket_id, dest_key, [nv]))

    return web.Response(
        text=xml_doc(
            "CopyObjectResult",
            [("LastModified", _http_iso(ts)), ("ETag", f'"{meta.get("etag", "")}"')],
        ),
        content_type="application/xml",
    )


async def handle_delete_objects(garage, bucket_id, request, ctx=None):
    body = await request.read()
    from ..common.signature import check_payload

    if ctx:
        await check_payload(body, ctx)
    try:
        root = ET.fromstring(body.decode())
    except ET.ParseError as e:
        raise BadRequest(f"malformed Delete XML: {e}") from e
    quiet = any(
        c.tag.endswith("Quiet") and (c.text or "").strip() == "true" for c in root
    )
    keys = []
    for obj in root.iter():
        if obj.tag.endswith("Object"):
            for c in obj:
                if c.tag.endswith("Key"):
                    keys.append(c.text)
    children = []
    for k in keys:
        try:
            await handle_delete_object(garage, bucket_id, k)
            if not quiet:
                children.append(("Deleted", [("Key", k)]))
        except Exception as e:  # noqa: BLE001
            children.append(
                (
                    "Error",
                    [("Key", k), ("Code", "InternalError"), ("Message", repr(e))],
                )
            )
    return web.Response(
        text=xml_doc("DeleteResult", children), content_type="application/xml"
    )