"""PostObject: browser-based form uploads (reference src/api/s3/
post_object.rs, 530 LoC).

A multipart/form-data POST to the bucket URL carrying a signed POLICY
document instead of a SigV4 Authorization header: the policy (base64
JSON) states expiration and conditions (bucket, key prefix/eq,
content-length-range, ...) and is signed with the same SigV4 key
derivation; the signature authenticates exactly that policy, so a web
page can let end users upload without holding credentials.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
from datetime import datetime, timezone

from aiohttp import web

from ..common.error import ApiError, BadRequest, Forbidden
from ..common.signature import signing_key
from .objects import handle_put_object

MAX_FIELD = 64 * 1024


async def handle_post_object(server, bucket_name: str, request) -> web.Response:
    reader = await request.multipart()
    fields: dict[str, str] = {}
    file_part = None
    while True:
        part = await reader.next()
        if part is None:
            break
        name = (part.name or "").lower()
        if name == "file":
            file_part = part
            break  # per the S3 spec, fields after `file` are ignored
        data = await part.read()
        if len(data) > MAX_FIELD:
            raise BadRequest(f"form field {name!r} too large")
        fields[name] = data.decode()
    if file_part is None:
        raise BadRequest("no file field in POST body")

    policy_b64 = fields.get("policy")
    if not policy_b64:
        raise Forbidden("POST without policy is not allowed")
    try:
        policy = json.loads(base64.b64decode(policy_b64))
    except Exception as e:
        raise BadRequest(f"malformed policy: {e}") from e

    # --- verify the policy signature -----------------------------------------
    try:
        cred = fields["x-amz-credential"].split("/")
        key_id, date, region, service = cred[0], cred[1], cred[2], cred[3]
        signature = fields["x-amz-signature"]
        algorithm = fields.get("x-amz-algorithm", "")
    except (KeyError, IndexError) as e:
        raise Forbidden(f"missing signature fields: {e}") from e
    if algorithm != "AWS4-HMAC-SHA256":
        raise BadRequest(f"unsupported x-amz-algorithm {algorithm!r}")
    secret = await server._get_secret(key_id)
    if secret is None:
        raise Forbidden(f"unknown access key {key_id}")
    key = signing_key(secret, date, region, service)
    expected = hmac.new(key, policy_b64.encode(), hashlib.sha256).hexdigest()
    if not hmac.compare_digest(expected, signature):
        raise Forbidden("policy signature does not match")

    # --- check policy conditions ----------------------------------------------
    try:
        exp_str = policy["expiration"].rstrip("Z").split(".")[0]
        exp = datetime.strptime(exp_str, "%Y-%m-%dT%H:%M:%S").replace(
            tzinfo=timezone.utc
        )
    except (KeyError, ValueError) as e:
        raise BadRequest(f"bad policy expiration: {e}") from e
    if datetime.now(timezone.utc) > exp:
        raise Forbidden("policy expired")

    object_key = fields.get("key", "")
    if "${filename}" in object_key:
        object_key = object_key.replace("${filename}", file_part.filename or "file")
    def field_value(name: str) -> str:
        if name == "bucket":
            return bucket_name
        if name == "key":
            return object_key
        return fields.get(name, "")

    length_range = None
    for cond in policy.get("conditions", []):
        if isinstance(cond, dict):
            for k, v in cond.items():
                if field_value(k.lower()) != v:
                    raise Forbidden(f"policy condition failed for {k}")
        elif isinstance(cond, list) and len(cond) == 3:
            op, name, val = cond[0], str(cond[1]).lstrip("$").lower(), cond[2]
            if op == "eq":
                if field_value(name) != val:
                    raise Forbidden(f"policy eq condition failed for {name}")
            elif op == "starts-with":
                if not field_value(name).startswith(val):
                    raise Forbidden(f"policy starts-with failed for {name}")
            elif op == "content-length-range":
                try:
                    length_range = (int(cond[1]), int(cond[2]))
                except (TypeError, ValueError) as e:
                    raise BadRequest(f"bad content-length-range: {e}") from e
    if not object_key:
        raise BadRequest("no key for POST upload")

    # --- authorization + store ------------------------------------------------
    api_key = await server.garage.helper.get_key(key_id)
    bucket_id = await server.garage.helper.resolve_bucket(bucket_name, api_key)
    if not api_key.bucket_permissions(bucket_id).allow_write:
        raise Forbidden("key has no write permission on this bucket")

    class _FormBody:
        """Adapts the file part to the .read(n) interface of the put path,
        enforcing content-length-range as bytes stream in."""

        def __init__(self, part, length_range):
            self.part = part
            self.range = length_range
            self.total = 0

        async def read(self, n: int) -> bytes:
            chunk = await self.part.read_chunk(n)
            self.total += len(chunk)
            if self.range and self.total > self.range[1]:
                raise ApiError(
                    "upload exceeds policy content-length-range",
                    code="EntityTooLarge",
                    status=400,
                )
            return chunk

    body = _FormBody(file_part, length_range)
    saved_headers = {}
    if "content-type" in fields:
        saved_headers["content-type"] = fields["content-type"]

    class _FakeRequest:
        content = body
        headers = saved_headers

    resp = await handle_put_object(server.garage, bucket_id, object_key, _FakeRequest())
    if length_range and body.total < length_range[0]:
        # the object was already stored: roll it back before failing
        from .objects import handle_delete_object

        await handle_delete_object(server.garage, bucket_id, object_key)
        raise ApiError(
            "upload below policy content-length-range",
            code="EntityTooSmall",
            status=400,
        )
    try:
        status = int(fields.get("success_action_status", "204"))
    except ValueError:
        status = 204
    if status not in (200, 201, 204):
        status = 204
    if status == 201:
        from .xml_util import xml_doc

        return web.Response(
            status=201,
            text=xml_doc(
                "PostResponse",
                [("Bucket", bucket_name), ("Key", object_key),
                 ("ETag", resp.headers.get("ETag", ""))],
            ),
            content_type="application/xml",
        )
    return web.Response(status=status, headers={"ETag": resp.headers.get("ETag", "")})
