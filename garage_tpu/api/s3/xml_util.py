"""Tiny XML response builder (reference src/api/s3/xml.rs uses serde;
here a minimal escaping tree-builder keeps responses readable)."""

from __future__ import annotations

from xml.sax.saxutils import escape

XMLNS = "http://s3.amazonaws.com/doc/2006-03-01/"


def xml_doc(root: str, children: list, xmlns: bool = True) -> str:
    attrs = f' xmlns="{XMLNS}"' if xmlns else ""
    return (
        '<?xml version="1.0" encoding="UTF-8"?>\n'
        f"<{root}{attrs}>{_render(children)}</{root}>"
    )


def _render(children) -> str:
    out = []
    for item in children:
        if item is None:
            continue
        name, value = item
        if name == "":
            # bare text content of the parent element (e.g. the region in
            # <LocationConstraint>garage</LocationConstraint>)
            out.append(escape(str(value)))
        elif isinstance(value, list):
            out.append(f"<{name}>{_render(value)}</{name}>")
        elif isinstance(value, bool):
            out.append(f"<{name}>{'true' if value else 'false'}</{name}>")
        else:
            out.append(f"<{name}>{escape(str(value))}</{name}>")
    return "".join(out)


def http_iso(ts_ms: int) -> str:
    """ISO-8601 object timestamp used across listings/copy results."""
    from datetime import datetime, timezone

    return datetime.fromtimestamp(ts_ms / 1000, tz=timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%S.000Z"
    )
