"""S3 API server: routing + auth + bucket-level handlers.

Reference src/api/s3/api_server.rs + router.rs.  Path-style addressing
(`/bucket/key`) and vhost-style when a `root_domain` is configured.
Every request is SigV4-verified against the key table, then checked
against the key's bucket permissions.
"""

from __future__ import annotations

import logging
import urllib.parse

from aiohttp import web

from ...model.key_table import Key
from ...utils.error import Error
from ..common.error import (
    ApiError,
    BadRequest,
    BucketNotEmpty,
    Forbidden,
    NoSuchBucket,
    NotImplementedError_,
)
from ..common.error import error_xml
from ..common.signature import check_payload, verify_request
from .list import handle_list_objects_v1, handle_list_objects_v2
from .objects import (
    handle_delete_object,
    handle_get_object,
    handle_put_object,
)
from .xml_util import xml_doc

logger = logging.getLogger("garage.api.s3")

UNIMPLEMENTED_SUBRESOURCES = {
    "acl", "tagging", "versioning", "policy", "logging", "notification",
    "replication", "encryption", "requestPayment", "accelerate", "analytics",
    "inventory", "metrics", "ownershipControls", "publicAccessBlock",
    "intelligent-tiering", "object-lock", "legal-hold", "retention", "torrent",
}


class S3ApiServer:
    def __init__(self, garage):
        self.garage = garage
        self.region = garage.config.s3_api.s3_region
        self.root_domain = garage.config.s3_api.root_domain
        self.app = web.Application(client_max_size=64 * 1024 * 1024 * 1024)
        self.app.router.add_route("*", "/{tail:.*}", self._entry)
        # streamed responses (multi-block GETs) prepare inside the
        # handler, before _entry can stamp headers — this signal fires
        # at prepare time, while the request span is still open
        self.app.on_response_prepare.append(self._stamp_request_id)
        self.runner: web.AppRunner | None = None

    async def _stamp_request_id(self, request, response) -> None:
        from ...utils.tracing import tracer

        s = tracer.current()
        if s is not None and "x-amz-request-id" not in response.headers:
            response.headers["x-amz-request-id"] = s.trace_id.hex()

    async def start(self, host: str, port: int) -> None:
        self.runner = web.AppRunner(self.app, access_log=None)
        await self.runner.setup()
        site = web.TCPSite(self.runner, host, port)
        await site.start()
        logger.info("s3 api listening on %s:%d", host, port)

    async def stop(self) -> None:
        if self.runner:
            await self.runner.cleanup()

    # --- request entry --------------------------------------------------------

    def _parse_target(self, request) -> tuple[str, str]:
        """-> (bucket, key); vhost-style if host matches root_domain."""
        path = urllib.parse.unquote(request.raw_path.split("?")[0])
        host = request.headers.get("Host", "").split(":")[0]
        if self.root_domain:
            # label-boundary match: "my-s3.example.com" must NOT match a
            # root_domain of "s3.example.com"
            rd = self.root_domain.lstrip(".")
            if host != rd and host.endswith("." + rd):
                bucket = host[: -(len(rd) + 1)]
                if bucket:
                    return bucket, path.lstrip("/")
        parts = path.lstrip("/").split("/", 1)
        bucket = parts[0]
        key = parts[1] if len(parts) > 1 else ""
        return bucket, key

    async def _get_secret(self, key_id: str):
        k = await self.garage.key_table.get(key_id.encode(), b"")
        if k is None or k.is_deleted():
            return None
        return k.secret()

    def _slow_down(self, request, ticket) -> web.Response:
        """503 SlowDown with a Retry-After hint (api/overload.py shed
        verdict).  Deliberately OUTSIDE request_metrics: an intentional
        shed must not count as an S3 request or burn the availability
        SLO budget (the shedding controller reads that budget — see
        overload.py module docstring)."""
        from ..common.error import SlowDown

        err = SlowDown(ticket.reason or "please reduce your request rate")
        return web.Response(
            status=err.status,
            text=error_xml(err, request.path),
            content_type="application/xml",
            headers={"Retry-After": str(max(1, int(ticket.retry_after)))},
        )

    async def _entry(self, request: web.Request) -> web.StreamResponse:
        # overload-control plane: admission happens FIRST, before any
        # SigV4 work — the point is to turn excess load away at the
        # cheapest possible place.  _entry is the single choke point.
        ticket = None
        ctl = getattr(self.garage, "overload", None)
        if ctl is not None:
            bucket_name, obj_key = self._parse_target(request)
            ticket = await ctl.admit(request, bucket_name, obj_key)
            if not ticket.admitted:
                return self._slow_down(request, ticket)
        try:
            return await self._admitted_entry(
                request, lead_secs=ticket.queued_secs if ticket else 0.0
            )
        finally:
            if ticket is not None:
                ticket.release()

    async def _admitted_entry(
        self, request: web.Request, lead_secs: float = 0.0
    ) -> web.StreamResponse:
        # traffic observatory (rpc/traffic.py): every ADMITTED request
        # feeds the hot-object/op-mix sketches — shed 503s never reach
        # here, consistent with the overload plane's "sheds are not
        # traffic" invariant.  Runs in the finally so errored requests
        # (they are traffic too) still count.
        import time

        from ...rpc.traffic import observatory

        t0 = time.perf_counter()
        resp: web.StreamResponse | None = None
        try:
            resp = await self._instrumented_entry(request, lead_secs)
            return resp
        finally:
            if observatory.enabled:
                try:
                    bucket_name, obj_key = self._parse_target(request)
                    # canary probes are synthetic: recording them would
                    # make an idle cluster report the canary bucket as
                    # its hot bucket and bake probe noise into the
                    # replayable workload profile (the prober has its
                    # own canary_* telemetry families)
                    if bucket_name != self.garage.config.admin.canary_bucket:
                        observatory.record_http(
                            request.method, bucket_name, obj_key,
                            request.query,
                            self._moved_bytes(request, resp),
                            time.perf_counter() - t0,
                        )
                except Exception as e:  # noqa: BLE001
                    logger.debug("traffic record failed: %r", e)
            try:
                self._record_tenant(
                    request, resp, time.perf_counter() - t0, lead_secs
                )
            except Exception as e:  # noqa: BLE001
                logger.debug("tenant record failed: %r", e)

    def _record_tenant(
        self, request, resp, secs: float, lead_secs: float
    ) -> None:
        """Tenant observatory feed (rpc/tenant.py): per-AUTHENTICATED-
        key accounting, post-SigV4.  The admission controller admitted
        on the CLAIMED key id (the only identity available pre-auth);
        here the verified identity is known, so mismatches become the
        `api_admission_claimed_mismatch_total` signal and only the
        authenticated id is ever attributed usage."""
        from ...rpc.tenant import class_for
        from ...rpc.tenant import observatory as tenant_obs
        from ...utils.metrics import registry
        from ..overload import AdmissionController

        if not tenant_obs.enabled:
            return
        # stashed by _handle right after verify_request; absent when
        # auth never completed (failed signature, PostObject form path)
        auth_id = request.get("tenant_key_id")
        if not auth_id:
            return
        claimed = AdmissionController.claimed_key_id(request)
        if claimed and claimed != auth_id:
            # spoof attempts are a visible counter, never a tenant row
            registry.incr("api_admission_claimed_mismatch_total", ())
            tenant_obs.record_mismatch()
        bucket_name, obj_key = self._parse_target(request)
        if bucket_name == self.garage.config.admin.canary_bucket:
            return  # synthetic probe traffic (same carve-out as traffic)
        from ...rpc.traffic import classify_op

        bytes_in = (
            int(request.content_length or 0)
            if request.method in ("PUT", "POST")
            else 0
        )
        bytes_out = (
            int(resp.content_length or 0)
            if resp is not None and request.method in ("GET", "HEAD")
            else 0
        )
        tenant_obs.record_request(
            auth_id,
            classify_op(request.method, obj_key, request.query),
            bytes_in,
            bytes_out,
            secs,
            is_err=resp is None or resp.status >= 500,
            queued_secs=lead_secs,
            tenant_class=class_for(self.garage.config, auth_id),
        )

    @staticmethod
    def _moved_bytes(request, resp) -> int:
        """Object-payload bytes a request moved, best effort: uploads
        report the request body, downloads the response body (streamed
        GETs set Content-Length before prepare)."""
        if request.method in ("PUT", "POST"):
            return int(request.content_length or 0)
        if resp is not None and resp.content_length:
            return int(resp.content_length)
        return 0

    async def _instrumented_entry(
        self, request: web.Request, lead_secs: float = 0.0
    ) -> web.StreamResponse:
        from ...utils.metrics import registry, request_metrics
        from ...utils.tracing import tracer

        # correlate client-observed latency (and failures) with the
        # node's slow-request flight recorder (/v1/debug/slow) and
        # exported traces: the request id IS the trace id.  Captured
        # inside the request span so error responses carry it too —
        # the failed slow PUT is exactly the one worth joining.
        trace_hex: str | None = None

        def rid(resp: web.StreamResponse) -> web.StreamResponse:
            if trace_hex and not resp.prepared:
                resp.headers["x-amz-request-id"] = trace_hex
            return resp

        def err(status: int) -> None:
            # status-labelled error counter: the SLO tracker and the
            # cluster telemetry digest count code >= 500 against the
            # availability budget (4xx are the client's errors)
            registry.incr(
                "api_s3_error_counter",
                (("method", request.method), ("code", str(status))),
            )

        try:
            with request_metrics(
                "api_s3", request.method, "api:s3",
                lead_secs=lead_secs, path=request.path,
            ):
                s = tracer.current()
                trace_hex = s.trace_id.hex() if s is not None else None
                return rid(await self._handle(request))
        except ApiError as e:
            if e.status == 304:
                return rid(web.Response(status=304))
            err(e.status)
            return rid(web.Response(
                status=e.status,
                text=error_xml(e, request.path),
                content_type="application/xml",
            ))
        except Error as e:
            msg = str(e)
            if "not found" in msg:
                err(404)
                return rid(web.Response(
                    status=404,
                    text=error_xml(NoSuchBucket(msg), request.path),
                    content_type="application/xml",
                ))
            logger.exception("internal error")
            err(500)
            return rid(web.Response(
                status=500,
                text=error_xml(ApiError(msg), request.path),
                content_type="application/xml",
            ))
        except Exception as e:  # noqa: BLE001
            logger.exception("unhandled API error")
            err(500)
            return rid(web.Response(
                status=500,
                text=error_xml(ApiError(repr(e)), request.path),
                content_type="application/xml",
            ))

    async def _handle(self, request: web.Request) -> web.StreamResponse:
        # PostObject: browser form uploads authenticate via a signed policy
        # document in the form fields, not an Authorization header
        if (
            request.method == "POST"
            and "Authorization" not in request.headers
            and request.content_type == "multipart/form-data"
        ):
            from .post_object import handle_post_object

            bucket_name, key = self._parse_target(request)
            if bucket_name and not key:
                return await handle_post_object(self, bucket_name, request)

        from ...utils.latency import phase_span

        with phase_span("auth"):
            ctx = await verify_request(request, self._get_secret, self.region)
            api_key: Key = await self.garage.helper.get_key(ctx.key_id)
        # stash the AUTHENTICATED identity on the request mapping: the
        # streaming-body proxy created below only rebinds a local, so
        # this survives into _admitted_entry's tenant-accounting finally
        request["tenant_key_id"] = ctx.key_id
        bucket_name, key = self._parse_target(request)
        method = request.method

        for sub in UNIMPLEMENTED_SUBRESOURCES:
            if sub in request.query:
                # sole implemented carve-out: bucket-level GET ?versioning
                # (reference implements exactly GetBucketVersioning and
                # 501s every other versioning/tagging/acl operation)
                if sub == "versioning" and method == "GET" and not key:
                    continue
                raise NotImplementedError_(f"subresource {sub!r} not implemented")

        if not bucket_name:
            if method == "GET":
                return await self._list_buckets(api_key)
            raise BadRequest("no bucket specified")

        if (
            method == "PUT"
            and not key
            and not any(s in request.query for s in ("website", "cors", "lifecycle"))
        ):
            return await self._create_bucket(bucket_name, api_key, request, ctx)

        with phase_span("index_read"):
            bucket_id = await self.garage.helper.resolve_bucket(
                bucket_name, api_key
            )
        perm = api_key.bucket_permissions(bucket_id)
        q = request.query

        from . import bucket_config as bc
        from .copy_delete import handle_copy_object, handle_delete_objects
        from . import multipart as mp

        if not key:
            # bucket-level ops
            if method == "HEAD":
                _require(perm.allow_read or perm.allow_write or perm.allow_owner)
                return web.Response(status=200)
            if method == "GET":
                _require(perm.allow_read)
                for sub, h in (
                    ("website", bc.handle_get_website),
                    ("cors", bc.handle_get_cors),
                    ("lifecycle", bc.handle_get_lifecycle),
                ):
                    if sub in q:
                        bucket = await self.garage.helper.get_bucket(bucket_id)
                        return await h(self.garage, bucket, request)
                if "uploads" in q:
                    return await mp.handle_list_multipart_uploads(
                        self.garage, bucket_id, bucket_name, request
                    )
                if "location" in q:
                    return web.Response(
                        text=xml_doc("LocationConstraint", [("", self.region)]),
                        content_type="application/xml",
                    )
                if "versioning" in q:
                    # buckets are unversioned: empty configuration, like
                    # the reference (src/api/s3/bucket.rs:34-45)
                    return web.Response(
                        text=xml_doc("VersioningConfiguration", []),
                        content_type="application/xml",
                    )
                if q.get("list-type") == "2":
                    return await handle_list_objects_v2(
                        self.garage, bucket_id, bucket_name, request
                    )
                return await handle_list_objects_v1(
                    self.garage, bucket_id, bucket_name, request
                )
            if method == "PUT":
                _require(perm.allow_owner)
                for sub, h in (
                    ("website", bc.handle_put_website),
                    ("cors", bc.handle_put_cors),
                    ("lifecycle", bc.handle_put_lifecycle),
                ):
                    if sub in q:
                        bucket = await self.garage.helper.get_bucket(bucket_id)
                        return await h(self.garage, bucket, request, ctx=ctx)
                raise BadRequest("unsupported bucket PUT")
            if method == "POST":
                if "delete" in q:
                    _require(perm.allow_write)
                    return await handle_delete_objects(self.garage, bucket_id, request, ctx=ctx)
                raise BadRequest("unsupported bucket POST")
            if method == "DELETE":
                for sub, h in (
                    ("website", bc.handle_delete_website),
                    ("cors", bc.handle_delete_cors),
                    ("lifecycle", bc.handle_delete_lifecycle),
                ):
                    if sub in q:
                        _require(perm.allow_owner)
                        bucket = await self.garage.helper.get_bucket(bucket_id)
                        return await h(self.garage, bucket, request)
                _require(perm.allow_owner)
                try:
                    await self.garage.helper.delete_bucket(bucket_id)
                except Error as e:
                    if "not empty" in str(e):
                        raise BucketNotEmpty(str(e)) from e
                    raise
                return web.Response(status=204)
            raise BadRequest(f"unsupported bucket method {method}")

        # aws-chunked streaming bodies decode (and verify per-chunk
        # signatures) transparently before the put pipelines see them
        if ctx.streaming is not None and method == "PUT" and key:
            from ..common.streaming import ChunkedDecoder

            sctx = None if ctx.streaming == "unsigned" else ctx.streaming
            request = _StreamingRequestProxy(request, ChunkedDecoder(request.content, sctx))

        # object-level ops
        if method == "POST":
            _require(perm.allow_write)
            if "uploads" in q:
                return await mp.handle_create_multipart_upload(
                    self.garage, bucket_id, key, request
                )
            if "uploadId" in q:
                return await mp.handle_complete_multipart_upload(
                    self.garage, bucket_id, key, request, ctx=ctx
                )
            raise BadRequest("unsupported object POST")
        if method == "PUT":
            _require(perm.allow_write)
            if "partNumber" in q:
                if "x-amz-copy-source" in request.headers:
                    return await mp.handle_upload_part_copy(
                        self.garage, self.garage.helper, api_key,
                        bucket_id, key, request, ctx=ctx,
                    )
                return await mp.handle_upload_part(
                    self.garage, bucket_id, key, request, ctx=ctx
                )
            if "x-amz-copy-source" in request.headers:
                return await handle_copy_object(
                    self.garage, self.garage.helper, api_key, bucket_id, key, request
                )
            return await handle_put_object(
                self.garage, bucket_id, key, request, ctx=ctx
            )
        if method == "GET":
            _require(perm.allow_read)
            if "uploadId" in q:
                return await mp.handle_list_parts(self.garage, bucket_id, key, request)
            return await handle_get_object(self.garage, bucket_id, key, request)
        if method == "HEAD":
            _require(perm.allow_read)
            return await handle_get_object(
                self.garage, bucket_id, key, request, head_only=True
            )
        if method == "DELETE":
            _require(perm.allow_write)
            if "uploadId" in q:
                return await mp.handle_abort_multipart_upload(
                    self.garage, bucket_id, key, request
                )
            return await handle_delete_object(self.garage, bucket_id, key)
        raise BadRequest(f"unsupported method {method}")

    # --- bucket handlers ------------------------------------------------------

    async def _list_buckets(self, api_key: Key) -> web.Response:
        params = api_key.params()
        buckets = []
        if params:
            for bid, perm_obj in params.authorized_buckets.items():
                from ...model.permission import BucketKeyPerm

                if not BucketKeyPerm.from_obj(perm_obj).is_any():
                    continue
                try:
                    b = await self.garage.helper.get_bucket(bytes(bid))
                except Error:
                    continue
                for name, v in b.params().aliases.items():
                    if v:
                        buckets.append((name, b.params().creation_date))
        from .xml_util import http_iso as _http_iso

        children = [
            ("Owner", [("ID", api_key.key_id), ("DisplayName", api_key.key_id)]),
            (
                "Buckets",
                [
                    ("Bucket", [("Name", n), ("CreationDate", _http_iso(cd))])
                    for n, cd in sorted(buckets)
                ],
            ),
        ]
        return web.Response(
            text=xml_doc("ListAllMyBucketsResult", children),
            content_type="application/xml",
        )

    async def _create_bucket(self, name: str, api_key: Key, request, ctx) -> web.Response:
        body = await request.read()
        await check_payload(body, ctx)
        params = api_key.params()
        try:
            existing = await self.garage.helper.resolve_bucket(name, api_key)
        except Error:
            existing = None
        if existing is not None:
            perm = api_key.bucket_permissions(existing)
            if perm.allow_owner:  # idempotent re-create by the owner
                return web.Response(status=200, headers={"Location": f"/{name}"})
            from ..common.error import BucketAlreadyExists

            raise BucketAlreadyExists(f"bucket {name!r} already exists")
        if params is None or not params.allow_create_bucket.get():
            raise Forbidden("this key cannot create buckets")
        bucket_id = await self.garage.helper.create_bucket(name)
        await self.garage.helper.set_bucket_key_permissions(
            bucket_id, api_key.key_id, True, True, True
        )
        return web.Response(status=200, headers={"Location": f"/{name}"})


def _require(cond: bool) -> None:
    if not cond:
        raise Forbidden("access denied for this operation")


class _StreamingRequestProxy:
    """A request whose body reads through the aws-chunked decoder."""

    def __init__(self, request, decoder):
        self._request = request
        self.content = decoder

    def __getattr__(self, name):
        return getattr(self._request, name)
