"""Object listings: ListObjectsV2 / V1 (reference src/api/s3/list.rs —
the pagination state machines over CRDT version lists)."""

from __future__ import annotations

import base64

from aiohttp import web

from .xml_util import http_iso as _http_iso, xml_doc


async def _collect(
    garage,
    bucket_id: bytes,
    prefix: str,
    delimiter: str,
    start_after: str,
    max_keys: int,
):
    """Walk the object table; fold keys under `delimiter` into common
    prefixes.  Returns (entries, common_prefixes, truncated, next_start)
    where next_start is the LAST PROCESSED key — the continuation resumes
    strictly after it, so no key is dropped at page boundaries."""
    entries = []
    prefixes: set[str] = set()
    # seek straight to the interesting range; only start_after is an
    # EXCLUSIVE bound — a key exactly equal to the prefix must be listed
    cursor = max(start_after, prefix).encode() if prefix else start_after.encode()
    floor = start_after  # strictly-greater-than bound
    last = floor or cursor.decode(errors="surrogateescape")
    while True:
        batch = await garage.object_table.get_range(
            bucket_id, cursor, "visible", 1000
        )
        if not batch:
            break
        for obj in batch:
            k = obj.key
            if floor and k <= floor:
                continue
            if prefix:
                if not k.startswith(prefix):
                    if k > prefix:
                        return entries, sorted(prefixes), False, ""  # past range
                    continue
            if delimiter:
                rest = k[len(prefix):]
                if delimiter in rest:
                    cp = prefix + rest.split(delimiter)[0] + delimiter
                    if cp not in prefixes:
                        if len(entries) + len(prefixes) + 1 > max_keys:
                            return entries, sorted(prefixes), True, last
                        prefixes.add(cp)
                    last = k
                    continue
            if len(entries) + len(prefixes) + 1 > max_keys:
                return entries, sorted(prefixes), True, last
            v = obj.last_visible()
            meta = v.data.get("meta", {})
            entries.append(
                {
                    "key": k,
                    "size": meta.get("size", 0),
                    "etag": meta.get("etag", ""),
                    "ts": v.timestamp,
                }
            )
            last = k
        cursor = batch[-1].key.encode()
        floor = batch[-1].key  # next batch starts strictly after
        if len(batch) < 1000:
            break
    return entries, sorted(prefixes), False, ""


def uriencode(s: str, encode_slash: bool = False) -> str:
    """S3 `encoding-type=url` key encoding: RFC 3986 unreserved characters
    kept verbatim, '/' kept unless encode_slash (reference
    src/api/common/encoding.rs uri_encode) — the SigV4 canonical encoding."""
    from ..common.signature import _uri_encode

    return _uri_encode(s, encode_slash=encode_slash)


# Owner/Initiator are access-control concepts Garage doesn't model per
# object; fixed placeholder identity (reference list.rs:25-26 does the same)
OWNER_XML = ("Owner", [("ID", "garage-tpu-owner"), ("DisplayName", "garage-tpu")])


def _maybe_enc(s: str, urlencode: bool) -> str:
    return uriencode(s) if urlencode else s


def _contents_xml(e: dict, urlencode: bool, with_owner: bool):
    fields = [
        ("Key", _maybe_enc(e["key"], urlencode)),
        ("LastModified", _http_iso(e["ts"])),
        ("ETag", f'"{e["etag"]}"'),
        ("Size", e["size"]),
        ("StorageClass", "STANDARD"),
    ]
    if with_owner:
        fields.append(OWNER_XML)
    return ("Contents", fields)


async def handle_list_objects_v2(garage, bucket_id: bytes, bucket_name: str, request):
    q = request.query
    prefix = q.get("prefix", "")
    delimiter = q.get("delimiter", "")
    max_keys = min(int(q.get("max-keys", "1000")), 1000)
    urlencode = q.get("encoding-type") == "url"
    fetch_owner = q.get("fetch-owner") == "true"
    token = q.get("continuation-token")
    start_after = q.get("start-after", "")
    if token:
        start_after = base64.urlsafe_b64decode(token.encode()).decode()

    entries, prefixes, truncated, next_start = await _collect(
        garage, bucket_id, prefix, delimiter, start_after, max_keys
    )
    children = [
        ("Name", bucket_name),
        ("Prefix", _maybe_enc(prefix, urlencode)),
        ("KeyCount", len(entries) + len(prefixes)),
        ("MaxKeys", max_keys),
        ("Delimiter", _maybe_enc(delimiter, urlencode)) if delimiter else None,
        ("EncodingType", "url") if urlencode else None,
        (
            "StartAfter", _maybe_enc(q.get("start-after", ""), urlencode)
        ) if q.get("start-after") else None,
        ("IsTruncated", truncated),
    ]
    if truncated:
        children.append(
            (
                "NextContinuationToken",
                base64.urlsafe_b64encode(next_start.encode()).decode(),
            )
        )
    for e in entries:
        children.append(_contents_xml(e, urlencode, fetch_owner))
    for p in prefixes:
        children.append(("CommonPrefixes", [("Prefix", _maybe_enc(p, urlencode))]))
    return web.Response(
        text=xml_doc("ListBucketResult", children),
        content_type="application/xml",
    )


async def handle_list_objects_v1(garage, bucket_id: bytes, bucket_name: str, request):
    q = request.query
    prefix = q.get("prefix", "")
    delimiter = q.get("delimiter", "")
    max_keys = min(int(q.get("max-keys", "1000")), 1000)
    urlencode = q.get("encoding-type") == "url"
    marker = q.get("marker", "")
    entries, prefixes, truncated, next_start = await _collect(
        garage, bucket_id, prefix, delimiter, marker, max_keys
    )
    children = [
        ("Name", bucket_name),
        ("Prefix", _maybe_enc(prefix, urlencode)),
        ("Marker", _maybe_enc(marker, urlencode)),
        ("MaxKeys", max_keys),
        ("Delimiter", _maybe_enc(delimiter, urlencode)) if delimiter else None,
        ("EncodingType", "url") if urlencode else None,
        ("IsTruncated", truncated),
    ]
    if truncated and next_start:
        children.append(("NextMarker", _maybe_enc(next_start, urlencode)))
    for e in entries:
        # V1 always reports the owner
        children.append(_contents_xml(e, urlencode, with_owner=True))
    for p in prefixes:
        children.append(("CommonPrefixes", [("Prefix", _maybe_enc(p, urlencode))]))
    return web.Response(
        text=xml_doc("ListBucketResult", children),
        content_type="application/xml",
    )
