"""Canary prober: low-rate synthetic S3 traffic against a hidden bucket.

The latency X-ray (`utils/latency.py`), the SLO budgets (PR 5) and the
outlier detector all feed off real S3 request metrics — which means an
IDLE cluster is blind: no requests, no phase waterfall, no budget burn
signal, and a node that would fail every PUT looks healthy until a user
arrives.  The canary keeps a heartbeat of real traffic flowing: a
background `Worker` drives a PUT → GET (with payload verification) →
DELETE cycle through the node's own S3 HTTP frontend (full SigV4 + block
pipeline — the probe exercises exactly what a user request would) every
`[admin] canary_interval_secs`, against `[admin] canary_bucket` (default
`canary-probe`; hidden in the sense that only the canary's own key is
authorized on it, so normal keys' ListBuckets never show it).

Each probe leg lands in `canary_probe_duration{op,outcome}`; the cycle
health is the `canary_healthy{id}` gauge (registered at spawn,
unregistered at node shutdown, process-unique `id` per the PR 3
convention — several in-process nodes share the registry).  Probe totals
and p99 fold into the PR 5 telemetry digest (`canary` block), so
`cluster top` shows canary health per node and a node whose canary fails
is visible cluster-wide with zero foreground traffic.

The probes also flow into the ordinary `api_s3_*` families and the phase
histograms — that is the point, not a side effect: the waterfall and the
SLO trackers always have a trickle of signal.
"""

from __future__ import annotations

import itertools
import logging
import os
import time

from ...utils.background import Worker, WorkerState
from ...utils.error import Error
from ...utils.metrics import registry

logger = logging.getLogger("garage.canary")

# process-unique gauge id (several in-process nodes share the registry;
# a per-node id would collide and one node's shutdown would delete the
# others' canary gauge)
_gauge_ids = itertools.count(1)

CANARY_KEY_NAME = "canary-probe"
# bounded object churn: probe keys rotate through a small ring so a
# wedged DELETE leg can't grow the hidden bucket without bound
KEY_RING = 16


class CanaryWorker(Worker):
    """One PUT/GET/DELETE probe cycle per `interval` seconds."""

    def __init__(
        self,
        garage,
        endpoint: str,
        interval: float = 60.0,
        object_bytes: int = 65536,
        bucket: str = "canary-probe",
    ):
        self.garage = garage
        self.endpoint = endpoint
        self.interval = float(interval)
        self.object_bytes = int(object_bytes)
        self.bucket = bucket
        self.gauge_id = str(next(_gauge_ids))
        self.healthy: float | None = None  # None until the first cycle
        self.probes = 0
        self.failed = 0
        self.last_error: str | None = None
        self._client = None
        self._seq = 0

    def name(self) -> str:
        return "canary"

    def status(self) -> dict:
        return {
            "bucket": self.bucket,
            "endpoint": self.endpoint,
            "probes": self.probes,
            "failed": self.failed,
            **({"last_error": self.last_error} if self.last_error else {}),
        }

    async def _ensure_client(self) -> None:
        """Find-or-create the canary key + hidden bucket.  The key is
        shared cluster-wide by name (the key table is replicated), so N
        nodes probing the same bucket don't accrete N keys."""
        if self._client is not None:
            return
        g = self.garage
        key = None
        for k in await g.helper.list_keys():
            if (k.params().name.get() or "") == CANARY_KEY_NAME:
                key = k
                break
        if key is None:
            key = await g.helper.create_key(CANARY_KEY_NAME)
        # admission exemption (api/overload.py): the canary's probes must
        # keep flowing at EVERY shedding-ladder level — shedding them
        # would blind the exact signal the shedding controller uses to
        # decide the node has recovered
        ctl = getattr(g, "overload", None)
        if ctl is not None:
            ctl.exempt_key(key.key_id)
        try:
            bid = await g.helper.resolve_bucket(self.bucket)
        except Error:
            bid = await g.helper.create_bucket(self.bucket)
        await g.helper.set_bucket_key_permissions(
            bid, key.key_id, True, True, False
        )
        from .client import S3Client

        self._client = S3Client(self.endpoint, key.key_id, key.secret())

    def _layout_can_store(self) -> bool:
        """A PUT needs a layout with enough storage nodes (EC: k+m per
        block).  A fresh node that hasn't been assigned a layout yet
        would fail every probe — that's bring-up, not an outage, and it
        must not burn the SLO budget or spam 500s."""
        cur = self.garage.layout_manager.history.current()
        need = max(1, self.garage.block_manager.codec.n_pieces)
        return len(cur.storage_nodes()) >= need

    async def work(self):
        if not self._layout_can_store():
            return (WorkerState.THROTTLED, self.interval)
        try:
            await self._ensure_client()
        except Exception as e:  # noqa: BLE001 — setup failure IS canary
            # data: raising would hand it to the worker supervisor, whose
            # exponential backoff silences the canary exactly during the
            # outage it should be reporting
            self.probes += 1
            self.failed += 1
            self.healthy = 0.0
            self.last_error = f"setup: {e!r}"
            logger.warning("canary setup failed: %r", e)
            return (WorkerState.THROTTLED, self.interval)
        c = self._client
        # per-node key ring: nodes sharing the hidden bucket must not
        # race each other's probe objects
        obj = (
            f"probe-{self.garage.node_id.hex()[:8]}-{self._seq % KEY_RING:02d}"
        )
        self._seq += 1
        body = os.urandom(self.object_bytes)

        async def get_and_verify():
            got = await c.get_object(self.bucket, obj)
            if got != body:
                raise Error("canary readback does not match what was PUT")

        ok_all = True
        for op, fn in (
            ("put", lambda: c.put_object(self.bucket, obj, body)),
            ("get", get_and_verify),
            ("delete", lambda: c.delete_object(self.bucket, obj)),
        ):
            t0 = time.perf_counter()
            try:
                await fn()
                outcome = "ok"
            except Exception as e:  # noqa: BLE001 — a probe failure is a
                # datum, not a worker error (the supervisor would back off
                # and STOP probing exactly when signal matters most)
                outcome = "error"
                ok_all = False
                self.last_error = f"{op}: {e!r}"
                logger.warning("canary %s probe failed: %r", op, e)
            registry.observe(
                "canary_probe_duration",
                (("op", op), ("outcome", outcome)),
                time.perf_counter() - t0,
            )
        self.probes += 1
        if not ok_all:
            self.failed += 1
        self.healthy = 1.0 if ok_all else 0.0
        return (WorkerState.THROTTLED, self.interval)

    async def stop_client(self) -> None:
        if self._client is not None:
            await self._client.close()
            self._client = None


def digest_fields(reg=None) -> dict:
    """The `canary` block of the gossiped telemetry digest: cumulative
    probe count / failures + probe latency p99, read straight off the
    `canary_probe_duration` histogram (no parallel counter family to
    drift).  Zero-valued on nodes without a canary."""
    r = reg if reg is not None else registry
    return {
        "ops": r.histogram_family_count("canary_probe_duration"),
        "err": r.histogram_family_count(
            "canary_probe_duration",
            lambda labels: ("outcome", "error") in labels,
        ),
        "p99": r.family_quantile("canary_probe_duration", 0.99),
    }
