"""Minimal async S3 client with SigV4 signing.

Replaces the aws-sdk client the reference uses in its integration tests
(src/garage/tests/ uses aws-sdk-s3 + a custom requester; this image has
no boto3).  Also used by the CLI and the smoke scripts.
"""

from __future__ import annotations

import urllib.parse
import xml.etree.ElementTree as ET

import aiohttp

from ..common.signature import sign_request_headers


class S3Error(Exception):
    def __init__(self, status: int, code: str, message: str):
        super().__init__(f"{status} {code}: {message}")
        self.status = status
        self.code = code


class S3Client:
    def __init__(self, endpoint: str, key_id: str, secret: str, region: str = "garage"):
        self.endpoint = endpoint.rstrip("/")
        self.key_id = key_id
        self.secret = secret
        self.region = region
        host = urllib.parse.urlparse(self.endpoint).netloc
        self.host = host
        self._session: aiohttp.ClientSession | None = None

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None

    def _sess(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession()
        return self._session

    async def _req(
        self,
        method: str,
        path: str,
        query: list[tuple[str, str]] | None = None,
        body: bytes = b"",
        headers: dict[str, str] | None = None,
    ) -> tuple[int, dict, bytes]:
        query = query or []
        h = dict(headers or {})
        h["host"] = self.host
        signed = sign_request_headers(
            method, path, query, h, body, self.key_id, self.secret, self.region
        )
        qs = urllib.parse.urlencode(query)
        url = self.endpoint + urllib.parse.quote(path) + ("?" + qs if qs else "")
        async with self._sess().request(
            method, url, data=body, headers=signed, skip_auto_headers=["Content-Type"]
        ) as resp:
            data = await resp.read()
            return resp.status, resp.headers.copy(), data  # case-insensitive

    def _check(self, status: int, data: bytes, ok=(200, 204, 206)):
        if status not in ok:
            code, msg = "Unknown", data.decode(errors="replace")[:200]
            try:
                root = ET.fromstring(data.decode())
                code = root.findtext("Code") or code
                msg = root.findtext("Message") or msg
            except ET.ParseError:
                pass
            raise S3Error(status, code, msg)

    # --- operations -----------------------------------------------------------

    async def create_bucket(self, bucket: str) -> None:
        st, _h, data = await self._req("PUT", f"/{bucket}")
        self._check(st, data)

    async def delete_bucket(self, bucket: str) -> None:
        st, _h, data = await self._req("DELETE", f"/{bucket}")
        self._check(st, data)

    async def list_buckets(self) -> list[str]:
        st, _h, data = await self._req("GET", "/")
        self._check(st, data)
        ns = {"s3": "http://s3.amazonaws.com/doc/2006-03-01/"}
        root = ET.fromstring(data.decode())
        return [e.text for e in root.findall(".//s3:Bucket/s3:Name", ns)]

    async def put_object(
        self, bucket: str, key: str, body: bytes,
        content_type: str | None = None,
        metadata: dict[str, str] | None = None,
    ) -> str:
        """`metadata` entries become x-amz-meta-* user metadata."""
        headers = {"content-type": content_type} if content_type else {}
        for k, v in (metadata or {}).items():
            headers[f"x-amz-meta-{k}"] = v
        st, h, data = await self._req("PUT", f"/{bucket}/{key}", body=body, headers=headers)
        self._check(st, data)
        return h.get("ETag", "").strip('"')

    async def get_object(
        self,
        bucket: str,
        key: str,
        range_: str | None = None,
        part_number: int | None = None,
        headers: dict[str, str] | None = None,
    ) -> bytes:
        h = dict(headers or {})
        if range_:
            h["range"] = range_
        q = [("partNumber", str(part_number))] if part_number is not None else []
        st, _h, data = await self._req("GET", f"/{bucket}/{key}", query=q, headers=h)
        self._check(st, data)
        return data

    async def get_object_full(
        self,
        bucket: str,
        key: str,
        part_number: int | None = None,
        headers: dict[str, str] | None = None,
    ) -> tuple[int, dict, bytes]:
        """Raw (status, headers, body) — for conditional/part-read tests."""
        q = [("partNumber", str(part_number))] if part_number is not None else []
        return await self._req("GET", f"/{bucket}/{key}", query=q, headers=headers)

    async def head_object(
        self, bucket: str, key: str, part_number: int | None = None
    ) -> dict:
        q = [("partNumber", str(part_number))] if part_number is not None else []
        st, h, data = await self._req("HEAD", f"/{bucket}/{key}", query=q)
        self._check(st, data)
        return h

    async def upload_part_copy(
        self,
        bucket: str,
        key: str,
        upload_id: str,
        part_number: int,
        src_bucket: str,
        src_key: str,
        src_range: str | None = None,
        headers: dict[str, str] | None = None,
    ) -> str:
        h = dict(headers or {})
        h["x-amz-copy-source"] = f"/{src_bucket}/{src_key}"
        if src_range:
            h["x-amz-copy-source-range"] = src_range
        st, _h, data = await self._req(
            "PUT",
            f"/{bucket}/{key}",
            query=[("partNumber", str(part_number)), ("uploadId", upload_id)],
            headers=h,
        )
        self._check(st, data)
        ns = {"s3": "http://s3.amazonaws.com/doc/2006-03-01/"}
        root = ET.fromstring(data.decode())
        return (root.findtext("s3:ETag", namespaces=ns) or "").strip('"')

    async def delete_object(self, bucket: str, key: str) -> None:
        st, _h, data = await self._req("DELETE", f"/{bucket}/{key}")
        self._check(st, data)

    async def list_objects_v2(
        self,
        bucket: str,
        prefix: str = "",
        delimiter: str = "",
        max_keys: int = 1000,
        continuation_token: str | None = None,
    ) -> dict:
        q = [("list-type", "2"), ("max-keys", str(max_keys))]
        if prefix:
            q.append(("prefix", prefix))
        if delimiter:
            q.append(("delimiter", delimiter))
        if continuation_token:
            q.append(("continuation-token", continuation_token))
        st, _h, data = await self._req("GET", f"/{bucket}", query=q)
        self._check(st, data)
        ns = {"s3": "http://s3.amazonaws.com/doc/2006-03-01/"}
        root = ET.fromstring(data.decode())
        return {
            "keys": [
                {
                    "key": c.findtext("s3:Key", namespaces=ns),
                    "size": int(c.findtext("s3:Size", namespaces=ns) or 0),
                    "etag": (c.findtext("s3:ETag", namespaces=ns) or "").strip('"'),
                }
                for c in root.findall("s3:Contents", ns)
            ],
            "common_prefixes": [
                p.findtext("s3:Prefix", namespaces=ns)
                for p in root.findall("s3:CommonPrefixes", ns)
            ],
            "truncated": root.findtext("s3:IsTruncated", namespaces=ns) == "true",
            "next_token": root.findtext("s3:NextContinuationToken", namespaces=ns),
        }

    async def put_object_streaming(
        self, bucket: str, key: str, body: bytes, chunk_size: int = 65536
    ) -> str:
        """PUT with aws-chunked signed streaming (per-chunk signatures)."""
        from datetime import datetime, timezone

        from ..common.signature import compute_signature, signing_key
        from ..common.streaming import (
            STREAMING_SIGNED,
            StreamingContext,
            encode_chunked,
        )

        now = datetime.now(timezone.utc)
        timestamp = now.strftime("%Y%m%dT%H%M%SZ")
        date = now.strftime("%Y%m%d")
        path = f"/{bucket}/{key}"
        h = {
            "host": self.host,
            "x-amz-date": timestamp,
            "x-amz-content-sha256": STREAMING_SIGNED,
            "content-encoding": "aws-chunked",
            "x-amz-decoded-content-length": str(len(body)),
        }
        signed_headers = sorted(h.keys())
        seed = compute_signature(
            self.secret, "PUT", path, [], h, signed_headers,
            STREAMING_SIGNED, timestamp, date, self.region,
        )
        scope = f"{date}/{self.region}/s3/aws4_request"
        sctx = StreamingContext(
            signing_key(self.secret, date, self.region), timestamp, scope, seed
        )
        h["authorization"] = (
            f"AWS4-HMAC-SHA256 Credential={self.key_id}/{scope}, "
            f"SignedHeaders={';'.join(signed_headers)}, Signature={seed}"
        )
        wire = encode_chunked(body, sctx, chunk_size)
        url = self.endpoint + urllib.parse.quote(path)
        async with self._sess().put(
            url, data=wire, headers=h, skip_auto_headers=["Content-Type"]
        ) as resp:
            data = await resp.read()
            self._check(resp.status, data)
            return resp.headers.get("ETag", "").strip('"')

    # --- multipart ------------------------------------------------------------

    async def create_multipart_upload(
        self, bucket: str, key: str, metadata: dict[str, str] | None = None
    ) -> str:
        headers = {f"x-amz-meta-{k}": v for k, v in (metadata or {}).items()}
        st, _h, data = await self._req(
            "POST", f"/{bucket}/{key}", query=[("uploads", "")], headers=headers
        )
        self._check(st, data)
        root = ET.fromstring(data.decode())
        ns = {"s3": "http://s3.amazonaws.com/doc/2006-03-01/"}
        return root.findtext("s3:UploadId", namespaces=ns)

    async def upload_part(
        self, bucket: str, key: str, upload_id: str, part_number: int, body: bytes
    ) -> str:
        st, h, data = await self._req(
            "PUT",
            f"/{bucket}/{key}",
            query=[("partNumber", str(part_number)), ("uploadId", upload_id)],
            body=body,
        )
        self._check(st, data)
        return h.get("ETag", "").strip('"')

    async def complete_multipart_upload(
        self, bucket: str, key: str, upload_id: str, parts: list[tuple[int, str]]
    ) -> str:
        body = (
            '<CompleteMultipartUpload>'
            + "".join(
                f"<Part><PartNumber>{pn}</PartNumber><ETag>\"{etag}\"</ETag></Part>"
                for pn, etag in parts
            )
            + "</CompleteMultipartUpload>"
        ).encode()
        st, _h, data = await self._req(
            "POST", f"/{bucket}/{key}", query=[("uploadId", upload_id)], body=body
        )
        self._check(st, data)
        root = ET.fromstring(data.decode())
        ns = {"s3": "http://s3.amazonaws.com/doc/2006-03-01/"}
        return (root.findtext("s3:ETag", namespaces=ns) or "").strip('"')

    async def abort_multipart_upload(self, bucket: str, key: str, upload_id: str):
        st, _h, data = await self._req(
            "DELETE", f"/{bucket}/{key}", query=[("uploadId", upload_id)]
        )
        self._check(st, data)

    async def list_parts(self, bucket: str, key: str, upload_id: str) -> list[dict]:
        st, _h, data = await self._req(
            "GET", f"/{bucket}/{key}", query=[("uploadId", upload_id)]
        )
        self._check(st, data)
        ns = {"s3": "http://s3.amazonaws.com/doc/2006-03-01/"}
        root = ET.fromstring(data.decode())
        return [
            {
                "part": int(p.findtext("s3:PartNumber", namespaces=ns)),
                "etag": (p.findtext("s3:ETag", namespaces=ns) or "").strip('"'),
                "size": int(p.findtext("s3:Size", namespaces=ns) or 0),
            }
            for p in root.findall("s3:Part", ns)
        ]

    async def copy_object(
        self, src_bucket: str, src_key: str, dst_bucket: str, dst_key: str,
        headers: dict[str, str] | None = None,
    ):
        st, _h, data = await self._req(
            "PUT",
            f"/{dst_bucket}/{dst_key}",
            headers={
                "x-amz-copy-source": f"/{src_bucket}/{src_key}",
                **(headers or {}),
            },
        )
        self._check(st, data)

    async def delete_objects(self, bucket: str, keys: list[str]) -> None:
        body = (
            "<Delete>"
            + "".join(f"<Object><Key>{k}</Key></Object>" for k in keys)
            + "</Delete>"
        ).encode()
        st, _h, data = await self._req(
            "POST", f"/{bucket}", query=[("delete", "")], body=body
        )
        self._check(st, data)

    async def put_bucket_config(self, bucket: str, sub: str, xml_body: bytes):
        st, _h, data = await self._req(
            "PUT", f"/{bucket}", query=[(sub, "")], body=xml_body
        )
        self._check(st, data)

    async def get_bucket_config(self, bucket: str, sub: str) -> bytes:
        st, _h, data = await self._req("GET", f"/{bucket}", query=[(sub, "")])
        self._check(st, data)
        return data
