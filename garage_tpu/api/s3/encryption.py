"""SSE-C: server-side encryption with customer-provided keys
(reference src/api/s3/encryption.rs:54-305).

The customer supplies a 256-bit key per request
(`x-amz-server-side-encryption-customer-{algorithm,key,key-MD5}`); each
block is sealed independently with AES-256-GCM (12-byte random nonce +
16-byte tag framed around the ciphertext), so ranged reads only decrypt
the blocks they touch.  Blocks are content-addressed by their CIPHERTEXT
hash (random nonces make ciphertext non-deterministic, so SSE-C blocks do
not deduplicate); plaintext never leaves the API process unencrypted.  The object records only the algorithm + key MD5; the server
stores no key material.
"""

from __future__ import annotations

import base64
import hashlib
import os

try:  # SSE-C needs real AES-GCM; everything else works without it
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
except ImportError:
    AESGCM = None

from ..common.error import ApiError, BadRequest

ALG_HEADER = "x-amz-server-side-encryption-customer-algorithm"
KEY_HEADER = "x-amz-server-side-encryption-customer-key"
MD5_HEADER = "x-amz-server-side-encryption-customer-key-md5"
# UploadPartCopy names the SOURCE key with these (AWS spec: the
# "x-amz-copy-source-" prefix replaces the leading "x-amz-", it does not
# stack on top of it)
COPY_ALG_HEADER = "x-amz-copy-source-server-side-encryption-customer-algorithm"

NONCE_LEN = 12
TAG_LEN = 16
OVERHEAD = NONCE_LEN + TAG_LEN  # per stored block


class EncryptionParams:
    """Parsed + validated SSE-C request parameters."""

    def __init__(self, key: bytes, key_md5_b64: str):
        if AESGCM is None:
            raise BadRequest(
                "SSE-C unavailable: the 'cryptography' package is not "
                "installed on this server"
            )
        self.key = key
        self.key_md5_b64 = key_md5_b64
        self._aead = AESGCM(key)

    @classmethod
    def from_headers(cls, headers, copy_source: bool = False) -> "EncryptionParams | None":
        def hname(base: str) -> str:
            if copy_source:
                return "x-amz-copy-source-" + base[len("x-amz-"):]
            return base

        h = {k.lower(): v for k, v in headers.items()}
        alg = h.get(hname(ALG_HEADER))
        if alg is None:
            if hname(KEY_HEADER) in h or hname(MD5_HEADER) in h:
                raise BadRequest("SSE-C key supplied without algorithm header")
            return None
        if alg != "AES256":
            raise BadRequest(f"unsupported SSE-C algorithm {alg!r}")
        try:
            key = base64.b64decode(h.get(hname(KEY_HEADER), ""))
        except Exception as e:
            raise BadRequest(f"bad SSE-C key encoding: {e}") from e
        if len(key) != 32:
            raise BadRequest("SSE-C key must be 256 bits")
        md5_b64 = h.get(hname(MD5_HEADER), "")
        if base64.b64encode(hashlib.md5(key).digest()).decode() != md5_b64:
            raise BadRequest("SSE-C key MD5 mismatch")
        return cls(key, md5_b64)

    @classmethod
    def from_copy_source_headers(cls, headers) -> "EncryptionParams | None":
        """The x-amz-copy-source-server-side-encryption-customer-* key
        naming the SOURCE object of an UploadPartCopy (reference
        encryption.rs)."""
        return cls.from_headers(headers, copy_source=True)

    # --- block sealing --------------------------------------------------------

    def encrypt_block(self, plaintext: bytes) -> bytes:
        nonce = os.urandom(NONCE_LEN)
        return nonce + self._aead.encrypt(nonce, plaintext, None)

    def decrypt_block(self, stored: bytes) -> bytes:
        if len(stored) < OVERHEAD:
            raise ApiError("encrypted block too short", status=500)
        try:
            return self._aead.decrypt(stored[:NONCE_LEN], stored[NONCE_LEN:], None)
        except Exception as e:
            raise ApiError(
                "decryption failed (wrong SSE-C key?)",
                code="AccessDenied",
                status=403,
            ) from e

    def meta(self) -> dict:
        return {"alg": "AES256", "md5": self.key_md5_b64}

    def response_headers(self) -> dict[str, str]:
        return {
            ALG_HEADER: "AES256",
            MD5_HEADER: self.key_md5_b64,
        }


def check_match(meta_enc: dict | None, params: EncryptionParams | None) -> None:
    """An encrypted object requires the matching key; a plain object
    requires no key (reference encryption.rs check)."""
    if meta_enc is None and params is None:
        return
    if meta_enc is None:
        raise BadRequest("object is not SSE-C encrypted")
    if params is None:
        raise ApiError(
            "object is SSE-C encrypted: key headers required",
            code="BadRequest",
            status=400,
        )
    if meta_enc.get("md5") != params.key_md5_b64:
        raise ApiError("wrong SSE-C key", code="AccessDenied", status=403)
