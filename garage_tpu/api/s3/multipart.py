"""Multipart uploads (reference src/api/s3/multipart.rs).

Create/UploadPart/Complete/Abort/ListParts/ListMultipartUploads.  Each
part gets its own Version entry whose blocks are written with the normal
bounded pipeline; Complete assembles a final Version referencing every
kept part's blocks as [part_number, offset] keys, inserts fresh block
refs for it, then tombstones the part versions (stale re-uploads
included) — refcounts make the handoff safe.
"""

from __future__ import annotations

import asyncio
import hashlib
import xml.etree.ElementTree as ET

from aiohttp import web

from ...model.s3.block_ref_table import BlockRef
from ...model.s3.mpu_table import MultipartUpload
from ...model.s3.object_table import Object, ObjectVersion
from ...model.s3.version_table import Version
from ...utils.data import blake2sum, gen_uuid
from ...utils.latency import mark_op, phase_span
from ...utils.time_util import now_msec
from ..common.error import ApiError, BadRequest, NoSuchKey, NoSuchUpload
from .objects import PUT_BLOCKS_MAX_PARALLEL, _check_sha256, extract_meta_headers
from .xml_util import xml_doc


async def _gather_chunked(coros, window: int = 64) -> list:
    """Await independent metadata ops in bounded concurrent windows: one
    round-trip per window instead of one per op, without letting a
    1000-part complete flood the RPC layer all at once."""
    out: list = []
    for i in range(0, len(coros), window):
        try:
            # return_exceptions: the whole window DRAINS before a
            # failure re-raises — a plain gather would return on the
            # first error while its sibling tasks keep mutating the
            # metadata tables behind the handler's 500
            res = await asyncio.gather(
                *coros[i : i + window], return_exceptions=True
            )
        except BaseException:
            # caller cancelled: gather already cancelled the window
            for c in coros[i + window :]:
                c.close()  # never-awaited coroutines would warn at GC
            raise
        err = next((r for r in res if isinstance(r, BaseException)), None)
        if err is not None:
            for c in coros[i + window :]:
                c.close()
            raise err
        out.extend(res)
    return out


async def handle_create_multipart_upload(garage, bucket_id, key, request):
    from .encryption import EncryptionParams

    from .objects import next_timestamp

    enc = EncryptionParams.from_headers(request.headers)
    upload_id = gen_uuid()
    headers = extract_meta_headers(request)
    existing = await garage.object_table.get(bucket_id, key.encode())
    mpu = MultipartUpload(
        upload_id, bucket_id, key, timestamp=next_timestamp(existing),
        enc=enc.meta() if enc else None, hdrs=headers,
    )
    await garage.mpu_table.insert(mpu)
    # an uploading object version marks the in-flight upload in listings
    ov = ObjectVersion(
        upload_id, mpu.timestamp, "uploading",
        {"t": "first_block", "vid": upload_id, "mpu": True, "hdrs": headers},
    )
    await garage.object_table.insert(Object(bucket_id, key, [ov]))
    return web.Response(
        text=xml_doc(
            "InitiateMultipartUploadResult",
            [("Bucket", ""), ("Key", key), ("UploadId", upload_id.hex())],
        ),
        content_type="application/xml",
    )


async def _get_mpu(garage, bucket_id, key, upload_id_hex) -> MultipartUpload:
    try:
        upload_id = bytes.fromhex(upload_id_hex)
        assert len(upload_id) == 32
    except (ValueError, AssertionError) as e:
        raise NoSuchUpload(f"malformed upload id") from e
    mpu = await garage.mpu_table.get(upload_id, b"")
    if mpu is None or mpu.deleted.get() or mpu.bucket_id != bucket_id or mpu.key != key:
        raise NoSuchUpload("upload not found")
    return mpu


async def handle_upload_part(garage, bucket_id, key, request, ctx=None):
    mark_op("upload_part")
    q = request.query
    part_number = int(q.get("partNumber", "0"))
    if not (1 <= part_number <= 10000):
        raise BadRequest("partNumber must be in 1..10000")
    with phase_span("index_read"):
        mpu = await _get_mpu(garage, bucket_id, key, q.get("uploadId", ""))

    from ..common.checksum import ChecksumRequest
    from .encryption import EncryptionParams, check_match

    enc = EncryptionParams.from_headers(request.headers)
    check_match(mpu.enc, enc)  # SSE-C fixed at create; parts must match
    cks = ChecksumRequest.from_headers(request.headers)

    vid = gen_uuid()  # this part's own version
    with phase_span("meta_commit"):
        await garage.version_table.insert(Version(vid, bucket_id, key))
    from .objects import stream_blocks

    try:
        md5_hex, sha, total, _blocks = await stream_blocks(
            garage, vid, bucket_id, key, part_number,
            request.content, garage.config.block_size,
            transform=enc.encrypt_block if enc else None, extra_hash=cks,
        )
        _check_sha256(ctx, sha)
        if cks is not None:
            cks.verify()
    except BaseException:
        await garage.version_table.insert(
            Version.deleted_marker(vid, bucket_id, key)
        )
        raise

    etag = md5_hex
    upd = MultipartUpload(mpu.upload_id, bucket_id, key, timestamp=mpu.timestamp)
    upd.parts.put([part_number, now_msec()], {"vid": vid, "etag": etag, "s": total})
    with phase_span("meta_commit"):
        await garage.mpu_table.insert(upd)
    return web.Response(status=200, headers={"ETag": f'"{etag}"'})


class _GenBody:
    """Adapts an async chunk generator to the .read(n) body interface the
    stream_blocks pipeline consumes."""

    def __init__(self, gen):
        self._gen = gen
        self._buf = b""

    async def read(self, n: int) -> bytes:
        while len(self._buf) < n:
            try:
                self._buf += await self._gen.__anext__()
            except StopAsyncIteration:
                break
        out, self._buf = self._buf[:n], self._buf[n:]
        return out


def _parse_copy_source_range(request, size: int) -> tuple[int, int]:
    """x-amz-copy-source-range: "bytes=a-b" (both bounds inclusive and
    required, unlike a GET Range)."""
    hdr = request.headers.get("x-amz-copy-source-range")
    if hdr is None:
        return (0, size)
    if not hdr.startswith("bytes="):
        raise BadRequest(f"bad x-amz-copy-source-range {hdr!r}")
    a_s, _, b_s = hdr[len("bytes="):].partition("-")
    try:
        a, b = int(a_s), int(b_s)
    except ValueError as e:
        raise BadRequest(f"bad x-amz-copy-source-range {hdr!r}") from e
    if a > b or b >= size:
        raise ApiError(
            f"copy source range {hdr!r} outside object of size {size}",
            code="InvalidRange",
            status=416,
        )
    return (a, b + 1)


async def handle_upload_part_copy(
    garage, helper, api_key, bucket_id, key, request, ctx=None
):
    """UploadPartCopy (reference src/api/s3/copy.rs:353
    handle_upload_part_copy): read the source object (decrypting SSE-C
    with the x-amz-copy-source-…-customer-* key when present), re-chunk
    the plaintext at this cluster's block size, and store it as a part of
    the destination upload under the destination's own encryption — the
    cross-encryption path re-seals every block."""
    q = request.query
    part_number = int(q.get("partNumber", "0"))
    if not (1 <= part_number <= 10000):
        raise BadRequest("partNumber must be in 1..10000")
    mpu = await _get_mpu(garage, bucket_id, key, q.get("uploadId", ""))

    from .copy_delete import resolve_copy_source
    from .encryption import EncryptionParams, check_match
    from .objects import plain_block_stream, stream_blocks

    dst_enc = EncryptionParams.from_headers(request.headers)
    check_match(mpu.enc, dst_enc)
    sv = await resolve_copy_source(garage, helper, api_key, request)
    src_meta = sv.data.get("meta", {})
    src_enc = EncryptionParams.from_copy_source_headers(request.headers)
    check_match(src_meta.get("enc"), src_enc)
    size = src_meta.get("size", 0)
    start, end = _parse_copy_source_range(request, size)

    if sv.data.get("t") == "inline":
        data = sv.data["bytes"]
        if src_enc is not None:
            data = src_enc.decrypt_block(data)

        async def _one():
            yield data[start:end]

        body = _GenBody(_one())
    else:
        src_ver = await garage.version_table.get(bytes(sv.data["vid"]), b"")
        if src_ver is None or src_ver.deleted.get():
            raise NoSuchKey("copy source data missing")
        body = _GenBody(
            plain_block_stream(garage, src_ver.sorted_blocks(), start, end, src_enc)
        )

    vid = gen_uuid()
    await garage.version_table.insert(Version(vid, bucket_id, key))
    try:
        md5_hex, _sha, total, _blocks = await stream_blocks(
            garage, vid, bucket_id, key, part_number,
            body, garage.config.block_size,
            transform=dst_enc.encrypt_block if dst_enc else None,
        )
    except BaseException:
        await garage.version_table.insert(
            Version.deleted_marker(vid, bucket_id, key)
        )
        raise

    etag = md5_hex
    ts = now_msec()
    upd = MultipartUpload(mpu.upload_id, bucket_id, key, timestamp=mpu.timestamp)
    upd.parts.put([part_number, ts], {"vid": vid, "etag": etag, "s": total})
    await garage.mpu_table.insert(upd)
    from .xml_util import http_iso

    return web.Response(
        text=xml_doc(
            "CopyPartResult",
            [("LastModified", http_iso(ts)), ("ETag", f'"{etag}"')],
        ),
        content_type="application/xml",
    )


async def handle_complete_multipart_upload(garage, bucket_id, key, request, ctx=None):
    body = await request.read()
    from ..common.signature import check_payload

    await check_payload(body, ctx) if ctx else None
    mpu = await _get_mpu(garage, bucket_id, key, request.query.get("uploadId", ""))
    try:
        root = ET.fromstring(body.decode())
        req_parts = []
        for p in root.iter():
            if p.tag.endswith("Part"):
                pn = etag = None
                for c in p:
                    if c.tag.endswith("PartNumber"):
                        pn = int(c.text)
                    elif c.tag.endswith("ETag"):
                        etag = c.text.strip().strip('"')
                req_parts.append((pn, etag))
    except ET.ParseError as e:
        raise BadRequest(f"malformed CompleteMultipartUpload XML: {e}") from e
    if not req_parts:
        raise BadRequest("no parts in CompleteMultipartUpload")
    # strictly increasing (reference multipart.rs InvalidPartOrder): a
    # duplicated PartNumber would be assembled once but double-counted in
    # size/ETag-part-count metadata
    pns = [p for p, _ in req_parts]
    if any(p1 >= p2 for p1, p2 in zip(pns, pns[1:])):
        raise BadRequest(
            "parts must be listed in strictly ascending order",
            code="InvalidPartOrder",
        )

    have = mpu.latest_parts()
    for pn, etag in req_parts:
        if pn not in have or have[pn]["etag"] != etag:
            raise ApiError("part missing or etag mismatch", code="InvalidPart", status=400)

    # assemble the final version from the kept parts' blocks.  The part
    # versions are independent rows: fetch them in one concurrent window
    # instead of one quorum read per part (a 1000-part complete used to
    # pay 1000 sequential round-trips here).
    final = Version(mpu.upload_id, bucket_id, key)
    total = 0
    etags_md5 = hashlib.md5()
    kept_vids = []
    part_versions = await _gather_chunked(
        [
            garage.version_table.get(bytes(have[pn]["vid"]), b"")
            for pn, _etag in req_parts
        ]
    )
    for (pn, _etag), pv in zip(req_parts, part_versions):
        part = have[pn]
        kept_vids.append(bytes(part["vid"]))
        etags_md5.update(bytes.fromhex(part["etag"]))
        if pv is None or pv.deleted.get():
            raise ApiError("part data lost", code="InvalidPart", status=400)
        for (p_pn, off), blk in pv.sorted_blocks():
            final.blocks.put([pn, off], {"h": blk["h"], "s": blk["s"]})
            total += blk["s"]
            if mpu.enc is not None:
                from .encryption import OVERHEAD

                total -= OVERHEAD  # meta size is plaintext
    await garage.version_table.insert(final)
    # fresh refs for the final version BEFORE tombstoning part versions
    # (same ordering guarantee as the sequential loop — every ref commit
    # completes before any tombstone below is issued)
    await _gather_chunked(
        [
            garage.block_ref_table.insert(BlockRef(blk["h"], final.uuid))
            for _k, blk in final.sorted_blocks()
        ]
    )
    etag = f"{etags_md5.hexdigest()}-{len(req_parts)}"
    # metadata captured at CreateMultipartUpload lives on the mpu row
    # (the uploading marker version can be pruned by a concurrent
    # complete PutObject; upgrade path: fall back to the marker for
    # uploads created before hdrs moved here)
    hdrs = [list(h) for h in mpu.hdrs] if mpu.hdrs else []
    if not hdrs:
        obj = await garage.object_table.get(bucket_id, key.encode())
        if obj is not None:
            for v in obj.versions:
                if bytes(v.uuid) == bytes(mpu.upload_id):
                    hdrs = [list(h) for h in v.data.get("hdrs", [])]
                    break
    meta = {"size": total, "etag": etag, "headers": hdrs}
    if mpu.enc is not None:
        meta["enc"] = mpu.enc
    ov = ObjectVersion(
        mpu.upload_id,
        mpu.timestamp,
        "complete",
        {"t": "first_block", "vid": final.uuid, "meta": meta},
    )
    await garage.object_table.insert(Object(bucket_id, key, [ov]))
    # warm the metadata fast path with the assembled final version (the
    # exact row quorum-committed above) — the next GET skips the
    # version quorum read
    garage.version_cache.put(final.uuid, final)
    # tombstone part versions (incl. stale re-uploads) and close the mpu
    await _gather_chunked(
        [
            garage.version_table.insert(
                Version.deleted_marker(bytes(v["vid"]), bucket_id, key)
            )
            for _k, v in mpu.parts.items()
            if bytes(v["vid"]) != final.uuid
        ]
    )
    closed = MultipartUpload(mpu.upload_id, bucket_id, key, timestamp=mpu.timestamp)
    closed.deleted.set()
    await garage.mpu_table.insert(closed)
    return web.Response(
        text=xml_doc(
            "CompleteMultipartUploadResult",
            [("Bucket", ""), ("Key", key), ("ETag", f'"{etag}"')],
        ),
        content_type="application/xml",
    )


async def handle_abort_multipart_upload(garage, bucket_id, key, request):
    mpu = await _get_mpu(garage, bucket_id, key, request.query.get("uploadId", ""))
    closed = MultipartUpload(mpu.upload_id, bucket_id, key, timestamp=mpu.timestamp)
    closed.deleted.set()
    await garage.mpu_table.insert(closed)  # cascade deletes part versions
    aborted = ObjectVersion(
        mpu.upload_id, mpu.timestamp, "aborted", {"t": "first_block", "vid": mpu.upload_id}
    )
    await garage.object_table.insert(Object(bucket_id, key, [aborted]))
    return web.Response(status=204)


async def handle_list_parts(garage, bucket_id, key, request):
    mpu = await _get_mpu(garage, bucket_id, key, request.query.get("uploadId", ""))
    # pagination (reference list.rs ListParts state machine):
    # part-number-marker is exclusive, max-parts caps the page
    q = request.query
    max_parts = max(1, min(int(q.get("max-parts", "1000")), 1000))
    marker = int(q.get("part-number-marker", "0"))
    parts = mpu.latest_parts()
    pns = [pn for pn in sorted(parts) if pn > marker]
    page, rest = pns[:max_parts], pns[max_parts:]
    children = [
        ("Bucket", ""),
        ("Key", key),
        ("UploadId", mpu.upload_id.hex()),
        ("StorageClass", "STANDARD"),
        ("MaxParts", max_parts),
        ("PartNumberMarker", marker) if marker else None,
        ("IsTruncated", bool(rest)),
    ]
    if rest:
        children.append(("NextPartNumberMarker", page[-1]))
    for pn in page:
        p = parts[pn]
        children.append(
            (
                "Part",
                [
                    ("PartNumber", pn),
                    ("ETag", f'"{p["etag"]}"'),
                    ("Size", p["s"]),
                ],
            )
        )
    return web.Response(
        text=xml_doc("ListPartsResult", children), content_type="application/xml"
    )


async def handle_list_multipart_uploads(garage, bucket_id, bucket_name, request):
    """In-flight uploads = objects holding an uploading mpu version.
    One paginated pass over (key, upload_id) with prefix/delimiter folding
    (reference list.rs ListMultipartUploads state machine); the object
    table is scanned only as far as the page needs."""
    q = request.query
    prefix = q.get("prefix", "")
    delimiter = q.get("delimiter", "")
    max_uploads = max(1, min(int(q.get("max-uploads", "1000")), 1000))
    key_marker = q.get("key-marker", "")
    uid_marker = q.get("upload-id-marker", "")

    uploads: list[tuple[str, str]] = []
    prefixes: list[str] = []
    # the last entry emitted IN SORT ORDER — uploads and prefixes
    # interleave, so the continuation marker must track both kinds
    last_emitted: tuple[str, str | None] | None = None
    truncated = False

    def page_full() -> bool:
        return len(uploads) + len(prefixes) >= max_uploads

    cursor = max(key_marker, prefix).encode() if (key_marker or prefix) else None
    done = False
    while not done:
        objs = await garage.object_table.get_range(bucket_id, cursor, None, 1000)
        if not objs:
            break
        for o in objs:
            k = o.key
            if prefix and not k.startswith(prefix):
                if k > prefix:
                    done = True
                    break
                continue
            pairs = sorted(
                (k, v.uuid.hex())
                for v in o.versions
                if v.state == "uploading" and v.data.get("mpu")
            )
            for k, uid in pairs:
                # markers: exclusive on key alone, or on (key, upload_id)
                # when an upload-id-marker narrows within the key
                if uid_marker:
                    if k < key_marker or (k == key_marker and uid <= uid_marker):
                        continue
                elif key_marker and k <= key_marker:
                    continue
                if delimiter and delimiter in k[len(prefix):]:
                    cp = prefix + k[len(prefix):].split(delimiter)[0] + delimiter
                    # a CommonPrefix consumes its whole group
                    if cp <= key_marker or (prefixes and prefixes[-1] == cp):
                        continue
                    if page_full():
                        truncated, done = True, True
                        break
                    prefixes.append(cp)
                    last_emitted = (cp, None)
                    continue
                if page_full():
                    truncated, done = True, True
                    break
                uploads.append((k, uid))
                last_emitted = (k, uid)
            if done:
                break
        else:
            if len(objs) < 1000:
                break
            cursor = objs[-1].key.encode() + b"\x00"
            continue
        break

    children = [
        ("Bucket", bucket_name),
        ("Prefix", prefix),
        ("Delimiter", delimiter) if delimiter else None,
        ("KeyMarker", key_marker) if key_marker else None,
        ("UploadIdMarker", uid_marker) if uid_marker else None,
        ("MaxUploads", max_uploads),
        ("IsTruncated", truncated),
    ]
    if truncated and last_emitted is not None:
        children.append(("NextKeyMarker", last_emitted[0]))
        if last_emitted[1] is not None:
            children.append(("NextUploadIdMarker", last_emitted[1]))
    for k, uid in uploads:
        children.append(
            (
                "Upload",
                [
                    ("Key", k),
                    ("UploadId", uid),
                    ("StorageClass", "STANDARD"),
                ],
            )
        )
    for cp in prefixes:
        children.append(("CommonPrefixes", [("Prefix", cp)]))
    return web.Response(
        text=xml_doc("ListMultipartUploadsResult", children),
        content_type="application/xml",
    )