"""Admin REST API (reference src/api/admin/api_server.rs).

  GET /health            no auth: cluster health summary (for LBs)
  GET /metrics           Prometheus text (metrics_token bearer auth)
  GET /v1/status         cluster status
  GET /v1/layout  POST /v1/layout  POST /v1/layout/apply|revert
  GET/POST /v1/bucket[?id=..]  GET/POST /v1/key[?id=..]
  POST /v1/bucket/allow|deny

Bearer-token auth with admin_token (metrics_token for /metrics only).
"""

from __future__ import annotations

import json
import logging

from aiohttp import web

from ...rpc.layout.types import NodeRole
from ...utils.data import hex_of

logger = logging.getLogger("garage.api.admin")


class AdminApiServer:
    def __init__(self, garage):
        self.garage = garage
        self.admin_token = garage.config.admin.admin_token
        self.metrics_token = garage.config.admin.metrics_token
        self.app = web.Application()
        self.app.router.add_route("*", "/{tail:.*}", self._entry)
        self.runner: web.AppRunner | None = None

    async def start(self, host: str, port: int) -> None:
        self.runner = web.AppRunner(self.app, access_log=None)
        await self.runner.setup()
        site = web.TCPSite(self.runner, host, port)
        await site.start()
        logger.info("admin api listening on %s:%d", host, port)

    async def stop(self) -> None:
        if self.runner:
            await self.runner.cleanup()

    def _check_token(self, request, token: str | None) -> bool:
        if token is None:
            return False
        import hmac

        auth = request.headers.get("Authorization", "")
        return hmac.compare_digest(auth, f"Bearer {token}")

    async def _entry(self, request: web.Request) -> web.Response:
        path = request.path
        try:
            if path == "/health":
                return self._health()
            if path == "/check":
                # reverse-proxy hook (e.g. on-demand TLS): is this domain
                # served by the cluster?  (reference api_server.rs:79-137)
                domain = request.query.get("domain")
                if not domain:
                    return web.Response(status=400, text="no domain query")
                if await self._check_domain(domain):
                    return web.Response(
                        text=f"Domain '{domain}' is managed by garage-tpu"
                    )
                return web.Response(
                    status=400,
                    text=f"Domain '{domain}' is not managed by garage-tpu",
                )
            if path in ("/metrics", "/metrics/cluster"):
                if self.metrics_token and not (
                    self._check_token(request, self.metrics_token)
                    or self._check_token(request, self.admin_token)
                ):
                    return web.Response(status=403, text="forbidden")
                if path == "/metrics/cluster":
                    # federated exposition of the gossiped telemetry
                    # digests: one scrape of ANY node covers the cluster
                    # (rpc/telemetry_digest.py)
                    from ...rpc.telemetry_digest import render_cluster_metrics

                    return web.Response(
                        text=render_cluster_metrics(self.garage),
                        content_type="text/plain",
                    )
                return self._metrics()
            if not self._check_token(request, self.admin_token):
                return web.Response(status=403, text="forbidden")
            if path.startswith("/v0/"):
                # legacy v0 admin router: same operations, same handlers
                # (reference router_v0.rs delegates to the v1 handlers
                # the same way)
                path = "/v1/" + path[len("/v0/"):]
            return await self._v1(request, path)
        except Exception as e:  # noqa: BLE001
            logger.exception("admin api error")
            return web.json_response({"error": repr(e)}, status=500)

    # --- public endpoints -----------------------------------------------------

    async def _check_domain(self, domain: str) -> bool:
        """Domain -> bucket: under the S3 root_domain any existing bucket
        counts; under the web root_domain (or as a bare vhost) the bucket
        must have website access enabled (reference api_server.rs:116-137)."""
        from ...utils.error import Error

        g = self.garage

        def strip(rd: str | None) -> str | None:
            # label-boundary match, leading dot optional in the config —
            # same normalization as the S3/web vhost routing
            if not rd:
                return None
            rd = rd.lstrip(".")
            if domain.endswith("." + rd) and len(domain) > len(rd) + 1:
                return domain[: -(len(rd) + 1)]
            return None

        bname = strip(g.config.s3_api.root_domain)
        must_website = False
        if bname is None:
            bname = strip(g.config.s3_web.root_domain)
            must_website = True
            if bname is None:
                bname = domain  # vhost-style: the domain IS the bucket name
        try:
            bucket = await g.helper.get_bucket(
                await g.helper.resolve_bucket(bname)
            )
        except Error:
            return False
        if must_website:
            return bucket.params().website.get() is not None
        return True

    def _health(self) -> web.Response:
        h = self.garage.system.health()
        status = 200 if h.status in ("healthy", "degraded") else 503
        return web.json_response(h.__dict__, status=status)

    def _metrics(self) -> web.Response:
        """Prometheus exposition (metric families per layer, reference
        doc/book/reference-manual/monitoring.md).

        Only families the registry does NOT own are rendered inline; the
        resync/merkle/gc queue lengths and `cluster_connected_nodes` come
        exclusively from the registry gauges (model/garage.py), and
        per-worker health from the runner's `worker_*` families
        (utils/background.py) — emitting them here too was a strict
        exposition-format violation (duplicate families), caught by the
        metrics-lint test."""
        g = self.garage
        h = g.system.health()
        lines = []

        def m(name, value, help_=""):
            if help_:
                lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name} {value}")

        m("cluster_healthy", 1 if h.status == "healthy" else 0, "cluster health")
        m("cluster_known_nodes", h.known_nodes)
        m("cluster_storage_nodes", h.storage_nodes)
        m("cluster_storage_nodes_up", h.storage_nodes_up)
        m("cluster_partitions_quorum", h.partitions_quorum)
        m("cluster_partitions_all_ok", h.partitions_all_ok)
        m(
            "cluster_outlier_nodes", len(h.outlier_nodes),
            "nodes MAD-flagged as outliers (see /metrics/cluster for which)",
        )
        m("cluster_layout_version", g.layout_manager.history.current().version)
        lines.append("# TYPE table_size gauge")
        for t in g.tables:
            n = t.schema.table_name
            lines.append(f'table_size{{table_name="{n}"}} {len(t.data.store)}')
        m("block_rc_entries", len(g.block_manager.rc.tree))
        from ...utils.metrics import registry

        lines.extend(registry.render())
        return web.Response(text="\n".join(lines) + "\n", content_type="text/plain")

    # --- v1 admin -------------------------------------------------------------

    async def _v1(self, request, path) -> web.Response:
        g = self.garage
        if path == "/v1/status" and request.method == "GET":
            h = g.system.health()
            cur = g.layout_manager.history.current()
            ph = getattr(g, "peer_health", None)
            rpc_health = ph.snapshot() if ph is not None else {}
            nodes = []
            for nid in set(
                list(cur.roles.keys()) + [g.node_id] + list(g.system.peering.peers.keys())
            ):
                role = cur.roles.get(nid)
                nodes.append(
                    {
                        "id": hex_of(nid),
                        "role": {
                            "zone": role.zone,
                            "capacity": role.capacity,
                            "tags": role.tags,
                        }
                        if role
                        else None,
                        "isUp": nid == g.node_id or g.netapp.is_connected(nid),
                        # circuit-breaker / EWMA view of this peer from the
                        # answering node (rpc/peer_health.py); None for
                        # self and never-contacted peers
                        "rpcHealth": rpc_health.get(hex_of(nid)),
                    }
                )
            return web.json_response(
                {
                    "node": hex_of(g.node_id),
                    "garageVersion": "garage-tpu/0.1.0",
                    "layoutVersion": cur.version,
                    "health": h.__dict__,
                    "nodes": nodes,
                }
            )

        if path == "/v1/health" and request.method == "GET":
            # GetClusterHealth: standalone health JSON resource (reference
            # router_v1.rs:102, cluster.rs ClusterHealth struct) — same
            # payload /health serves LBs, but authenticated + always 200
            # so operators can read the *reason* a cluster is unavailable.
            # Field casing follows the reference admin API (camelCase,
            # cluster.rs ClusterHealth serde rename_all) like the sibling
            # /v1/node endpoint.
            h = g.system.health()
            return web.json_response(
                {
                    "status": h.status,
                    "knownNodes": h.known_nodes,
                    "connectedNodes": h.connected_nodes,
                    "storageNodes": h.storage_nodes,
                    "storageNodesOk": h.storage_nodes_up,
                    "partitions": h.partitions,
                    "partitionsQuorum": h.partitions_quorum,
                    "partitionsAllOk": h.partitions_all_ok,
                    "outlierNodes": h.outlier_nodes,
                }
            )

        if path == "/v1/cluster/telemetry" and request.method == "GET":
            # cluster telemetry rollup (rpc/telemetry_digest.py): per-node
            # digest rows + cluster aggregates + MAD outliers + SLO state,
            # assembled entirely from gossiped state — answering this
            # needs NO fan-out to the other nodes
            from ...rpc.telemetry_digest import rollup

            return web.json_response(rollup(g))

        if path == "/v1/cluster/durability" and request.method == "GET":
            # durability observatory (block/durability.py): redundancy
            # ledger classes, zone-loss exposure, repair ETA and layout
            # progress — per-node rows from the gossiped dur.* digest
            # keys plus the local ledger detail.  Zone NAMES live here
            # (JSON), never as metric labels.
            from ...block.durability import durability_response

            return web.json_response(durability_response(g))

        if path == "/v1/cluster/transition" and request.method == "GET":
            # rebalance observatory (rpc/transition.py): local transition
            # flight deck (partition states, per-pair bytes, throughput,
            # ETA, last report) + every node's gossiped lt.* digest +
            # cluster aggregate (version spread, stale nodes, worst
            # skew) — assembled from gossip, no fan-out needed
            from ...rpc.transition import transition_response

            return web.json_response(transition_response(g))

        if path == "/v1/cluster/events" and request.method == "GET":
            # federated event timeline (rpc/transition.py): fan out to
            # every connected peer's flight-event bank and merge into
            # one skew-corrected, causally-ordered timeline.
            # ?since=<epoch secs> and ?min_severity=info|warn|critical
            from ...rpc.transition import cluster_events_response

            return web.json_response(
                await cluster_events_response(
                    g,
                    since=float(request.query.get("since", 0) or 0),
                    min_severity=request.query.get("min_severity", "info"),
                )
            )

        if path == "/v1/cluster/tenants" and request.method == "GET":
            # tenant observatory (rpc/tenant.py): cluster-summed
            # per-tenant consumption + fairness stats + per-node rows
            # from the gossiped tn.* digest keys — tenant KEY IDS live
            # here (JSON), never as metric labels (cardinality guard)
            from ...rpc.tenant import tenants_response

            return web.json_response(tenants_response(g))

        if path == "/v1/codec" and request.method == "GET":
            # codec X-ray (ops/telemetry.py + rpc/telemetry_digest.py):
            # local per-kernel pad accounting, compile events, overlap
            # efficiency, batcher lane linger, plus the cluster view from
            # the gossiped codec.* digest keys — kernel/cache/lane
            # breakdowns live HERE (JSON), the exposition only carries
            # bounded label sets
            from ...rpc.telemetry_digest import codec_response

            return web.json_response(codec_response(g))

        if path == "/v1/traffic" and request.method == "GET":
            # traffic observatory (rpc/traffic.py): local hot-object /
            # hot-bucket top-K, op mix, size histogram, zipf skew, the
            # slow-peer piece-fetch ranking, and the cluster rollup from
            # the gossiped trf.* digest keys.  Per-key data lives HERE,
            # never as Prometheus series (cardinality guard).
            from ...rpc.traffic import traffic_response

            return web.json_response(traffic_response(g))

        if path == "/v1/traffic/profile" and request.method == "GET":
            # replayable workload profile: op mix + size distribution +
            # popularity skew + inter-arrival stats — the contract the
            # workload generator (ROADMAP item 5) consumes
            from ...rpc.traffic import profile_response

            return web.json_response(profile_response(g))

        if path == "/v1/debug/profile" and request.method == "GET":
            # flight recorder: on-demand sampling profiler (utils/flight.py).
            # Folded-stack text by default; ?format=speedscope for JSON.
            from ...utils import flight

            prof = await flight.profile(
                request.query.get("seconds", "2"),
                hz=request.query.get("hz", "100"),
            )
            if request.query.get("format") == "speedscope":
                return web.json_response(prof.speedscope())
            return web.Response(
                text=prof.folded(),
                content_type="text/plain",
                headers={"x-garage-profile-samples": str(prof.samples)},
            )

        if path == "/v1/debug/latency" and request.method == "GET":
            # latency X-ray (utils/latency.py): rolling per-op phase
            # waterfall — p50/p95/p99 per phase, critical-path share,
            # coverage, overlap efficiency
            from ...utils.latency import latency_response

            return web.json_response(latency_response())

        if path == "/v1/debug/slow" and request.method == "GET":
            # flight recorder: span trees of the slowest recent requests
            from ...utils import flight

            return web.json_response(
                flight.slow_response(getattr(g, "flight_recorder", None))
            )

        if path == "/v1/connect" and request.method == "POST":
            # ConnectClusterNodes (reference router_v1.rs:103,
            # cluster.rs:139-161): body = JSON array of "id@host:port";
            # response = per-node [{success, error}] in request order.
            body = await request.json()
            if not isinstance(body, list):
                return web.Response(status=400, text="expected a JSON array")
            results = []
            for node in body:
                try:
                    nid_hex, _, addr = str(node).partition("@")
                    host, _, port = addr.rpartition(":")
                    if not (nid_hex and host and port):
                        raise ValueError(f"malformed node address {node!r}")
                    await g.netapp.connect(
                        (host, int(port)), bytes.fromhex(nid_hex)
                    )
                    results.append({"success": True, "error": None})
                except Exception as e:  # noqa: BLE001 — per-node report
                    results.append({"success": False, "error": str(e)})
            return web.json_response(results)

        if path == "/v1/overload" and request.method == "GET":
            # overload-control plane (api/overload.py + rpc/shedding.py):
            # admission counters per tier, tenant token levels, ladder
            # level + applied rungs + hysteresis signals
            return web.json_response(g.overload_status())

        if path == "/v1/repair/plan" and request.method == "GET":
            # repair plane (block/repair_plan.py): plan state, backlog by
            # urgency class, progress counters, admission-control knobs
            return web.json_response(g.repair_plan_status())
        if path == "/v1/repair/plan/launch" and request.method == "POST":
            body = await request.json() if request.can_read_body else {}
            try:
                g.launch_repair_plan(fresh=bool(body.get("fresh")))
            except ValueError as e:
                # already running / replica codec: a client error, not a
                # server fault (mirrors the cancel endpoint's 400)
                return web.json_response({"error": str(e)}, status=400)
            return web.json_response(g.repair_plan_status())
        if path == "/v1/repair/plan/cancel" and request.method == "POST":
            p = g.repair_planner
            if p is None or p.finished:
                return web.json_response(
                    {"cancelled": False, "error": "no repair plan running"},
                    status=400,
                )
            p.cmd_cancel()
            return web.json_response({"cancelled": True})

        if path == "/v1/node" and request.method == "GET":
            # GetNodeInfo: the node answering the request (not the
            # cluster): identity, version, engine, data/metadata dirs.
            import sys as _sys

            return web.json_response(
                {
                    "nodeId": hex_of(g.node_id),
                    "garageVersion": "garage-tpu/0.1.0",
                    "garageFeatures": ["k2v", "erasure-coding", "tpu"],
                    "pythonVersion": _sys.version.split()[0],
                    "dbEngine": g.config.db_engine,
                    "metadataDir": g.config.metadata_dir,
                    "dataDirs": [d.path for d in g.config.data_dir],
                }
            )

        if path == "/v1/layout":
            if request.method == "GET":
                lay = g.layout_manager.history
                cur = lay.current()
                return web.json_response(
                    {
                        "version": cur.version,
                        "roles": [
                            {
                                "id": hex_of(n),
                                "zone": r.zone,
                                "capacity": r.capacity,
                                "tags": r.tags,
                            }
                            for n, r in cur.roles.items()
                        ],
                        "stagedRoleChanges": [
                            {"id": hex_of(bytes(k)), "role": v}
                            for k, v in lay.staging.roles.items()
                        ],
                    }
                )
            if request.method == "POST":
                body = await request.json()
                for change in body:
                    nid = bytes.fromhex(change["id"])
                    if change.get("remove"):
                        g.layout_manager.stage_role(nid, None)
                    else:
                        g.layout_manager.stage_role(
                            nid,
                            NodeRole(
                                zone=change["zone"],
                                capacity=change.get("capacity"),
                                tags=change.get("tags", []),
                            ),
                        )
                return web.json_response({"staged": len(body)})

        if path == "/v1/layout/apply" and request.method == "POST":
            body = await request.json() if request.can_read_body else {}
            lv, report = g.layout_manager.apply_staged(body.get("version"))
            warn = g.ec_layout_warning(lv)
            if warn:
                report = list(report) + [warn]
            return web.json_response({"version": lv.version, "report": report})
        if path == "/v1/layout/revert" and request.method == "POST":
            g.layout_manager.revert_staged()
            return web.json_response({"ok": True})

        if path == "/v1/bucket":
            if request.method == "GET":
                if "id" in request.query or "globalAlias" in request.query:
                    if "id" in request.query:
                        bid = bytes.fromhex(request.query["id"])
                    else:
                        bid = await g.helper.resolve_bucket(
                            request.query["globalAlias"]
                        )
                    return web.json_response(await self._bucket_info(bid))
                out = []
                for b in await g.helper.list_buckets():
                    out.append(
                        {
                            "id": hex_of(b.id),
                            "globalAliases": [
                                n for n, v in b.params().aliases.items() if v
                            ],
                        }
                    )
                return web.json_response(out)
            if request.method == "POST":
                body = await request.json()
                bid = await g.helper.create_bucket(body["globalAlias"])
                if body.get("localAlias"):
                    la = body["localAlias"]
                    await g.helper.set_local_alias(
                        bid, la["accessKeyId"], la["alias"]
                    )
                    if la.get("allow"):
                        perms = la["allow"]
                        await g.helper.set_bucket_key_permissions(
                            bid, la["accessKeyId"],
                            perms.get("read", False),
                            perms.get("write", False),
                            perms.get("owner", False),
                        )
                return web.json_response(await self._bucket_info(bid))
            if request.method == "PUT":
                # UpdateBucket (reference api/admin/bucket.rs
                # handle_update_bucket): website access + quotas
                bid = bytes.fromhex(request.query["id"])
                body = await request.json()
                b = await g.helper.get_bucket(bid)
                p = b.params()
                if "websiteAccess" in body:
                    wa = body["websiteAccess"]
                    if wa.get("enabled"):
                        p.website.update(
                            {
                                "index_document": wa.get("indexDocument", "index.html"),
                                "error_document": wa.get("errorDocument"),
                            }
                        )
                    else:
                        p.website.update(None)
                if "quotas" in body:
                    q = body["quotas"]
                    p.quotas.update(
                        {
                            "max_size": q.get("maxSize"),
                            "max_objects": q.get("maxObjects"),
                        }
                    )
                await g.bucket_table.insert(b)
                return web.json_response(await self._bucket_info(bid))
            if request.method == "DELETE":
                await g.helper.delete_bucket(bytes.fromhex(request.query["id"]))
                return web.json_response({"ok": True})

        if path in (
            "/v1/bucket/alias/global", "/v1/bucket/alias/local"
        ) and request.method in ("PUT", "DELETE"):
            q = request.query
            bid = bytes.fromhex(q["id"])
            alias = q["alias"]
            if path.endswith("global"):
                if request.method == "PUT":
                    await g.helper.set_global_alias(bid, alias)
                else:
                    await g.helper.unset_global_alias(bid, alias)
            else:
                if request.method == "PUT":
                    await g.helper.set_local_alias(bid, q["accessKeyId"], alias)
                else:
                    await g.helper.unset_local_alias(bid, q["accessKeyId"], alias)
            return web.json_response(await self._bucket_info(bid))

        if path in ("/v1/bucket/allow", "/v1/bucket/deny") and request.method == "POST":
            body = await request.json()
            perms = body.get("permissions", {})
            allow = path.endswith("allow")
            await g.helper.set_bucket_key_permissions(
                bytes.fromhex(body["bucketId"]),
                body["accessKeyId"],
                allow and perms.get("read", False),
                allow and perms.get("write", False),
                allow and perms.get("owner", False),
            )
            return web.json_response({"ok": True})

        if path == "/v1/key":
            if request.method == "GET":
                if "id" in request.query or "search" in request.query:
                    if "id" in request.query:
                        k = await g.helper.get_key(request.query["id"])
                    else:
                        pat = request.query["search"]
                        matches = [
                            k
                            for k in await g.helper.list_keys()
                            if k.key_id.startswith(pat)
                            or pat.lower() in (k.params().name.get() or "").lower()
                        ]
                        if len(matches) != 1:
                            return web.json_response(
                                {"error": f"{len(matches)} keys match"}, status=400
                            )
                        k = matches[0]
                    return web.json_response(
                        self._key_info(
                            k, request.query.get("showSecretKey") == "true"
                        )
                    )
                return web.json_response(
                    [
                        {"id": k.key_id, "name": k.params().name.get()}
                        for k in await g.helper.list_keys()
                    ]
                )
            if request.method == "POST":
                body = await request.json() if request.can_read_body else {}
                if "id" in request.query:
                    # UpdateKey (reference api/admin/key.rs handle_update_key)
                    k = await g.helper.update_key(
                        request.query["id"],
                        name=body.get("name"),
                        allow_create_bucket=(body.get("allow") or {}).get(
                            "createBucket"
                        )
                        if "allow" in body
                        else (
                            False
                            if (body.get("deny") or {}).get("createBucket")
                            else None
                        ),
                    )
                else:
                    k = await g.helper.create_key(body.get("name", ""))
                return web.json_response(self._key_info(k, True))
            if request.method == "DELETE":
                await g.helper.delete_key(request.query["id"])
                return web.json_response({"ok": True})

        if path == "/v1/key/import" and request.method == "POST":
            body = await request.json()
            k = await g.helper.import_key(
                body["accessKeyId"], body["secretAccessKey"], body.get("name", "")
            )
            return web.json_response(self._key_info(k, False))

        return web.json_response({"error": "no such endpoint"}, status=404)

    async def _bucket_info(self, bid: bytes) -> dict:
        """Full GetBucketInfo shape (reference api/admin/bucket.rs):
        aliases, per-key permissions, website/quotas, usage counters."""
        g = self.garage
        b = await g.helper.get_bucket(bid)
        p = b.params()
        keys = []
        for k in await g.helper.list_keys():
            kp = k.params()
            perm = k.bucket_permissions(bid)
            local = [
                n
                for n, v in kp.local_aliases.items()
                if v is not None and bytes(v) == bid
            ]
            if perm.allow_read or perm.allow_write or perm.allow_owner or local:
                keys.append(
                    {
                        "accessKeyId": k.key_id,
                        "name": kp.name.get(),
                        "permissions": {
                            "read": perm.allow_read,
                            "write": perm.allow_write,
                            "owner": perm.allow_owner,
                        },
                        "bucketLocalAliases": local,
                    }
                )
        counts = await g.object_counter.get_values(bid)
        website = p.website.get()
        quotas = p.quotas.get() or {}
        return {
            "id": hex_of(bid),
            "globalAliases": [n for n, v in p.aliases.items() if v],
            "websiteAccess": website is not None,
            "websiteConfig": website,
            "keys": keys,
            "objects": counts.get("objects", 0),
            "bytes": counts.get("bytes", 0),
            "unfinishedUploads": counts.get("unfinished_uploads", 0),
            "quotas": {
                "maxSize": quotas.get("max_size"),
                "maxObjects": quotas.get("max_objects"),
            },
        }

    def _key_info(self, k, show_secret: bool) -> dict:
        kp = k.params()
        return {
            "accessKeyId": k.key_id,
            "name": kp.name.get(),
            "secretAccessKey": k.secret() if show_secret else None,
            "permissions": {"createBucket": bool(kp.allow_create_bucket.get())},
            "buckets": [
                {
                    "id": hex_of(bytes(b)),
                    "permissions": perm,
                }
                for b, perm in kp.authorized_buckets.items()
            ],
        }
