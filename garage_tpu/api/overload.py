"""Overload-control plane, admission side: per-tenant token buckets,
a global in-flight cap, and priority classes in front of the S3
frontend.

PRs 5-6 built the sensors (SLO burn-rate gauges, latency X-ray, canary
probing); this module is the *actuator* on the request path.  Every S3
request passes through `AdmissionController.admit()` at the single
`_entry` choke point (api/s3/api_server.py) BEFORE any SigV4 work:

  - priority classes: interactive GET/HEAD (tier 0) > PUT/multipart
    (tier 1) > list/batch (tier 2) > anonymous (tier 3) — the HTTP-level
    mirror of the RPC frame priorities (net/message.py PRIO_*);
  - per-key and per-bucket token buckets (tenant isolation: one noisy
    key drains its own bucket, not the node);
  - a global in-flight concurrency cap (the knob that actually bounds
    memory/event-loop pressure under a burst);
  - queue-rather-than-reject for the TOP tier only: an interactive GET
    waits a bounded `queue_wait_msec` for capacity before shedding —
    every other tier sheds immediately (its work is retryable by
    design);
  - over-limit requests receive the S3-semantic `503 SlowDown` with a
    `Retry-After` hint (the response every AWS SDK backs off on).

Shed requests never enter `request_metrics` — they are counted in their
own `api_admission_shed_total{tier}` family and deliberately do NOT
increment `api_s3_request_counter` / `api_s3_error_counter`.  An
intentional shed must not burn the availability SLO budget: the
shedding controller (rpc/shedding.py) reads that budget, and counting
its own 503s against it would close a positive feedback loop (shed ->
more 5xx -> higher burn -> shed harder).

Admission happens before signature verification, so tenant identity is
the *claimed* key id parsed from the Authorization header.  A client
spoofing another tenant's key id can at worst drain that tenant's
token bucket (fairness accounting), never gain access — it still fails
SigV4 afterwards, and the global in-flight cap bounds the damage.

The canary prober's key is EXEMPT (registered by api/s3/canary.py at
client setup): shedding must not blind the exact probe signal the
shedding controller needs to decide when to recover.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import math
import re
import time
from collections import OrderedDict
from typing import Any

from ..utils.metrics import registry as global_registry

logger = logging.getLogger("garage.overload")

# priority classes, best (never ladder-shed, may queue) first
TIER_INTERACTIVE = 0  # authenticated object GET / HEAD
TIER_WRITE = 1  # PUT / POST / DELETE objects, multipart legs
TIER_LIST = 2  # listings, batch ops, bucket-config reads
TIER_ANON = 3  # no credential at all (incl. PostObject form uploads)
TIER_NAMES = ("interactive", "write", "list", "anonymous")

# claimed tenant identity, pre-auth: SigV4 header or presigned query
_CRED_RE = re.compile(r"Credential=([^/,\s]+)/")

# bounded queue poll quantum: waiters re-check capacity at this cadence
# (pure polling — _release() deliberately does not wake waiters early)
_QUEUE_QUANTUM = 0.02

# exemption is claimed pre-auth (the canary's key id travels in
# cleartext Authorization headers, so it is NOT a secret): bound how
# many concurrent requests the claim can admit past the normal checks.
# The canary probes serially — 4 is generous for it, and a spoofer
# replaying the id buys at most this much concurrency before falling
# through to normal admission (where the spoofed id just drains the
# canary's own token bucket)
_EXEMPT_MAX_IN_FLIGHT = 4

# per-tenant gauges carry a process-unique id label: several in-process
# nodes share the global registry (PR 3 convention), and two controllers
# tracking the same key id must not overwrite / unregister each other
_ctl_ids = itertools.count(1)


class TokenBucket:
    """Classic token bucket: `rate` tokens/s up to `burst`.  Rates are
    read live from the attributes so `worker set` style tuning applies
    to existing tenants, not only new ones."""

    def __init__(self, rate: float, burst: float, clock=time.monotonic):
        self.rate = float(rate)
        self.burst = float(burst)
        self.clock = clock
        self.tokens = self.burst
        self._last = clock()

    def _refill(self) -> None:
        now = self.clock()
        self.tokens = min(
            self.burst, self.tokens + (now - self._last) * self.rate
        )
        self._last = now

    def take(self, n: float = 1.0) -> bool:
        self._refill()
        if self.tokens >= n:
            self.tokens -= n
            return True
        return False

    def level(self) -> float:
        self._refill()
        return self.tokens

    def time_until(self, n: float = 1.0) -> float:
        """Seconds until `n` tokens will be available (0 if now)."""
        self._refill()
        if self.tokens >= n:
            return 0.0
        if self.rate <= 0:
            return math.inf
        return (n - self.tokens) / self.rate


class Ticket:
    """The admit() verdict.  An admitted ticket MUST be release()d
    exactly once (the api server does it in a finally); release is
    idempotent so error paths can't double-free the in-flight slot."""

    __slots__ = ("admitted", "tier", "queued", "queued_secs", "retry_after",
                 "reason", "exempt", "_ctl")

    def __init__(self, admitted: bool, tier: int, *, queued: bool = False,
                 queued_secs: float = 0.0, retry_after: float = 1.0,
                 reason: str = "", exempt: bool = False, ctl=None):
        self.admitted = admitted
        self.tier = tier
        self.queued = queued
        # time spent in the admission queue before the slot opened —
        # the api server folds it into api_s3_request_duration so the
        # latency the SLO tracker sees is the latency the CLIENT saw
        # (queueing under load must be able to step the ladder)
        self.queued_secs = queued_secs
        self.retry_after = retry_after
        self.reason = reason
        self.exempt = exempt
        self._ctl = ctl

    def release(self) -> None:
        if self._ctl is not None:
            ctl, self._ctl = self._ctl, None
            ctl._release(exempt=self.exempt)


class AdmissionController:
    """One per node, constructed by model/garage.py from `[overload]`
    config.  All knobs are read live off the shared OverloadConfig
    dataclass, so `worker set overload-max-in-flight` (and tests
    mutating the config) apply immediately."""

    def __init__(self, cfg, registry=None, clock=time.monotonic):
        self.cfg = cfg
        self.registry = registry if registry is not None else global_registry
        self.clock = clock
        self.in_flight = 0
        self._exempt_in_flight = 0
        self._queue_len = 0
        self._shed_from: int | None = None  # ladder: shed tier >= this
        self._exempt: set[str] = set()
        self._key_buckets: OrderedDict[str, TokenBucket] = OrderedDict()
        self._bucket_buckets: OrderedDict[str, TokenBucket] = OrderedDict()
        self._gauge_id = str(next(_ctl_ids))
        # mirrors of the registry counters for status() (the registry is
        # process-global and may aggregate several in-process nodes)
        self.counts = {
            kind: [0] * len(TIER_NAMES) for kind in ("admitted", "queued", "shed")
        }
        self.exempt_admitted = 0

    # --- classification -------------------------------------------------------

    @staticmethod
    def claimed_key_id(request) -> str | None:
        """Pre-auth tenant identity: SigV4 `Credential=<key>/...` from
        the Authorization header, or `X-Amz-Credential` on presigned
        URLs.  None = anonymous."""
        auth = request.headers.get("Authorization", "")
        m = _CRED_RE.search(auth)
        if m:
            return m.group(1)
        cred = request.query.get("X-Amz-Credential")
        if cred:
            return cred.split("/", 1)[0]
        return None

    @staticmethod
    def classify(request, key: str, key_id: str | None) -> int:
        """Priority class of a request (`key` = object key from the
        path, "" for bucket-level)."""
        if key_id is None:
            return TIER_ANON
        q = request.query
        m = request.method
        if m in ("GET", "HEAD"):
            if not key:
                return TIER_LIST  # ListObjects / ListBuckets / bucket config
            if "uploadId" in q:
                return TIER_LIST  # ListParts
            return TIER_INTERACTIVE
        if m == "POST" and "delete" in q:
            return TIER_LIST  # DeleteObjects batch
        return TIER_WRITE  # PUT / POST / DELETE, incl. multipart legs

    # --- tenant buckets -------------------------------------------------------

    def _tenant_bucket(
        self, table: OrderedDict, ident: str, rate: float, burst: float,
        gauge: str, label: str, kind: str,
    ) -> TokenBucket:
        b = table.get(ident)
        if b is not None:
            table.move_to_end(ident)
            # live-tune existing tenants when the config knobs change
            b.rate, b.burst = float(rate), float(burst)
            return b
        b = TokenBucket(rate, burst, clock=self.clock)
        cap = max(1, int(self.cfg.max_tracked_tenants))
        if len(table) >= cap:
            # tenant-churn pressure: this create rides an eviction.
            # Identities are CLAIMED pre-auth, so an attacker cycling
            # > max_tracked_tenants fake ids could evict every real
            # tenant and hand each (itself included) a fresh full burst
            # per cycle — under pressure, new buckets start at one
            # second's refill instead of the full burst, bounding what
            # eviction churn can mint
            b.tokens = min(b.burst, max(b.rate, 1.0))
            self.registry.incr(
                "api_admission_tenant_evictions_total", (("kind", kind),)
            )
        table[ident] = b
        # graft-lint: allow-taint(claimed pre-auth id as a label value is by design — metrics._fmt applies _esc to EVERY label at exposition, so a hostile id cannot corrupt the scrape)
        self.registry.register_gauge(
            gauge, ((label, ident), ("id", self._gauge_id)), b.level
        )
        while len(table) > cap:
            old_ident, _old = table.popitem(last=False)
            self.registry.unregister_gauge(
                gauge, ((label, old_ident), ("id", self._gauge_id))
            )
        return b

    def _token_wait(
        self, key_id: str | None, bucket_name: str
    ) -> tuple[float, tuple]:
        """(seconds until one token is available on BOTH tenant buckets,
        the bucket pair) — a pure peek, nothing debited.  Debiting is
        separate (`_debit`) and happens only at the moment of admission:
        a request shed at the in-flight cap, or an interactive waiter
        re-checking every poll quantum, must not burn tokens it never
        used (the queue loop would otherwise drain a tenant's whole
        budget while waiting for a slot)."""
        cfg = self.cfg
        # the tenant label is named `tenant`, NOT `key`/`bucket`: the
        # metrics-lint cardinality guard (script/dashboard_lint.py)
        # reserves those label names for statically-bounded value sets —
        # per-object series are how exposition cardinality explodes
        # (hot-key data belongs in /v1/traffic's sketch JSON instead).
        # This family's value set is LRU-bounded by max_tracked_tenants.
        kb = (
            self._tenant_bucket(
                self._key_buckets, key_id, cfg.key_rate, cfg.key_burst,
                "api_admission_key_tokens", "tenant", "key",
            )
            if key_id
            else None
        )
        bb = (
            self._tenant_bucket(
                self._bucket_buckets, bucket_name, cfg.bucket_rate,
                cfg.bucket_burst, "api_admission_bucket_tokens", "tenant",
                "bucket",
            )
            if bucket_name
            else None
        )
        wait = 0.0
        for b in (kb, bb):
            if b is not None:
                wait = max(wait, b.time_until())
        return wait, (kb, bb)

    @staticmethod
    def _debit(buckets: tuple) -> None:
        for b in buckets:
            if b is not None:
                b.take()

    # --- admission ------------------------------------------------------------

    def exempt_key(self, key_id: str) -> None:
        """Exempt a key from admission entirely (canary prober): its
        probes must keep flowing at every ladder level, or shedding
        would blind the very signal that decides recovery."""
        self._exempt.add(key_id)

    def set_shed_tier(self, tier: int | None) -> None:
        """Ladder actuator (rpc/shedding.py): shed every request of
        tier >= `tier`; None sheds nothing.  Tier 0 is never shed —
        the floor is TIER_WRITE."""
        self._shed_from = max(TIER_WRITE, int(tier)) if tier is not None else None

    @property
    def shed_from_tier(self) -> int | None:
        return self._shed_from

    def _count(self, kind: str, tier: int) -> None:
        self.counts[kind][tier] += 1
        self.registry.incr(
            f"api_admission_{kind}_total", (("tier", TIER_NAMES[tier]),)
        )

    @staticmethod
    def _shed_tenant(key_id: str | None) -> None:
        # join admission sheds into the tenant observatory under the
        # CLAIMED key id — sheds happen pre-auth, so the claim is the
        # only identity there is (rpc/tenant.py keeps it sketch-bounded)
        try:
            from ..rpc.tenant import observatory

            observatory.record_shed(key_id)
        except Exception:  # noqa: BLE001
            pass  # graft-lint: allow-swallow(accounting must never turn a shed into a 500)

    def _release(self, exempt: bool = False) -> None:
        # queued waiters poll on _QUEUE_QUANTUM, so freeing a slot is
        # observed within ~20 ms without any notification machinery
        self.in_flight -= 1
        if exempt:
            self._exempt_in_flight -= 1

    async def admit(self, request, bucket_name: str, key: str) -> Ticket:
        """The one admission decision, called from `_entry` before any
        auth/parse work.  Never raises; returns an (un)admitted Ticket."""
        cfg = self.cfg
        key_id = self.claimed_key_id(request)
        tier = self.classify(request, key, key_id)
        if not cfg.enabled:
            return Ticket(True, tier)
        if (
            key_id is not None
            and key_id in self._exempt
            and self._exempt_in_flight < _EXEMPT_MAX_IN_FLIGHT
        ):
            # exempt = canary: admitted past the ladder/buckets/cap so
            # shedding can't blind the recovery signal — but the claim
            # is pre-auth data, so the bypass is concurrency-bounded
            # (_EXEMPT_MAX_IN_FLIGHT); over the bound the claim falls
            # through to normal admission like any other request
            self.exempt_admitted += 1
            self.registry.incr(
                "api_admission_admitted_total", (("tier", "exempt"),)
            )
            self.in_flight += 1
            self._exempt_in_flight += 1
            return Ticket(True, tier, exempt=True, ctl=self)

        if self._shed_from is not None and tier >= self._shed_from:
            self._count("shed", tier)
            self._shed_tenant(key_id)
            return Ticket(
                False, tier,
                retry_after=max(1.0, float(cfg.shed_retry_after_secs)),
                reason=f"load shedding active (ladder sheds tier >= "
                       f"{TIER_NAMES[self._shed_from]})",
            )

        token_wait, buckets = self._token_wait(key_id, bucket_name)
        cap_full = self.in_flight >= int(cfg.max_in_flight)
        if token_wait == 0.0 and not cap_full:
            self._debit(buckets)
            self._count("admitted", tier)
            self.in_flight += 1
            return Ticket(True, tier, ctl=self)

        if tier != TIER_INTERACTIVE:
            self._count("shed", tier)
            self._shed_tenant(key_id)
            reason = (
                "request rate over the tenant budget"
                if token_wait > 0
                else "node at its concurrency limit"
            )
            retry = token_wait if token_wait > 0 else float(
                cfg.shed_retry_after_secs
            )
            return Ticket(False, tier, retry_after=max(1.0, retry), reason=reason)

        # top tier: queue-rather-than-reject, bounded in depth and time
        if self._queue_len >= int(cfg.queue_depth):
            self._count("shed", tier)
            self._shed_tenant(key_id)
            return Ticket(
                False, tier, retry_after=max(1.0, float(cfg.shed_retry_after_secs)),
                reason="interactive admission queue is full",
            )
        entered = self.clock()
        deadline = entered + float(cfg.queue_wait_msec) / 1000.0
        self._queue_len += 1
        try:
            while True:
                remaining = deadline - self.clock()
                if remaining <= 0:
                    break
                await asyncio.sleep(min(_QUEUE_QUANTUM, remaining))
                token_wait, buckets = self._token_wait(key_id, bucket_name)
                if token_wait == 0.0 and self.in_flight < int(cfg.max_in_flight):
                    self._debit(buckets)
                    self._count("queued", tier)
                    self._count("admitted", tier)
                    self.in_flight += 1
                    return Ticket(True, tier, queued=True,
                                  queued_secs=self.clock() - entered, ctl=self)
        finally:
            self._queue_len -= 1
        self._count("shed", tier)
        self._shed_tenant(key_id)
        return Ticket(
            False, tier, retry_after=max(1.0, token_wait),
            reason=f"no capacity within {cfg.queue_wait_msec:g} ms queue wait",
        )

    # --- surfaces -------------------------------------------------------------

    def status(self) -> dict[str, Any]:
        """Admission half of admin `GET /v1/overload` / `cli overload
        status`."""
        cfg = self.cfg

        def top(table: OrderedDict, n: int = 8) -> dict[str, float]:
            # most-recently-active tenants (LRU order, newest last)
            return {
                ident: round(b.level(), 2)
                for ident, b in list(table.items())[-n:]
            }

        return {
            "enabled": bool(cfg.enabled),
            "inFlight": self.in_flight,
            "maxInFlight": int(cfg.max_in_flight),
            "queued": self._queue_len,
            "queueDepth": int(cfg.queue_depth),
            "shedFromTier": (
                TIER_NAMES[self._shed_from]
                if self._shed_from is not None
                else None
            ),
            "tiers": {
                TIER_NAMES[t]: {
                    "admitted": self.counts["admitted"][t],
                    "queued": self.counts["queued"][t],
                    "shed": self.counts["shed"][t],
                }
                for t in range(len(TIER_NAMES))
            },
            "exemptAdmitted": self.exempt_admitted,
            "exemptKeys": sorted(self._exempt),
            "keyTokens": top(self._key_buckets),
            "bucketTokens": top(self._bucket_buckets),
            "rates": {
                "keyRate": cfg.key_rate,
                "keyBurst": cfg.key_burst,
                "bucketRate": cfg.bucket_rate,
                "bucketBurst": cfg.bucket_burst,
            },
        }

    def digest_fields(self) -> dict[str, Any]:
        """The `ovl` block of the gossiped telemetry digest (additive
        keys; DIGEST_VERSION stays 1)."""
        return {
            "inf": self.in_flight,
            "shed": sum(self.counts["shed"]),
            "adm": sum(self.counts["admitted"]),
        }

    def close(self) -> None:
        """Unregister every per-tenant gauge (node shutdown — several
        in-process nodes share the registry, so leaking them would
        poison later tests/scrapes)."""
        for ident in self._key_buckets:
            self.registry.unregister_gauge(
                "api_admission_key_tokens",
                (("tenant", ident), ("id", self._gauge_id)),
            )
        for ident in self._bucket_buckets:
            self.registry.unregister_gauge(
                "api_admission_bucket_tokens",
                (("tenant", ident), ("id", self._gauge_id)),
            )
        self._key_buckets.clear()
        self._bucket_buckets.clear()
