"""Engine selection (reference src/db/open.rs)."""

from __future__ import annotations

import os

from . import Db


def open_db(path: str, engine: str = "sqlite", fsync: bool = True) -> Db:
    if engine == "sqlite":
        from .sqlite_engine import SqliteDb

        if os.path.isdir(path) or not os.path.splitext(path)[1]:
            path = os.path.join(path, "db.sqlite")
        return SqliteDb(path, fsync=fsync)
    if engine == "log":
        from .log_engine import LogDb

        if os.path.isdir(path) or not os.path.splitext(path)[1]:
            path = os.path.join(path, "db.log")
        return LogDb(path, fsync=fsync)
    if engine == "native":
        from .native_engine import NativeDb

        if os.path.isdir(path) or not os.path.splitext(path)[1]:
            path = os.path.join(path, "db.log")  # WAL-compatible with "log"
        return NativeDb(path, fsync=fsync)
    if engine == "memory":
        from .memory_engine import MemDb

        return MemDb()
    raise ValueError(
        f"unknown db engine {engine!r} (supported: sqlite, log, native, memory)"
    )
