"""Ordered in-memory engine: dict + bisect-maintained sorted key list.

Stands in for the reference's LMDB adapter (src/db/lmdb_adapter.rs) as the
second engine the dual-engine test suite runs against; also used for
ephemeral/test nodes.
"""

from __future__ import annotations

import bisect
from typing import Callable, Iterator, TypeVar

from . import Db, Tree, Tx, TxAbort

T = TypeVar("T")


class _MemTreeData:
    __slots__ = ("d", "keys")

    def __init__(self) -> None:
        self.d: dict[bytes, bytes] = {}
        self.keys: list[bytes] = []

    def put(self, k: bytes, v: bytes) -> None:
        if k not in self.d:
            bisect.insort(self.keys, k)
        self.d[k] = v

    def delete(self, k: bytes) -> None:
        if k in self.d:
            del self.d[k]
            i = bisect.bisect_left(self.keys, k)
            del self.keys[i]


class MemTree(Tree):
    def __init__(self, db: "MemDb", name: str):
        self.db = db
        self.name = name
        self.data = _MemTreeData()

    def get(self, k: bytes) -> bytes | None:
        return self.data.d.get(k)

    def insert(self, k: bytes, v: bytes) -> None:
        self.db.assert_not_in_tx()
        self.data.put(k, v)

    def remove(self, k: bytes) -> None:
        self.db.assert_not_in_tx()
        self.data.delete(k)

    def __len__(self) -> int:
        return len(self.data.d)

    def iter_range(
        self,
        start: bytes | None = None,
        end: bytes | None = None,
        reverse: bool = False,
    ) -> Iterator[tuple[bytes, bytes]]:
        # Re-bisect from the last yielded key on every step so callers may
        # mutate the tree mid-iteration (GC/sync workers do exactly that) —
        # same contract as the sqlite engine's paged iteration.
        keys = self.data.keys
        last: bytes | None = None
        while True:
            if reverse:
                hi = (
                    (len(keys) if end is None else bisect.bisect_left(keys, end))
                    if last is None
                    else bisect.bisect_left(keys, last)
                )
                i = hi - 1
                if i < 0:
                    return
                k = keys[i]
                if start is not None and k < start:
                    return
            else:
                lo = (
                    (0 if start is None else bisect.bisect_left(keys, start))
                    if last is None
                    else bisect.bisect_right(keys, last)
                )
                if lo >= len(keys):
                    return
                k = keys[lo]
                if end is not None and k >= end:
                    return
            last = k
            v = self.data.d.get(k)
            if v is not None:
                yield (k, v)


class _MemTx(Tx):
    def __init__(self, db: "MemDb"):
        self.db = db
        # journal of (tree, key, old_value | None-if-absent) for rollback
        self.journal: list[tuple[MemTree, bytes, bytes | None]] = []

    def get(self, tree: Tree, k: bytes) -> bytes | None:
        assert isinstance(tree, MemTree)
        return tree.data.d.get(k)

    def insert(self, tree: Tree, k: bytes, v: bytes) -> None:
        assert isinstance(tree, MemTree)
        self.journal.append((tree, k, tree.data.d.get(k)))
        tree.data.put(k, v)

    def remove(self, tree: Tree, k: bytes) -> None:
        assert isinstance(tree, MemTree)
        self.journal.append((tree, k, tree.data.d.get(k)))
        tree.data.delete(k)

    def len(self, tree: Tree) -> int:
        assert isinstance(tree, MemTree)
        return len(tree.data.d)

    def rollback(self) -> None:
        for tree, k, old in reversed(self.journal):
            if old is None:
                tree.data.delete(k)
            else:
                tree.data.put(k, old)


class MemDb(Db):
    engine = "memory"

    def __init__(self) -> None:
        self.trees: dict[str, MemTree] = {}
        self._in_tx = False

    def open_tree(self, name: str) -> Tree:
        if name not in self.trees:
            self.trees[name] = MemTree(self, name)
        return self.trees[name]

    def list_trees(self) -> list[str]:
        return sorted(self.trees)

    def assert_not_in_tx(self) -> None:
        # same contract as the sqlite engine: no auto-commit ops mid-tx
        if self._in_tx:
            raise RuntimeError(
                "auto-commit Tree op called inside a transaction(); "
                "use the Tx handle instead"
            )

    def transaction(self, fn: Callable[[Tx], T]) -> T:
        tx = _MemTx(self)
        self._in_tx = True
        try:
            return fn(tx)
        except TxAbort as a:
            tx.rollback()
            return a.value
        except BaseException:
            tx.rollback()
            raise
        finally:
            self._in_tx = False

    def snapshot(self, to_dir: str) -> None:
        raise NotImplementedError("memory engine has no snapshot")
