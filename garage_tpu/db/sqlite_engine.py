"""SQLite engine (reference src/db/sqlite_adapter.rs:1-596).

One SQL table per tree (`tree_<hex(name)>`), BLOB key/value, WAL mode.
Transactions use a process-wide lock + BEGIN IMMEDIATE; iteration during a
write transaction is served from the same connection (sqlite allows reads
mid-transaction).
"""

from __future__ import annotations

import os
import sqlite3
import threading
from typing import Callable, Iterator, TypeVar

from . import Db, Tree, Tx, TxAbort

T = TypeVar("T")


def _tbl(name: str) -> str:
    return "tree_" + name.encode().hex()


class SqliteTree(Tree):
    def __init__(self, db: "SqliteDb", name: str):
        self.db = db
        self.name = name
        self.tbl = _tbl(name)

    def get(self, k: bytes) -> bytes | None:
        with self.db.lock:
            row = self.db.conn.execute(
                f"SELECT v FROM {self.tbl} WHERE k = ?", (k,)
            ).fetchone()
        return row[0] if row else None

    def insert(self, k: bytes, v: bytes) -> None:
        with self.db.lock:
            self.db.assert_not_in_tx()
            self.db.conn.execute(
                f"INSERT INTO {self.tbl}(k, v) VALUES(?, ?) "
                "ON CONFLICT(k) DO UPDATE SET v = excluded.v",
                (k, v),
            )
            self.db.conn.commit()

    def remove(self, k: bytes) -> None:
        with self.db.lock:
            self.db.assert_not_in_tx()
            self.db.conn.execute(f"DELETE FROM {self.tbl} WHERE k = ?", (k,))
            self.db.conn.commit()

    def __len__(self) -> int:
        with self.db.lock:
            (n,) = self.db.conn.execute(f"SELECT COUNT(*) FROM {self.tbl}").fetchone()
        return n

    def iter_range(
        self,
        start: bytes | None = None,
        end: bytes | None = None,
        reverse: bool = False,
    ) -> Iterator[tuple[bytes, bytes]]:
        q = f"SELECT k, v FROM {self.tbl}"
        conds, params = [], []
        if start is not None:
            conds.append("k >= ?")
            params.append(start)
        if end is not None:
            conds.append("k < ?")
            params.append(end)
        if conds:
            q += " WHERE " + " AND ".join(conds)
        q += " ORDER BY k" + (" DESC" if reverse else "")
        # fetch in pages so callers may mutate between yields
        last: bytes | None = None
        while True:
            qq, pp = q, list(params)
            if last is not None:
                op = "k < ?" if reverse else "k > ?"
                qq = f"SELECT k, v FROM {self.tbl} WHERE {op}"
                pp = [last]
                if start is not None:
                    qq += " AND k >= ?"
                    pp.append(start)
                if end is not None:
                    qq += " AND k < ?"
                    pp.append(end)
                qq += " ORDER BY k" + (" DESC" if reverse else "")
            with self.db.lock:
                rows = self.db.conn.execute(qq + " LIMIT 256", pp).fetchall()
            if not rows:
                return
            for k, v in rows:
                yield (bytes(k), bytes(v))
            last = bytes(rows[-1][0])


class _SqliteTx(Tx):
    def __init__(self, db: "SqliteDb"):
        self.db = db

    def get(self, tree: Tree, k: bytes) -> bytes | None:
        assert isinstance(tree, SqliteTree)
        row = self.db.conn.execute(
            f"SELECT v FROM {tree.tbl} WHERE k = ?", (k,)
        ).fetchone()
        return bytes(row[0]) if row else None

    def insert(self, tree: Tree, k: bytes, v: bytes) -> None:
        assert isinstance(tree, SqliteTree)
        self.db.conn.execute(
            f"INSERT INTO {tree.tbl}(k, v) VALUES(?, ?) "
            "ON CONFLICT(k) DO UPDATE SET v = excluded.v",
            (k, v),
        )

    def remove(self, tree: Tree, k: bytes) -> None:
        assert isinstance(tree, SqliteTree)
        self.db.conn.execute(f"DELETE FROM {tree.tbl} WHERE k = ?", (k,))

    def len(self, tree: Tree) -> int:
        assert isinstance(tree, SqliteTree)
        (n,) = self.db.conn.execute(f"SELECT COUNT(*) FROM {tree.tbl}").fetchone()
        return n


class SqliteDb(Db):
    engine = "sqlite"

    def __init__(self, path: str, fsync: bool = True):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self.conn = sqlite3.connect(path, check_same_thread=False)
        self.lock = threading.RLock()
        self.conn.execute("PRAGMA journal_mode = WAL")
        # WAL + NORMAL already skips the per-commit fsync (it syncs only
        # at checkpoints), so that is the fsync=False setting; OFF would
        # additionally skip checkpoint syncs and can corrupt the whole DB
        # on power loss.  fsync=True buys per-commit durability (FULL).
        self.conn.execute(
            "PRAGMA synchronous = " + ("FULL" if fsync else "NORMAL")
        )
        self.conn.execute(
            "CREATE TABLE IF NOT EXISTS _trees (name TEXT PRIMARY KEY)"
        )
        self.conn.commit()
        self._trees: dict[str, SqliteTree] = {}

    def open_tree(self, name: str) -> Tree:
        if name not in self._trees:
            with self.lock:
                self.conn.execute(
                    f"CREATE TABLE IF NOT EXISTS {_tbl(name)} "
                    "(k BLOB PRIMARY KEY, v BLOB NOT NULL)"
                )
                self.conn.execute(
                    "INSERT OR IGNORE INTO _trees(name) VALUES(?)", (name,)
                )
                self.conn.commit()
            self._trees[name] = SqliteTree(self, name)
        return self._trees[name]

    def list_trees(self) -> list[str]:
        with self.lock:
            rows = self.conn.execute("SELECT name FROM _trees ORDER BY name").fetchall()
        return [r[0] for r in rows]

    def assert_not_in_tx(self) -> None:
        # Auto-commit Tree ops inside a transaction() closure would commit
        # the half-done outer transaction; force callers to use the Tx handle.
        if self.conn.in_transaction:
            raise RuntimeError(
                "auto-commit Tree op called inside a transaction(); "
                "use the Tx handle instead"
            )

    def transaction(self, fn: Callable[[Tx], T]) -> T:
        with self.lock:
            self.conn.execute("BEGIN IMMEDIATE")
            tx = _SqliteTx(self)
            try:
                res = fn(tx)
                self.conn.commit()
                return res
            except TxAbort as a:
                self.conn.rollback()
                return a.value
            except BaseException:
                self.conn.rollback()
                raise

    def snapshot(self, to_dir: str) -> None:
        os.makedirs(to_dir, exist_ok=True)
        dest_path = os.path.join(to_dir, "db.sqlite")
        with self.lock:
            dest = sqlite3.connect(dest_path)
            try:
                self.conn.backup(dest)
            finally:
                dest.close()

    def close(self) -> None:
        with self.lock:
            self.conn.close()
