"""Durable log-structured engine (the second production engine beside
sqlite — filling the reference's LMDB slot, src/db/lmdb_adapter.rs, with a
write-optimized design instead of a binding we don't have).

Bitcask/WAL architecture:

  - ALL mutations append to one log file as crc-framed commit batches; a
    transaction is exactly one frame, so atomicity = frame integrity and
    recovery is "replay frames until the first bad/short one" (a torn
    write at the tail rolls back the interrupted commit and nothing else).
  - The full keyspace lives in RAM as ordered per-tree maps (dict +
    sorted key list), so reads and range scans never touch disk — the
    right trade for metadata tables that fit memory (same bet LMDB's
    mmap makes, minus the page cache misses).
  - When the log exceeds COMPACT_RATIO x the live data size it is
    rewritten: full state into `<path>.new`, fsync, atomic rename.
    Compaction also runs on close() and snapshot().

Frame format (little-endian):
    [u32 payload_len][u32 crc32(payload)][payload]
payload = concatenated records:
    [u8 op 1=put 2=del][u16 tree_len][tree][u32 klen][k]([u32 vlen][v] if put)
"""

from __future__ import annotations

import bisect
import os
import shutil
import struct
import zlib
from typing import Callable, Iterator, TypeVar

from . import Db, Tree, Tx, TxAbort

T = TypeVar("T")

COMPACT_RATIO = 3  # compact when log bytes > ratio * live bytes
COMPACT_MIN_BYTES = 4 * 1024 * 1024

_PUT, _DEL = 1, 2


def _enc_record(op: int, tree: str, k: bytes, v: bytes | None) -> bytes:
    t = tree.encode()
    out = [struct.pack("<BH", op, len(t)), t, struct.pack("<I", len(k)), k]
    if op == _PUT:
        out += [struct.pack("<I", len(v)), v]
    return b"".join(out)


class _Data:
    """Ordered map: dict + bisect-maintained key list."""

    __slots__ = ("d", "keys")

    def __init__(self) -> None:
        self.d: dict[bytes, bytes] = {}
        self.keys: list[bytes] = []

    def put(self, k: bytes, v: bytes) -> None:
        if k not in self.d:
            bisect.insort(self.keys, k)
        self.d[k] = v

    def delete(self, k: bytes) -> None:
        if k in self.d:
            del self.d[k]
            del self.keys[bisect.bisect_left(self.keys, k)]


class LogTree(Tree):
    def __init__(self, db: "LogDb", name: str):
        self.db = db
        self.name = name
        self.data = _Data()

    def get(self, k: bytes) -> bytes | None:
        return self.data.d.get(k)

    def insert(self, k: bytes, v: bytes) -> None:
        self.db._autocommit([(self, _PUT, bytes(k), bytes(v))])

    def remove(self, k: bytes) -> None:
        self.db._autocommit([(self, _DEL, bytes(k), None)])

    def __len__(self) -> int:
        return len(self.data.d)

    def iter_range(
        self,
        start: bytes | None = None,
        end: bytes | None = None,
        reverse: bool = False,
    ) -> Iterator[tuple[bytes, bytes]]:
        keys = self.data.keys
        lo = bisect.bisect_left(keys, start) if start is not None else 0
        hi = bisect.bisect_left(keys, end) if end is not None else len(keys)
        # snapshot the key range: workers mutate the tree mid-iteration
        snap = keys[lo:hi]
        if reverse:
            snap.reverse()
        d = self.data.d
        for k in snap:
            v = d.get(k)
            if v is not None:  # deleted since the snapshot
                yield (k, v)


class LogTx(Tx):
    def __init__(self, db: "LogDb"):
        self.db = db
        # overlay: (tree_name, key) -> (op, value); reads see the overlay
        self.writes: dict[tuple[str, bytes], tuple[int, bytes | None]] = {}
        self.order: list[tuple[LogTree, int, bytes, bytes | None]] = []

    def get(self, tree: LogTree, k: bytes) -> bytes | None:
        ent = self.writes.get((tree.name, bytes(k)))
        if ent is not None:
            return ent[1]
        return tree.data.d.get(bytes(k))

    def insert(self, tree: LogTree, k: bytes, v: bytes) -> None:
        k, v = bytes(k), bytes(v)
        self.writes[(tree.name, k)] = (_PUT, v)
        self.order.append((tree, _PUT, k, v))

    def remove(self, tree: LogTree, k: bytes) -> None:
        k = bytes(k)
        self.writes[(tree.name, k)] = (_DEL, None)
        self.order.append((tree, _DEL, k, None))

    def len(self, tree: LogTree) -> int:
        n = len(tree.data.d)
        for (tname, k), (op, _v) in self.writes.items():
            if tname != tree.name:
                continue
            present = k in tree.data.d
            if op == _PUT and not present:
                n += 1
            elif op == _DEL and present:
                n -= 1
        return n


class LogDb(Db):
    engine = "log"

    def __init__(self, path: str, fsync: bool = True):
        self.path = path
        self.fsync = fsync
        self.trees: dict[str, LogTree] = {}
        self._live_bytes = 0
        self._in_tx = False
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._replay()
        self._f = open(path, "ab")
        self._log_bytes = self._f.tell()

    # --- recovery -------------------------------------------------------------

    def _replay(self) -> None:
        if not os.path.exists(self.path):
            return
        with open(self.path, "rb") as f:
            buf = f.read()
        pos = 0
        valid_end = 0
        while pos + 8 <= len(buf):
            plen, crc = struct.unpack_from("<II", buf, pos)
            if pos + 8 + plen > len(buf):
                break  # torn tail
            payload = buf[pos + 8 : pos + 8 + plen]
            if zlib.crc32(payload) != crc:
                break  # corrupt frame: everything after is suspect
            self._apply_payload(payload)
            pos += 8 + plen
            valid_end = pos
        if valid_end < len(buf):
            # roll the interrupted commit back on disk too
            with open(self.path, "r+b") as f:
                f.truncate(valid_end)

    def _apply_payload(self, payload: bytes) -> None:
        pos = 0
        while pos < len(payload):
            op, tlen = struct.unpack_from("<BH", payload, pos)
            pos += 3
            tree = payload[pos : pos + tlen].decode()
            pos += tlen
            (klen,) = struct.unpack_from("<I", payload, pos)
            pos += 4
            k = payload[pos : pos + klen]
            pos += klen
            t = self.open_tree(tree)
            if op == _PUT:
                (vlen,) = struct.unpack_from("<I", payload, pos)
                pos += 4
                v = payload[pos : pos + vlen]
                pos += vlen
                old = t.data.d.get(k)
                if old is not None:
                    self._live_bytes -= len(k) + len(old)
                t.data.put(k, v)
                self._live_bytes += len(k) + len(v)
            else:
                old = t.data.d.get(k)
                if old is not None:
                    self._live_bytes -= len(k) + len(old)
                t.data.delete(k)

    # --- commit ---------------------------------------------------------------

    def _write_frame(self, records: list[tuple[LogTree, int, bytes, bytes | None]]):
        payload = b"".join(
            _enc_record(op, t.name, k, v) for t, op, k, v in records
        )
        frame = struct.pack("<II", len(payload), zlib.crc32(payload)) + payload
        self._f.write(frame)
        self._f.flush()
        if self.fsync:
            os.fsync(self._f.fileno())
        self._log_bytes += len(frame)

    def _apply_mem(self, records) -> None:
        for t, op, k, v in records:
            old = t.data.d.get(k)
            if old is not None:
                self._live_bytes -= len(k) + len(old)
            if op == _PUT:
                t.data.put(k, v)
                self._live_bytes += len(k) + len(v)
            else:
                t.data.delete(k)

    def _autocommit(self, records) -> None:
        if self._in_tx:
            raise RuntimeError(
                "direct tree mutation inside a transaction; use the tx handle"
            )
        self._write_frame(records)
        self._apply_mem(records)
        self._maybe_compact()

    # --- Db interface ---------------------------------------------------------

    def open_tree(self, name: str) -> LogTree:
        t = self.trees.get(name)
        if t is None:
            t = self.trees[name] = LogTree(self, name)
        return t

    def list_trees(self) -> list[str]:
        return sorted(self.trees)

    def transaction(self, fn: Callable[[Tx], T]) -> T:
        self._in_tx = True
        tx = LogTx(self)
        try:
            res = fn(tx)
        except TxAbort as e:
            return e.value
        finally:
            self._in_tx = False
        if tx.order:
            self._write_frame(tx.order)
            self._apply_mem(tx.order)
            self._maybe_compact()
        return res

    def snapshot(self, to_dir: str) -> None:
        os.makedirs(to_dir, exist_ok=True)
        dst = os.path.join(to_dir, os.path.basename(self.path))
        self._compact()  # snapshot the compacted form
        shutil.copy2(self.path, dst)

    def close(self) -> None:
        if getattr(self, "_f", None) is None:
            return
        self._compact()
        self._f.close()
        self._f = None

    # --- compaction -----------------------------------------------------------

    def _maybe_compact(self) -> None:
        if (
            self._log_bytes > COMPACT_MIN_BYTES
            and self._log_bytes > COMPACT_RATIO * max(self._live_bytes, 1)
        ):
            self._compact()

    def _compact(self) -> None:
        """Rewrite the log as one frame per tree of live state; atomic
        swap via rename."""
        tmp = self.path + ".new"
        with open(tmp, "wb") as f:
            total = 0
            for name in sorted(self.trees):
                t = self.trees[name]
                if not t.data.d:
                    continue
                records = [
                    (t, _PUT, k, t.data.d[k]) for k in t.data.keys
                ]
                payload = b"".join(
                    _enc_record(_PUT, name, k, v) for _t, _op, k, v in records
                )
                frame = (
                    struct.pack("<II", len(payload), zlib.crc32(payload))
                    + payload
                )
                f.write(frame)
                total += len(frame)
            f.flush()
            os.fsync(f.fileno())
        self._f.close()
        os.replace(tmp, self.path)
        self._f = open(self.path, "ab")
        self._log_bytes = total
